"""WatchMux: ONE apiserver watch stream fanned out to per-tenant routes.

The fleet's watch-amplification killer (ISSUE 13, ROADMAP item 1): K tenants
sharing one cluster each used to own a full informer set — K apiserver watch
streams per resource, and every disruption × K relists. The mux inverts
that: ONE `SharedInformer` (one upstream list+watch, bookmark-resumable,
relist only on a genuine 410) feeds an indexer, and events fan out to
per-tenant routes keyed by a tenant label.

Per-route delivery discipline (the cacher contract, one layer up):

  * every route owns a BOUNDED queue drained by its own consumer thread —
    one slow tenant can never stall the upstream pump or its siblings;
  * a route that overflows (or is hit by the `watch.stall@<route>` chaos
    seam) is BROKEN, not blocked: its queue is cleared, a sequence fence is
    raised past every event it may have lost, and a RESYNC marker replays
    the route's world from the mux's OWN indexer snapshot — the apiserver
    never sees a relist for a route-local failure;
  * in-flight events racing the fence are discarded by sequence number, so
    a resynced route can't interleave stale deltas into its rebuilt view.

Mux-stream death (`mux.die@stream` seam, or the upstream informer thread
exiting) leaves every route serving from its last-delivered state; `revive()`
restarts the upstream informer, which RESUMES from its last (possibly
bookmarked) resourceVersion — the indexer survives, so recovery costs one
watch re-establishment, not K relists.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from kubernetes_tpu.machinery import meta
from kubernetes_tpu.client.informers import SharedInformer
from kubernetes_tpu.utils import faultline

Obj = Dict[str, Any]

TENANT_LABEL = "ktpu.io/tenant"

_RESYNC = "RESYNC"


class MuxRoute:
    """One tenant's delivery lane: bounded queue + consumer thread + the
    route's own view of the world (what the fence-and-resync diff runs
    against)."""

    def __init__(self, name: str,
                 on_add: Callable[[Obj], None] = lambda o: None,
                 on_update: Callable[[Obj, Obj], None] = lambda o, n: None,
                 on_delete: Callable[[Obj], None] = lambda o: None,
                 capacity: int = 1024):
        self.name = name
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete
        # clamp: 0/negative would defeat the bounded-queue overflow check
        # (len >= capacity) and let a deaf route grow without eviction
        self.capacity = max(1, capacity)
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._q: deque = deque()
        self._stop = False
        self.seq = 0              # per-route event sequence (producer side)
        self.fence = 0            # events with seq <= fence are void
        # the route's delivered view: key → last object handed to handlers
        # (object REFERENCES shared with the mux indexer — no copies)
        self.view: Dict[str, Obj] = {}
        # counters the chaos drills and the bench read
        self.delivered = 0
        self.resyncs = 0          # indexer-snapshot rebuilds taken
        self.evictions = 0        # queue overflows / injected stalls
        self.discarded_stale = 0  # fenced-off events dropped by seq
        self.handler_errors = 0   # tenant-handler exceptions swallowed —
                                  # a silently-diverging tenant must show
                                  # up in metrics, not nowhere
        self.last_event = time.monotonic()
        self._thread = threading.Thread(
            target=self._drain, name=f"muxroute-{name}", daemon=True)
        self._thread.start()

    # -- producer side (called from the informer handler thread) -------- #

    def offer(self, typ: str, old: Optional[Obj], new: Optional[Obj],
              stall: bool = False) -> None:
        """Enqueue one event; a full queue (or an injected stall) breaks
        the route — clear, fence, resync — instead of blocking the mux."""
        with self._cv:
            if self._stop:
                return
            if stall or len(self._q) >= self.capacity:
                # slow-consumer backpressure: this ONE route pays with a
                # local resync; the upstream stream and sibling routes
                # never notice (the deaf-watcher contract, route-local)
                self.evictions += 1
                self._break_locked()
            else:
                self.seq += 1
                self._q.append((self.seq, typ, old, new))
                self._cv.notify()

    def _break_locked(self) -> None:
        """Break the route (caller holds `_cv`): raise the fence past every
        event the queue may have lost, clear the backlog, and leave one
        RESYNC marker — the ONE fence protocol both the overflow path and
        explicit resyncs must share."""
        self.seq += 1
        self.fence = self.seq
        self._q.clear()
        self._q.append((self.seq, _RESYNC, None, None))
        self._cv.notify()

    def resync(self) -> None:
        """Force a fence+resync (used when a route joins late or after a
        mux revive where per-route delivery may have gaps)."""
        with self._cv:
            if self._stop:
                return
            self._break_locked()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            # drop the undelivered backlog: _drain only exits on an EMPTY
            # queue, so a deep backlog behind a handler blocked on the
            # tenant's ingest lock could outlive the bounded join and keep
            # mutating a supposedly-quiesced tenant — clearing bounds the
            # leak to the ONE in-flight handler
            self._q.clear()
            self._cv.notify()
        self._thread.join(timeout=3)

    def depth(self) -> int:
        with self._mu:
            return len(self._q)

    # -- consumer side --------------------------------------------------- #

    def _snapshot(self) -> Dict[str, Obj]:
        """Set by the owning mux: returns this route's slice of the mux
        indexer. Patched in WatchMux.route(); a standalone route (unit
        tests) resyncs to empty."""
        return {}

    def _drain(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._stop and not self._q:
                    return
                if not self._q:
                    continue
                seq, typ, old, new = self._q.popleft()
            if typ == _RESYNC:
                self._apply_resync()
                continue
            with self._mu:
                stale = seq <= self.fence
            if stale:
                self.discarded_stale += 1
                continue
            self._apply(typ, old, new)

    def _apply(self, typ: str, old: Optional[Obj], new: Optional[Obj]) -> None:
        try:
            if typ == "DELETED":
                key = meta.namespaced_key(old or new)
                known = self.view.pop(key, None)
                self.on_delete(known if known is not None else (old or new))
            else:  # ADDED / MODIFIED / synthetic sync
                key = meta.namespaced_key(new)
                known = self.view.get(key)
                self.view[key] = new
                if known is None:
                    self.on_add(new)
                else:
                    self.on_update(known, new)
            self.delivered += 1
            self.last_event = time.monotonic()
        except Exception:  # noqa: BLE001 — one tenant's handler bug must
            self.handler_errors += 1  # not kill the route thread

    def _apply_resync(self) -> None:
        """Rebuild the route's view from the mux's indexer snapshot — a
        DeltaFIFO Replace at route granularity, sourced locally. The
        apiserver is NOT consulted: a route-local failure has route-local
        cost."""
        snap = self._snapshot()
        gone = [k for k in self.view if k not in snap]
        for k in gone:
            obj = self.view.pop(k)
            try:
                self.on_delete(obj)
            except Exception:  # noqa: BLE001
                self.handler_errors += 1
        for k, obj in snap.items():
            known = self.view.get(k)
            if known is obj:
                continue  # same object reference: nothing changed
            if known is not None and meta.resource_version(known) == \
                    meta.resource_version(obj):
                self.view[k] = obj
                continue
            self.view[k] = obj
            try:
                if known is None:
                    self.on_add(obj)
                else:
                    self.on_update(known, obj)
            except Exception:  # noqa: BLE001
                self.handler_errors += 1
        self.resyncs += 1
        self.delivered += 1
        self.last_event = time.monotonic()


class WatchMux:
    """One upstream SharedInformer, K per-tenant routes.

    `route_key(obj)` names the route an object belongs to (default: the
    `ktpu.io/tenant` label); unrouted objects are counted and dropped.
    The mux OWNS its informer's lifecycle: `start()`/`stop()`, plus
    `die()`/`revive()` for the mux-stream death drill."""

    def __init__(self, informer: SharedInformer,
                 route_key: Optional[Callable[[Obj], str]] = None,
                 tenant_label: str = TENANT_LABEL,
                 buffer: int = 1024, name: str = ""):
        self.informer = informer
        self.name = name or informer.rc.resource
        self.tenant_label = tenant_label
        self.route_key = route_key or (
            lambda o: meta.labels_of(o).get(tenant_label, ""))
        self.buffer = buffer
        self._mu = threading.Lock()
        self.routes: Dict[str, MuxRoute] = {}
        self.unrouted_events = 0
        self.deaths = 0           # upstream stream deaths (die()/seam)
        self.revives = 0
        # route snapshots are served off a named index, not a full
        # indexer scan: a revive() resyncing K routes costs O(per-route
        # slice) each instead of K copies of the whole object list
        self._index_name = f"mux-route:{self.name}"
        informer.indexer.add_index(
            self._index_name, lambda o: [self.route_key(o)])
        informer.add_handlers(on_add=self._on_add,
                              on_update=self._on_update,
                              on_delete=self._on_delete)

    # -- lifecycle ------------------------------------------------------- #

    def start(self) -> "WatchMux":
        self.informer.start()
        return self

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self.informer.wait_for_sync(timeout)

    def stop(self) -> None:
        self.informer.stop()
        with self._mu:
            routes = list(self.routes.values())
        for r in routes:
            r.stop()

    @property
    def alive(self) -> bool:
        t = self.informer._thread
        return t is not None and t.is_alive()

    @property
    def last_signal(self) -> float:
        """Monotonic stamp of the last upstream signal (event, bookmark, or
        list) — the staleness metric's anchor."""
        return self.informer.last_signal

    def die(self) -> None:
        """Kill the upstream stream (the `mux.die@stream` drill): the
        informer stops, routes keep serving their last-delivered state."""
        self.deaths += 1
        self.informer.stop()

    def revive(self) -> "WatchMux":
        """Restart the upstream informer. Restart-as-resume: the indexer and
        last (possibly bookmark-advanced) resourceVersion survived, so this
        re-establishes ONE watch — no relist unless the resume token fell
        beneath the compaction floor while dead. Routes are fenced+resynced
        from the indexer once the stream is back, closing any per-route gap
        from the dead window."""
        self.revives += 1
        self.informer.start()
        self.informer.wait_for_sync(10.0)
        with self._mu:
            routes = list(self.routes.values())
        for r in routes:
            r.resync()
        return self

    # -- routes ---------------------------------------------------------- #

    def route(self, name: str,
              on_add: Callable[[Obj], None] = lambda o: None,
              on_update: Callable[[Obj, Obj], None] = lambda o, n: None,
              on_delete: Callable[[Obj], None] = lambda o: None,
              buffer: Optional[int] = None) -> MuxRoute:
        r = MuxRoute(name, on_add, on_update, on_delete,
                     capacity=self.buffer if buffer is None else buffer)
        r._snapshot = lambda: self._route_snapshot(name)
        with self._mu:
            # check-and-insert under ONE lock hold: two racing
            # registrations of the same name must not silently replace a
            # live route (stranding its consumer thread and splitting the
            # tenant's event flow); the loser tears its route down and
            # raises
            duplicate = name in self.routes
            if not duplicate:
                self.routes[name] = r
        if duplicate:
            r.stop()  # outside the lock: stop() joins the drain thread
            raise ValueError(f"route {name!r} already registered")
        if self.informer.has_synced:
            r.resync()  # late joiner: synthesize its world from the indexer
        return r

    def _route_snapshot(self, name: str) -> Dict[str, Obj]:
        return {meta.namespaced_key(o): o
                for o in self.informer.indexer.by_index(self._index_name,
                                                        name)}

    def depths(self) -> Dict[str, int]:
        with self._mu:
            return {n: r.depth() for n, r in self.routes.items()}

    # -- upstream handlers (informer thread) ----------------------------- #

    def _maybe_die(self) -> None:
        # per-mux site (mux.die@pods / mux.die@nodes) targets ONE mux with
        # a deterministic hit count; the shared legacy site "stream" kills
        # whichever attached mux fans the Nth event overall
        if faultline.should("mux.die", self.name) or \
                faultline.should("mux.die", "stream"):
            # the stream dies FROM the delivery path (a broken pump, a
            # half-closed socket): stopping the informer from its own
            # handler thread would self-join — detach
            threading.Thread(target=self.die, name="mux-die",
                             daemon=True).start()

    def _fan(self, typ: str, old: Optional[Obj], new: Optional[Obj]) -> None:
        self._maybe_die()
        obj = new if new is not None else old
        key = self.route_key(obj)
        with self._mu:
            r = self.routes.get(key)
        if r is None:
            self.unrouted_events += 1
            return
        stall = faultline.should("watch.stall", r.name)
        r.offer(typ, old, new, stall=stall)

    def _on_add(self, obj: Obj) -> None:
        self._fan("ADDED", None, obj)

    def _on_update(self, old: Obj, new: Obj) -> None:
        ko, kn = self.route_key(old), self.route_key(new)
        if ko != kn:
            # the object moved tenants: a delete on the old route, an add
            # on the new — each route's view stays internally consistent
            self._fan("DELETED", old, None)
            self._fan("ADDED", None, new)
            return
        self._fan("MODIFIED", old, new)

    def _on_delete(self, obj: Obj) -> None:
        self._fan("DELETED", obj, None)

    # -- stats ------------------------------------------------------------ #

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            routes = dict(self.routes)
        return {
            "name": self.name,
            "upstream_streams": 1,
            "alive": self.alive,
            "relists": self.informer.relists,
            "resumes": self.informer.resumes,
            "bookmark_resumes": self.informer.bookmark_resumes,
            "bookmarks_seen": self.informer.bookmarks_seen,
            "deaths": self.deaths,
            "revives": self.revives,
            "unrouted_events": self.unrouted_events,
            "route_evictions": sum(r.evictions for r in routes.values()),
            "route_resyncs": sum(r.resyncs for r in routes.values()),
            "handler_errors": sum(r.handler_errors
                                  for r in routes.values()),
            "routes": {n: {"delivered": r.delivered,
                           "evictions": r.evictions,
                           "resyncs": r.resyncs,
                           "handler_errors": r.handler_errors,
                           "depth": r.depth()}
                       for n, r in routes.items()},
        }
