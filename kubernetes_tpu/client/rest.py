"""REST transports + the typed client surface.

Analog of client-go's rest.RESTClient + typed clientsets. Two transports
serve the same interface: `LocalTransport` calls the in-process engine
directly (the integration-test path), `HTTPTransport` crosses the real wire
with chunked watch streams. Components depend only on `Client`.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, Iterator, Optional, Tuple

from kubernetes_tpu.machinery import errors, meta
from kubernetes_tpu.machinery import watch as mwatch

Obj = Dict[str, Any]


@dataclass
class RetryPolicy:
    """Client-side retry budget for server PUSHBACK (ISSUE 9): 429 from
    the apiserver's max-inflight filter and 503 from a restart window are
    rejected BEFORE the request mutates anything, so retrying them is
    safe for every verb. Capped exponential backoff with jitter; the
    Status' `retryAfterSeconds` (the wire form of the reference's
    `Retry-After: 1` header) is honored as a floor; `deadline_s` bounds
    the whole attempt train. Any other failure propagates immediately."""

    attempts: int = 3          # retries after the first try
    base_s: float = 0.05
    cap_s: float = 1.0
    deadline_s: float = 5.0
    # observability hook: called once per retry actually taken (APIBinder
    # counts absorbed pushback through it)
    on_retry: Optional[Any] = None

    def run(self, fn):
        deadline = time.monotonic() + self.deadline_s
        delay = self.base_s
        for attempt in range(self.attempts + 1):
            try:
                return fn()
            except errors.StatusError as e:
                if e.code not in (429, 503) or attempt >= self.attempts:
                    raise
                ra = float((e.details or {}).get("retryAfterSeconds") or 0)
                wait = max(ra, delay * random.uniform(0.5, 1.0))
                if time.monotonic() + wait > deadline:
                    raise
                if self.on_retry is not None:
                    self.on_retry()
                time.sleep(wait)
                delay = min(delay * 2, self.cap_s)
        raise AssertionError("unreachable")  # loop always returns/raises


class LocalTransport:
    """Direct calls into an in-process APIServer (no serialization cost —
    the reference's integration suite does the same with its in-proc
    master). `retry` opts into the pushback budget — the in-proc
    max-inflight filter raises the same 429s the wire path serves."""

    def __init__(self, api, retry: Optional[RetryPolicy] = None):
        self.api = api
        self.retry = retry

    def request(self, method: str, path: str, query: Dict[str, str],
                body: Optional[Obj]) -> Obj:
        from kubernetes_tpu.apiserver.server import handle_rest

        def once() -> Obj:
            code, obj = handle_rest(self.api, method, path, dict(query), body)
            return obj

        return once() if self.retry is None else self.retry.run(once)

    def stream_watch(self, path: str, query: Dict[str, str]) -> mwatch.Watch:
        from kubernetes_tpu.apiserver.server import handle_rest

        q = dict(query)
        q["watch"] = "true"
        try:
            tag, w = handle_rest(self.api, "GET", path, q, None)
        except errors.StatusError as e:
            # a REFUSED watch (410 Gone on a compacted resume RV, a restart
            # window's 503) surfaces as a terminal watch ERROR event — the
            # same shape the HTTP transport's pump delivers — so the
            # reflector's relist-vs-resume decision reads ONE code path on
            # both transports instead of a raised exception on one and a
            # Status event on the other
            w = mwatch.Watch(capacity=1)
            w.terminate(mwatch.Event(mwatch.ERROR, e.status()))
            return w
        assert tag == "WATCH"
        return w


class HTTPTransport:
    """The wire path: REST + chunked watch streams. `binary=True` opts the
    client into the negotiated binary codec (machinery/codec.py — the
    `application/vnd.kubernetes.protobuf` seat every internal reference
    client takes, protobuf.go); JSON stays the default and the fallback."""

    def __init__(self, base_url: str, timeout: float = 30.0,
                 token: str = "", binary: bool = False,
                 retry: Optional[RetryPolicy] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token
        self.binary = binary
        self.retry = retry

    def _url(self, path: str, query: Dict[str, str]) -> str:
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        return url

    def _decode_body(self, raw: bytes, content_type: str) -> Obj:
        from kubernetes_tpu.machinery import codec

        if content_type.split(";")[0] == codec.BINARY_MEDIA_TYPE:
            return codec.decode(raw)
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return {"raw": raw.decode(errors="replace")}

    def request(self, method: str, path: str, query: Dict[str, str],
                body: Optional[Obj]) -> Obj:
        if self.retry is None:
            return self._request_once(method, path, query, body)
        return self.retry.run(
            lambda: self._request_once(method, path, query, body))

    def _request_once(self, method: str, path: str, query: Dict[str, str],
                      body: Optional[Obj]) -> Obj:
        from kubernetes_tpu.machinery import codec

        # the patch dialect travels as a Content-Type on the wire (the
        # gateway maps it back; apiserver patch.go patchTypes) — pop the
        # local-transport query key and translate
        query = dict(query)
        ptype = query.pop("__patchType", None)
        req = urllib.request.Request(self._url(path, query), method=method)
        data = None
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        if self.binary:
            req.add_header("Accept", codec.BINARY_MEDIA_TYPE)
        if body is not None:
            if self.binary and method != "PATCH":
                data = codec.encode(body)
                req.add_header("Content-Type", codec.BINARY_MEDIA_TYPE)
            else:
                # PATCH always rides JSON: the dialect IS the Content-Type,
                # and a binary body would make the server read the dialect
                # as "merge" (patch bodies are partial docs/op lists — the
                # typed binary codec has no frame for them anyway)
                data = json.dumps(body).encode()
                req.add_header("Content-Type", {
                    "strategic": "application/strategic-merge-patch+json",
                    "json": "application/json-patch+json",
                    "merge": "application/merge-patch+json",
                }.get(ptype, "application/json"))
        try:
            with urllib.request.urlopen(req, data=data,
                                        timeout=self.timeout) as r:
                return self._decode_body(
                    r.read(), r.headers.get("Content-Type", ""))
        except urllib.error.HTTPError as e:
            try:
                status = self._decode_body(
                    e.read(), e.headers.get("Content-Type", ""))
            except Exception:  # noqa: BLE001
                raise errors.StatusError(e.code, "Unknown", str(e))
            raise errors.from_status(status)

    def stream_watch(self, path: str, query: Dict[str, str]) -> mwatch.Watch:
        q = dict(query)
        q["watch"] = "true"
        q.setdefault("timeoutSeconds", "3600")
        # the socket timeout derives from the timeoutSeconds ACTUALLY sent
        # (plus the request-timeout margin) — a short-timeout watch must
        # hang up when the server does, not 1 h later (the old hardcoded
        # `self.timeout + 3600` kept a 10 s watch's socket open 3610 s)
        try:
            server_timeout = float(q["timeoutSeconds"])
        except (TypeError, ValueError):
            server_timeout = 3600.0
        sock_timeout = self.timeout + server_timeout
        w = mwatch.Watch(capacity=8192)

        def pump_json(r) -> None:
            for raw_line in r:
                if w.stopped:
                    return
                line = raw_line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                w.send(mwatch.Event(ev["type"], ev["object"]))

        def pump_binary(r) -> None:
            from kubernetes_tpu.machinery import codec

            buf = b""
            while not w.stopped:
                chunk = r.read1(65536)
                if not chunk:
                    return
                buf += chunk
                events, buf = codec.decode_frames(buf)
                for ev in events:
                    w.send(mwatch.Event(ev["type"], ev["object"]))

        def pump() -> None:
            from kubernetes_tpu.machinery import codec

            try:
                req = urllib.request.Request(self._url(path, q))
                if self.token:
                    req.add_header("Authorization", f"Bearer {self.token}")
                if self.binary:
                    req.add_header("Accept", codec.BINARY_MEDIA_TYPE)
                with urllib.request.urlopen(req, timeout=sock_timeout) as r:
                    ctype = (r.headers.get("Content-Type") or "").split(";")[0]
                    if ctype == codec.BINARY_MEDIA_TYPE:
                        pump_binary(r)
                    else:
                        pump_json(r)
            except urllib.error.HTTPError as e:
                # a refused watch (410 Gone on a compacted resume RV) must
                # surface as a watch ERROR, not masquerade as a clean
                # stream end — the reflector's relist path keys on it
                try:
                    status = self._decode_body(
                        e.read(), e.headers.get("Content-Type", ""))
                except Exception:  # noqa: BLE001
                    status = {"kind": "Status", "code": e.code,
                              "reason": "Unknown"}
                w.send(mwatch.Event(mwatch.ERROR, status))
            except Exception:  # noqa: BLE001 — stream teardown
                pass
            finally:
                w.stop()

        threading.Thread(target=pump, name="http-watch", daemon=True).start()
        return w


class ResourceClient:
    """Verbs for one resource (a typed clientset entry)."""

    def __init__(self, transport, group: str, version: str, resource: str,
                 namespaced: bool):
        self.transport = transport
        self.group = group
        self.version = version
        self.resource = resource
        self.namespaced = namespaced

    def _path(self, namespace: str = "", name: str = "", sub: str = "") -> str:
        root = f"/api/{self.version}" if not self.group else \
            f"/apis/{self.group}/{self.version}"
        parts = [root]
        if self.namespaced and namespace:
            parts.append(f"namespaces/{namespace}")
        parts.append(self.resource)
        if name:
            parts.append(name)
        if sub:
            parts.append(sub)
        return "/".join(parts)

    # -- verbs -------------------------------------------------------------- #

    def create(self, obj: Obj, namespace: str = "") -> Obj:
        ns = namespace or meta.namespace(obj) or ("default" if self.namespaced else "")
        return self.transport.request("POST", self._path(ns), {}, obj)

    def get(self, name: str, namespace: str = "default") -> Obj:
        return self.transport.request("GET", self._path(namespace, name), {}, None)

    def list(self, namespace: str = "", label_selector: str = "",
             field_selector: str = "") -> Obj:
        q = {}
        if label_selector:
            q["labelSelector"] = label_selector
        if field_selector:
            q["fieldSelector"] = field_selector
        return self.transport.request("GET", self._path(namespace), q, None)

    def update(self, obj: Obj, namespace: str = "") -> Obj:
        ns = namespace or meta.namespace(obj)
        return self.transport.request("PUT", self._path(ns, meta.name(obj)),
                                      {}, obj)

    def update_status(self, obj: Obj, namespace: str = "") -> Obj:
        ns = namespace or meta.namespace(obj)
        return self.transport.request(
            "PUT", self._path(ns, meta.name(obj), "status"), {}, obj)

    def patch(self, name: str, patch: Obj, namespace: str = "default",
              patch_type: str = "merge") -> Obj:
        q = {"__patchType": patch_type} if patch_type != "merge" else {}
        return self.transport.request("PATCH", self._path(namespace, name),
                                      q, patch)

    def patch_status(self, name: str, patch: Obj,
                     namespace: str = "default",
                     patch_type: str = "merge") -> Obj:
        q = {"__patchType": patch_type} if patch_type != "merge" else {}
        return self.transport.request(
            "PATCH", self._path(namespace, name, "status"), q, patch)

    def delete(self, name: str, namespace: str = "default",
               resource_version: str = "") -> Obj:
        body = None
        if resource_version:
            body = {"preconditions": {"resourceVersion": resource_version}}
        return self.transport.request("DELETE", self._path(namespace, name),
                                      {}, body)

    def delete_collection(self, namespace: str = "",
                          label_selector: str = "") -> Obj:
        q = {"labelSelector": label_selector} if label_selector else {}
        return self.transport.request("DELETE", self._path(namespace), q, None)

    def watch(self, namespace: str = "", label_selector: str = "",
              field_selector: str = "", resource_version: str = "",
              allow_bookmarks: bool = False,
              timeout_seconds: Optional[int] = None) -> mwatch.Watch:
        q: Dict[str, str] = {}
        if label_selector:
            q["labelSelector"] = label_selector
        if field_selector:
            q["fieldSelector"] = field_selector
        if resource_version:
            q["resourceVersion"] = resource_version
        if allow_bookmarks:
            q["allowWatchBookmarks"] = "true"
        if timeout_seconds is not None:
            # rides to the server AND (HTTP transport) sizes the socket
            # timeout — the two can no longer disagree by an hour
            q["timeoutSeconds"] = str(int(timeout_seconds))
        return self.transport.stream_watch(self._path(namespace), q)

    # -- subresources ------------------------------------------------------- #

    def bind(self, name: str, node_name: str, namespace: str = "default",
             uid: str = "", annotations: Optional[Dict[str, str]] = None
             ) -> Obj:
        binding = {"apiVersion": "v1", "kind": "Binding",
                   "metadata": {"name": name, "namespace": namespace},
                   "target": {"kind": "Node", "name": node_name}}
        if uid:
            binding["metadata"]["uid"] = uid
        if annotations:
            # fencing-token stamping rides here (api.types
            # FENCING_TOKEN_ANNOTATION); the server fences on it
            binding["metadata"]["annotations"] = dict(annotations)
        return self.transport.request(
            "POST", self._path(namespace, name, "binding"), {}, binding)

    def evict(self, name: str, namespace: str = "default") -> Obj:
        return self.transport.request(
            "POST", self._path(namespace, name, "eviction"), {},
            {"apiVersion": "policy/v1beta1", "kind": "Eviction",
             "metadata": {"name": name, "namespace": namespace}})

    def get_scale(self, name: str, namespace: str = "default") -> Obj:
        return self.transport.request("GET", self._path(namespace, name, "scale"),
                                      {}, None)

    def put_scale(self, name: str, replicas: int,
                  namespace: str = "default") -> Obj:
        return self.transport.request(
            "PUT", self._path(namespace, name, "scale"), {},
            {"spec": {"replicas": replicas}})

    def finalize(self, name: str, obj: Obj) -> Obj:
        return self.transport.request("PUT", self._path("", name, "finalize"),
                                      {}, obj)


_KNOWN = {
    # attr: (group, version, resource, namespaced)
    "pods": ("", "v1", "pods", True),
    "nodes": ("", "v1", "nodes", False),
    "namespaces": ("", "v1", "namespaces", False),
    "services": ("", "v1", "services", True),
    "endpoints": ("", "v1", "endpoints", True),
    "events": ("", "v1", "events", True),
    "configmaps": ("", "v1", "configmaps", True),
    "secrets": ("", "v1", "secrets", True),
    "serviceaccounts": ("", "v1", "serviceaccounts", True),
    "persistentvolumes": ("", "v1", "persistentvolumes", False),
    "persistentvolumeclaims": ("", "v1", "persistentvolumeclaims", True),
    "replicationcontrollers": ("", "v1", "replicationcontrollers", True),
    "resourcequotas": ("", "v1", "resourcequotas", True),
    "limitranges": ("", "v1", "limitranges", True),
    "deployments": ("apps", "v1", "deployments", True),
    "replicasets": ("apps", "v1", "replicasets", True),
    "statefulsets": ("apps", "v1", "statefulsets", True),
    "daemonsets": ("apps", "v1", "daemonsets", True),
    "controllerrevisions": ("apps", "v1", "controllerrevisions", True),
    "jobs": ("batch", "v1", "jobs", True),
    "cronjobs": ("batch", "v1beta1", "cronjobs", True),
    "poddisruptionbudgets": ("policy", "v1beta1", "poddisruptionbudgets", True),
    "leases": ("coordination.k8s.io", "v1", "leases", True),
    "endpointslices": ("discovery.k8s.io", "v1beta1", "endpointslices", True),
    "horizontalpodautoscalers": ("autoscaling", "v1",
                                 "horizontalpodautoscalers", True),
    "storageclasses": ("storage.k8s.io", "v1", "storageclasses", False),
    "csinodes": ("storage.k8s.io", "v1", "csinodes", False),
    "priorityclasses": ("scheduling.k8s.io", "v1", "priorityclasses", False),
    "customresourcedefinitions": ("apiextensions.k8s.io", "v1",
                                  "customresourcedefinitions", False),
    "roles": ("rbac.authorization.k8s.io", "v1", "roles", True),
    "rolebindings": ("rbac.authorization.k8s.io", "v1", "rolebindings", True),
    "clusterroles": ("rbac.authorization.k8s.io", "v1", "clusterroles",
                     False),
    "clusterrolebindings": ("rbac.authorization.k8s.io", "v1",
                            "clusterrolebindings", False),
    "certificatesigningrequests": ("certificates.k8s.io", "v1beta1",
                                   "certificatesigningrequests", False),
}


class Client:
    """The clientset: `client.pods.create(...)`, `client.resource(...)`."""

    def __init__(self, transport):
        self.transport = transport
        self._cache: Dict[Tuple[str, str, str], ResourceClient] = {}

    @staticmethod
    def local(api, retry: Optional[RetryPolicy] = None) -> "Client":
        return Client(LocalTransport(api, retry=retry))

    @staticmethod
    def http(base_url: str, token: str = "", binary: bool = False,
             retry: Optional[RetryPolicy] = None) -> "Client":
        """`binary=True` negotiates the binary codec for every request and
        watch stream — the internal-client configuration (protobuf.go).
        `retry` opts into the 429/503 pushback budget (RetryPolicy)."""
        return Client(HTTPTransport(base_url, token=token, binary=binary,
                                    retry=retry))

    def resource(self, group: str, version: str, resource: str,
                 namespaced: bool = True) -> ResourceClient:
        key = (group, version, resource)
        if key not in self._cache:
            self._cache[key] = ResourceClient(self.transport, group, version,
                                              resource, namespaced)
        return self._cache[key]

    def __getattr__(self, attr: str) -> ResourceClient:
        spec = _KNOWN.get(attr)
        if spec is None:
            raise AttributeError(attr)
        return self.resource(spec[0], spec[1], spec[2], spec[3])

    def version(self) -> Obj:
        return self.transport.request("GET", "/version", {}, None)
