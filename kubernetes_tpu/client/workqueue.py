"""Rate-limited work queues.

Analog of client-go `util/workqueue`: the Interface (Add/Get/Done with
dirty/processing dedup), DelayingQueue (AddAfter), and RateLimitingQueue
(AddRateLimited with per-item exponential backoff capped by an overall
limiter) — the retry spine of every controller.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class WorkQueue:
    """workqueue.Type: exactly-once in-flight semantics. An item re-added
    while processing is marked dirty and requeued on Done."""

    def __init__(self):
        self._mu = threading.Condition()
        self._queue: List[Any] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._shutting_down = False

    def add(self, item: Any) -> None:
        with self._mu:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._mu.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Blocks until an item or shutdown; None on shutdown/timeout."""
        with self._mu:
            if not self._mu.wait_for(
                    lambda: self._queue or self._shutting_down,
                    timeout=timeout):
                return None
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: Any) -> None:
        with self._mu:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._mu.notify()

    def shutdown(self) -> None:
        with self._mu:
            self._shutting_down = True
            self._mu.notify_all()

    @property
    def is_shutdown(self) -> bool:
        with self._mu:
            return self._shutting_down

    def __len__(self) -> int:
        with self._mu:
            return len(self._queue)


class DelayingQueue(WorkQueue):
    """workqueue.DelayingInterface: AddAfter via a waiting heap + pump."""

    def __init__(self):
        super().__init__()
        self._heap: List[Tuple[float, int, Any]] = []
        self._seq = 0
        self._heap_mu = threading.Condition()
        self._stop = threading.Event()
        self._pump = threading.Thread(target=self._loop, daemon=True,
                                      name="delaying-queue")
        self._pump.start()

    def add_after(self, item: Any, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._heap_mu:
            self._seq += 1
            heapq.heappush(self._heap, (time.monotonic() + delay, self._seq, item))
            self._heap_mu.notify()

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._heap_mu:
                now = time.monotonic()
                while self._heap and self._heap[0][0] <= now:
                    _, _, item = heapq.heappop(self._heap)
                    self.add(item)
                wait = (self._heap[0][0] - now) if self._heap else 1.0
                self._heap_mu.wait(timeout=min(wait, 1.0))

    def shutdown(self) -> None:
        self._stop.set()
        with self._heap_mu:
            self._heap_mu.notify_all()
        super().shutdown()


class RateLimiter:
    """workqueue.DefaultControllerRateLimiter: per-item exponential backoff
    (5ms→1000s) — the token-bucket half is a no-op here since consumers are
    in-process (no API QPS to protect)."""

    def __init__(self, base: float = 0.005, max_delay: float = 1000.0):
        self.base = base
        self.max_delay = max_delay
        self._mu = threading.Lock()
        self._failures: Dict[Any, int] = {}

    def when(self, item: Any) -> float:
        with self._mu:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base * (2 ** n), self.max_delay)

    def forget(self, item: Any) -> None:
        with self._mu:
            self._failures.pop(item, None)

    def retries(self, item: Any) -> int:
        with self._mu:
            return self._failures.get(item, 0)


class RateLimitingQueue(DelayingQueue):
    """workqueue.RateLimitingInterface."""

    def __init__(self, limiter: Optional[RateLimiter] = None):
        super().__init__()
        self.limiter = limiter or RateLimiter()

    def add_rate_limited(self, item: Any) -> None:
        self.add_after(item, self.limiter.when(item))

    def forget(self, item: Any) -> None:
        self.limiter.forget(item)

    def num_requeues(self, item: Any) -> int:
        return self.limiter.retries(item)
