"""Informers: reflector-fed shared caches with event handlers.

Analog of client-go `tools/cache`: Reflector.ListAndWatch
(`tools/cache/reflector.go:187`) → delta processing → thread-safe indexer
store + handler fan-out (`shared_informer.go:293`). A 410 Gone (compacted
watch) triggers relist, exactly as the reference reflector does; handlers see
the same add/update/delete stream DeltaFIFO would deliver, including initial
list synthesis.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.component.metrics import DEFAULT_REGISTRY as _REG
from kubernetes_tpu.machinery import errors, meta
from kubernetes_tpu.machinery import watch as mwatch
from kubernetes_tpu.machinery.wait import Backoff
from kubernetes_tpu.client.rest import ResourceClient
from kubernetes_tpu.utils import faultline

Obj = Dict[str, Any]
IndexFn = Callable[[Obj], List[str]]

# ingest telemetry (ISSUE 7): watch-event volume per resource/type and the
# relist cadence — the denominators the watch→bind e2e latency histogram
# (sched/metrics.py POD_E2E_LATENCY) is read against. The scheduler's pod
# stamp itself happens at handler time (the queue-add inside the dispatch
# below), so these series bound how much ingest the stamps cover.
INFORMER_EVENTS = _REG.counter(
    "informer_watch_events_total",
    "Watch events dispatched to informer handlers",
    labels=("resource", "type"))
INFORMER_RELISTS = _REG.counter(
    "informer_relists_total",
    "Full list+replace rounds (initial sync, 410 Gone, deaf watch)",
    labels=("resource",))
# ISSUE 13 watch plane: bookmarks keep a quiet stream's resume token fresh,
# and resumes are the relists we DIDN'T pay — the ratio of these two series
# against informer_relists_total is the watch plane's health at a glance.
INFORMER_BOOKMARKS = _REG.counter(
    "informer_bookmarks_total",
    "BOOKMARK events received (resume token advanced without a relist)",
    labels=("resource",))
INFORMER_RESUMES = _REG.counter(
    "informer_watch_resumes_total",
    "Watch streams re-established from the last resourceVersion instead of "
    "relisting, by what last advanced the token (bookmark vs event)",
    labels=("resource", "via"))


class RelistBackoff:
    """Failure-counting wrapper around machinery/wait.Backoff for reflector
    relists.

    The reference reflector retries ListAndWatch through a backoff manager
    (reflector.go:187 + wait.Backoff); a fixed 0.5 s cadence means a
    compaction storm — every resume earning a fresh 410 — has N informers
    hammering the apiserver at 2 Hz each, exactly when it is busiest. Delays
    double per consecutive failed round, jittered, clamped to `cap` so a
    fleet of reflectors doesn't relist in lockstep."""

    def __init__(self, base: float = 0.5, cap: float = 30.0,
                 factor: float = 2.0, jitter: float = 0.5):
        self.base = base
        self.cap = cap
        self._b = Backoff(base=base, factor=factor, max_delay=cap,
                          jitter=jitter)
        self.attempts = 0

    def next(self) -> float:
        d = self._b.delay(self.attempts)
        self.attempts += 1
        return d

    def reset(self) -> None:
        self.attempts = 0

    def collapse(self) -> None:
        """Collapse the ladder to its FIRST rung (not a full reset): a
        successful list proves the failure the backoff was pricing is
        over, but a watch phase that keeps dying right after every good
        list must still pace on rung 1, not the raw base cadence — only
        a delivered watch signal earns `reset()`."""
        self.attempts = min(self.attempts, 1)


class Indexer:
    """cache.ThreadSafeStore + Indexers: objects by key, plus named indexes
    (e.g. pods by node name)."""

    def __init__(self, index_fns: Optional[Dict[str, IndexFn]] = None):
        self._mu = threading.RLock()
        self._items: Dict[str, Obj] = {}
        self._index_fns = dict(index_fns or {})
        self._indexes: Dict[str, Dict[str, set]] = {
            name: {} for name in self._index_fns}

    def add_index(self, name: str, fn: IndexFn) -> None:
        """cache.AddIndexers: register an index late and backfill it."""
        with self._mu:
            if name in self._index_fns:
                return
            self._index_fns[name] = fn
            idx: Dict[str, set] = {}
            for key, obj in self._items.items():
                for v in fn(obj):
                    idx.setdefault(v, set()).add(key)
            self._indexes[name] = idx

    def _update_index(self, key: str, old: Optional[Obj],
                      new: Optional[Obj]) -> None:
        for name, fn in self._index_fns.items():
            idx = self._indexes[name]
            if old is not None:
                for v in fn(old):
                    idx.get(v, set()).discard(key)
            if new is not None:
                for v in fn(new):
                    idx.setdefault(v, set()).add(key)

    def replace(self, objs: List[Obj]) -> None:
        with self._mu:
            self._items = {meta.namespaced_key(o): o for o in objs}
            self._indexes = {name: {} for name in self._index_fns}
            for k, o in self._items.items():
                self._update_index(k, None, o)

    def upsert(self, obj: Obj) -> Optional[Obj]:
        key = meta.namespaced_key(obj)
        with self._mu:
            old = self._items.get(key)
            self._items[key] = obj
            self._update_index(key, old, obj)
            return old

    def delete(self, obj: Obj) -> Optional[Obj]:
        key = meta.namespaced_key(obj)
        with self._mu:
            old = self._items.pop(key, None)
            if old is not None:
                self._update_index(key, old, None)
            return old

    def get(self, key: str) -> Optional[Obj]:
        with self._mu:
            return self._items.get(key)

    def list(self) -> List[Obj]:
        with self._mu:
            return list(self._items.values())

    def keys(self) -> List[str]:
        with self._mu:
            return list(self._items.keys())

    def by_index(self, name: str, value: str) -> List[Obj]:
        with self._mu:
            keys = self._indexes.get(name, {}).get(value, set())
            return [self._items[k] for k in keys if k in self._items]

    def __len__(self) -> int:
        with self._mu:
            return len(self._items)


class Lister:
    """Namespace-aware read interface over an Indexer (client-go listers)."""

    def __init__(self, indexer: Indexer):
        self.indexer = indexer

    def list(self, namespace: str = "",
             selector: Optional[Callable[[Obj], bool]] = None) -> List[Obj]:
        out = []
        for o in self.indexer.list():
            if namespace and meta.namespace(o) != namespace:
                continue
            if selector is not None and not selector(o):
                continue
            out.append(o)
        return out

    def get(self, namespace: str, name: str) -> Optional[Obj]:
        key = f"{namespace}/{name}" if namespace else name
        return self.indexer.get(key)


class SharedInformer:
    """One reflector + one indexer + N handlers for one resource."""

    def __init__(self, rc: ResourceClient, namespace: str = "",
                 label_selector: str = "", field_selector: str = "",
                 index_fns: Optional[Dict[str, IndexFn]] = None,
                 relist_backoff: float = 0.5):
        self.rc = rc
        self.namespace = namespace
        self.label_selector = label_selector
        self.field_selector = field_selector
        self.indexer = Indexer(index_fns)
        self.lister = Lister(self.indexer)
        self.relist_backoff = relist_backoff  # base delay (back-compat name)
        self.backoff = RelistBackoff(base=relist_backoff)
        # a round that survived this long was healthy: reset the ladder so
        # one transient blip after a quiet hour doesn't start at the cap
        self._backoff_reset_after = max(5.0, 4 * relist_backoff)
        self._handlers: List[Tuple[Callable, Callable, Callable]] = []
        self._handler_mu = threading.Lock()
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watch: Optional[mwatch.Watch] = None
        self.last_sync_rv = ""
        # watch-plane bookkeeping (ISSUE 13): how the resume token last
        # advanced, and the resume/relist split the bench budgets read
        self._rv_from_bookmark = False
        self.relists = 0            # full list+replace rounds
        self.resumes = 0            # re-watches from last_sync_rv
        self.bookmark_resumes = 0   # ... where a BOOKMARK supplied the rv
        self.bookmarks_seen = 0
        # liveness: monotonic stamp of the last signal (event, bookmark, or
        # successful list) — the staleness metric's denominator upstream
        self.last_signal = time.monotonic()

    # -- handler registration (AddEventHandler) ----------------------------- #

    def add_handlers(self, on_add: Callable[[Obj], None] = lambda o: None,
                     on_update: Callable[[Obj, Obj], None] = lambda o, n: None,
                     on_delete: Callable[[Obj], None] = lambda o: None) -> None:
        with self._handler_mu:
            self._handlers.append((on_add, on_update, on_delete))
            if self._synced.is_set():
                # late joiner gets synthetic adds for current state
                for o in self.indexer.list():
                    on_add(o)

    # -- lifecycle ---------------------------------------------------------- #

    def start(self) -> "SharedInformer":
        """Start — or RESTART — the reflector. A stopped informer keeps its
        indexer and last_sync_rv, so starting it again is a watch RESUME
        (the WatchMux revive path rides this: a mux-stream death must not
        cost a relist when the resume token is still above the floor)."""
        if self._thread is not None and self._thread.is_alive():
            if not self._stop.is_set():
                return self  # genuinely running
            # the old lifecycle is stopping but its thread outlived
            # stop()'s bounded join (wedged in a synchronous handler).
            # Returning here would leave NO reflector once it exits, and
            # replacing _stop while it still runs would resurrect it (the
            # loop re-reads self._stop) — so wait it out, bounded, and
            # fail LOUDLY rather than report a restart that never happened
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                raise RuntimeError(
                    f"informer-{self.rc.resource}: previous lifecycle's "
                    "thread is still exiting (a handler is likely wedged); "
                    "cannot restart yet")
        if self._stop.is_set():
            self._stop = threading.Event()  # fresh lifecycle, old thread dead
        self._thread = threading.Thread(target=self._run,
                                        name=f"informer-{self.rc.resource}",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        w = self._watch
        if w is not None:
            w.stop()
        if self._thread is not None:
            self._thread.join(timeout=3)

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    @property
    def has_synced(self) -> bool:
        return self._synced.is_set()

    # -- the reflector loop (reflector.go:187 ListAndWatch) ----------------- #

    def _run(self) -> None:
        # a RESTART of a previously-synced informer (WatchMux revive, a
        # stopped-then-started reflector) resumes from its last token
        # instead of relisting — the indexer and last_sync_rv survived
        resume_first = self._synced.is_set() and bool(self.last_sync_rv)
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                self._list_and_watch(skip_list=resume_first)
            except Exception:  # noqa: BLE001 — reflector retries everything
                pass
            resume_first = False
            if time.monotonic() - t0 >= self._backoff_reset_after:
                self.backoff.reset()  # the round was healthy for a while
            if self._stop.wait(self.backoff.next()):
                return

    @staticmethod
    def _error_code(obj) -> int:
        """Status code off a watch ERROR event's payload (0 if unreadable)."""
        try:
            return int(obj.get("code") or 0)
        except (AttributeError, TypeError, ValueError):
            return 0

    def _list_and_watch(self, skip_list: bool = False) -> None:
        if not skip_list:
            INFORMER_RELISTS.inc(resource=self.rc.resource)
            self.relists += 1
            lst = self.rc.list(self.namespace, self.label_selector,
                               self.field_selector)
            items = lst.get("items", [])
            rv = lst.get("metadata", {}).get("resourceVersion", "")
            old_keys = set(self.indexer.keys())
            # last-known objects become delete tombstones (DeltaFIFO
            # DeletedFinalStateUnknown carries the final object, not a key)
            old_objs = {k: self.indexer.get(k) for k in old_keys}
            self.indexer.replace(items)
            self.last_sync_rv = rv
            self._rv_from_bookmark = False
            self.last_signal = time.monotonic()
            # ANY successful list+replace collapses the relist ladder to
            # its first rung (the old after-a-healthy-round-only reset
            # left a watch that died right after the initial list
            # retrying at the decayed cap forever); the full reset
            # happens below, once the watch actually delivers a signal
            self.backoff.collapse()
            # synthesize deltas for the replace (DeltaFIFO Replace)
            new_keys = {meta.namespaced_key(o) for o in items}
            with self._handler_mu:
                handlers = list(self._handlers)
            for o in items:
                k = meta.namespaced_key(o)
                for add, upd, _ in handlers:
                    if k in old_keys:
                        # deliver the pre-gap cached object as old so
                        # diffing handlers see changes that happened during
                        # the watch gap (DeltaFIFO Replace semantics)
                        upd(old_objs.get(k) or o, o)
                    else:
                        add(o)
            for k in old_keys - new_keys:
                tomb = old_objs.get(k) or {"metadata": dict(zip(
                    ("namespace", "name"), meta.split_key(k)))}
                for _, _, dele in handlers:
                    dele(tomb)
            self._synced.set()

        # Watch, RESUMING across clean stream ends: bookmarks keep
        # last_sync_rv fresh on quiet resources, so a dropped stream
        # re-watches from there (reflector.go re-establishes the watch
        # from its lastSyncResourceVersion). Only a GENUINE 410 Gone —
        # the resume token fell beneath the compaction floor — forces the
        # full relist this method restarts with; any other terminal ERROR
        # (an apiserver restart's 503, a converter failure) re-establishes
        # by resourceVersion, which is the whole point of ISSUE 13: one
        # compaction blip must not become a fleet-wide list storm.
        # Silence bound: a healthy opted-in stream carries a bookmark at
        # least every KTPU_WATCH_BOOKMARK_INTERVAL (10s default); total
        # silence far beyond that means the watch is deaf (e.g. resumed
        # from a future RV after a storage reset, where the server happily
        # streams nothing forever) — relist rather than trust it. The
        # bound scales with the configured interval so a slow-bookmark
        # server doesn't turn every quiet watch into a relist loop.
        import os as _os

        silence_limit = max(9 * float(_os.environ.get(
            "KTPU_WATCH_BOOKMARK_INTERVAL", "10") or 10), 90.0)
        last_signal = time.monotonic()
        first_stream = not skip_list
        pending_resume: Optional[str] = None
        while not self._stop.is_set():
            if not first_stream:
                # this re-watch IS the resume path (classified by what last
                # advanced the token — a bookmark-funded resume is the
                # compaction-immunity signal the bench asserts) — but it is
                # only COUNTED once the re-established stream delivers its
                # first signal: an attempt that is refused, insta-closes,
                # or 410s straight into a relist never resumed anything,
                # and counting it would falsely certify the bookmark
                # property (each new attempt overwrites the pending slot)
                pending_resume = ("bookmark" if self._rv_from_bookmark
                                  else "event")
            first_stream = False
            error_break = False
            w = self.rc.watch(self.namespace, self.label_selector,
                              self.field_selector,
                              resource_version=self.last_sync_rv,
                              allow_bookmarks=True)
            self._watch = w
            try:
                while not self._stop.is_set():
                    ev = w.next(timeout=1.0)
                    if ev is None:
                        if w.stopped:
                            break  # stream ended → resume from last rv
                        if time.monotonic() - last_signal > silence_limit:
                            return  # deaf watch → full relist
                        continue
                    if ev.type == mwatch.ERROR:
                        # ERROR frames are NOT liveness: a server stuck
                        # erroring every resume must eventually trip the
                        # silence bound below and relist, not spin forever
                        if self._error_code(ev.object) == 410:
                            # 410 Gone: the token is beneath the compaction
                            # floor — only a full relist can close the gap
                            return
                        # any other terminal error (restart 503, a 429
                        # refused re-establishment, stream teardown): the
                        # token is still good — resume, but UNDER THE
                        # LADDER: a refused watch is server pushback, and
                        # re-watching at the bare 0.05 s resume cadence
                        # would hammer a saturated apiserver ~20×/s (the
                        # ladder fully resets on the first real signal)
                        error_break = True
                        break
                    last_signal = time.monotonic()
                    self.last_signal = last_signal
                    # the watch phase is demonstrably alive: NOW the round
                    # is healthy and the relist ladder fully resets (the
                    # counterpart of the rung-1 collapse after the list)
                    if self.backoff.attempts:
                        self.backoff.reset()
                    if pending_resume is not None:
                        # first delivered signal on a re-established watch:
                        # the resume actually happened — count it now
                        self.resumes += 1
                        if pending_resume == "bookmark":
                            self.bookmark_resumes += 1
                        INFORMER_RESUMES.inc(resource=self.rc.resource,
                                             via=pending_resume)
                        pending_resume = None
                    if ev.type == mwatch.BOOKMARK:
                        # the server's liveness+progress pulse: advance the
                        # resume token without touching the indexer
                        rv = meta.resource_version(ev.object)
                        if rv:
                            self.last_sync_rv = rv
                            self._rv_from_bookmark = True
                        self.bookmarks_seen += 1
                        INFORMER_BOOKMARKS.inc(resource=self.rc.resource)
                        continue
                    if faultline.should("watch.drop", "informer"):
                        # chaos: the stream dies mid-flight and THIS event
                        # is lost with it — the resume from last_sync_rv
                        # (which has not advanced past it) must redeliver
                        break
                    if faultline.should("watch.relist", "informer"):
                        return  # chaos: 410-equivalent → full relist
                    if faultline.should("watch.storm", "informer"):
                        # chaos: an event storm — the whole world redelivers
                        # at once (a relist IS a storm: every object arrives
                        # as one burst of upserts). The overload governor's
                        # ingest-pressure signal is what this exercises; the
                        # at-least-once contract makes the redelivery safe.
                        return
                    self._dispatch(ev)
                    rv = meta.resource_version(ev.object)
                    if rv:
                        self.last_sync_rv = rv
                        self._rv_from_bookmark = False
            finally:
                w.stop()
                self._watch = None
            if time.monotonic() - last_signal > silence_limit:
                return  # repeated silent resumes → full relist
            if error_break:
                # terminal-error resumes pace on the relist ladder (capped
                # exponential + jitter): consecutive refusals escalate,
                # the first delivered signal resets
                if self._stop.wait(self.backoff.next()):
                    return
                continue
            if self._stop.wait(0.05):
                return  # brief pause: a server that insta-closes streams
                # must not spin the resume loop hot

    def _dispatch(self, ev: mwatch.Event) -> None:
        INFORMER_EVENTS.inc(resource=self.rc.resource, type=str(ev.type))
        with self._handler_mu:
            handlers = list(self._handlers)
        if ev.type == mwatch.ADDED:
            old = self.indexer.upsert(ev.object)
            for add, upd, _ in handlers:
                if old is None:
                    add(ev.object)
                else:
                    upd(old, ev.object)
        elif ev.type == mwatch.MODIFIED:
            old = self.indexer.upsert(ev.object)
            for add, upd, _ in handlers:
                if old is None:
                    add(ev.object)
                else:
                    upd(old, ev.object)
        elif ev.type == mwatch.DELETED:
            old = self.indexer.delete(ev.object)
            for _, _, dele in handlers:
                dele(old if old is not None else ev.object)


class InformerFactory:
    """SharedInformerFactory: one informer per resource, shared by consumers."""

    def __init__(self, client):
        self.client = client
        self._informers: Dict[Tuple[str, str, str], SharedInformer] = {}
        self._mu = threading.Lock()

    def informer(self, attr: str, namespace: str = "",
                 field_selector: str = "",
                 index_fns: Optional[Dict[str, IndexFn]] = None) -> SharedInformer:
        rc: ResourceClient = getattr(self.client, attr)
        key = (rc.group, rc.resource, namespace, field_selector)
        with self._mu:
            inf = self._informers.get(key)
            if inf is None:
                inf = SharedInformer(
                    rc, namespace=namespace, field_selector=field_selector,
                    index_fns=index_fns)
                self._informers[key] = inf
            elif index_fns:
                # a later consumer's indexes must still materialize on the
                # shared informer (client-go AddIndexers)
                for name, fn in index_fns.items():
                    inf.indexer.add_index(name, fn)
            return inf

    def start(self) -> None:
        with self._mu:
            for inf in self._informers.values():
                if inf._thread is None:
                    inf.start()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        with self._mu:
            infs = list(self._informers.values())
        return all(i.wait_for_sync(timeout) for i in infs)

    def stop(self) -> None:
        with self._mu:
            for inf in self._informers.values():
                inf.stop()


def pods_by_node_index(pod: Obj) -> List[str]:
    """The pods-by-nodeName index every node-centric consumer wants."""
    node = pod.get("spec", {}).get("nodeName", "")
    return [node] if node else []
