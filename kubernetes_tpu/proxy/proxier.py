"""Proxier: Services × Endpoints → a service VIP rule table.

Analog of `pkg/proxy/iptables/proxier.go:251` reduced to its essential
computation: track Service/Endpoints changes (the serviceChanges/
endpointsChanges trackers), and on each syncProxyRules pass rebuild only
what changed into a routing table mapping (clusterIP, port) → backend
endpoints with round-robin selection and sessionAffinity ClientIP pinning.
The kernel-programming half (iptables-restore writes) is environment
plumbing, not semantics; `RuleTable.render_iptables()` emits the equivalent
restore input for inspection/tests.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.client.informers import InformerFactory
from kubernetes_tpu.machinery import meta

Obj = dict

ServicePortKey = Tuple[str, str, str]  # (namespace, service, port name)


@dataclass
class ServicePortRules:
    cluster_ip: str
    port: int
    protocol: str
    node_port: int = 0
    session_affinity: str = "None"
    affinity_timeout: int = 10800
    endpoints: List[str] = field(default_factory=list)  # "ip:port"
    _rr: int = 0
    _affinity: Dict[str, Tuple[str, float]] = field(default_factory=dict)

    def pick(self, client_ip: str = "", now: Optional[float] = None) -> Optional[str]:
        """One balancing decision (round robin; ClientIP affinity pins)."""
        if not self.endpoints:
            return None
        now = time.monotonic() if now is None else now
        if self.session_affinity == "ClientIP" and client_ip:
            pinned = self._affinity.get(client_ip)
            if pinned and pinned[0] in self.endpoints and \
                    now - pinned[1] < self.affinity_timeout:
                self._affinity[client_ip] = (pinned[0], now)
                return pinned[0]
        choice = self.endpoints[self._rr % len(self.endpoints)]
        self._rr += 1
        if self.session_affinity == "ClientIP" and client_ip:
            self._affinity[client_ip] = (choice, now)
        return choice


class RuleTable:
    """The programmed dataplane state."""

    def __init__(self):
        self._mu = threading.Lock()
        self.by_port: Dict[ServicePortKey, ServicePortRules] = {}
        self.by_vip: Dict[Tuple[str, int], ServicePortKey] = {}
        self.sync_count = 0

    def replace_service(self, key_ns: str, key_name: str,
                        rules: Dict[str, ServicePortRules]) -> None:
        with self._mu:
            # drop this service's old ports, install the new set; active
            # session-affinity pins and the round-robin cursor survive a
            # reprogram (the kernel's conntrack does in the reference)
            old_rules: Dict[str, ServicePortRules] = {}
            for (ns, name, pname) in [k for k in self.by_port
                                      if k[0] == key_ns and k[1] == key_name]:
                old = self.by_port.pop((ns, name, pname))
                old_rules[pname] = old
                self.by_vip.pop((old.cluster_ip, old.port), None)
            for pname, r in rules.items():
                prev = old_rules.get(pname)
                if prev is not None:
                    r._affinity = {ip: pin for ip, pin in
                                   prev._affinity.items()
                                   if pin[0] in r.endpoints}
                    r._rr = prev._rr
                self.by_port[(key_ns, key_name, pname)] = r
                if r.cluster_ip:
                    self.by_vip[(r.cluster_ip, r.port)] = (key_ns, key_name,
                                                           pname)
            self.sync_count += 1

    def drop_service(self, ns: str, name: str) -> None:
        self.replace_service(ns, name, {})

    def lookup(self, vip: str, port: int,
               client_ip: str = "") -> Optional[str]:
        """Route one connection: VIP:port → endpoint ip:port."""
        with self._mu:
            key = self.by_vip.get((vip, port))
            if key is None:
                return None
            return self.by_port[key].pick(client_ip)

    def render_iptables(self) -> str:
        """The iptables-restore document the reference writes
        (proxier.go syncProxyRules chain layout, abbreviated)."""
        with self._mu:
            lines = ["*nat", ":KUBE-SERVICES - [0:0]"]
            for (ns, name, pname), r in sorted(self.by_port.items()):
                svc_chain = f"KUBE-SVC-{ns}-{name}-{pname}".upper()[:28]
                lines.append(
                    f"-A KUBE-SERVICES -d {r.cluster_ip}/32 -p "
                    f"{r.protocol.lower()} --dport {r.port} -j {svc_chain}")
                n = len(r.endpoints)
                for i, ep in enumerate(r.endpoints):
                    sep_chain = f"KUBE-SEP-{ns}-{name}-{pname}-{i}".upper()[:28]
                    if i < n - 1:
                        lines.append(
                            f"-A {svc_chain} -m statistic --mode random "
                            f"--probability {1.0 / (n - i):.5f} -j {sep_chain}")
                    else:
                        lines.append(f"-A {svc_chain} -j {sep_chain}")
                    lines.append(f"-A {sep_chain} -p {r.protocol.lower()} "
                                 f"-m {r.protocol.lower()} -j DNAT "
                                 f"--to-destination {ep}")
            lines.append("COMMIT")
            return "\n".join(lines)

    def render_ipvs(self) -> str:
        """The ipvsadm-restore document of the ipvs proxier
        (pkg/proxy/ipvs/proxier.go:318 syncProxyRules): one virtual server
        per ClusterIP:port with rr scheduling, the persistence flag for
        ClientIP session affinity (ipvs VirtualServer.Flags persistence,
        matching the reference — scheduling stays rr), and one masqueraded
        real server per endpoint."""
        with self._mu:
            lines = []
            proto_flag = {"TCP": "-t", "UDP": "-u", "SCTP": "--sctp-service"}
            for (ns, name, pname), r in sorted(self.by_port.items()):
                if not r.cluster_ip:
                    continue
                proto = proto_flag.get(r.protocol.upper(), "-t")
                sched = "rr"
                persist = ""
                if r.session_affinity == "ClientIP":
                    # ipvs persistence replaces the iptables recent-match
                    persist = f" -p {r.affinity_timeout}"
                lines.append(f"-A {proto} {r.cluster_ip}:{r.port} "
                             f"-s {sched}{persist}")
                for ep in r.endpoints:
                    lines.append(f"-a {proto} {r.cluster_ip}:{r.port} "
                                 f"-r {ep} -m")
            return "\n".join(lines)


class Proxier:
    """Watch-driven sync loop over Services + Endpoints."""

    def __init__(self, client, factory: Optional[InformerFactory] = None,
                 cluster_ip_prefix: str = "10.96",
                 node_name: str = "",
                 health_server=None, healthz=None):
        self.client = client
        self.factory = factory or InformerFactory(client)
        self.table = RuleTable()
        self._ip_seq = 0
        self._ip_by_svc: Dict[str, str] = {}
        self.cluster_ip_prefix = cluster_ip_prefix
        # healthCheckNodePort serving (proxy/healthcheck.py): this node's
        # identity decides which endpoints count as LOCAL
        self.node_name = node_name
        self.health_server = health_server
        self.healthz = healthz
        # conntrack cleanup ledger (pkg/util/conntrack ClearEntriesForIP /
        # ClearEntriesForPort): UDP flows pin DNAT decisions in the kernel
        # conntrack table, so deleting a UDP service VIP or any of its
        # endpoints must flush matching entries or traffic keeps flowing
        # to dead backends. Render-not-program (PARITY #8): the commands
        # are recorded, not executed.
        self.conntrack_commands: List[str] = []
        self._udp_state: Dict[ServicePortKey, Tuple[str, int, tuple]] = {}
        # desired healthcheck registrations, owned HERE (the server only
        # mirrors it): (ns, name) → (hc port, local endpoint count)
        self._hc_state: Dict[Tuple[str, str], Tuple[int, int]] = {}
        self._pending: set = set()
        self._pending_mu = threading.Lock()
        self.svc_informer = self.factory.informer("services")
        self.ep_informer = self.factory.informer("endpoints")
        for inf in (self.svc_informer, self.ep_informer):
            inf.add_handlers(on_add=self._changed,
                             on_update=lambda o, n: self._changed(n),
                             on_delete=self._changed)

    def _changed(self, obj: Obj) -> None:
        with self._pending_mu:
            self._pending.add(meta.namespaced_key(obj))
        if self.healthz is not None:
            self.healthz.queued_update()

    def _cluster_ip(self, svc: Obj) -> str:
        """Allocate/remember a ClusterIP (the apiserver's allocator role)."""
        explicit = svc.get("spec", {}).get("clusterIP", "")
        if explicit and explicit != "None":
            return explicit
        if explicit == "None":
            return ""  # headless
        key = meta.namespaced_key(svc)
        if key not in self._ip_by_svc:
            self._ip_seq += 1
            self._ip_by_svc[key] = (f"{self.cluster_ip_prefix}."
                                    f"{(self._ip_seq >> 8) & 255}."
                                    f"{self._ip_seq & 255}")
        return self._ip_by_svc[key]

    def sync(self) -> int:
        """One syncProxyRules pass over changed services. Returns the number
        of services reprogrammed."""
        with self._pending_mu:
            pending, self._pending = self._pending, set()
        n = 0
        hc_changed = False
        for key in pending:
            ns, name = meta.split_key(key)
            svc = self.svc_informer.lister.get(ns, name)
            if svc is None:
                self.table.drop_service(ns, name)
                self._conntrack_reconcile(ns, name, {})
                # the deleted service's healthCheckNodePort listener must
                # close too, or an external LB keeps getting 200s for a
                # service that no longer exists
                hc_changed |= self._hc_state.pop((ns, name), None) \
                    is not None
                n += 1
                continue
            ep = self.ep_informer.lister.get(ns, name)
            subsets = (ep or {}).get("subsets") or []
            rules: Dict[str, ServicePortRules] = {}
            local_counts: Dict[str, int] = {}
            cluster_ip = self._cluster_ip(svc)
            for p in svc.get("spec", {}).get("ports", []) or []:
                pname = p.get("name", "")
                tp = p.get("targetPort", p.get("port", 0))
                if isinstance(tp, str) and tp.isdigit():
                    tp = int(tp)  # IntOrString: numeric strings are ports
                backends: List[str] = []
                local = 0
                for ss in subsets:
                    eps_port = next(
                        (int(sp.get("port", 0)) for sp in ss.get("ports", [])
                         if sp.get("name", "") == pname),
                        # fall back to the literal target port; a NAMED
                        # target port unresolvable via endpoints port names
                        # keeps the service port (nothing better is known)
                        tp if isinstance(tp, int) else int(p.get("port", 0)))
                    for addr in ss.get("addresses", []) or []:
                        backends.append(f"{addr['ip']}:{eps_port}")
                        if self.node_name and \
                                addr.get("nodeName") == self.node_name:
                            local += 1
                rules[pname] = ServicePortRules(
                    cluster_ip=cluster_ip,
                    port=int(p.get("port", 0)),
                    protocol=p.get("protocol", "TCP"),
                    node_port=int(p.get("nodePort", 0) or 0),
                    session_affinity=svc.get("spec", {})
                    .get("sessionAffinity", "None"),
                    endpoints=backends)
                local_counts[pname] = local
            self.table.replace_service(ns, name, rules)
            self._conntrack_reconcile(ns, name, rules)
            hc_changed |= self._healthcheck_reconcile(ns, name, svc,
                                                      local_counts)
            n += 1
        if hc_changed and self.health_server is not None:
            # one listener reconcile per PASS, not per service
            self.health_server.sync(dict(self._hc_state))
        if self.healthz is not None:
            # every completed pass counts — an idle proxier with nothing
            # to program is healthy, not "never synced" (healthcheck.go
            # calls Updated() after each syncProxyRules)
            self.healthz.updated()
            # …but updated() also CLEARS the queued-update stamp, and an
            # event that arrived after this pass popped _pending is not
            # programmed yet: re-stamp it, or a sync loop that wedges right
            # after this pass would report 200 forever for a change it
            # never programmed
            with self._pending_mu:
                still_pending = bool(self._pending)
            if still_pending:
                self.healthz.queued_update()
        return n

    def _conntrack_reconcile(self, ns: str, name: str,
                             rules: Dict[str, ServicePortRules]) -> None:
        """Record the conntrack deletions endpoint/service changes imply
        (proxier.go deleteEndpointConnections + the stale-services /
        stale-nodePorts sweeps in syncProxyRules). UDP only: TCP flows
        reset themselves; UDP conntrack entries must be flushed or
        clients keep hitting a deleted backend."""
        old = {k: v for k, v in self._udp_state.items()
               if k[0] == ns and k[1] == name}
        new: Dict[ServicePortKey, Tuple[str, int, tuple]] = {}
        for pname, r in rules.items():
            # headless services (no VIP) have no conntrack DNAT entries to
            # flush — and an empty --orig-dst would match EVERY UDP flow
            if r.protocol.upper() == "UDP" and r.cluster_ip:
                new[(ns, name, pname)] = (r.cluster_ip, r.port,
                                          tuple(sorted(r.endpoints)))
        for k, (vip, port, endpoints) in old.items():
            if k not in new:
                # service port gone: flush everything to its VIP
                self.conntrack_commands.append(
                    f"conntrack -D --orig-dst {vip} -p udp --dport {port}")
                self._udp_state.pop(k, None)
                continue
            gone = set(endpoints) - set(new[k][2])
            for ep in sorted(gone):
                ip = ep.rsplit(":", 1)[0]
                self.conntrack_commands.append(
                    f"conntrack -D --orig-dst {vip} --dst-nat {ip} -p udp")
        self._udp_state.update(new)

    def _healthcheck_reconcile(self, ns: str, name: str, svc: Obj,
                               local_counts: Dict[str, int]) -> bool:
        """externalTrafficPolicy: Local services with a healthCheckNodePort
        get a per-service health listener reporting this node's LOCAL
        endpoint count (healthcheck.go SyncServices/SyncEndpoints). The
        desired set lives in self._hc_state; the caller pushes it to the
        server ONCE per sync pass. Returns whether this entry changed."""
        if self.health_server is None:
            return False
        spec = svc.get("spec", {}) or {}
        hc_port = int(spec.get("healthCheckNodePort", 0) or 0)
        old = self._hc_state.get((ns, name))
        if hc_port and spec.get("externalTrafficPolicy") == "Local":
            new = (hc_port, sum(local_counts.values()))
            self._hc_state[(ns, name)] = new
            return old != new
        self._hc_state.pop((ns, name), None)
        return old is not None

    def sync_all(self) -> int:
        for svc in self.svc_informer.lister.list():
            self._changed(svc)
        return self.sync()
