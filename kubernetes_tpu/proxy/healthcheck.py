"""Proxy health checking — the `pkg/proxy/healthcheck/healthcheck.go` seat.

Two servers, as in the reference:

  * `ProxierHealthServer` — the proxier's own /healthz: 200 while the
    last successful syncProxyRules pass is younger than the timeout,
    503 once the proxier is stale (healthcheck.go healthzServer).
  * `ServiceHealthServer` — per-service healthCheckNodePort listeners for
    `externalTrafficPolicy: Local` services: 200 + the local endpoint
    count when this node has local endpoints for the service, 503 when
    it has none — that is how external load balancers learn which nodes
    can serve a Local service (healthcheck.go hcInstance).

Responses carry the reference's JSON shape
(`{"service": {"namespace": ..., "name": ...}, "localEndpoints": N}`).
"""

from __future__ import annotations

import json
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional, Tuple


class _ThreadingHTTPServer(socketserver.ThreadingMixIn, socketserver.TCPServer):
    daemon_threads = True
    allow_reuse_address = True


class ProxierHealthServer:
    """healthz for the proxier itself: stale sync → 503."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 healthy_timeout: float = 60.0, clock=time.monotonic):
        self.clock = clock
        self.healthy_timeout = healthy_timeout
        self._last_updated = 0.0
        self._queued_update = 0.0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                healthy, last = outer.is_healthy()
                body = json.dumps({
                    "lastUpdated": last,
                    "currentTime": outer.clock()}).encode()
                self.send_response(200 if healthy else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = _ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="proxier-healthz")

    def start(self) -> "ProxierHealthServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def queued_update(self) -> None:
        """A sync is PENDING: the proxier saw changes it has not yet
        programmed (healthcheck.go QueuedUpdate). Only the OLDEST pending
        time is kept — re-stamping on every event would let steady churn
        mask a wedged sync loop forever."""
        if self._queued_update == 0.0:
            self._queued_update = self.clock()

    def updated(self) -> None:
        """syncProxyRules completed (healthcheck.go Updated)."""
        self._last_updated = self.clock()
        self._queued_update = 0.0

    def is_healthy(self) -> Tuple[bool, float]:
        """Healthy while no pending update is older than the timeout —
        a proxier that keeps syncing promptly stays 200 even under
        constant churn."""
        now = self.clock()
        pending_stale = (self._queued_update > 0.0
                         and now - self._queued_update
                         > self.healthy_timeout)
        never_synced = self._last_updated == 0.0
        return (not pending_stale and not never_synced), self._last_updated


class ServiceHealthServer:
    """Per-service healthCheckNodePort listeners.

    `sync(services)` takes {(ns, name): (port, local_endpoint_count)} and
    reconciles listeners: new ports open, dropped ports close, counts
    update in place (healthcheck.go SyncServices + SyncEndpoints)."""

    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self._mu = threading.Lock()
        # (ns, name) → (port, server, thread)
        self._listeners: Dict[Tuple[str, str], tuple] = {}
        self._counts: Dict[Tuple[str, str], int] = {}

    def sync(self, services: Dict[Tuple[str, str], Tuple[int, int]]) -> None:
        with self._mu:
            for key in [k for k in self._listeners if k not in services]:
                _, httpd, _ = self._listeners.pop(key)
                self._counts.pop(key, None)
                httpd.shutdown()
                httpd.server_close()
            for key, (port, count) in services.items():
                self._counts[key] = count
                cur = self._listeners.get(key)
                if cur is not None and cur[0] == port:
                    continue
                if cur is not None:  # port moved: reopen
                    cur[1].shutdown()
                    cur[1].server_close()
                    self._listeners.pop(key, None)
                try:
                    self._listeners[key] = self._open(key, port)
                except OSError:
                    # the reference logs a per-service listen failure
                    # (port in use) and keeps serving the others; a
                    # failed bind must never abort the caller's sync pass
                    pass

    def _open(self, key: Tuple[str, str], port: int) -> tuple:
        outer = self
        ns, name = key

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                with outer._mu:
                    count = outer._counts.get(key, 0)
                body = json.dumps({
                    "service": {"namespace": ns, "name": name},
                    "localEndpoints": count}).encode()
                self.send_response(200 if count > 0 else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("X-Content-Type-Options", "nosniff")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = _ThreadingHTTPServer((self.host, port), Handler)
        t = threading.Thread(target=httpd.serve_forever, daemon=True,
                             name=f"svc-healthcheck-{ns}-{name}")
        t.start()
        return (httpd.server_address[1], httpd, t)

    def port_of(self, ns: str, name: str) -> Optional[int]:
        with self._mu:
            cur = self._listeners.get((ns, name))
            return cur[0] if cur else None

    def stop(self) -> None:
        with self._mu:
            for _, httpd, _ in self._listeners.values():
                httpd.shutdown()
                httpd.server_close()
            self._listeners.clear()
            self._counts.clear()
