"""Service dataplane: the kube-proxy analog.

TPU-native analog of SURVEY.md layer 9 (`pkg/proxy`, `cmd/kube-proxy`).
"""

from kubernetes_tpu.proxy.proxier import Proxier, RuleTable

__all__ = ["Proxier", "RuleTable"]
