"""Static (class × node) lattice: everything that does not change as pods land.

The reference evaluates ALL predicates per (pod, node) inside the scheduling
loop (generic_scheduler.go:473-537). On TPU we split Filter/Score into:

  * static parts — nodeSelector, node affinity (required + preferred), taints/
    tolerations, spec.unschedulable — which depend only on (pod-class, node) and
    are evaluated ONCE per cycle here, as [SC, N] tensors;
  * dynamic parts — resources, host ports, inter-pod affinity counts, topology
    spread counts — which depend on what landed earlier in the cycle and are
    re-evaluated as O(N) rows inside the assignment scan (ops/assign.py), the
    faithful analog of the reference's sequential assume semantics
    (scheduler.go:676 → cache.go:283).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..state.arrays import Array, ClusterTables, PodArrays
from .interpod import class_term_membership, per_node_counts, term_class_matrix
from .labels import node_term_matrix
from .scores import image_locality_static, symmetric_weight_cols, weighted_per_node
from .taints import taint_matrices, taint_toleration_score
from .topospread import eligible_domains


class EngineConfig(NamedTuple):
    """How KubeSchedulerConfiguration's plugin composition reaches the fused
    one-dispatch engines: per-component filter enables and score weights as
    TRACED f32 scalars — config changes never recompile, a disabled plugin is
    flag/weight 0. Components correspond 1:1 to the in-tree plugin names
    (framework/plugins.py); plugins outside this fixed set (NodeLabel,
    RequestedToCapacityRatio, …) run through the Framework plugin path.

    The reference analog is the plugin set built by CreateFromConfig/
    CreateFromKeys (factory.go:309,387) driving which predicates/priorities
    run inside the scheduling loop."""

    f_unsched: Array        # NodeUnschedulable
    f_name: Array           # NodeName (spec.nodeName)
    f_ports: Array          # NodePorts
    f_node_affinity: Array  # NodeAffinity (nodeSelector + required affinity)
    f_fit: Array            # NodeResourcesFit
    f_taints: Array         # TaintToleration
    f_interpod: Array       # InterPodAffinity (required + symmetry)
    f_spread: Array         # PodTopologySpread (DoNotSchedule)
    f_volrestrict: Array    # VolumeRestrictions (NoDiskConflict)
    f_vollimits: Array      # NodeVolumeLimits (max attach counts)
    w_node_affinity: Array  # NodeAffinityScore (preferred terms)
    w_taint: Array          # TaintToleration score
    w_img: Array            # ImageLocality
    w_least: Array          # NodeResourcesLeastAllocated
    w_balanced: Array       # NodeResourcesBalancedAllocation
    w_most: Array           # NodeResourcesMostAllocated (0 in defaults)
    w_interpod: Array       # InterPodAffinity soft score (both directions)
    w_even: Array           # PodTopologySpread ScheduleAnyway score
    w_ssel: Array           # SelectorSpread
    # wave-admission score window (ops/waves.py): a class admits this wave
    # only on nodes scoring within `w_window` of its per-class feasible
    # max. MaxNodeScore=100 (interface.go:87) — one plugin's full swing —
    # keeps near-tied spreading parallel while a decisively-scored
    # preference (NodePreferAvoidPods' 0-vs-100 at configured weight,
    # strong preferred affinity) is honored instead of steamrolled by
    # same-wave intra-class spreading. The best node always qualifies, so
    # feasibility is untouched; tied clusters are unaffected.
    w_window: Array = 100.0


def _strong_f32(x):
    # python scalars become NUMPY f32 scalars: concrete (safe to build and
    # cache even while a jit trace is active — jnp.asarray there would
    # stage a traced constant and leak the tracer via the cache) and
    # strong-typed for jit. Already-normalized np.float32 leaves pass
    # through untouched so re-normalizing a config on the per-dispatch hot
    # path is free; other array leaves go through jnp.asarray.
    if isinstance(x, np.float32):
        return x
    if isinstance(x, (bool, int, float)):
        return np.float32(x)
    return jnp.asarray(x, jnp.float32)


def strong_engine_config(cfg: "EngineConfig") -> "EngineConfig":
    """Normalize an EngineConfig's leaves to STRONG-typed f32 scalars.
    Python floats trace as weak-typed f32, which keys a different jit cache
    entry (and a different persistent-cache HLO hash) than the prewarmer's
    strongly-typed abstract scalars — the prewarmed executable would never
    be reused. Every dispatch boundary routes its config through this."""
    return EngineConfig(*(_strong_f32(x) for x in cfg))


_DEFAULT_ECFG: "EngineConfig | None" = None


def default_engine_config() -> EngineConfig:
    """The default provider's composition: every filter on, the default score
    set at weight 1, MostAllocated off (algorithmprovider/defaults).
    Strong-typed and cached: see strong_engine_config."""
    global _DEFAULT_ECFG
    if _DEFAULT_ECFG is None:
        one, zero = 1.0, 0.0
        _DEFAULT_ECFG = strong_engine_config(EngineConfig(
            f_unsched=one, f_name=one, f_ports=one, f_node_affinity=one,
            f_fit=one, f_taints=one, f_interpod=one, f_spread=one,
            f_volrestrict=one, f_vollimits=one,
            w_node_affinity=one, w_taint=one, w_img=one, w_least=one,
            w_balanced=one, w_most=zero, w_interpod=one, w_even=one,
            w_ssel=one,
        ))
    return _DEFAULT_ECFG


def _on(flag: Array) -> Array:
    """A filter component is enforced when its flag ≥ 0.5 (f32 scalar)."""
    return jnp.asarray(flag, jnp.float32) >= 0.5


class StaticLattice(NamedTuple):
    mask: Array        # [SC, N] — static Filter conjunction
    node_match: Array  # [SC, N] — nodeSelector ∧ node-affinity only (spread eligibility)
    score: Array       # [SC, N] f32 — static Score sum (pref + taint + image)
    pref_score: Array  # [SC, N] f32 — preferred node affinity, 0..100-normalized
    taint_score: Array # [SC, N] f32 — taint PreferNoSchedule score, 0..100
    img_score: Array   # [SC, N] f32 — ImageLocality, 0..100


class CycleArrays(NamedTuple):
    """Per-cycle precomputed tensors fed to the assignment scan."""

    static: StaticLattice
    TM: Array        # [S, SC] term × class match
    has_anti: Array  # [SC, S] class anti-term membership
    CNT: Array       # [S, N] per-node term match counts (live carry seed)
    HOLD: Array      # [S, N] per-node anti-term holder counts (live carry seed)
    ELD: Array       # [SC, TS, D+1] eligible domains per class × constraint
    WCOLS: Array     # [S, SC] f32 signed symmetric-preference weights per class
    WSYM: Array      # [S, N] f32 symmetric weight seed from existing pods
    ecfg: EngineConfig  # traced plugin composition (filters + score weights)


def _safe_row_gather(M: Array, ids: Array, default: bool) -> Array:
    """M: [SN, N]; ids: [...] with -1 ⇒ `default` row."""
    rows = M[jnp.maximum(ids, 0)]
    return jnp.where((ids >= 0)[..., None], rows, default)


def build_static(
    tables: ClusterTables, unschedulable_key: int, empty_val: int,
    ecfg: EngineConfig | None = None,
) -> StaticLattice:
    if ecfg is None:
        ecfg = default_engine_config()
    nodes, classes = tables.nodes, tables.classes

    MT = node_term_matrix(tables.nterms, nodes)  # [SN, N]

    # spec.nodeSelector (PodMatchNodeSelector half, predicates.go:879-886)
    nsel_ok = _safe_row_gather(MT, classes.nsel_term, True)  # [SC, N]

    # node affinity required: OR of terms (predicates.go:894-906); present but
    # term-less affinity matches nothing
    term_rows = _safe_row_gather(MT, classes.nterm_ids, False)  # [SC, T, N]
    aff_any = term_rows.any(axis=1)
    aff_ok = (~classes.aff_active)[:, None] | aff_any

    node_match = nsel_ok & aff_ok & nodes.valid[None, :]
    # spread eligibility always uses the raw node_match; the FILTER honors
    # the NodeAffinity plugin flag
    node_match_f = (node_match | ~_on(ecfg.f_node_affinity)) & nodes.valid[None, :]

    # taints (PodToleratesNodeTaints) + spec.unschedulable (CheckNodeUnschedulable)
    tol_ok, prefer_cnt, unsched_ok = taint_matrices(
        tables.tolsets, nodes, unschedulable_key, empty_val
    )
    ts = classes.tolset  # [SC]
    taint_ok = tol_ok[ts]  # [SC, N]
    unsched_pass = (~nodes.unschedulable)[None, :] | unsched_ok[ts][:, None]

    taint_ok_f = taint_ok | ~_on(ecfg.f_taints)
    unsched_f = unsched_pass | ~_on(ecfg.f_unsched)
    mask = node_match_f & taint_ok_f & unsched_f & classes.valid[:, None]

    # --- static scores ---
    # preferred node affinity (node_affinity.go:34-80): Σ weight·match, then
    # NormalizeReduce(100, false) per pod-class across nodes
    pref_rows = _safe_row_gather(MT, classes.pterm_ids, False)  # [SC, PT, N]
    w = jnp.where(classes.pterm_ids >= 0, classes.pterm_w, 0).astype(jnp.float32)
    pref_raw = (w[:, :, None] * pref_rows).sum(axis=1)  # [SC, N]
    mx = pref_raw.max(axis=1, keepdims=True)
    pref_score = jnp.where(mx > 0, pref_raw * 100.0 / jnp.maximum(mx, 1e-9), 0.0)

    taint_score = taint_toleration_score(prefer_cnt[ts])  # [SC, N]
    img_score = image_locality_static(tables)              # [SC, N]

    w = ecfg
    score = (pref_score * w.w_node_affinity + taint_score * w.w_taint
             + img_score * w.w_img)
    return StaticLattice(mask=mask, node_match=node_match,
                         score=score,
                         pref_score=pref_score, taint_score=taint_score,
                         img_score=img_score)


def build_cycle(
    tables: ClusterTables,
    existing: PodArrays,
    unschedulable_key: int,
    empty_val: int,
    D: int,
    hard_weight=1,
    ecfg: EngineConfig | None = None,
) -> CycleArrays:
    """Everything the scan needs, computed in one fused pass on device.
    The analog of RunPreFilterPlugins + GetPredicateMetadata
    (generic_scheduler.go:206, metadata.go:334) — but once per *cycle*, shared
    by every pod, instead of once per pod. `D` (domain-axis capacity) must be
    static under jit — pass via static_argnums/partial."""
    if ecfg is None:
        ecfg = default_engine_config()
    ecfg = EngineConfig(*[jnp.asarray(x, jnp.float32) for x in ecfg])
    static = build_static(tables, unschedulable_key, empty_val, ecfg)
    TM = term_class_matrix(tables.terms, tables.labelsets, tables.classes)
    S = TM.shape[0]
    N = tables.nodes.valid.shape[0]
    has_anti = class_term_membership(tables.classes.anti_terms, S)
    CNT = per_node_counts(TM, existing, N)
    HOLD = per_node_counts(has_anti.T, existing, N)
    ELD = eligible_domains(static.node_match, tables.classes, tables.nodes, D)
    WCOLS = symmetric_weight_cols(tables.classes, S, hard_weight)
    WSYM = weighted_per_node(WCOLS, existing, N)
    return CycleArrays(static=static, TM=TM, has_anti=has_anti, CNT=CNT,
                       HOLD=HOLD, ELD=ELD, WCOLS=WCOLS, WSYM=WSYM, ecfg=ecfg)
