"""Taints/tolerations as tensor ops.

Reference semantics: PodToleratesNodeTaints (predicates.go:1543-1549) filters on
NoSchedule + NoExecute taints; PreferNoSchedule feeds the taint_toleration.go
score (count of intolerable PreferNoSchedule taints, max-normalized + reversed).
Toleration matching is v1helper ToleratesTaint: effect matches (empty = all),
key matches (empty key + Exists = all), then Exists | value equality.

Also covers CheckNodeUnschedulablePredicate (predicates.go:1522-1541): node
.spec.unschedulable acts as a synthetic NoSchedule taint with a well-known key.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..api.types import TaintEffect, TolerationOp
from ..state.arrays import Array, NodeArrays, TolSetTable


def _tolerates(
    tol_valid: Array,   # [..., TL]
    tol_keys: Array,    # [..., TL]
    tol_ops: Array,     # [..., TL]
    tol_vals: Array,    # [..., TL]
    tol_effects: Array, # [..., TL]
    taint_key: Array,   # [...]
    taint_val: Array,   # [...]
    taint_effect: Array # [...]
) -> Array:
    """[...] bool: any toleration in the set tolerates the given taint."""
    tk, tv, te = taint_key[..., None], taint_val[..., None], taint_effect[..., None]
    eff_ok = (tol_effects < 0) | (tol_effects == te)
    key_ok = (tol_keys < 0) | (tol_keys == tk)
    val_ok = (tol_ops == TolerationOp.EXISTS) | (tol_vals == tv)
    return (tol_valid & eff_ok & key_ok & val_ok).any(-1)


def taint_matrices(
    tolsets: TolSetTable, nodes: NodeArrays, unschedulable_key: int, empty_val: int
) -> tuple[Array, Array, Array]:
    """Returns:
      ok        [STL, N] bool — all NoSchedule/NoExecute taints tolerated
      prefer    [STL, N] i32  — count of intolerable PreferNoSchedule taints
      unsched_ok[STL]    bool — tolerates the synthetic unschedulable taint
    """
    # [STL, 1, 1, TL] vs taints [1, N, TT]
    tol = lambda a: a[:, None, None, :]
    per_taint = _tolerates(
        tol(tolsets.valid), tol(tolsets.keys), tol(tolsets.ops),
        tol(tolsets.vals), tol(tolsets.effects),
        nodes.taint_keys[None, :, :],
        nodes.taint_vals[None, :, :],
        nodes.taint_effects[None, :, :],
    )  # [STL, N, TT]
    present = nodes.taint_keys[None, :, :] >= 0
    filtering = present & (
        (nodes.taint_effects[None, :, :] == TaintEffect.NO_SCHEDULE)
        | (nodes.taint_effects[None, :, :] == TaintEffect.NO_EXECUTE)
    )
    ok = (~filtering | per_taint).all(-1)
    prefer = (
        present
        & (nodes.taint_effects[None, :, :] == TaintEffect.PREFER_NO_SCHEDULE)
        & ~per_taint
    ).sum(-1)

    unsched_ok = _tolerates(
        tolsets.valid, tolsets.keys, tolsets.ops, tolsets.vals, tolsets.effects,
        jnp.full((tolsets.valid.shape[0],), unschedulable_key, jnp.int32),
        jnp.full((tolsets.valid.shape[0],), empty_val, jnp.int32),
        jnp.full((tolsets.valid.shape[0],), int(TaintEffect.NO_SCHEDULE), jnp.int32),
    )  # [STL]
    return ok, prefer, unsched_ok


def taint_toleration_score(prefer_counts: Array) -> Array:
    """[..., N] i32 counts → 0..100 score per row, reversed max-normalization
    (taint_toleration.go ComputeTaintTolerationPriorityReduce via
    NormalizeReduce(MaxNodeScore, reverse=true))."""
    c = prefer_counts.astype(jnp.float32)
    mx = jnp.max(c, axis=-1, keepdims=True)
    return jnp.where(mx > 0, 100.0 * (1.0 - c / jnp.maximum(mx, 1.0)), 100.0)
