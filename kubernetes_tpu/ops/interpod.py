"""Inter-pod affinity/anti-affinity as tensor ops over interned terms.

The reference's approach (predicates.go:1212-1520 + metadata.go:60-112) builds,
per incoming pod, maps (topoKey, topoValue) → matching existing pods by scanning
all pods × all terms with 16-way goroutine fan-out. The TPU re-design exploits
two quotients:

  1. terms are interned (TermTable) — each distinct (selector, namespaces,
     topologyKey) is evaluated once per cycle, not once per pod;
  2. matching is factored through label-set classes: TM[S, SC] says "term s
     matches pod-class c".

Live state is carried as per-NODE counts (CNT_node[S, N]: matching pods of term
s on node n; HOLD_node[S, N]: holders of anti-term s on node n) and aggregated
over topology domains on demand by scatter-add — because different consumers
aggregate differently: inter-pod affinity counts pods on ALL nodes carrying the
key (metadata.go:407-437 has no node filter), while topology spread counts only
pods on nodes *eligible* for the incoming pod (metadata.go:145-151). Keeping the
node axis as the source of truth makes both exact.

The predicate semantics (satisfiesPodsAffinityAntiAffinity :1421-1520):
  * affinity:  ∀ term: node-has-key ∧ domain-count > 0, with the first-pod
    escape (:1436-1440): total potential matches == 0 ∧ pod matches its own
    terms ⇒ pass on every node;
  * anti-affinity: ∄ term with count > 0 in-domain;
  * existing-pod symmetry (:1319-1360): node blocked iff some anti-term matches
    the incoming pod and has a holder in the node's domain.

CNT_node/HOLD_node live in the assignment scan's carry so pods placed earlier in
the cycle are visible to later pods — the device analog of the assume cache
(scheduler.go:676, cache.go:283).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..state.arrays import (
    Array,
    LabelSetTable,
    NodeArrays,
    PodArrays,
    PodClassTable,
    TermTable,
)
from .labels import ns_bit, term_labelset_matrix


def term_class_matrix(
    terms: TermTable, labelsets: LabelSetTable, classes: PodClassTable
) -> Array:
    """TM [S, SC] bool: term s (selector ∧ namespaces) matches pod-class c."""
    M = term_labelset_matrix(terms, labelsets)  # [S, SL]
    sel = jnp.take_along_axis(
        M, jnp.maximum(classes.labelset, 0)[None, :], axis=1
    )  # [S, SC]
    nsok = ns_bit(terms.ns_words[:, None, :], classes.ns[None, :])  # [S, SC]
    return sel & nsok & classes.valid[None, :] & terms.valid[:, None]


def class_term_membership(term_ids: Array, S: int) -> Array:
    """[SC, A] term-id slots → [SC, S] multi-hot membership (-1 pads dropped)."""
    ids = term_ids
    hot = (ids[..., None] == jnp.arange(S)[None, None, :]) & (ids[..., None] >= 0)
    return hot.any(axis=1)  # [SC, S]


def per_node_counts(TM_or_membership: Array, pods: PodArrays, N: int) -> Array:
    """[S, E]-style values scattered by each existing pod's node → [S, N] i32.
    TM_or_membership: [S, SC] (term matches class). Counts matching existing
    pods per node — the node-axis source of truth for all domain aggregations."""
    vals = TM_or_membership  # [S, SC]
    node_e = pods.node_id  # [E]
    on_node = (node_e >= 0) & pods.valid
    per_e = jnp.take_along_axis(
        vals, jnp.maximum(pods.cls, 0)[None, :], axis=1
    ) & on_node[None, :]  # [S, E]
    idx = jnp.where(on_node, node_e, N)[None, :].repeat(vals.shape[0], axis=0)
    out = jnp.zeros((vals.shape[0], N + 1), jnp.int32)
    out = out.at[jnp.arange(vals.shape[0])[:, None], idx].add(per_e.astype(jnp.int32))
    return out[:, :N]


def domain_of_term(nodes: NodeArrays, topo_key: Array) -> tuple[Array, Array]:
    """topo_key: [S] → (dom [S, N] compact domain index with -1 absent,
    has_key [S, N])."""
    k = jnp.maximum(topo_key, 0)
    dom = nodes.domain[:, k].T  # [S, N]
    dom = jnp.where((topo_key[:, None] >= 0) & nodes.valid[None, :], dom, -1)
    return dom, dom >= 0


def domain_agg(
    cnt_rows: Array,   # [A, N] per-node counts for A terms
    dom: Array,        # [A, N] compact domain index (-1 absent)
    D: int,
    eligible: Array | None = None,  # [N] or [A, N] node mask, optional
) -> Array:
    """Aggregate per-node counts over topology domains → [A, D+1] (slot D is
    the discard bucket). Optionally restrict to eligible nodes (spread)."""
    vals = cnt_rows
    if eligible is not None:
        vals = jnp.where(eligible, vals, 0)
    idx = jnp.where(dom >= 0, dom, D)
    A = vals.shape[0]
    seg = jnp.zeros((A, D + 1), vals.dtype)
    return seg.at[jnp.arange(A)[:, None], idx].add(vals)


def affinity_rows(
    cls: Array,              # scalar class id
    classes: PodClassTable,
    terms: TermTable,
    TM: Array,               # [S, SC]
    CNT_node: Array,         # [S, N]
    HOLD_node: Array,        # [S, N]
    nodes: NodeArrays,
    D: int,
) -> tuple[Array, Array]:
    """(affinity_ok [N], anti_ok [N]) for one pod against live counts."""

    # --- required affinity (satisfiesPodsAffinityAntiAffinity :1431-1444) ---
    ats = classes.aff_terms[cls]  # [AT]
    s = jnp.maximum(ats, 0)
    dom, has_key = domain_of_term(nodes, terms.topo_key[s])  # [AT, N]
    seg = domain_agg(CNT_node[s], dom, D)                    # [AT, D+1]
    cnt = jnp.take_along_axis(seg, jnp.where(dom >= 0, dom, D), axis=1)  # [AT, N]
    term_ok = has_key & (cnt > 0)
    active = ats >= 0
    all_terms = (~active[:, None] | term_ok).all(0)  # [N]
    total = jnp.sum(jnp.where(active[:, None] & has_key, CNT_node[s], 0))
    self_all = (~active | TM[s, cls]).all()
    escape = (total == 0) & self_all
    has_any = active.any()
    aff_ok = ~has_any | all_terms | escape

    # --- incoming pod's anti-affinity (nodeMatchesAnyTopologyTerm :1447-1456) ---
    ans = classes.anti_terms[cls]  # [AN]
    sa = jnp.maximum(ans, 0)
    dom_a, has_key_a = domain_of_term(nodes, terms.topo_key[sa])
    seg_a = domain_agg(CNT_node[sa], dom_a, D)
    cnt_a = jnp.take_along_axis(seg_a, jnp.where(dom_a >= 0, dom_a, D), axis=1)
    blocked_own = ((ans >= 0)[:, None] & has_key_a & (cnt_a > 0)).any(0)  # [N]

    # --- existing pods' anti-affinity symmetry (:1319-1360) ---
    S = TM.shape[0]
    dom_s, _ = domain_of_term(nodes, terms.topo_key)  # [S, N]
    seg_h = domain_agg(HOLD_node, dom_s, D)           # [S, D+1]
    hold = jnp.take_along_axis(seg_h, jnp.where(dom_s >= 0, dom_s, D), axis=1)
    blocked_sym = (TM[:, cls][:, None] & (dom_s >= 0) & (hold > 0)).any(0)  # [N]

    return aff_ok, ~(blocked_own | blocked_sym)


def soft_affinity_row(
    cls: Array,
    classes: PodClassTable,
    terms: TermTable,
    CNT_node: Array,
    nodes: NodeArrays,
    D: int,
    TM: Array | None = None,
    WSYM: Array | None = None,
) -> Array:
    """Preferred inter-pod (anti)affinity score [N] f32, 0..100 after min/max
    normalization (interpod_affinity.go:119-215). Both directions: the incoming
    pod's preferred terms against existing pods, AND — when TM/WSYM are given —
    the symmetric pass (existing pods' preferred terms and hard-affinity
    symmetric weight matching the incoming pod, :156-185), summed into the raw
    counts before normalization exactly as the reference's single `counts`
    array is."""

    def contrib(term_slots: Array, weights: Array, sign: float) -> Array:
        s = jnp.maximum(term_slots, 0)
        dom, has_key = domain_of_term(nodes, terms.topo_key[s])
        seg = domain_agg(CNT_node[s], dom, D)
        cnt = jnp.take_along_axis(seg, jnp.where(dom >= 0, dom, D), axis=1)
        w = jnp.where(term_slots >= 0, weights, 0).astype(jnp.float32)
        return sign * (w[:, None] * jnp.where(has_key, cnt, 0)).sum(0)

    raw = contrib(classes.paff_terms[cls], classes.paff_w[cls], 1.0) + contrib(
        classes.panti_terms[cls], classes.panti_w[cls], -1.0
    )
    if TM is not None and WSYM is not None:
        from .scores import sym_affinity_contrib

        raw = raw + sym_affinity_contrib(cls, TM, WSYM, terms, nodes, D)
    lo = jnp.min(jnp.where(nodes.valid, raw, jnp.inf))
    hi = jnp.max(jnp.where(nodes.valid, raw, -jnp.inf))
    return jnp.where(hi > lo, 100.0 * (raw - lo) / jnp.maximum(hi - lo, 1e-9), 0.0)
