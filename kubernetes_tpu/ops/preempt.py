"""Preemption as a batched device what-if.

Reference semantics (core/generic_scheduler.go):
  * Preempt (:325) → selectNodesForPreemption (:1032, 16-way parallel) →
    selectVictimsOnNode (:1125): remove ALL lower-priority pods from the node,
    check the preemptor fits; then *reprieve* victims one at a time in
    priority-descending order, keeping each whose restoration still leaves the
    preemptor feasible; the rest are the node's victims.
  * pickOneNodeForPreemption (:903): choose the candidate node by (1) fewest
    PDB violations, (2) minimum highest victim priority, (3) smallest priority
    sum, (4) fewest victims, (5) latest earliest start time.

TPU re-design — everything is one jitted dispatch:
  * "remove all potential victims" is a scatter-subtract of victim request rows
    and term-count contributions over the node axis (no per-node loop);
  * port what-ifs avoid bitset un-OR-ing (not invertible) by precomputing the
    pairwise pod-vs-existing-pod conflict vector [E] and scatter-maxing it;
  * the reprieve loop is a single lax.scan over existing pods in global
    priority-descending order — each victim only touches its own node's carry
    row, so per-node sequential semantics are preserved exactly;
  * node choice is a masked lexicographic argmin on device.

PDB awareness (criterion 1): `pdb_blocked[e]` — computed host-side from the
PodDisruptionBudget state (filterPodsWithPDBViolation, :1071-1100: pod matches
a PDB in its namespace with PodDisruptionsAllowed ≤ 0) — orders the reprieve
pass so PDB-violating victims are restored FIRST (:1149-1156), counts the
surviving violations per node, and makes that count the PRIMARY node-choice
key. Criterion 5 (latest earliest start among highest-priority victims,
:1000-1028) uses creation_index as the start-time proxy.

Documented deviation (docs/PARITY.md): reprieve re-checks resources/ports
exactly, and handles affinity/spread via a conservative precomputed
"restoration would re-block" bit instead of a full predicate re-run (a victim
that *might* re-block is simply not reprieved — strictly more victims than the
reference in rare affinity cases, never a false 'schedulable')."""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..state.arrays import Array, ClusterTables, PodArrays
from .assign import AssignState
from .fit import _fit
from .interpod import affinity_rows, domain_of_term, per_node_counts
from .lattice import CycleArrays
from .topospread import spread_row


class PreemptResult(NamedTuple):
    node: Array      # scalar i32 — chosen node index, -1 if preemption can't help
    victims: Array   # [E] bool — victims on the chosen node
    n_candidates: Array  # scalar i32 — nodes where preemption would work
    n_pdb_violations: Array  # scalar i32 — PDB-violating victims on the node


def _pairwise_port_conflict(
    tables: ClusterTables, cls: Array, cls_e: Array
) -> Array:
    """[E] bool: the preemptor's port-set conflicts with existing pod e's."""
    psets = tables.portsets
    ps_p = tables.classes.portset[cls]
    ps_e = tables.classes.portset[jnp.maximum(cls_e, 0)]
    pp = jnp.maximum(ps_p, 0)
    pe = jnp.maximum(ps_e, 0)
    wild_p, pair_p, trip_p = psets.wild_words[pp], psets.pair_words[pp], psets.trip_words[pp]
    any_e, wild_e, trip_e = psets.pair_words[pe], psets.wild_words[pe], psets.trip_words[pe]
    # conflict iff a shared (proto,port) pair where either side is wildcard,
    # or a shared exact (proto,port,ip) triple — port_conflict_row pairwise
    hits = ((wild_p[None, :] & any_e) | (pair_p[None, :] & wild_e)) != 0
    trip = (trip_p[None, :] & trip_e) != 0
    c = hits.any(-1) | trip.any(-1)
    return c & (ps_p >= 0) & (ps_e >= 0)


def preempt_batch(
    tables: ClusterTables,
    cyc: CycleArrays,
    existing: PodArrays,
    cls: Array,            # [B] i32: preemptor class ids
    node_name_req: Array,  # [B] i32: spec.nodeName ids or -1
    priority: Array,       # [B] i32: preemptor priorities
    D: int,
    pdb_blocked: Array | None = None,   # [E] bool — shared across the burst
) -> PreemptResult:
    """The whole preemption burst as ONE dispatch: vmap of preempt_for_pod
    over the B preemptor lanes, sharing the cycle lattice, the existing-pod
    arrays and the PDB mask. Each lane's result is exactly what the
    single-pod what-if computes against the same snapshot — the host commit
    (sched/preemption.py) resolves victim overlap between lanes. Replaces B
    separate build_cycle+preempt dispatches (the 11.6 s per-pod burst at
    the control shape) with one."""
    if pdb_blocked is None:
        pdb_blocked = jnp.zeros((existing.valid.shape[0],), bool)

    def one(c, nnr, prio):
        return preempt_for_pod(tables, cyc, existing, c, nnr, prio, D,
                               pdb_blocked)

    return jax.vmap(one)(cls, node_name_req, priority)


def preempt_for_pod(
    tables: ClusterTables,
    cyc: CycleArrays,
    existing: PodArrays,
    cls: Array,            # scalar: preemptor's class id
    node_name_req: Array,  # scalar: spec.nodeName id or -1
    priority: Array,       # scalar: preemptor's priority
    D: int,
    pdb_blocked: Array | None = None,   # [E] bool — eviction violates a PDB
) -> PreemptResult:
    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    N = nodes.valid.shape[0]
    E = existing.valid.shape[0]
    I32MAX = jnp.iinfo(jnp.int32).max

    if pdb_blocked is None:
        pdb_blocked = jnp.zeros((existing.valid.shape[0],), bool)
    cls_e = jnp.maximum(existing.cls, 0)
    node_e = existing.node_id
    on_node = existing.valid & (node_e >= 0)
    vict_pot = on_node & (existing.priority < priority)        # [E]
    node_e_safe = jnp.where(on_node, node_e, N)

    # ---- what-if: all potential victims removed (selectVictimsOnNode pass 1)
    req_e = tables.reqs.vec[classes.rid[cls_e]]                # [E, R]
    vict_req = jnp.where(vict_pot[:, None], req_e, 0)
    used_wo = nodes.used.at[jnp.minimum(node_e_safe, N - 1)].add(
        -jnp.where((node_e_safe < N)[:, None], vict_req, 0)
    )

    survivors = PodArrays(
        valid=existing.valid & ~vict_pot,
        name_id=existing.name_id, ns=existing.ns, cls=existing.cls,
        priority=existing.priority, creation=existing.creation,
        node_id=existing.node_id, node_name_req=existing.node_name_req,
    )
    CNT_wo = per_node_counts(cyc.TM, survivors, N)             # [S, N]
    HOLD_wo = per_node_counts(cyc.has_anti.T, survivors, N)

    # ports: conflict[n] = any surviving pod on n whose ports clash with ours
    c_e = _pairwise_port_conflict(tables, cls, cls_e)          # [E]
    live_clash = (c_e & on_node & ~vict_pot).astype(jnp.int32)
    conflict_wo = jnp.zeros((N + 1,), jnp.int32).at[node_e_safe].max(live_clash)[:N] > 0

    # feasibility with all victims gone
    req_p = tables.reqs.vec[classes.rid[cls]]
    fit = _fit(req_p[None, :], nodes.alloc - used_wo) & nodes.valid
    aff_ok, anti_ok = affinity_rows(cls, classes, terms, cyc.TM, CNT_wo, HOLD_wo, nodes, D)
    spread_ok = spread_row(cls, classes, terms, cyc.TM, CNT_wo, cyc.ELD,
                           cyc.static.node_match[cls], nodes, D)
    host_ok = (node_name_req < 0) | (nodes.name_id == node_name_req)
    cand = (cyc.static.mask[cls] & fit & ~conflict_wo & aff_ok & anti_ok
            & spread_ok & host_ok)                              # [N]

    # ---- precompute "restoring pod e would re-block the preemptor" [E] ----
    # own anti-affinity: an anti term of ours matches e's class and e's node
    # carries the term's key
    ans = classes.anti_terms[cls]                               # [AN]
    sa = jnp.maximum(ans, 0)
    _, hk_anti = domain_of_term(nodes, terms.topo_key[sa])      # [AN, N]
    m_own = (ans >= 0)[:, None] & cyc.TM[sa]                    # [AN, SC]
    own_block = (m_own[:, cls_e] &
                 hk_anti[:, jnp.minimum(node_e_safe, N - 1)]).any(0)   # [E]
    # symmetry: e holds an anti term that matches us, key present on e's node
    _, hk_s = domain_of_term(nodes, terms.topo_key)             # [S, N]
    sym_terms = cyc.has_anti[cls_e] & cyc.TM[:, cls][None, :]   # [E, S]
    sym_block = (sym_terms & hk_s[:, jnp.minimum(node_e_safe, N - 1)].T).any(1)
    # hard topology-spread: restoring a matching pod bumps the domain count —
    # conservatively never reprieve such victims
    ts_ids = classes.tsc_term[cls]
    ts = jnp.maximum(ts_ids, 0)
    hard_ts = (ts_ids >= 0) & classes.tsc_hard[cls]
    spread_block = (hard_ts[:, None] & cyc.TM[ts][:, cls_e]).any(0)     # [E]
    reblock = own_block | sym_block | spread_block

    # ---- reprieve scan (selectVictimsOnNode pass 2): PDB-violating victims
    # are reprieved FIRST (generic_scheduler.go:1149-1156), each group in
    # priority-descending order ----
    order = jnp.lexsort((jnp.arange(E), -existing.priority,
                         (~pdb_blocked).astype(jnp.int32), ~vict_pot))

    def step(carry, e):
        used, conflict, victim = carry
        n = jnp.minimum(node_e_safe[e], N - 1)
        is_v = vict_pot[e] & cand[n]
        new_used_n = used[n] + req_e[e]
        fit_n = _fit(req_p, nodes.alloc[n] - new_used_n)
        new_conf = conflict[n] | c_e[e]
        keep = is_v & fit_n & ~new_conf & ~reblock[e]
        used = used.at[n].set(jnp.where(keep, new_used_n, used[n]))
        conflict = conflict.at[n].set(jnp.where(keep, new_conf, conflict[n]))
        victim = victim.at[e].set(is_v & ~keep)
        return (used, conflict, victim), None

    init = (used_wo, conflict_wo, jnp.zeros((E,), bool))
    (used_f, conf_f, victim), _ = jax.lax.scan(step, init, order)

    # ---- pickOneNodeForPreemption (:903): lexicographic over
    # (1) PDB violations, (2) highest victim priority, (3) priority sum,
    # (4) victim count, (5) latest earliest start of highest-prio victims ----
    vmask = victim & (node_e_safe < N)
    idx = jnp.where(vmask, node_e_safe, N)
    num_v = jnp.zeros((N + 1,), jnp.int32).at[idx].add(vmask.astype(jnp.int32))[:N]
    sum_p = jnp.zeros((N + 1,), jnp.int32).at[idx].add(jnp.where(vmask, existing.priority, 0))[:N]
    max_p = jnp.full((N + 1,), -I32MAX, jnp.int32).at[idx].max(
        jnp.where(vmask, existing.priority, -I32MAX))[:N]
    num_pdb = jnp.zeros((N + 1,), jnp.int32).at[idx].add(
        (vmask & pdb_blocked).astype(jnp.int32))[:N]
    # earliest (min) creation among each node's highest-priority victims;
    # pick the node where it is LATEST (GetEarliestPodStartTime, :1000-1028)
    is_top = vmask & (existing.priority == max_p[jnp.minimum(node_e_safe, N - 1)])
    est = jnp.full((N + 1,), I32MAX, jnp.int32).at[idx].min(
        jnp.where(is_top, existing.creation, I32MAX))[:N]

    big = I32MAX
    key0 = jnp.where(cand, num_pdb, big)
    key1 = jnp.where(cand, jnp.where(num_v > 0, max_p, -I32MAX), big)
    key2 = jnp.where(cand, sum_p, big)
    key3 = jnp.where(cand, num_v, big)
    key4 = jnp.where(cand, -est, big)       # latest earliest-start wins
    choice_order = jnp.lexsort((jnp.arange(N), key4, key3, key2, key1, key0))
    best = choice_order[0]
    any_cand = cand.any()
    node = jnp.where(any_cand, best, -1)
    victims = victim & (node_e == node) & any_cand
    nv = (victims & pdb_blocked).sum().astype(jnp.int32)
    return PreemptResult(node=node.astype(jnp.int32), victims=victims,
                         n_candidates=cand.sum().astype(jnp.int32),
                         n_pdb_violations=nv)
