"""NodeResourcesFit and resource-based scores as tensor ops.

Reference semantics: PodFitsResources (algorithm/predicates/predicates.go:789-845)
— a pod fits iff for every resource r: request_r ≤ allocatable_r − used_r, with
zero requests always passing (the zero-request fast path :800-806 falls out of
the per-resource rule), plus the pod-count check used+1 ≤ allowedPodNumber
(encoded as resource RES_PODS with request 1).

Scores: least_requested.go / most_requested.go / balanced_resource_allocation.go.
The reference computes integer (cap−total)*100/cap per resource; we compute in
float32 (memory capacities exceed int32×100), which can differ from the
reference by <1 score point — masks stay bit-exact, scores are within ±1.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..state.arrays import Array, NodeArrays, ReqTable

MAX_NODE_SCORE = 100.0  # framework/v1alpha1/interface.go:87


def fit_matrix(reqs: ReqTable, nodes: NodeArrays) -> Array:
    """[SR, N] bool: request-class r fits on node n given current `used`."""
    free = nodes.alloc - nodes.used  # [N, R]
    vec = reqs.vec  # [SR, R]
    ok = (vec[:, None, :] == 0) | (vec[:, None, :] <= free[None, :, :])
    return ok.all(-1) & nodes.valid[None, :]


def fit_row(req_vec: Array, used: Array, alloc: Array, valid: Array) -> Array:
    """[N] bool for one request vector against live used — the scan inner check."""
    free = alloc - used
    ok = (req_vec[None, :] == 0) | (req_vec[None, :] <= free)
    return ok.all(-1) & valid


def _frac(total: Array, cap: Array) -> Array:
    cap_f = cap.astype(jnp.float32)
    return jnp.where(cap > 0, total.astype(jnp.float32) / jnp.maximum(cap_f, 1.0), 0.0)


def resource_scores_row(req_vec: Array, used: Array, alloc: Array) -> tuple[Array, Array]:
    """(least_requested [N], balanced_allocation [N]) in 0..100 float32.

    least_requested.go:60-77: per-resource (cap−total)*100/cap clamped at 0,
    averaged over cpu+memory. balanced_resource_allocation.go:68-102:
    100 − |cpuFraction−memFraction|*100, 0 if either fraction ≥ 1."""
    total = used + req_vec[None, :]  # [N, R]
    cpu_cap, mem_cap = alloc[:, 0], alloc[:, 1]
    cpu_t, mem_t = total[:, 0], total[:, 1]

    def least(t, cap):
        s = (cap.astype(jnp.float32) - t.astype(jnp.float32)) * MAX_NODE_SCORE
        s = s / jnp.maximum(cap.astype(jnp.float32), 1.0)
        return jnp.where((cap > 0) & (t <= cap), s, 0.0)

    least_score = (least(cpu_t, cpu_cap) + least(mem_t, mem_cap)) / 2.0

    cf, mf = _frac(cpu_t, cpu_cap), _frac(mem_t, mem_cap)
    balanced = jnp.where(
        (cf >= 1.0) | (mf >= 1.0), 0.0, MAX_NODE_SCORE - jnp.abs(cf - mf) * MAX_NODE_SCORE
    )
    return least_score, balanced
