"""NodeResourcesFit and resource-based scores as tensor ops.

Reference semantics: PodFitsResources (algorithm/predicates/predicates.go:789-845)
— the pod-count check used+1 ≤ allowedPodNumber always applies; then, UNLESS the
pod requests zero of everything (the fast path :800-806), every resource must
satisfy request_r ≤ allocatable_r − used_r. Note the asymmetry this implies on
overcommitted nodes: a pod requesting 0 memory still FAILS if memory free is
negative (Go: 0 > negative ⇒ insufficient), but an all-zero pod passes — found
by the randomized golden tests, not obvious from the prose.

Scores: least_requested.go / most_requested.go / balanced_resource_allocation.go.
The reference computes integer (cap−total)*100/cap per resource; we compute in
float32 (memory capacities exceed int32×100), which can differ from the
reference by <1 score point — masks stay bit-exact, scores are within ±1.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..api.types import NUM_FIXED_RES, RES_PODS
from ..state.arrays import Array, NodeArrays, ReqTable

MAX_NODE_SCORE = 100.0  # framework/v1alpha1/interface.go:87


def _fit(vec: Array, free: Array) -> Array:
    """vec: [..., R], free: [..., R] → [...] bool per PodFitsResources.

    Asymmetry of the reference (predicates.go:800-845): cpu/mem/ephemeral are
    checked even when the pod requests 0 of them (0 > negative-free fails on an
    overcommitted node), but *scalar* resources are only checked when requested
    (Go iterates podRequest.ScalarResources), so a zero scalar request passes
    regardless of that scalar's free. Oracle: api/semantics.py pod_fits_resources."""
    R = vec.shape[-1]
    idx = jnp.arange(R)
    is_pods = idx == RES_PODS
    is_scalar = idx >= NUM_FIXED_RES
    pods_ok = (jnp.where(is_pods, vec, 0) <= jnp.where(is_pods, free, 0)).all(-1)
    zero_all = jnp.where(is_pods, 0, vec).max(-1) == 0
    res_ok = (is_pods | (is_scalar & (vec == 0)) | (vec <= free)).all(-1)
    return pods_ok & (zero_all | res_ok)


def fit_matrix(reqs: ReqTable, nodes: NodeArrays) -> Array:
    """[SR, N] bool: request-class r fits on node n given current `used`."""
    free = nodes.alloc - nodes.used  # [N, R]
    return _fit(reqs.vec[:, None, :], free[None, :, :]) & nodes.valid[None, :]


def fit_row(req_vec: Array, used: Array, alloc: Array, valid: Array) -> Array:
    """[N] bool for one request vector against live used — the scan inner check."""
    return _fit(req_vec[None, :], alloc - used) & valid


def _frac(total: Array, cap: Array) -> Array:
    cap_f = cap.astype(jnp.float32)
    return jnp.where(cap > 0, total.astype(jnp.float32) / jnp.maximum(cap_f, 1.0), 0.0)


def resource_scores_row(
    req_vec: Array, used: Array, alloc: Array
) -> tuple[Array, Array, Array]:
    """(least_requested [N], balanced_allocation [N], most_requested [N]) in
    0..100 float32.

    least_requested.go:60-77: per-resource (cap−total)*100/cap clamped at 0,
    averaged over cpu+memory. balanced_resource_allocation.go:68-102:
    100 − |cpuFraction−memFraction|*100, 0 if either fraction ≥ 1.
    most_requested.go:52-70: total*100/cap averaged (bin packing; weight 0 in
    the default provider, enabled via config EngineConfig.w_most)."""
    total = used + req_vec[None, :]  # [N, R]
    cpu_cap, mem_cap = alloc[:, 0], alloc[:, 1]
    cpu_t, mem_t = total[:, 0], total[:, 1]

    def least(t, cap):
        s = (cap.astype(jnp.float32) - t.astype(jnp.float32)) * MAX_NODE_SCORE
        s = s / jnp.maximum(cap.astype(jnp.float32), 1.0)
        return jnp.where((cap > 0) & (t <= cap), s, 0.0)

    def most(t, cap):
        s = t.astype(jnp.float32) * MAX_NODE_SCORE \
            / jnp.maximum(cap.astype(jnp.float32), 1.0)
        return jnp.where((cap > 0) & (t <= cap), s, 0.0)

    least_score = (least(cpu_t, cpu_cap) + least(mem_t, mem_cap)) / 2.0
    most_score = (most(cpu_t, cpu_cap) + most(mem_t, mem_cap)) / 2.0

    cf, mf = _frac(cpu_t, cpu_cap), _frac(mem_t, mem_cap)
    balanced = jnp.where(
        (cf >= 1.0) | (mf >= 1.0), 0.0, MAX_NODE_SCORE - jnp.abs(cf - mf) * MAX_NODE_SCORE
    )
    return least_score, balanced, most_score
