"""Host-port conflict checking as bitset tensor ops.

Reference semantics: PodFitsHostPorts (predicates.go:1104-1120) over the node's
HostPortInfo (nodeinfo/node_info.go): a wanted (proto, ip, port) conflicts with
an existing one iff same proto+port and (either side is the 0.0.0.0 wildcard or
the IPs are equal).

Encoding (state/encode.py): (proto,port) pairs and (proto,port,ip) triples are
interned; each node carries three uint32 bitsets —
  pair_any : pairs used by any pod (any IP)
  pair_wild: pairs used with the wildcard IP
  triple   : exact (proto,port,ip) triples in use
and each port-set class carries the matching union word-masks, so a conflict
check is three ANDs over words.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..state.arrays import Array, NodeArrays, PortSetTable


def port_conflict_matrix(portsets: PortSetTable, nodes: NodeArrays) -> Array:
    """[SPP, N] bool — True where the port-set CONFLICTS with the node."""
    wild_hits = portsets.wild_words[:, None, :] & nodes.port_pair_any[None, :, :]
    spec_hits = portsets.pair_words[:, None, :] & nodes.port_pair_wild[None, :, :]
    trip_hits = portsets.trip_words[:, None, :] & nodes.port_triple[None, :, :]
    return (
        ((wild_hits | spec_hits) != 0).any(-1) | (trip_hits != 0).any(-1)
    )


def port_conflict_row(
    wild_words: Array, pair_words: Array, trip_words: Array,
    ppa: Array, ppw: Array, ppt: Array,
) -> Array:
    """[N] bool conflict for one port-set against live node bitsets (scan path)."""
    hits = (wild_words[None, :] & ppa) | (pair_words[None, :] & ppw)
    return (hits != 0).any(-1) | ((trip_words[None, :] & ppt) != 0).any(-1)
