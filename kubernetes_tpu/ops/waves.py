"""Wave-parallel batched assignment: the scheduling cycle as a fixpoint of
dense [SC, N] evaluations instead of a P-step sequential scan.

The reference schedules one pod at a time (scheduler.go:596-763); ops/assign.py
reproduces that literally as a lax.scan whose 50k serialized steps leave the
TPU idle. This module replaces it as the default path. Per wave:

  1. every pod CLASS still holding pending pods evaluates its full Filter mask
     and Score row against the committed state — one vmapped dense pass over
     [SC, N], the shape the MXU/VPU wants (pods of a class are spec-identical,
     so per-pod rows would be redundant);
  2. admission is cross-tier: queue order (activeQ: priority desc, creation
     asc — internal/queue/scheduling_queue.go:119-138) is enforced where it
     is OBSERVABLE — through the interaction graph (step 4) and the
     rank-ordered contention passes (step 5) — instead of a global
     priority-tier gate, so independent lower-priority classes need not
     wait out higher tiers wave-by-wave;
  3. each admitting class claims up to one pod per node on its top-scored
     feasible nodes, subject to per-domain quotas that make every same-wave
     admission pair NON-INTERFERING:
       - hard topology-spread (predicates.go:1643): at most
         maxSkew + minMatch − count(d) new matching pods per domain d
         (the criticalPaths online-min, metadata.go:78-112, evaluated at
         wave start — conservative, never violating);
       - self-matching anti-affinity (predicates.go:1447-1456): one pod per
         domain per wave;
       - required-affinity first-pod escape (predicates.go:1436-1440): a class
         whose terms have zero matches admits exactly one pod, so followers
         co-locate with it next wave;
  4. cross-CLASS term interactions (my anti/spread/affinity term matches your
     pods) are serialized through an [SC, SC] interaction graph: a class
     admits only if no earlier-queued class it interacts with admits in the
     same wave (vectorized independent set — no scan);
  5. same-node contention between classes is resolved in queue order by a
     cumulative resource-sum / port-OR pass; losers retry next wave;
  6. failed runs consume eagerly: a zero-progress wave marks the frozen
     priority run of every attempting class unschedulable (the sequential
     scan's outcome on unchanging state), and a class that is
     Filter-infeasible on every node while ranked ahead of all same-wave
     admitters consumes its run in that same wave (its pods replay first,
     against exactly the state that rejected them) — so the loop always
     terminates and an infeasible head class never costs a dedicated wave.

Soundness invariant (tested in tests/test_waves.py): replaying the final
assignment wave-by-wave, each pod in queue order, every placement passes the
full Filter mask at replay time — i.e. the output is a valid greedy execution
of the reference's per-pod loop. Deviations (which valid execution gets
picked) are documented in docs/PARITY.md.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..state.arrays import Array, ClusterTables, PodArrays
from .assign import AssignResult, AssignState, pod_mask_row, score_row
from .fit import _fit
from .interpod import class_term_membership, domain_agg
from .lattice import CycleArrays

# plain Python ints only: a module-level jnp scalar would be captured as a
# closure *device array* and hoisted into executable parameters, which
# miscompiles under multi-trace dispatch (jax 0.9 CPU)
_I32_MAX = int(jnp.iinfo(jnp.int32).max)
_I32_MIN = int(jnp.iinfo(jnp.int32).min)

# class-axis tile size for the per-wave dense evaluation (long-context
# tiling; KTPU_CLASS_BLOCK overrides — the bench shapes stay un-tiled)
import os as _os

_CLASS_BLOCK = int(_os.environ.get("KTPU_CLASS_BLOCK", "1024"))
# block size for the per-node contention scan (bounds the [block, N, R]
# temporaries — see the block comment at the scan)
_CONTENTION_BLOCK = int(_os.environ.get("KTPU_CONTENTION_BLOCK", "256"))


class _WaveCarry(NamedTuple):
    state: AssignState
    cursor: Array     # [SC] pods consumed per class (placed or tier-failed)
    placed: Array     # [SC] pods actually placed per class
    node_out: Array   # [P+1] chosen node per sorted-pod slot (last = sink)
    wave_out: Array   # [P+1] wave index each pod was admitted in (-1 = never)
    waves: Array      # scalar i32


def interaction_graph(tables: ClusterTables, cyc: CycleArrays) -> Array:
    """G [SC, SC]: classes whose same-wave admissions could interact through
    affinity/anti-affinity/hard-spread terms (resource/port contention is
    resolved per node instead and needs no edge). Symmetric, no self-edges —
    a class's interaction with itself is handled exactly by the per-domain
    quotas."""
    classes = tables.classes
    S = cyc.TM.shape[0]
    M = cyc.TM.astype(jnp.int32)  # [S, SC] term matches class

    def edges(member: Array) -> Array:  # member: [SC, S]
        return (member.astype(jnp.int32) @ M) > 0  # [SC, SC]

    anti = edges(cyc.has_anti)
    hard_spread_ids = jnp.where(classes.tsc_hard, classes.tsc_term, -1)
    spread = edges(class_term_membership(hard_spread_ids, S))
    aff = edges(class_term_membership(classes.aff_terms, S))
    G = anti | anti.T | spread | spread.T | aff | aff.T
    G = G & classes.valid[:, None] & classes.valid[None, :]
    return G & ~jnp.eye(G.shape[0], dtype=bool)


def _class_mask_score(tables, cyc, state):
    """[SC, N] Filter mask + Score for every class against `state` — the
    dense analog of findNodesThatFit + prioritizeNodes, once per class.

    Long-context tiling (SURVEY §5 "blockwise tiles over the pod axis"):
    vmapping the full row over SC materializes per-class intermediates like
    [SC, S, N] domain gathers — fine at the class-interned SC of replicated
    workloads, but with thousands of DISTINCT pod specs SC approaches P and
    those temporaries outgrow HBM long before the [SC, N] outputs do. Above
    _CLASS_BLOCK classes the vmap runs under lax.map over class blocks, so
    peak intermediate memory is bounded by block size while outputs stay the
    full lattice (the same shape the rest of the wave consumes)."""
    classes = tables.classes
    SC = classes.valid.shape[0]

    def row(c):
        mask = pod_mask_row(tables, cyc, state, c, jnp.int32(-1),
                            classes.valid[c])
        score = score_row(tables, cyc, state, c)
        return mask, jnp.where(mask, score, -jnp.inf)

    if SC <= _CLASS_BLOCK:
        return jax.vmap(row)(jnp.arange(SC))
    n_blocks = -(-SC // _CLASS_BLOCK)
    blocks = jnp.arange(n_blocks * _CLASS_BLOCK, dtype=jnp.int32).reshape(
        n_blocks, _CLASS_BLOCK)
    # padded tail indexes clamp to SC-1; the duplicate rows are sliced off
    masks, scores = lax.map(
        lambda blk: jax.vmap(row)(jnp.minimum(blk, SC - 1)), blocks)
    return (masks.reshape(-1, masks.shape[-1])[:SC],
            scores.reshape(-1, scores.shape[-1])[:SC])


def _domain_quota_pass(tables, cyc, state, mask, order_n, allowed_sorted):
    """AND per-domain admission quotas into `allowed_sorted` [SC, N] (nodes in
    per-class score order). Quotas keep same-wave same-class admissions from
    violating hard spread / self-anti-affinity when replayed sequentially."""
    classes = tables.classes
    nodes = tables.nodes
    terms = tables.terms
    D = cyc.ELD.shape[2] - 1
    SC, N = mask.shape
    TS = classes.tsc_term.shape[1]
    AN = classes.anti_terms.shape[1]

    def slot_quota(c, s_id, topo_key, active, quota_d):
        """quota_d: [D+1] cap per domain; returns [N] allowed-in-sorted-order
        for this class/slot. rank-in-domain is computed by a (domain, score
        rank) lexsort — O(N log N), never materializing an [N, D] one-hot
        (D can be N itself for hostname-keyed constraints)."""
        k = jnp.maximum(topo_key, 0)
        dom = jnp.where((topo_key >= 0) & nodes.valid, nodes.domain[:, k], -1)
        dom_sorted = dom[order_n[c]]                  # [N] score-desc order
        dsafe = jnp.where(dom_sorted >= 0, dom_sorted, D)
        # stable-sort score-ordered positions by domain: within each domain
        # group the score order is preserved, so rank-in-domain = index in
        # the grouped array minus the group's start index
        gidx = jnp.arange(N, dtype=jnp.int32)
        grp = jnp.argsort(dsafe, stable=True)         # grouped order
        dom_g = dsafe[grp]
        start = jnp.full((D + 1,), N, jnp.int32).at[dom_g].min(gidx)
        rank_g = gidx - start[dom_g]
        rank_in_dom = jnp.zeros((N,), jnp.int32).at[grp].set(rank_g)
        return ~active | (rank_in_dom < quota_d[dsafe])

    # --- hard topology-spread slots (only self-matching classes move their
    # own counts; others are quota-free here and guarded by the graph).
    # Slots are a vmapped axis, not a Python loop: the traced graph stays the
    # same size no matter how many TS/AN slots the constraint schema needs.
    # Each family is under lax.cond: a batch with no active slots anywhere
    # (e.g. gang jobs with plain resource requests) skips the [SC·slots]
    # sorts entirely at runtime. ---
    def spread_slot(c, t):
        s_id = classes.tsc_term[c, t]
        s = jnp.maximum(s_id, 0)
        active = (
            (s_id >= 0) & classes.tsc_hard[c, t] & cyc.TM[s, c]
        )
        eld = cyc.ELD[c, t, :D]
        active = active & eld.any()
        k = terms.topo_key[s]
        dom = jnp.where((k >= 0) & nodes.valid,
                        nodes.domain[:, jnp.maximum(k, 0)], -1)
        seg = domain_agg(state.CNT[s][None], dom[None], D,
                         eligible=cyc.static.node_match[c][None])[0]
        min_cnt = jnp.min(jnp.where(eld, seg[:D], _I32_MAX))
        quota = jnp.clip(
            classes.tsc_maxskew[c, t] + min_cnt - seg, 0, _I32_MAX
        )
        quota = jnp.where(active, quota, _I32_MAX)
        return slot_quota(c, s_id, k, active, quota)

    def apply_spread(allowed):
        rows = jax.vmap(
            lambda c: jax.vmap(lambda t: spread_slot(c, t))(
                jnp.arange(TS, dtype=jnp.int32))
        )(jnp.arange(SC, dtype=jnp.int32))        # [SC, TS, N]
        return allowed & rows.all(axis=1)

    any_spread = ((classes.tsc_term >= 0) & classes.tsc_hard
                  & classes.valid[:, None]).any()
    allowed_sorted = lax.cond(any_spread, apply_spread,
                              lambda a: a, allowed_sorted)

    # --- self-matching anti-affinity slots: one per domain per wave ---
    def anti_slot(c, t):
        s_id = classes.anti_terms[c, t]
        s = jnp.maximum(s_id, 0)
        k = terms.topo_key[s]
        active = (s_id >= 0) & cyc.TM[s, c] & (k >= 0)
        quota = jnp.where(active, jnp.ones((D + 1,), jnp.int32),
                          _I32_MAX)
        return slot_quota(c, s_id, k, active, quota)

    def apply_anti(allowed):
        rows = jax.vmap(
            lambda c: jax.vmap(lambda t: anti_slot(c, t))(
                jnp.arange(AN, dtype=jnp.int32))
        )(jnp.arange(SC, dtype=jnp.int32))        # [SC, AN, N]
        return allowed & rows.all(axis=1)

    any_anti = ((classes.anti_terms >= 0)
                & classes.valid[:, None]).any()
    allowed_sorted = lax.cond(any_anti, apply_anti,
                              lambda a: a, allowed_sorted)

    return allowed_sorted


def _escape_cap(tables, cyc, state, r):
    """Required-affinity first-pod escape: a class whose required terms have
    zero potential matches (predicates.go:1436-1440) admits at most ONE pod
    this wave, so the followers see its counts next wave."""
    classes = tables.classes
    terms = tables.terms
    nodes = tables.nodes

    def one(c):
        ats = classes.aff_terms[c]
        s = jnp.maximum(ats, 0)
        active = ats >= 0
        k = terms.topo_key[s]
        has_key = (k[:, None] >= 0) & nodes.valid[None, :]
        total = jnp.sum(jnp.where(active[:, None] & has_key,
                                  state.CNT[s], 0))
        return active.any() & (total == 0)

    escape = jax.vmap(one)(jnp.arange(classes.valid.shape[0]))
    return jnp.where(escape, jnp.minimum(r, 1), r)


def assign_waves(
    tables: ClusterTables,
    cyc: CycleArrays,
    pods: PodArrays,
    init: AssignState,
    max_waves: int | None = None,
    return_waves: bool = False,
) -> AssignResult:
    """Drop-in replacement for ops/assign.py:assign_batch (same signature,
    same result type). See the module docstring for the algorithm."""
    classes = tables.classes
    nodes = tables.nodes
    SC = classes.valid.shape[0]
    N = nodes.valid.shape[0]
    P = pods.valid.shape[0]
    R = tables.reqs.vec.shape[1]

    G = interaction_graph(tables, cyc)
    req_by_class = tables.reqs.vec[jnp.maximum(classes.rid, 0)]  # [SC, R]

    # classes whose Filter feasibility is MONOTONE within a dispatch: state
    # only tightens for them (used/CNT/ports/volumes grow; anti-affinity
    # only blocks more). Required pod-affinity (new matches open nodes) and
    # hard spread (a rising domain-min lifts other domains' quotas) are the
    # only relaxing predicates; classes without either, once infeasible on
    # every node, stay infeasible for the rest of the dispatch.
    mono = (
        ~(classes.aff_terms >= 0).any(axis=1)
        & ~((classes.tsc_term >= 0) & classes.tsc_hard).any(axis=1)
    )

    # --- queue order, grouped by class (activeQ comparator within class) ---
    cls_safe = jnp.where(pods.valid, pods.cls, SC)
    sorted_pods = jnp.lexsort((pods.creation, -pods.priority, cls_safe))  # [P]
    class_total = (
        jnp.zeros((SC + 1,), jnp.int32)
        .at[cls_safe].add(1)[:SC]
    )
    class_offset = jnp.cumsum(class_total) - class_total  # [SC] exclusive
    sorted_pods_pad = jnp.concatenate(
        [sorted_pods, jnp.full((1,), P, jnp.int32)])
    pos_in_class = jnp.arange(P, dtype=jnp.int32) - class_offset[
        jnp.minimum(cls_safe[sorted_pods], SC - 1)]
    pri_sorted = pods.priority[sorted_pods]
    cls_sorted = jnp.minimum(cls_safe[sorted_pods], SC - 1)
    sorted_valid = pods.valid[sorted_pods]

    def body(carry: _WaveCarry) -> _WaveCarry:
        state, cursor, placed, node_out, wave_out, waves = carry
        remaining = class_total - cursor
        active = classes.valid & (remaining > 0)

        # next pending pod per class. Admission is CROSS-TIER: a class needs
        # no global priority-tier gate because everything priority order can
        # observe is already serialized in rank order — interacting classes
        # through the graph block below, same-node resources/ports/volumes
        # through the rank-ordered cumulative passes. A lower-priority pod
        # admitted alongside a higher-priority one replays after it
        # (wave, priority, creation) and sees identical committed state.
        nxt = sorted_pods_pad[jnp.minimum(class_offset + cursor, P)]
        nxt_ok = active & (nxt < P)
        nxt_safe = jnp.minimum(nxt, P - 1)
        # i32 min is the neutral element, not a magic sentinel: run counts
        # also require nxt_ok, so real INT32_MIN priorities still work
        nxt_pri = jnp.where(nxt_ok, pods.priority[nxt_safe], _I32_MIN)
        nxt_cre = jnp.where(nxt_ok, pods.creation[nxt_safe], _I32_MAX)

        # length of each class's CURRENT priority run (pods at the class's
        # own head priority, at/after the cursor) — the unit that fails
        # together when the head pod is infeasible against frozen state
        run_pod = (
            sorted_valid & (pri_sorted == nxt_pri[cls_sorted])
            & (pos_in_class >= cursor[cls_sorted])
        )
        run_cnt = (
            jnp.zeros((SC,), jnp.int32).at[cls_sorted].add(
                run_pod.astype(jnp.int32))
        )
        r = jnp.where(nxt_ok, jnp.minimum(remaining, run_cnt), 0)

        mask, score = _class_mask_score(tables, cyc, state)
        mask = mask & nxt_ok[:, None]
        # score-window admission (EngineConfig.w_window): a class only
        # admits on nodes within the window of its per-class feasible max
        # this wave, so decisive score gaps (preferAvoidPods, strong
        # preferences) aren't steamrolled by same-wave intra-class
        # spreading. The max itself always qualifies → feasibility (and
        # the early-fail rule's mask.any) is unchanged; ties are
        # unaffected. Nodes outside the window become admissible in later
        # waves once the leading tier fills and the class max drops.
        best = jnp.max(jnp.where(mask, score, -jnp.inf), axis=1,
                       keepdims=True)
        adm_mask = mask & (score >= best - cyc.ecfg.w_window)
        r = _escape_cap(tables, cyc, state, r)

        # independent set over the interaction graph, queue-rank order:
        # a class yields to any earlier-ranked ACTIVE class it interacts
        # with (in-tier or not — the earlier class admits first, this wave
        # or a later one). Inactive classes rank LAST via the explicit
        # primary key (negating their _I32_MIN sentinel priority overflows
        # i32 and would rank them first, handing active classes nonzero
        # ranks — and nonzero tie-rotation offsets — they must not have);
        # priority-descending uses the order-preserving unsigned bias, so
        # real INT32_MIN priorities sort correctly without x64.
        pri_desc = ~(nxt_pri.astype(jnp.uint32) ^ jnp.uint32(0x80000000))
        rank_key = jnp.lexsort((nxt_cre, pri_desc, ~nxt_ok))  # [SC] perm
        crank = jnp.zeros((SC,), jnp.int32).at[rank_key].set(
            jnp.arange(SC, dtype=jnp.int32))
        earlier = crank[None, :] < crank[:, None]            # [SC, SC]
        blocked = (G & earlier & nxt_ok[None, :]).any(axis=1)
        attempted = nxt_ok & ~blocked & (r > 0)
        r = jnp.where(attempted, r, 0)

        # per-class admission: top-r feasible nodes by score, domain quotas.
        # Equal-score ties resolve from a rotated start index keyed to the
        # class's QUEUE RANK within this batch — the reference's round-robin
        # node offset (generic_scheduler.go:502 nextStartNodeIndex): on a
        # uniform cluster every class's score row is CONSTANT, and without
        # rotation all classes pile onto the same lowest-index nodes, so
        # rank-ordered contention admits a trickle per wave (observed: 69
        # waves at 2k nodes × 1.4k classes; ~7 with rotation). The rank (not
        # the global interned class index) keeps any single-pending-class
        # batch at offset 0 → identical to the sequential scan's
        # argmax-lowest-index (PARITY #1, tests' singleton agreement).
        offs = (crank * 97) % N
        rot = (jnp.arange(N, dtype=jnp.int32)[None, :]
               + offs[:, None]) % N                          # [SC, N]
        score_rot = jnp.take_along_axis(score, rot, axis=1)
        order_rot = jnp.argsort(-score_rot, axis=1)
        order_n = jnp.take_along_axis(rot, order_rot, axis=1)  # [SC, N]
        feas_sorted = jnp.take_along_axis(adm_mask, order_n, axis=1)
        allowed = _domain_quota_pass(
            tables, cyc, state, adm_mask, order_n, feas_sorted)
        grank = jnp.cumsum(allowed.astype(jnp.int32), axis=1) - 1
        adm_sorted = allowed & (grank < r[:, None])
        A = jnp.zeros((SC, N), bool).at[
            jnp.arange(SC)[:, None], order_n].set(adm_sorted)

        # per-node cross-class resolution in queue-rank order, as a scan
        # over CLASS BLOCKS: the cumulative passes need [block, N, …]
        # temporaries only, never [SC, N, R] — at thousands of distinct
        # classes (gang jobs each carry their own labels → their own class)
        # the un-blocked cumsum chain was an HBM-OOM worker crash at
        # 5k nodes × 100k pods. Carries thread the exact same exclusive
        # prefixes across blocks, so the result is bit-identical.
        cord = rank_key                                       # [SC] perm
        A_ord = A[cord]
        req_ord = req_by_class[cord]                          # [SC, R]
        ps_ord = classes.portset[cord]
        psafe = jnp.maximum(ps_ord, 0)
        has_p = (ps_ord >= 0)
        pairw = tables.portsets.pair_words[psafe]             # [SC, PWp]
        wildw = tables.portsets.wild_words[psafe]
        tripw = tables.portsets.trip_words[psafe]
        vs_ord = classes.volset[cord]
        vsafe = jnp.maximum(vs_ord, 0)
        has_v = (vs_ord >= 0)
        vanyw = tables.volsets.any_words[vsafe]               # [SC, VW]
        vrww = tables.volsets.rw_words[vsafe]

        B = min(_CONTENTION_BLOCK, SC)
        nb = -(-SC // B)
        pad = nb * B - SC

        def blocks_of(x):  # pad with inert rows (no admission, zero words)
            if pad:
                z = jnp.zeros((pad,) + x.shape[1:], x.dtype)
                x = jnp.concatenate([x, z])
            return x.reshape((nb, B) + x.shape[1:])

        shift = lambda M: jnp.concatenate(
            [jnp.zeros_like(M[:1]), M[:-1]], axis=0)
        or_red = lambda k, W: lax.associative_scan(
            jnp.bitwise_or, jnp.where(k, W[:, None, :], 0), axis=0)[-1]

        def block(carry, xs):
            cum_used, c_pa, c_pw, c_pt, c_va, c_vr = carry
            A_b, req_b, hp_b, pw_b, ww_b, tw_b, hv_b, va_b, vr_b = xs
            add = jnp.where(A_b[:, :, None], req_b[:, None, :], 0)
            cum_exc = (jnp.cumsum(add, axis=0) - add) + cum_used[None]
            # earlier same-wave classes consume free space; the pod itself
            # must fit per PodFitsResources semantics (zero scalar requests
            # ignore that scalar's free — fit._fit, predicates.go:800-845)
            free = nodes.alloc[None] - state.used[None] - cum_exc
            fits = _fit(req_b[:, None, :], free)
            keep = A_b & fits

            # ports: exclusive prefix over keep-after-resources (a class
            # that itself loses the port check still shadows later ones —
            # conservative, matching the un-blocked pass)
            kp = (keep & hp_b[:, None])[:, :, None]
            scan_or = lambda W: lax.associative_scan(
                jnp.bitwise_or, jnp.where(kp, W[:, None, :], 0), axis=0)
            inc_p, inc_w, inc_t = scan_or(pw_b), scan_or(ww_b), scan_or(tw_b)
            exc_p = shift(inc_p) | c_pa[None]
            exc_w = shift(inc_w) | c_pw[None]
            exc_t = shift(inc_t) | c_pt[None]
            conflict = (
                ((ww_b[:, None, :] & exc_p) != 0)
                | ((pw_b[:, None, :] & exc_w) != 0)
                | ((tw_b[:, None, :] & exc_t) != 0)
            ).any(-1)
            keep2 = keep & (~hp_b[:, None] | ~conflict)

            # volume conflict/limits against same-wave earlier classes on
            # the same node: exclusive-prefix OR, then conflict + limits
            kv = (keep2 & hv_b[:, None])[:, :, None]
            scan_orv = lambda W: lax.associative_scan(
                jnp.bitwise_or, jnp.where(kv, W[:, None, :], 0), axis=0)
            exc_va = shift(scan_orv(va_b)) | c_va[None]
            exc_vr = shift(scan_orv(vr_b)) | c_vr[None]
            tot_any = state.vol_any[None] | exc_va            # [B, N, VW]
            tot_rw = state.vol_rw[None] | exc_vr
            vconf = (
                ((va_b[:, None, :] & tot_rw) != 0)
                | ((vr_b[:, None, :] & tot_any) != 0)
            ).any(-1)
            after_v = tot_any | va_b[:, None, :]
            vcnt = jax.lax.population_count(
                after_v[:, :, None, :] & tables.drv_masks[None, None, :, :]
            ).sum(-1).astype(jnp.int32)                       # [B, N, DR]
            vlim = nodes.vol_limit[None]                      # [1, N, DR]
            vlim_ok = ((vlim < 0) | (vcnt <= vlim)).all(-1)
            keep3 = keep2 & (~hv_b[:, None] | (~vconf & vlim_ok))

            # carries: resources advance over A_b (pre-filter, as above);
            # port words over keep-after-resources; volume words over
            # keep-after-ports. Committed words (state update) come from
            # the FINAL keep and are emitted per block.
            carry2 = (
                cum_used + add.sum(axis=0),
                c_pa | inc_p[-1], c_pw | inc_w[-1], c_pt | inc_t[-1],
                c_va | scan_orv(va_b)[-1], c_vr | scan_orv(vr_b)[-1],
            )
            kp2 = (keep3 & hp_b[:, None])[:, :, None]
            kv2 = (keep3 & hv_b[:, None])[:, :, None]
            committed = (
                or_red(kp2, pw_b), or_red(kp2, ww_b), or_red(kp2, tw_b),
                or_red(kv2, va_b), or_red(kv2, vr_b),
            )
            return carry2, (keep3, committed)

        Wp = pairw.shape[1]
        VW = vanyw.shape[1]
        carry0 = (
            jnp.zeros((N, R), jnp.int32),
            jnp.zeros((N, Wp), pairw.dtype),
            jnp.zeros((N, Wp), wildw.dtype),
            jnp.zeros((N, Wp), tripw.dtype),
            jnp.zeros((N, VW), vanyw.dtype),
            jnp.zeros((N, VW), vrww.dtype),
        )
        _, (keep_b, committed_b) = lax.scan(
            block, carry0,
            (blocks_of(A_ord), blocks_of(req_ord), blocks_of(has_p),
             blocks_of(pairw), blocks_of(wildw), blocks_of(tripw),
             blocks_of(has_v), blocks_of(vanyw), blocks_of(vrww)))
        keep = keep_b.reshape(nb * B, N)[:SC]
        or_blocks = lambda x: lax.associative_scan(
            jnp.bitwise_or, x, axis=0)[-1]
        orp, orw, ort, orva, orvr = (or_blocks(cb) for cb in committed_b)

        A_final = jnp.zeros_like(A).at[cord].set(keep)
        m = A_final.sum(axis=1).astype(jnp.int32)             # [SC]
        total = m.sum()

        # ---- commit ----
        Ai = A_final.astype(jnp.int32)
        used2 = state.used + jnp.einsum("cn,cr->nr", Ai, req_by_class)
        CNT2 = state.CNT + cyc.TM.astype(jnp.int32) @ Ai
        HOLD2 = state.HOLD + cyc.has_anti.T.astype(jnp.int32) @ Ai
        WSYM2 = state.WSYM + cyc.WCOLS @ Ai.astype(jnp.float32)
        state2 = AssignState(
            used=used2,
            ppa=state.ppa | orp, ppw=state.ppw | orw, ppt=state.ppt | ort,
            CNT=CNT2, HOLD=HOLD2, WSYM=WSYM2,
            vol_any=state.vol_any | orva, vol_rw=state.vol_rw | orvr,
        )

        # ---- map admissions back to pods (rank among kept, score order) ----
        sck = jnp.where(A_final, score, -jnp.inf)
        ordk = jnp.argsort(-sck, axis=1)
        kept_sorted = jnp.take_along_axis(A_final, ordk, axis=1)
        rank_sorted = jnp.cumsum(kept_sorted.astype(jnp.int32), axis=1) - 1
        rank = jnp.zeros((SC, N), jnp.int32).at[
            jnp.arange(SC)[:, None], ordk].set(rank_sorted)
        tgt = jnp.where(A_final, class_offset[:, None] + cursor[:, None] + rank,
                        P)
        pod_id = jnp.where(A_final, sorted_pods_pad[jnp.minimum(tgt, P)], P)
        node_out2 = node_out.at[pod_id.reshape(-1)].set(
            jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32)[None, :],
                             (SC, N)).reshape(-1))
        wave_out2 = wave_out.at[pod_id.reshape(-1)].set(waves)

        # Failure consumption, two rules (both replay-sound):
        #  * global zero progress ⇒ state is frozen ⇒ every attempting
        #    class's priority run fails exactly as pod-by-pod in the scan;
        #  * EARLY per-class fail: an attempted class whose Filter mask is
        #    false on every node, ranked ahead of every class that admitted
        #    this wave, consumes its run NOW — its pods replay before any
        #    of this wave's placements, against exactly the wave-start
        #    state that rejected them. (Filter-infeasible only: a class
        #    losing to same-wave quota/contention retries next wave, where
        #    the sequential outcome may differ.)
        fail = total == 0
        infeasible = attempted & ~mask.any(axis=1)
        # monotone classes consume EVERYTHING once nowhere-feasible (state
        # never relaxes for them this dispatch). Non-monotone classes (a
        # later placement could open nodes for them: required affinity,
        # hard spread) consume only when they sit in the FAILING PREFIX of
        # the rank order — every class ranked before them this wave is
        # itself infeasible-attempted or inactive, so their sequential
        # replay position pops against exactly the wave-start state that
        # rejected them. (Ranked-behind a blocked or admitting class, they
        # retry: that class's later placements may feed their predicates.)
        ord_fail = (infeasible | ~nxt_ok)[rank_key]
        prefix = jnp.cumprod(ord_fail.astype(jnp.int32)) > 0
        in_prefix = jnp.zeros((SC,), bool).at[rank_key].set(prefix)
        early_fail = infeasible & (mono | in_prefix)
        run_left = jnp.minimum(run_cnt, remaining)
        consume = jnp.where(infeasible & mono, remaining,
                            jnp.where((fail & attempted) | early_fail,
                                      run_left, m))
        return _WaveCarry(
            state=state2, cursor=cursor + consume, placed=placed + m,
            node_out=node_out2, wave_out=wave_out2, waves=waves + 1,
        )

    cap = jnp.int32(max_waves if max_waves is not None else 2 * P + 2)

    def cond(carry: _WaveCarry) -> Array:
        remaining = (class_total - carry.cursor)
        return ((remaining > 0) & tables.classes.valid).any() & (
            carry.waves < cap)

    init_carry = _WaveCarry(
        state=init,
        cursor=jnp.zeros((SC,), jnp.int32),
        placed=jnp.zeros((SC,), jnp.int32),
        node_out=jnp.full((P + 1,), -1, jnp.int32),
        wave_out=jnp.full((P + 1,), -1, jnp.int32),
        waves=jnp.int32(0),
    )
    final = lax.while_loop(cond, body, init_carry)
    node = final.node_out[:P]
    result = AssignResult(node=node, feasible=node >= 0, state=final.state)
    if return_waves:
        return result, final.wave_out[:P]
    return result
