"""Wave-parallel batched assignment: the scheduling cycle as a fixpoint of
dense [SC, N] evaluations instead of a P-step sequential scan.

The reference schedules one pod at a time (scheduler.go:596-763); ops/assign.py
reproduces that literally as a lax.scan whose 50k serialized steps leave the
TPU idle. This module replaces it as the default path. Per wave:

  1. every pod CLASS still holding pending pods evaluates its full Filter mask
     and Score row against the committed state — one vmapped dense pass over
     [SC, N], the shape the MXU/VPU wants (pods of a class are spec-identical,
     so per-pod rows would be redundant);
  2. only classes whose next queued pod sits in the current top priority tier
     admit this wave (activeQ order: priority desc, creation asc —
     internal/queue/scheduling_queue.go:119-138);
  3. each admitting class claims up to one pod per node on its top-scored
     feasible nodes, subject to per-domain quotas that make every same-wave
     admission pair NON-INTERFERING:
       - hard topology-spread (predicates.go:1643): at most
         maxSkew + minMatch − count(d) new matching pods per domain d
         (the criticalPaths online-min, metadata.go:78-112, evaluated at
         wave start — conservative, never violating);
       - self-matching anti-affinity (predicates.go:1447-1456): one pod per
         domain per wave;
       - required-affinity first-pod escape (predicates.go:1436-1440): a class
         whose terms have zero matches admits exactly one pod, so followers
         co-locate with it next wave;
  4. cross-CLASS term interactions (my anti/spread/affinity term matches your
     pods) are serialized through an [SC, SC] interaction graph: a class
     admits only if no earlier-queued class it interacts with admits in the
     same wave (vectorized independent set — no scan);
  5. same-node contention between classes is resolved in queue order by a
     cumulative resource-sum / port-OR pass; losers retry next wave;
  6. zero-progress waves mark the entire frozen priority-tier run of each
     attempting class unschedulable — exactly the outcome of the sequential
     scan replayed with unchanging state — so the loop always terminates.

Soundness invariant (tested in tests/test_waves.py): replaying the final
assignment wave-by-wave, each pod in queue order, every placement passes the
full Filter mask at replay time — i.e. the output is a valid greedy execution
of the reference's per-pod loop. Deviations (which valid execution gets
picked) are documented in docs/PARITY.md.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..state.arrays import Array, ClusterTables, PodArrays
from .assign import AssignResult, AssignState, pod_mask_row, score_row
from .fit import _fit
from .interpod import class_term_membership, domain_agg
from .lattice import CycleArrays

# plain Python ints only: a module-level jnp scalar would be captured as a
# closure *device array* and hoisted into executable parameters, which
# miscompiles under multi-trace dispatch (jax 0.9 CPU)
_I32_MAX = int(jnp.iinfo(jnp.int32).max)
_I32_MIN = int(jnp.iinfo(jnp.int32).min)

# class-axis tile size for the per-wave dense evaluation (long-context
# tiling; KTPU_CLASS_BLOCK overrides — the bench shapes stay un-tiled)
import os as _os

_CLASS_BLOCK = int(_os.environ.get("KTPU_CLASS_BLOCK", "1024"))


class _WaveCarry(NamedTuple):
    state: AssignState
    cursor: Array     # [SC] pods consumed per class (placed or tier-failed)
    placed: Array     # [SC] pods actually placed per class
    node_out: Array   # [P+1] chosen node per sorted-pod slot (last = sink)
    wave_out: Array   # [P+1] wave index each pod was admitted in (-1 = never)
    waves: Array      # scalar i32


def interaction_graph(tables: ClusterTables, cyc: CycleArrays) -> Array:
    """G [SC, SC]: classes whose same-wave admissions could interact through
    affinity/anti-affinity/hard-spread terms (resource/port contention is
    resolved per node instead and needs no edge). Symmetric, no self-edges —
    a class's interaction with itself is handled exactly by the per-domain
    quotas."""
    classes = tables.classes
    S = cyc.TM.shape[0]
    M = cyc.TM.astype(jnp.int32)  # [S, SC] term matches class

    def edges(member: Array) -> Array:  # member: [SC, S]
        return (member.astype(jnp.int32) @ M) > 0  # [SC, SC]

    anti = edges(cyc.has_anti)
    hard_spread_ids = jnp.where(classes.tsc_hard, classes.tsc_term, -1)
    spread = edges(class_term_membership(hard_spread_ids, S))
    aff = edges(class_term_membership(classes.aff_terms, S))
    G = anti | anti.T | spread | spread.T | aff | aff.T
    G = G & classes.valid[:, None] & classes.valid[None, :]
    return G & ~jnp.eye(G.shape[0], dtype=bool)


def _class_mask_score(tables, cyc, state):
    """[SC, N] Filter mask + Score for every class against `state` — the
    dense analog of findNodesThatFit + prioritizeNodes, once per class.

    Long-context tiling (SURVEY §5 "blockwise tiles over the pod axis"):
    vmapping the full row over SC materializes per-class intermediates like
    [SC, S, N] domain gathers — fine at the class-interned SC of replicated
    workloads, but with thousands of DISTINCT pod specs SC approaches P and
    those temporaries outgrow HBM long before the [SC, N] outputs do. Above
    _CLASS_BLOCK classes the vmap runs under lax.map over class blocks, so
    peak intermediate memory is bounded by block size while outputs stay the
    full lattice (the same shape the rest of the wave consumes)."""
    classes = tables.classes
    SC = classes.valid.shape[0]

    def row(c):
        mask = pod_mask_row(tables, cyc, state, c, jnp.int32(-1),
                            classes.valid[c])
        score = score_row(tables, cyc, state, c)
        return mask, jnp.where(mask, score, -jnp.inf)

    if SC <= _CLASS_BLOCK:
        return jax.vmap(row)(jnp.arange(SC))
    n_blocks = -(-SC // _CLASS_BLOCK)
    blocks = jnp.arange(n_blocks * _CLASS_BLOCK, dtype=jnp.int32).reshape(
        n_blocks, _CLASS_BLOCK)
    # padded tail indexes clamp to SC-1; the duplicate rows are sliced off
    masks, scores = lax.map(
        lambda blk: jax.vmap(row)(jnp.minimum(blk, SC - 1)), blocks)
    return (masks.reshape(-1, masks.shape[-1])[:SC],
            scores.reshape(-1, scores.shape[-1])[:SC])


def _domain_quota_pass(tables, cyc, state, mask, order_n, allowed_sorted):
    """AND per-domain admission quotas into `allowed_sorted` [SC, N] (nodes in
    per-class score order). Quotas keep same-wave same-class admissions from
    violating hard spread / self-anti-affinity when replayed sequentially."""
    classes = tables.classes
    nodes = tables.nodes
    terms = tables.terms
    D = cyc.ELD.shape[2] - 1
    SC, N = mask.shape
    TS = classes.tsc_term.shape[1]
    AN = classes.anti_terms.shape[1]

    def slot_quota(c, s_id, topo_key, active, quota_d):
        """quota_d: [D+1] cap per domain; returns [N] allowed-in-sorted-order
        for this class/slot. rank-in-domain is computed by a (domain, score
        rank) lexsort — O(N log N), never materializing an [N, D] one-hot
        (D can be N itself for hostname-keyed constraints)."""
        k = jnp.maximum(topo_key, 0)
        dom = jnp.where((topo_key >= 0) & nodes.valid, nodes.domain[:, k], -1)
        dom_sorted = dom[order_n[c]]                  # [N] score-desc order
        dsafe = jnp.where(dom_sorted >= 0, dom_sorted, D)
        # stable-sort score-ordered positions by domain: within each domain
        # group the score order is preserved, so rank-in-domain = index in
        # the grouped array minus the group's start index
        gidx = jnp.arange(N, dtype=jnp.int32)
        grp = jnp.argsort(dsafe, stable=True)         # grouped order
        dom_g = dsafe[grp]
        start = jnp.full((D + 1,), N, jnp.int32).at[dom_g].min(gidx)
        rank_g = gidx - start[dom_g]
        rank_in_dom = jnp.zeros((N,), jnp.int32).at[grp].set(rank_g)
        return ~active | (rank_in_dom < quota_d[dsafe])

    # --- hard topology-spread slots (only self-matching classes move their
    # own counts; others are quota-free here and guarded by the graph).
    # Slots are a vmapped axis, not a Python loop: the traced graph stays the
    # same size no matter how many TS/AN slots the constraint schema needs. ---
    def spread_slot(c, t):
        s_id = classes.tsc_term[c, t]
        s = jnp.maximum(s_id, 0)
        active = (
            (s_id >= 0) & classes.tsc_hard[c, t] & cyc.TM[s, c]
        )
        eld = cyc.ELD[c, t, :D]
        active = active & eld.any()
        k = terms.topo_key[s]
        dom = jnp.where((k >= 0) & nodes.valid,
                        nodes.domain[:, jnp.maximum(k, 0)], -1)
        seg = domain_agg(state.CNT[s][None], dom[None], D,
                         eligible=cyc.static.node_match[c][None])[0]
        min_cnt = jnp.min(jnp.where(eld, seg[:D], _I32_MAX))
        quota = jnp.clip(
            classes.tsc_maxskew[c, t] + min_cnt - seg, 0, _I32_MAX
        )
        quota = jnp.where(active, quota, _I32_MAX)
        return slot_quota(c, s_id, k, active, quota)

    rows = jax.vmap(
        lambda c: jax.vmap(lambda t: spread_slot(c, t))(
            jnp.arange(TS, dtype=jnp.int32))
    )(jnp.arange(SC, dtype=jnp.int32))            # [SC, TS, N]
    allowed_sorted = allowed_sorted & rows.all(axis=1)

    # --- self-matching anti-affinity slots: one per domain per wave ---
    def anti_slot(c, t):
        s_id = classes.anti_terms[c, t]
        s = jnp.maximum(s_id, 0)
        k = terms.topo_key[s]
        active = (s_id >= 0) & cyc.TM[s, c] & (k >= 0)
        quota = jnp.where(active, jnp.ones((D + 1,), jnp.int32),
                          _I32_MAX)
        return slot_quota(c, s_id, k, active, quota)

    rows = jax.vmap(
        lambda c: jax.vmap(lambda t: anti_slot(c, t))(
            jnp.arange(AN, dtype=jnp.int32))
    )(jnp.arange(SC, dtype=jnp.int32))            # [SC, AN, N]
    allowed_sorted = allowed_sorted & rows.all(axis=1)

    return allowed_sorted


def _escape_cap(tables, cyc, state, r):
    """Required-affinity first-pod escape: a class whose required terms have
    zero potential matches (predicates.go:1436-1440) admits at most ONE pod
    this wave, so the followers see its counts next wave."""
    classes = tables.classes
    terms = tables.terms
    nodes = tables.nodes

    def one(c):
        ats = classes.aff_terms[c]
        s = jnp.maximum(ats, 0)
        active = ats >= 0
        k = terms.topo_key[s]
        has_key = (k[:, None] >= 0) & nodes.valid[None, :]
        total = jnp.sum(jnp.where(active[:, None] & has_key,
                                  state.CNT[s], 0))
        return active.any() & (total == 0)

    escape = jax.vmap(one)(jnp.arange(classes.valid.shape[0]))
    return jnp.where(escape, jnp.minimum(r, 1), r)


def assign_waves(
    tables: ClusterTables,
    cyc: CycleArrays,
    pods: PodArrays,
    init: AssignState,
    max_waves: int | None = None,
    return_waves: bool = False,
) -> AssignResult:
    """Drop-in replacement for ops/assign.py:assign_batch (same signature,
    same result type). See the module docstring for the algorithm."""
    classes = tables.classes
    nodes = tables.nodes
    SC = classes.valid.shape[0]
    N = nodes.valid.shape[0]
    P = pods.valid.shape[0]
    R = tables.reqs.vec.shape[1]

    G = interaction_graph(tables, cyc)
    req_by_class = tables.reqs.vec[jnp.maximum(classes.rid, 0)]  # [SC, R]

    # --- queue order, grouped by class (activeQ comparator within class) ---
    cls_safe = jnp.where(pods.valid, pods.cls, SC)
    sorted_pods = jnp.lexsort((pods.creation, -pods.priority, cls_safe))  # [P]
    class_total = (
        jnp.zeros((SC + 1,), jnp.int32)
        .at[cls_safe].add(1)[:SC]
    )
    class_offset = jnp.cumsum(class_total) - class_total  # [SC] exclusive
    sorted_pods_pad = jnp.concatenate(
        [sorted_pods, jnp.full((1,), P, jnp.int32)])
    pos_in_class = jnp.arange(P, dtype=jnp.int32) - class_offset[
        jnp.minimum(cls_safe[sorted_pods], SC - 1)]
    pri_sorted = pods.priority[sorted_pods]
    cls_sorted = jnp.minimum(cls_safe[sorted_pods], SC - 1)
    sorted_valid = pods.valid[sorted_pods]

    def body(carry: _WaveCarry) -> _WaveCarry:
        state, cursor, placed, node_out, wave_out, waves = carry
        remaining = class_total - cursor
        active = classes.valid & (remaining > 0)

        # next pending pod per class → tier selection
        nxt = sorted_pods_pad[jnp.minimum(class_offset + cursor, P)]
        nxt_ok = active & (nxt < P)
        nxt_safe = jnp.minimum(nxt, P - 1)
        # i32 min is the neutral element, not a magic sentinel: in_tier also
        # requires nxt_ok, so even real INT32_MIN priorities tier correctly
        nxt_pri = jnp.where(nxt_ok, pods.priority[nxt_safe], _I32_MIN)
        nxt_cre = jnp.where(nxt_ok, pods.creation[nxt_safe], _I32_MAX)
        tier = nxt_pri.max()
        in_tier = nxt_ok & (nxt_pri == tier)

        # length of the tier run per class (pods at exactly this priority
        # remaining at/after the cursor)
        tier_pod = (
            sorted_valid & (pri_sorted == tier)
            & (pos_in_class >= cursor[cls_sorted])
        )
        tier_cnt = (
            jnp.zeros((SC,), jnp.int32).at[cls_sorted].add(
                tier_pod.astype(jnp.int32))
        )
        r = jnp.where(in_tier, jnp.minimum(remaining, tier_cnt), 0)

        mask, score = _class_mask_score(tables, cyc, state)
        mask = mask & in_tier[:, None]
        r = _escape_cap(tables, cyc, state, r)

        # independent set over the interaction graph, queue-rank order:
        # a class yields to any earlier-ranked in-tier class it interacts with
        rank_key = jnp.lexsort((nxt_cre, -nxt_pri))          # [SC] perm
        crank = jnp.zeros((SC,), jnp.int32).at[rank_key].set(
            jnp.arange(SC, dtype=jnp.int32))
        earlier = crank[None, :] < crank[:, None]            # [SC, SC]
        blocked = (G & earlier & in_tier[None, :]).any(axis=1)
        attempted = in_tier & ~blocked & (r > 0)
        r = jnp.where(attempted, r, 0)

        # per-class admission: top-r feasible nodes by score, domain quotas
        order_n = jnp.argsort(-score, axis=1)                # [SC, N]
        feas_sorted = jnp.take_along_axis(mask, order_n, axis=1)
        allowed = _domain_quota_pass(
            tables, cyc, state, mask, order_n, feas_sorted)
        grank = jnp.cumsum(allowed.astype(jnp.int32), axis=1) - 1
        adm_sorted = allowed & (grank < r[:, None])
        A = jnp.zeros((SC, N), bool).at[
            jnp.arange(SC)[:, None], order_n].set(adm_sorted)

        # per-node cross-class resolution in queue-rank order
        cord = rank_key                                       # [SC] perm
        A_ord = A[cord]
        req_ord = req_by_class[cord]                          # [SC, R]
        add = jnp.where(A_ord[:, :, None], req_ord[:, None, :], 0)
        cum_exc = jnp.cumsum(add, axis=0) - add               # [SC, N, R]
        # earlier same-wave classes consume free space; the pod itself must
        # fit per PodFitsResources semantics (zero scalar requests ignore
        # that scalar's free — fit._fit, predicates.go:800-845)
        free = nodes.alloc[None] - state.used[None] - cum_exc
        fits = _fit(req_ord[:, None, :], free)
        keep = A_ord & fits

        ps_ord = classes.portset[cord]
        psafe = jnp.maximum(ps_ord, 0)
        has_p = (ps_ord >= 0)
        pairw = tables.portsets.pair_words[psafe]             # [SC, PWp]
        wildw = tables.portsets.wild_words[psafe]
        tripw = tables.portsets.trip_words[psafe]
        kp = (keep & has_p[:, None])[:, :, None]
        scan_or = lambda W: lax.associative_scan(
            jnp.bitwise_or, jnp.where(kp, W[:, None, :], 0), axis=0)
        inc_p, inc_w, inc_t = scan_or(pairw), scan_or(wildw), scan_or(tripw)
        shift = lambda M: jnp.concatenate(
            [jnp.zeros_like(M[:1]), M[:-1]], axis=0)
        exc_p, exc_w, exc_t = shift(inc_p), shift(inc_w), shift(inc_t)
        conflict = (
            ((wildw[:, None, :] & exc_p) != 0)
            | ((pairw[:, None, :] & exc_w) != 0)
            | ((tripw[:, None, :] & exc_t) != 0)
        ).any(-1)
        keep = keep & (~has_p[:, None] | ~conflict)

        # volume conflict/limits against same-wave earlier classes on the
        # same node (the per-node cumulative pass, like ports): exclusive-
        # prefix OR of volume words, then re-check conflict + attach limits
        vs_ord = classes.volset[cord]
        vsafe = jnp.maximum(vs_ord, 0)
        has_v = (vs_ord >= 0)
        vanyw = tables.volsets.any_words[vsafe]               # [SC, VW]
        vrww = tables.volsets.rw_words[vsafe]
        kv = (keep & has_v[:, None])[:, :, None]
        scan_orv = lambda W: lax.associative_scan(
            jnp.bitwise_or, jnp.where(kv, W[:, None, :], 0), axis=0)
        exc_va, exc_vr = (shift(scan_orv(vanyw)), shift(scan_orv(vrww)))
        tot_any = state.vol_any[None] | exc_va                # [SC, N, VW]
        tot_rw = state.vol_rw[None] | exc_vr
        vconf = (
            ((vanyw[:, None, :] & tot_rw) != 0)
            | ((vrww[:, None, :] & tot_any) != 0)
        ).any(-1)
        after_v = tot_any | vanyw[:, None, :]
        vcnt = jax.lax.population_count(
            after_v[:, :, None, :] & tables.drv_masks[None, None, :, :]
        ).sum(-1).astype(jnp.int32)                           # [SC, N, DR]
        vlim = nodes.vol_limit[None]                          # [1, N, DR]
        vlim_ok = ((vlim < 0) | (vcnt <= vlim)).all(-1)
        keep = keep & (~has_v[:, None] | (~vconf & vlim_ok))

        # committed port + volume words (kept classes only)
        kp2 = (keep & has_p[:, None])[:, :, None]
        or_last = lambda W: lax.associative_scan(
            jnp.bitwise_or, jnp.where(kp2, W[:, None, :], 0), axis=0)[-1]
        orp, orw, ort = or_last(pairw), or_last(wildw), or_last(tripw)
        kv2 = (keep & has_v[:, None])[:, :, None]
        or_lastv = lambda W: lax.associative_scan(
            jnp.bitwise_or, jnp.where(kv2, W[:, None, :], 0), axis=0)[-1]
        orva, orvr = or_lastv(vanyw), or_lastv(vrww)

        A_final = jnp.zeros_like(A).at[cord].set(keep)
        m = A_final.sum(axis=1).astype(jnp.int32)             # [SC]
        total = m.sum()

        # ---- commit ----
        Ai = A_final.astype(jnp.int32)
        used2 = state.used + jnp.einsum("cn,cr->nr", Ai, req_by_class)
        CNT2 = state.CNT + cyc.TM.astype(jnp.int32) @ Ai
        HOLD2 = state.HOLD + cyc.has_anti.T.astype(jnp.int32) @ Ai
        WSYM2 = state.WSYM + cyc.WCOLS @ Ai.astype(jnp.float32)
        state2 = AssignState(
            used=used2,
            ppa=state.ppa | orp, ppw=state.ppw | orw, ppt=state.ppt | ort,
            CNT=CNT2, HOLD=HOLD2, WSYM=WSYM2,
            vol_any=state.vol_any | orva, vol_rw=state.vol_rw | orvr,
        )

        # ---- map admissions back to pods (rank among kept, score order) ----
        sck = jnp.where(A_final, score, -jnp.inf)
        ordk = jnp.argsort(-sck, axis=1)
        kept_sorted = jnp.take_along_axis(A_final, ordk, axis=1)
        rank_sorted = jnp.cumsum(kept_sorted.astype(jnp.int32), axis=1) - 1
        rank = jnp.zeros((SC, N), jnp.int32).at[
            jnp.arange(SC)[:, None], ordk].set(rank_sorted)
        tgt = jnp.where(A_final, class_offset[:, None] + cursor[:, None] + rank,
                        P)
        pod_id = jnp.where(A_final, sorted_pods_pad[jnp.minimum(tgt, P)], P)
        node_out2 = node_out.at[pod_id.reshape(-1)].set(
            jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32)[None, :],
                             (SC, N)).reshape(-1))
        wave_out2 = wave_out.at[pod_id.reshape(-1)].set(waves)

        # zero-progress ⇒ state is frozen ⇒ the whole tier run of every
        # attempting class fails exactly as it would pod-by-pod in the scan
        fail = total == 0
        consume = jnp.where(fail & attempted,
                            jnp.minimum(tier_cnt, remaining), m)
        return _WaveCarry(
            state=state2, cursor=cursor + consume, placed=placed + m,
            node_out=node_out2, wave_out=wave_out2, waves=waves + 1,
        )

    cap = jnp.int32(max_waves if max_waves is not None else 2 * P + 2)

    def cond(carry: _WaveCarry) -> Array:
        remaining = (class_total - carry.cursor)
        return ((remaining > 0) & tables.classes.valid).any() & (
            carry.waves < cap)

    init_carry = _WaveCarry(
        state=init,
        cursor=jnp.zeros((SC,), jnp.int32),
        placed=jnp.zeros((SC,), jnp.int32),
        node_out=jnp.full((P + 1,), -1, jnp.int32),
        wave_out=jnp.full((P + 1,), -1, jnp.int32),
        waves=jnp.int32(0),
    )
    final = lax.while_loop(cond, body, init_carry)
    node = final.node_out[:P]
    result = AssignResult(node=node, feasible=node >= 0, state=final.state)
    if return_waves:
        return result, final.wave_out[:P]
    return result
