"""Run-length-collapsed admission: schedule replica RUNS, not pods.

The sequential scan (ops/assign.py) pays one O(S·N) step per POD — 50k
serialized steps at the north-star shape — even though `intern_pods`
(state/encode.py) already proves most pending pods are value-identical
replicas of a few hundred classes (a Deployment/Job backlog). Queue order
(priority desc, creation asc) keeps one controller's replicas ADJACENT, so
the pending wave factors into runs of consecutive same-class pods. This
engine scans one step per RUN and places a whole run per step:

  1. the queue-ordered wave is run-length encoded ON DEVICE (so gang
     rejection rounds, which re-mask validity mid-program, re-derive their
     own runs); the host supplies only the static run-capacity bound RC
     (`plan_runs` — masking pods can merge or shrink runs, never split
     them, so the unmasked host count bounds every gang round);
  2. per run, the class's expensive row CONTEXT — static lattice gathers,
     inter-pod affinity/anti-affinity, hard spread, the count-aggregated
     score components — is evaluated ONCE (ops/assign.py mask_context_row /
     score_context_row). This is sound for SELF-INTERACTION-FREE classes:
     classes none of whose read terms match the class itself (and that hold
     no anti-term/symmetric-weight on a term matching themselves), so their
     own placements move state only at the placed node, through the cheap
     dynamic components (resources, ports, volumes);
  3. the run's replicas are placed by a capacity waterfill over admission
     EPOCHS: each epoch sorts the live per-node head scores (score desc,
     node index asc — the argmax tie-break) and admits the longest prefix
     of distinct nodes that provably reproduces the per-pod argmax chain —
     position i+1 admits only if its head beats the running argmax of the
     already-admitted nodes' POST-placement heads, both sides computed by
     the exact shared expression tree (score_combine_row /
     mask_dynamic_row) the scan itself evaluates, so every rounding is
     identical and the admitted sequence is bit-equal to the scan's. The
     per-node admission capacity (min over resources of ⌊free/req⌋, the
     port/volume self-conflict clamp to one replica per node) enters
     through the same recomputed dynamic mask, not a parallel formula;
  4. runs whose class self-interacts (self-anti-affinity, self-matching
     affinity/spread/spread-selector terms, symmetric weight on a
     self-matching term) and runs pinned by spec.nodeName fall back to a
     per-pod inner loop executing the scan's exact step body
     (assign_step) — correctness never depends on the closed form firing.

Placements are BIT-EQUAL to assign_batch by construction — this is a pure
execution-schedule optimization with the same sequential assume semantics;
the serial chain shrinks from P steps to (#runs) steps plus cheap
per-epoch work (tests/test_runs.py enforces equality across golden, gang,
preemption, and mesh paths; docs/PERF.md round 8 has the scan-length math).

One documented state-representation nit: for classes with all-zero
symmetric weights the scan still ADDS 0.0 into WSYM per placement (which
canonicalizes a -0.0 cell to +0.0); this engine skips the no-op adds.
Score arithmetic and comparisons are sign-of-zero-blind there, so
placements are unaffected — only the WSYM plane can differ in the sign of
zeros.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..state.arrays import Array, ClusterTables, PodArrays
from ..state.dims import bucket
from .assign import (
    AssignResult,
    AssignState,
    assign_step,
    mask_context_row,
    mask_dynamic_row,
    queue_order,
    score_combine_row,
    score_context_row,
)

# floor for the bucketed run capacity: keeps the compile-signature count low
# when tiny batches produce a handful of runs
RC_MIN = 16


class RunPlan(NamedTuple):
    """Host-side sizing of one run-collapsed dispatch. Only `rc` (the
    bucketed static scan length) enters the compiled program; the rest is
    telemetry (CycleStats.class_runs / collapse_ratio). Emitted alongside
    the pending arrays by the snapshot (state/cache.py) — pure host
    metadata, so snapshots stay patch-compatible."""

    rc: int       # static run-axis capacity (bucketed, ≥ n_runs)
    n_runs: int   # actual class runs in the unmasked wave
    n_valid: int  # valid pending pods covered by those runs

    @property
    def collapse_ratio(self) -> float:
        """Scan-step reduction vs the per-pod engine: P_valid / runs."""
        return self.n_valid / max(self.n_runs, 1)


def plan_runs(cls, priority, creation, valid, node_name_req) -> RunPlan:
    """Count the class runs of the queue-ordered wave on the HOST (numpy
    over the staging columns — no device readback on the cache path) and
    bucket the count into the static scan length. The sort replicates
    queue_order exactly — including int32 negation wraparound on
    INT32_MIN priorities — so the host count matches the device RLE;
    runtime re-masking (gang rejection rounds) can only merge or shrink
    runs, so this is an upper bound for every round of the dispatch."""
    cls = np.asarray(cls)
    valid = np.asarray(valid).astype(bool)
    nnr = np.asarray(node_name_req)
    negpri = (-(np.asarray(priority).astype(np.int64))).astype(np.int32)
    order = np.lexsort((np.asarray(creation), negpri, ~valid))
    v = valid[order]
    n_valid = int(v.sum())
    if n_valid == 0:
        return RunPlan(rc=RC_MIN, n_runs=0, n_valid=0)
    c = cls[order][:n_valid]
    nn = nnr[order][:n_valid]
    brk = np.ones((n_valid,), bool)
    brk[1:] = (c[1:] != c[:-1]) | (nn[1:] != nn[:-1])
    n_runs = int(brk.sum())
    return RunPlan(rc=bucket(n_runs, minimum=RC_MIN),
                   n_runs=n_runs, n_valid=n_valid)


def self_interaction_vector(tables: ClusterTables, cyc) -> Array:
    """[SC] bool: classes whose own placements can feed back into their own
    Filter/Score rows — through a read term that matches the class itself
    (required/anti affinity, preferred affinity/anti, topology spread,
    SelectorSpread owners), or through an anti-term/symmetric-weight the
    class WRITES on a term that matches it. Such runs take the per-pod
    fallback; everything else gets the closed-form waterfill."""
    classes = tables.classes
    TM = cyc.TM  # [S, SC]
    SC = classes.valid.shape[0]
    cid = jnp.arange(SC, dtype=jnp.int32)

    def own_hit(ids: Array) -> Array:  # [SC, A] term slots → [SC]
        safe = jnp.maximum(ids, 0)
        hit = TM[safe, cid[:, None]] & (ids >= 0)
        return hit.any(axis=1)

    reads_self = (
        own_hit(classes.aff_terms) | own_hit(classes.anti_terms)
        | own_hit(classes.paff_terms) | own_hit(classes.panti_terms)
        | own_hit(classes.tsc_term) | own_hit(classes.ssel_terms)
    )
    # writes on a term matching me: HOLD via my anti membership, WSYM via
    # my symmetric weight column — both read back by my own row through
    # blocked_sym / sym_affinity_contrib
    matches_me = TM.T  # [SC, S]
    writes_self = (
        matches_me & (cyc.has_anti | (cyc.WCOLS.T != 0.0))
    ).any(axis=1)
    return reads_self | writes_self


def _encode_runs(pods: PodArrays, rc: int):
    """Device-side run-length encoding of the queue-ordered wave: maximal
    stretches of consecutive (class, nodeName-requirement)-identical VALID
    pods. Invalid pods sort last (queue_order's primary key), so the valid
    region is a prefix and every run is contiguous in sorted order."""
    P = pods.valid.shape[0]
    order = queue_order(pods)
    valid_s = pods.valid[order]
    cls_s = jnp.where(valid_s, pods.cls[order], -1)
    nnr_s = pods.node_name_req[order]
    pos = jnp.arange(P, dtype=jnp.int32)
    prev_cls = jnp.concatenate([jnp.full((1,), -2, jnp.int32), cls_s[:-1]])
    prev_nnr = jnp.concatenate([jnp.full((1,), -2, jnp.int32), nnr_s[:-1]])
    newrun = valid_s & ((cls_s != prev_cls) | (nnr_s != prev_nnr))
    rid = jnp.cumsum(newrun.astype(jnp.int32)) - 1
    rid = jnp.where(valid_s, rid, rc)  # discard slot for invalid pods
    run_len = jnp.zeros((rc,), jnp.int32).at[rid].add(1, mode="drop")
    run_start = jnp.full((rc,), P, jnp.int32).at[rid].min(pos, mode="drop")
    run_cls = jnp.zeros((rc,), jnp.int32).at[rid].max(
        jnp.maximum(cls_s, 0), mode="drop")
    run_nnr = jnp.full((rc,), -1, jnp.int32).at[rid].max(nnr_s, mode="drop")
    n_runs = newrun.sum()
    return order, run_start, run_len, run_cls, run_nnr, n_runs


def _perpod_run(tables, cyc, pods, state, node_out, order, k, start):
    """Fallback: the run's pods one at a time through the scan's exact step
    body — self-interacting classes and nodeName-pinned runs, where the
    closed form's frozen context would be unsound."""
    P = pods.valid.shape[0]

    def body(t, carry):
        state, node_out = carry
        idx = order[jnp.minimum(start + t, P - 1)]
        state, node, _feas = assign_step(
            tables, cyc, state, pods.cls[idx], pods.valid[idx],
            pods.node_name_req[idx])
        node_out = node_out.at[idx].set(node)
        return (state, node_out)

    return lax.fori_loop(0, k, body, (state, node_out))


def _closed_run(tables, cyc, pods, state, node_out, order, c, k, start):
    """The run-collapsed waterfill for one self-interaction-free run of `k`
    replicas of class `c`: admission epochs over the exact per-node head
    scores (module docstring, step 3). All float values flow through the
    SAME expression tree the scan evaluates (score_combine_row /
    mask_dynamic_row on synthesized per-node planes), so the admitted
    node sequence is bit-equal to the per-pod argmax chain."""
    classes = tables.classes
    nodes = tables.nodes
    N = nodes.valid.shape[0]
    P = pods.valid.shape[0]
    req_vec = tables.reqs.vec[classes.rid[c]]  # [R]
    ps = classes.portset[c]
    psafe = jnp.maximum(ps, 0)
    live_ps = ps >= 0
    pw = jnp.where(live_ps, tables.portsets.pair_words[psafe], 0)
    ww = jnp.where(live_ps, tables.portsets.wild_words[psafe], 0)
    tw = jnp.where(live_ps, tables.portsets.trip_words[psafe], 0)
    vs = classes.volset[c]
    vsafe = jnp.maximum(vs, 0)
    live_vs = vs >= 0
    va = jnp.where(live_vs, tables.volsets.any_words[vsafe], 0)
    vr = jnp.where(live_vs, tables.volsets.rw_words[vsafe], 0)

    # frozen per-run context: one expensive row evaluation per RUN (this is
    # the collapse — the scan pays these gathers per POD)
    ctx_mask = mask_context_row(tables, cyc, state, c, jnp.int32(-1), k > 0)
    sctx = score_context_row(tables, cyc, state, c)
    w_vec = cyc.WCOLS[:, c]  # [S] — zero for classes without preferences

    def words(base, own, placed):
        # node n's port/volume plane after its own placements: idempotent
        # OR, so one placement and j placements synthesize identically
        return base | jnp.where(placed[:, None], own[None, :], 0)

    def row_at(j, placed):
        """(mask, score) each [N]: the class's NEXT replica's row when node
        n already took j[n] replicas this run — exactly what the scan
        recomputes per pod, vectorized over nodes."""
        used_j = state.used + j[:, None] * req_vec[None, :]
        dyn = mask_dynamic_row(
            tables, cyc, c, used_j,
            words(state.ppa, pw, placed), words(state.ppw, ww, placed),
            words(state.ppt, tw, placed),
            words(state.vol_any, va, placed), words(state.vol_rw, vr, placed))
        m = ctx_mask & dyn
        s = score_combine_row(tables, cyc, c, used_j, sctx)
        return m, jnp.where(m, s, -jnp.inf)

    iota_n = jnp.arange(N, dtype=jnp.int32)

    def epoch(carry):
        j, remaining, consumed, node_out, _alive = carry
        placed = j > 0
        _m, cur = row_at(j, placed)
        _mp, plus = row_at(j + 1, jnp.ones_like(placed))
        ordn = jnp.argsort(-cur, stable=True)  # ties → lowest node index
        e = cur[ordn]    # head score of the i-th best node
        ep = plus[ordn]  # that node's head AFTER it admits one replica
        # running argmax (value desc, node index asc on ties) over the
        # POST-placement heads of the prefix — what the scan's argmax sees
        # from the nodes already admitted this epoch

        def comb(a, b):
            av, ai = a
            bv, bi = b
            take_b = (bv > av) | ((bv == av) & (bi < ai))
            return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

        Mv, Mi = lax.associative_scan(comb, (ep, ordn))
        prevMv = jnp.concatenate(
            [jnp.full((1,), -jnp.inf, cur.dtype), Mv[:-1]])
        prevMi = jnp.concatenate([jnp.full((1,), N, jnp.int32), Mi[:-1]])
        beats = (e > prevMv) | ((e == prevMv) & (ordn < prevMi))
        okpos = beats & (e != -jnp.inf) & (iota_n < remaining)
        T = jnp.cumprod(okpos.astype(jnp.int32)).sum()
        take = iota_n < T
        # replica (consumed + i) of the run → node ordn[i], i < T — the
        # scan's per-pod sequence for this stretch
        sp = start + consumed + iota_n
        pid = jnp.where(take, order[jnp.minimum(sp, P - 1)], P)
        node_out = node_out.at[pid].set(ordn)
        placed_now = jnp.zeros((N,), bool).at[ordn].set(take)
        j = j + placed_now.astype(jnp.int32)
        return (j, remaining - T, consumed + T, node_out, T > 0)

    def cond(carry):
        _j, remaining, _consumed, _no, alive = carry
        return (remaining > 0) & alive

    j0 = jnp.zeros((N,), jnp.int32)
    j, _rem, _cons, node_out, _alive = lax.while_loop(
        cond, epoch, (j0, k, jnp.int32(0), node_out, k > 0))

    # ---- commit the whole run to the carry (int/bitset closed forms are
    # exact; the WSYM float column replays the scan's per-placement add
    # chain so later runs see bit-identical weights) ----
    placed = j > 0
    used_f = state.used + j[:, None] * req_vec[None, :]
    ppa_f = words(state.ppa, pw, placed)
    ppw_f = words(state.ppw, ww, placed)
    ppt_f = words(state.ppt, tw, placed)
    vol_any_f = words(state.vol_any, va, placed)
    vol_rw_f = words(state.vol_rw, vr, placed)
    CNT_f = state.CNT + cyc.TM[:, c].astype(jnp.int32)[:, None] * j[None, :]
    HOLD_f = state.HOLD \
        + cyc.has_anti[c].astype(jnp.int32)[:, None] * j[None, :]

    def wsym_chain(W):
        # fl(x+w) applied j[n] times per column — the scan's exact rounding
        # sequence (j·w in one multiply would round differently)
        def add_round(carry):
            W, t = carry
            W = W + jnp.where((j > t)[None, :], w_vec[:, None], 0.0)
            return (W, t + 1)

        maxj = jnp.max(j)
        return lax.while_loop(lambda carry: carry[1] < maxj,
                              add_round, (W, jnp.int32(0)))[0]

    WSYM_f = lax.cond((w_vec != 0.0).any(), wsym_chain,
                      lambda W: W, state.WSYM)

    state_f = AssignState(
        used=used_f, ppa=ppa_f, ppw=ppw_f, ppt=ppt_f,
        CNT=CNT_f, HOLD=HOLD_f, WSYM=WSYM_f,
        vol_any=vol_any_f, vol_rw=vol_rw_f)
    return (state_f, node_out)


def assign_runs(
    tables: ClusterTables,
    cyc,
    pods: PodArrays,
    init: AssignState,
    rc: int,
) -> AssignResult:
    """Drop-in engine with assign_batch's signature plus the static run
    capacity `rc` (host-computed bound, plan_runs). Placements are bit-equal
    to the per-pod scan; the serial chain is one step per RUN."""
    P = pods.valid.shape[0]
    rc = int(rc)
    order, run_start, run_len, run_cls, run_nnr, n_runs = _encode_runs(
        pods, rc)
    selfi = self_interaction_vector(tables, cyc)

    def run_step(carry, r):
        state, node_out = carry
        active = r < n_runs
        c = run_cls[r]
        k = jnp.where(active, run_len[r], 0)
        start = run_start[r]
        nnr = run_nnr[r]
        closed_ok = ~selfi[c] & (nnr < 0)
        state, node_out = lax.cond(
            closed_ok,
            lambda s, no: _closed_run(tables, cyc, pods, s, no, order,
                                      c, k, start),
            lambda s, no: _perpod_run(tables, cyc, pods, s, no, order,
                                      k, start),
            state, node_out)
        return (state, node_out), None

    node_out0 = jnp.full((P + 1,), -1, jnp.int32)
    (final, node_out), _ = lax.scan(
        run_step, (init, node_out0), jnp.arange(rc, dtype=jnp.int32))
    node = node_out[:P]
    return AssignResult(node=node, feasible=node >= 0, state=final)
