"""Tensor kernels: the reference's per-(pod,node) Go predicates/priorities
re-expressed as batched XLA computations over interned class tables."""
