"""Vectorized label/selector matching.

The tensor re-statement of apimachinery's labels.Requirement.Matches
(staging/src/k8s.io/apimachinery/pkg/labels/selector.go:192-215) and
v1helper.MatchNodeSelectorTerms. A label *set* is two parallel id arrays
(keys, vals) padded with -1; a requirement is (key, op, values[V], int_rhs).

Everything is pure broadcasting over small trailing axes (L, Q, V) so XLA fuses
the whole thing into one elementwise kernel; the big axes (terms × nodes or
terms × labelsets) map onto the VPU lanes.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..api.types import Op
from ..state.arrays import Array, LabelSetTable, NodeArrays, NodeTermTable, TermTable
from ..state.vocab import INT_SENTINEL


def _lookup(label_keys: Array, label_vals: Array, key: Array) -> tuple[Array, Array]:
    """label_keys/vals: [..., L]; key: [...] → (has: [...], val: [...]).
    Keys are unique within a set; -1 pads never match (-1 keys vs key>=0)."""
    eq = (label_keys == key[..., None]) & (key[..., None] >= 0)
    has = eq.any(-1)
    val = jnp.max(jnp.where(eq, label_vals, -1), axis=-1)
    return has, val


def _lookup_int(label_keys: Array, label_ints: Array, key: Array) -> Array:
    eq = (label_keys == key[..., None]) & (key[..., None] >= 0)
    return jnp.max(jnp.where(eq, label_ints, INT_SENTINEL), axis=-1)


def match_requirements(
    req_keys: Array,   # [..., Q]
    req_ops: Array,    # [..., Q]
    req_vals: Array,   # [..., Q, V]
    req_ints: Array,   # [..., Q] (or None)
    label_keys: Array, # [..., L]
    label_vals: Array, # [..., L]
    label_ints: Array, # [..., L] (or None)
) -> Array:
    """AND over Q requirements (padded key == -1 ⇒ vacuously true) → [...] bool.
    Semantics per labels/selector.go:192-215:
      IN:             has && val ∈ values
      NOT_IN:         !has || val ∉ values          (absent key satisfies NotIn)
      EXISTS:         has
      DOES_NOT_EXIST: !has
      GT/LT:          has && int(val) <op> rhs      (non-numeric never matches)
    """
    lk = label_keys[..., None, :]  # [..., 1(Q), L]
    lv = label_vals[..., None, :]
    has, val = _lookup(lk, lv, req_keys)  # [..., Q]
    in_vals = ((val[..., None] == req_vals) & (req_vals >= 0)).any(-1)  # [..., Q]

    is_pad = req_keys < 0
    res_in = has & in_vals
    res_notin = (~has) | (~in_vals)
    res_exists = has
    res_dne = ~has

    if label_ints is not None and req_ints is not None:
        ival = _lookup_int(lk, label_ints[..., None, :], req_keys)
        # both sides must parse as ints (selector.go:208-233); a non-numeric
        # RHS is encoded as INT_SENTINEL and never matches
        numeric = has & (ival != INT_SENTINEL) & (req_ints != INT_SENTINEL)
        res_gt = numeric & (ival > req_ints)
        res_lt = numeric & (ival < req_ints)
    else:
        res_gt = jnp.zeros_like(has)
        res_lt = jnp.zeros_like(has)

    per_req = jnp.select(
        [
            is_pad,
            req_ops == Op.IN,
            req_ops == Op.NOT_IN,
            req_ops == Op.EXISTS,
            req_ops == Op.DOES_NOT_EXIST,
            req_ops == Op.GT,
        ],
        [jnp.ones_like(has), res_in, res_notin, res_exists, res_dne, res_gt],
        res_lt,
    )
    return per_req.all(-1)


def node_term_matrix(nterms: NodeTermTable, nodes: NodeArrays) -> Array:
    """[SN, N] bool: does node-selector term s match node n.

    v1helper.MatchNodeSelectorTerms: a term is the AND of its matchExpressions
    (against node labels, with Gt/Lt) and matchFields (metadata.name ∈ values);
    an empty/invalid term matches nothing (valid flag)."""
    SN = nterms.keys.shape[0]
    N = nodes.label_keys.shape[0]
    expr_ok = match_requirements(
        nterms.keys[:, None, :],            # [SN, 1, Q]
        nterms.ops[:, None, :],
        nterms.vals[:, None, :, :],
        nterms.ints[:, None, :],
        nodes.label_keys[None, :, :],       # [1, N, L]
        nodes.label_vals[None, :, :],
        nodes.label_ints[None, :, :],
    )  # [SN, N]
    field_hit = (
        (nterms.fields[:, None, :] == nodes.name_id[None, :, None])
        & (nterms.fields[:, None, :] >= 0)
    ).any(-1)  # [SN, N]
    field_ok = (nterms.nfields[:, None] == 0) | field_hit
    return nterms.valid[:, None] & expr_ok & field_ok & nodes.valid[None, :]


def term_labelset_matrix(terms: TermTable, labelsets: LabelSetTable) -> Array:
    """[S, SL] bool: does pod-selector term s's label selector match label set l.
    Label selectors use only IN/NOT_IN/EXISTS/DOES_NOT_EXIST; an empty selector
    matches everything (labels.Everything — all requirements padded)."""
    return match_requirements(
        terms.req_keys[:, None, :],     # [S, 1, Q]
        terms.req_ops[:, None, :],
        terms.req_vals[:, None, :, :],
        None,
        labelsets.keys[None, :, :],     # [1, SL, L]
        labelsets.vals[None, :, :],
        None,
    ) & terms.valid[:, None]


def ns_bit(ns_words: Array, ns_id: Array) -> Array:
    """ns_words: [..., NW] u32 bitset; ns_id: [...] → [...] bool membership."""
    word = jnp.take_along_axis(
        ns_words, jnp.maximum(ns_id[..., None], 0) >> 5, axis=-1
    )[..., 0]
    bit = (word >> (ns_id.astype(jnp.uint32) & 31)) & 1
    return (bit == 1) & (ns_id >= 0)
