"""Attachable-volume predicates as bitset ops: NoDiskConflict + the
max-volume-count family.

Reference semantics:
  * NoDiskConflict (predicates.go:156-221): two mounts of the same volume on
    one NODE conflict unless both are read-only (EBS-style always-conflict
    volumes are modeled read_only=False by the API layer);
  * MaxPDVolumeCount / CSIMaxVolumeLimit (predicates.go:223-…,
    csi_volume_predicate.go:89-160): DISTINCT attachable volumes per driver on
    a node must stay within the node's per-driver limit (CSINode allocatable /
    cloud caps; Node.volume_limits here, -1 = unlimited).

TPU design: the live per-node state is just two u32 bitsets over the volume
vocab — vol_any (attached) and vol_rw (attached read-write) — carried in the
assignment state exactly like the host-port words. Per-driver occupancy is
DERIVED by popcount against static driver masks, so limits need no extra
carry and same-wave commits compose with a bitwise-OR scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..state.arrays import Array, ClusterTables

def volume_components_row(
    tables: ClusterTables,
    vol_any: Array,   # [N, VW] live attached bitset
    vol_rw: Array,    # [N, VW] live read-write bitset
    cls: Array,       # scalar class id
) -> tuple[Array, Array]:
    """([N] conflict_free, [N] limit_ok) for one pod class against the live
    node volume state — the two predicates stay separable so the
    VolumeRestrictions and NodeVolumeLimits plugins can be toggled
    independently."""
    nodes = tables.nodes
    vs = tables.classes.volset[cls]
    safe = jnp.maximum(vs, 0)
    mine_any = tables.volsets.any_words[safe]   # [VW]
    mine_rw = tables.volsets.rw_words[safe]
    absent = vs < 0

    conflict = (
        ((mine_any[None, :] & vol_rw) != 0).any(-1)
        | ((mine_rw[None, :] & vol_any) != 0).any(-1)
    )

    after = vol_any | mine_any[None, :]                       # [N, VW]
    cnt = jax.lax.population_count(
        after[:, None, :] & tables.drv_masks[None, :, :]
    ).sum(-1).astype(jnp.int32)                               # [N, DR]
    lim = nodes.vol_limit                                      # [N, DR]
    limit_ok = ((lim < 0) | (cnt <= lim)).all(-1)

    return absent | ~conflict, absent | limit_ok


def volume_ok_row(tables, vol_any, vol_rw, cls) -> Array:
    """[N] bool: both volume predicates (golden-test / component surface)."""
    c, l = volume_components_row(tables, vol_any, vol_rw, cls)
    return c & l
