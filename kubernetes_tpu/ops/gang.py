"""Gang/co-scheduling: all-or-nothing pod groups on the wave engine
(BASELINE config 5 — 5k nodes × 100k pods in groups).

The reference has no in-tree gang scheduler (BASELINE.md: out-of-tree
coscheduling only); the semantics implemented here are the sig-scheduling
coscheduling protocol — a group of pods carrying a PodGroup with
`spec.minMember` either gets ≥ minMember members placed (counting members
already bound) or none at all — expressed the TPU way:

  1. run the wave engine (ops/waves.py) over the full batch: every group's
     members participate in the dense admission exactly like ungrouped pods,
     so a feasible gang places in the SAME single dispatch as everything
     else — no per-group what-if round-trips;
  2. count per-group placements with one scatter-add; groups that reached
     `needed` commit as-is;
  3. underfilled groups are rejected and the wave fixpoint RESTARTS from the
     original cycle state with the rejected groups' pods masked out — the
     device-resident analog of the Permit plugin rejecting every waiting
     member of a timed-out group (framework/v1alpha1/interface.go:339 +
     waiting_pods_map.go: un-reserving a group returns its resources before
     anyone else binds). Restarting (instead of subtracting the partial
     group post-hoc) is what keeps the committed assignment a valid greedy
     execution: pods that placed *because of* a rejected member (required
     affinity) are re-decided, never left dangling.
  4. rejection order resolves inter-group contention: when two groups split
     a resource pocket and both underfill, the LOWEST-ranked group (min
     member priority, then youngest) is rejected first and the survivors
     re-place into the freed capacity — the batched analog of the
     coscheduling plugin's per-group Permit timeout racing, made
     deterministic. After `soft_rounds` single-rejections the remaining
     underfilled groups reject together (bulk tail for many-group storms).

The loop is a lax.while_loop around the wave fixpoint: zero host round-trips,
one compiled program. Each iteration rejects ≥1 group, so it terminates in
≤ GR+1 iterations; with no underfilled groups it runs the waves exactly once
(the common case pays nothing over plain assign_waves).

Soundness invariant (tests/test_gang.py): for every group, either
placed ≥ needed or placed == 0 — no partial group ever commits — and the
final assignment replays through the sequential oracle like any wave result.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from ..state.arrays import Array, ClusterTables, PodArrays
from .assign import AssignResult, AssignState
from .lattice import CycleArrays
from .waves import assign_waves


class GangArrays(NamedTuple):
    """Per-cycle gang inputs (built host-side: state/encode.py
    build_gang_arrays)."""

    group: Array   # [P] i32 — group id per pending pod, -1 ungrouped
    needed: Array  # [GR] i32 — members still required (minMember - bound)
    valid: Array   # [GR] bool — group has members in this batch
    rank: Array    # [GR] i32 — rejection priority; argmax rejects first


class _GangCarry(NamedTuple):
    rejected: Array    # [GR] bool
    under: Array       # [GR] bool — underfilled in the latest run
    placed: Array      # [GR] i32 — members placed in the latest run
    rounds: Array      # scalar i32
    node: Array        # [P] i32 latest assignment
    feasible: Array    # [P] bool
    waves: Array       # [P] i32 wave index per pod (tests/replay)
    state: AssignState


def _placed_per_group(gang: GangArrays, pods: PodArrays,
                      feasible: Array) -> Array:
    GR = gang.needed.shape[0]
    g_safe = jnp.where(gang.group >= 0, gang.group, GR)
    hit = (feasible & pods.valid).astype(jnp.int32)
    return jnp.zeros((GR + 1,), jnp.int32).at[g_safe].add(hit)[:GR]


def assign_gang(
    tables: ClusterTables,
    cyc: CycleArrays,
    pods: PodArrays,
    init: AssignState,
    gang: GangArrays,
    max_waves: int | None = None,
    soft_rounds: int = 4,
    engine_fn=None,
    return_waves: bool = False,
) -> tuple[AssignResult, Array]:
    """Wave assignment with group-atomic admission. Returns the result plus
    the [GR] rejected-group mask (host surfaces per-group events from it).
    Pods of rejected groups come back node=-1/infeasible.

    engine_fn(tables, cyc, pods, init) -> AssignResult lets a sequential
    engine drive the feasibility loop instead of the wave engine: the
    literal scan (ops/assign.py, the executable spec) or the run-collapsed
    scan (ops/runs.py — each rejection round re-masks validity, which only
    merges or shrinks class runs, so the host-supplied run capacity bound
    holds for every round and the rounds stay bit-equal to the per-pod
    scan's). Default is the wave engine."""
    GR = gang.needed.shape[0]
    P = pods.valid.shape[0]

    def run(rejected: Array):
        ok = (gang.group < 0) | ~rejected[jnp.clip(gang.group, 0, GR - 1)]
        masked = pods._replace(valid=pods.valid & ok)
        if engine_fn is not None:
            res = engine_fn(tables, cyc, masked, init)
            waves = jnp.full((P,), -1, jnp.int32)
        else:
            res, waves = assign_waves(tables, cyc, masked, init, max_waves,
                                      return_waves=True)
        placed = _placed_per_group(gang, masked, res.feasible)
        under = gang.valid & ~rejected & (placed < gang.needed)
        return res, waves, under, placed

    def cond(c: _GangCarry) -> Array:
        # rounds==0 is the unconditional first run; afterwards loop while
        # any group is underfilled (each round rejects ≥1, cap GR+2)
        return (c.rounds == 0) | (c.under.any() & (c.rounds < GR + 2))

    def body(c: _GangCarry) -> _GangCarry:
        # zero-placed underfilled groups hold NOTHING: excluding them frees
        # no capacity, so no OTHER group's fill depends on them — reject
        # them all at once (collapses statically-infeasible jobs into one
        # extra round; a zero-placed group that might have filled after a
        # partial rejection simply retries next cycle via the queue, the
        # same deferral the Permit-timeout path gives it). PARTIALLY-filled
        # groups do hold capacity; release them one per round (lowest rank
        # first) so survivors absorb the freed space — until soft_rounds,
        # after which the remaining tail rejects in bulk. The first round
        # (rounds==0, dummy carry) rejects nothing.
        zero = c.under & (c.placed == 0)
        partial = c.under & (c.placed > 0)
        worst = jnp.argmax(jnp.where(partial, gang.rank, -1))
        one = jnp.zeros((GR,), bool).at[worst].set(True) & partial
        newly = zero | jnp.where(c.rounds > soft_rounds, partial, one)
        newly = newly & (c.rounds > 0)
        rejected = c.rejected | newly
        res, waves, under, placed = run(rejected)
        return _GangCarry(rejected=rejected, under=under, placed=placed,
                          rounds=c.rounds + 1, node=res.node,
                          feasible=res.feasible, waves=waves, state=res.state)

    # ONE instance of the wave fixpoint in the program: an unrolled initial
    # run plus the loop body doubled the compiled graph, which at
    # 5k nodes × 100k pods × 3.5k classes was enough to take the TPU
    # worker down; the dummy init carry (under=True, rounds=0) makes the
    # first loop iteration BE the initial run instead.
    final = lax.while_loop(cond, body, _GangCarry(
        rejected=jnp.zeros((GR,), bool),
        under=jnp.ones((GR,), bool),
        placed=jnp.zeros((GR,), jnp.int32),
        rounds=jnp.int32(0),
        node=jnp.full((P,), -1, jnp.int32),
        feasible=jnp.zeros((P,), bool),
        waves=jnp.full((P,), -1, jnp.int32),
        state=init))

    # the loop always exits with `under` empty (after the initial round,
    # each iteration rejects ≥1 group; rounds cap at GR+2 counting the
    # dummy-carry first iteration); the strip below also covers the
    # unreachable cap exit
    dead = final.rejected | final.under
    ok = (gang.group < 0) | ~dead[jnp.clip(gang.group, 0, GR - 1)]
    result = AssignResult(node=jnp.where(ok, final.node, -1),
                          feasible=final.feasible & ok, state=final.state)
    if return_waves:
        return result, dead, final.waves
    return result, dead
