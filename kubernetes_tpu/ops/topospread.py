"""PodTopologySpread (EvenPodsSpread) as tensor ops.

Reference semantics: EvenPodsSpreadPredicate (predicates.go:1643-1703) with
metadata (metadata.go:114-176): for each hard (DoNotSchedule) constraint,
  skew = matchNum(node's pair) + selfMatch − minMatchNum  must be ≤ maxSkew,
where matchNum counts same-namespace existing pods matching the constraint's
selector in the candidate node's topology domain — counting ONLY pods on nodes
that pass the incoming pod's nodeSelector/node-affinity (metadata.go:145-151
skips ineligible nodes) — and minMatchNum is the minimum over eligible domains
(the 2-slot criticalPaths online-min, metadata.go:78-112, becomes a masked min
over the domain axis). A node lacking the topology key fails; a pod whose
eligible-domain map is empty passes everywhere (predicates.go:1661-1663).

Constraint selectors are interned as terms with namespaces={pod.namespace}, so
counts come from the same CNT_node[S, N] carry as inter-pod affinity and stay
live as pods land during the assignment scan; eligibility masking happens at
aggregation time per class.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..state.arrays import Array, NodeArrays, PodClassTable, TermTable
from .interpod import domain_agg, domain_of_term


def eligible_domains(
    node_match: Array,     # [SC, N] — nodeSelector ∧ node-affinity only
    classes: PodClassTable,
    nodes: NodeArrays,
    D: int,
) -> Array:
    """ELD [SC, TS, D+1] bool: domains (of each constraint's key) containing at
    least one node eligible for the class (metadata.go:145-151's node filter)."""
    SC, TS = classes.tsc_key.shape
    k = jnp.maximum(classes.tsc_key, 0)          # [SC, TS]
    dom = nodes.domain[:, k]                      # [N, SC, TS]
    ok = (
        node_match.T[:, :, None]
        & (dom >= 0)
        & (classes.tsc_key >= 0)[None, :, :]
        & nodes.valid[:, None, None]
    )  # [N, SC, TS]
    idx = jnp.where(ok, dom, D)
    eld = jnp.zeros((SC, TS, D + 1), bool)
    return eld.at[
        jnp.arange(SC)[None, :, None], jnp.arange(TS)[None, None, :], idx
    ].max(ok)


def spread_row(
    cls: Array,            # scalar class id
    classes: PodClassTable,
    terms: TermTable,
    TM: Array,             # [S, SC]
    CNT_node: Array,       # [S, N] live per-node match counts
    ELD: Array,            # [SC, TS, D+1]
    node_match_row: Array, # [N] — this class's nodeSelector/affinity eligibility
    nodes: NodeArrays,
    D: int,
) -> Array:
    """[N] bool: all hard spread constraints satisfied on each node."""
    s_ids = classes.tsc_term[cls]      # [TS]
    s = jnp.maximum(s_ids, 0)
    hard = classes.tsc_hard[cls] & (s_ids >= 0)  # [TS]
    skew_max = classes.tsc_maxskew[cls]

    dom, has_key = domain_of_term(nodes, terms.topo_key[s])  # [TS, N]
    # counts restricted to nodes eligible for this pod (metadata.go:145-151)
    seg = domain_agg(CNT_node[s], dom, D, eligible=node_match_row[None, :])  # [TS, D+1]
    cnt = jnp.take_along_axis(seg, jnp.where(dom >= 0, dom, D), axis=1)     # [TS, N]

    eld = ELD[cls]  # [TS, D+1]
    any_eligible = eld[:, :D].any(-1)  # [TS]
    min_cnt = jnp.min(
        jnp.where(eld[:, :D], seg[:, :D], jnp.iinfo(jnp.int32).max), axis=-1
    )  # [TS]
    self_match = TM[s, cls]  # [TS] — constraint selector vs own labels

    skew = cnt + self_match[:, None].astype(jnp.int32) - min_cnt[:, None]
    ok = has_key & (skew <= skew_max[:, None])
    # empty eligible-domain map ⇒ constraint passes everywhere (:1661-1663)
    per_constraint = jnp.where(
        (hard & any_eligible)[:, None], ok, jnp.ones_like(ok)
    )
    return per_constraint.all(0)
