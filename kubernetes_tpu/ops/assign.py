"""Batched assignment: the whole scheduling cycle as one lax.scan on device.

The reference schedules one pod per `scheduleOne` call (scheduler.go:596-763):
snapshot → filter over nodes (16 goroutines) → score → selectHost → assume.
Each pod's placement updates the cache before the next pod is considered —
sequential *semantics* are load-bearing (two pods landing on one node must see
each other's resource usage and affinity counts).

Here the entire pending batch is scheduled in ONE device dispatch: a lax.scan
over pods in queue order (priority desc, creation asc — the activeQ comparator,
internal/queue/scheduling_queue.go:119-138 + util.GetPodPriority). The scan
carry is the assume-cache state: per-node used resources, port bitsets, and the
affinity/spread count tables. Per step: O(N) rows of dynamic checks + gathers
into the precomputed static [SC, N] lattice. This preserves the reference's
sequential assume semantics exactly while amortizing all O(SC·N·…) work outside
the loop.

Deviation (documented in docs/PARITY.md): ties in the max score pick the
lowest node index (deterministic) instead of the reference's reservoir-random
selectHost (generic_scheduler.go:290-311).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..state.arrays import Array, ClusterTables, PodArrays
from .fit import fit_row, resource_scores_row
from .interpod import affinity_rows, soft_affinity_row
from .lattice import CycleArrays
from .ports import port_conflict_row
from .scores import even_spread_soft_row, selector_spread_row
from .topospread import spread_row
from .volumes import volume_components_row, volume_ok_row


class AssignState(NamedTuple):
    used: Array  # [N, R] i32
    ppa: Array   # [N, PWp] u32 — (proto,port) pairs in use (any IP)
    ppw: Array   # [N, PWp] u32 — wildcard-IP pairs in use
    ppt: Array   # [N, PWt] u32 — exact triples in use
    CNT: Array   # [S, N] i32 — per-node term match counts
    HOLD: Array  # [S, N] i32 — per-node anti-term holders
    WSYM: Array  # [S, N] f32 — signed symmetric soft-affinity weights
    vol_any: Array  # [N, VW] u32 — attached volumes (NoDiskConflict/limits)
    vol_rw: Array   # [N, VW] u32 — attached read-write


class AssignResult(NamedTuple):
    node: Array       # [P] i32 — chosen node index, -1 unschedulable
    feasible: Array   # [P] bool
    state: AssignState


def queue_order(pods: PodArrays) -> Array:
    """activeQ pop order: valid first, then priority desc, then creation asc
    (scheduling_queue.go activeQComp → podutil.GetPodPriority + timestamp)."""
    return jnp.lexsort((pods.creation, -pods.priority, ~pods.valid))


def assign_step(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    c: Array,
    p_valid: Array,
    node_name_req: Array,
) -> Tuple[AssignState, Array, Array]:
    """ONE pod's Filter → Score → selectHost → assume against a live state —
    the body of the sequential scan, factored out so the run-collapsed
    engine's per-pod fallback (ops/runs.py) executes the IDENTICAL op
    sequence (bit-equality between the engines is by shared code, not by
    re-derivation). Returns (new state, node index or -1, feasible)."""
    classes = tables.classes
    req_vec = tables.reqs.vec[classes.rid[c]]
    ps = classes.portset[c]
    psafe = jnp.maximum(ps, 0)

    mask = pod_mask_row(tables, cyc, state, c, node_name_req, p_valid)

    # ---- Score row (weighted sum; component weights/enables come from
    #      the traced EngineConfig — generic_scheduler.go:823-832) ----
    score = score_row(tables, cyc, state, c)
    score = jnp.where(mask, score, -jnp.inf)

    choice = jnp.argmax(score)
    feasible = mask.any() & p_valid
    node = jnp.where(feasible, choice, -1)

    # ---- assume: commit to carry (cache.AssumePod analog) ----
    add = jnp.where(feasible, req_vec, 0)
    used = state.used.at[choice].add(add)

    live_ps = feasible & (ps >= 0)
    pw = jnp.where(live_ps, tables.portsets.pair_words[psafe], 0)
    ww = jnp.where(live_ps, tables.portsets.wild_words[psafe], 0)
    tw = jnp.where(live_ps, tables.portsets.trip_words[psafe], 0)
    ppa = state.ppa.at[choice].set(state.ppa[choice] | pw)
    ppw = state.ppw.at[choice].set(state.ppw[choice] | ww)
    ppt = state.ppt.at[choice].set(state.ppt[choice] | tw)

    # affinity/spread counts: this pod now matches its terms at its node
    inc = (cyc.TM[:, c] & feasible).astype(jnp.int32)   # [S]
    CNT = state.CNT.at[:, choice].add(inc)
    inc_h = (cyc.has_anti[c] & feasible).astype(jnp.int32)
    HOLD = state.HOLD.at[:, choice].add(inc_h)
    WSYM = state.WSYM.at[:, choice].add(
        jnp.where(feasible, cyc.WCOLS[:, c], 0.0))

    vs = tables.classes.volset[c]
    live_vs = feasible & (vs >= 0)
    va = jnp.where(live_vs, tables.volsets.any_words[jnp.maximum(vs, 0)], 0)
    vr = jnp.where(live_vs, tables.volsets.rw_words[jnp.maximum(vs, 0)], 0)
    vol_any = state.vol_any.at[choice].set(state.vol_any[choice] | va)
    vol_rw = state.vol_rw.at[choice].set(state.vol_rw[choice] | vr)

    return AssignState(used, ppa, ppw, ppt, CNT, HOLD, WSYM,
                       vol_any, vol_rw), node, feasible


def assign_batch(
    tables: ClusterTables,
    cyc: CycleArrays,
    pods: PodArrays,
    init: AssignState,
) -> AssignResult:
    order = queue_order(pods)

    def step(state: AssignState, idx):
        state, node, feasible = assign_step(
            tables, cyc, state, pods.cls[idx], pods.valid[idx],
            pods.node_name_req[idx])
        return state, (node, feasible)

    final, (nodes_sorted, feas_sorted) = jax.lax.scan(step, init, order)

    P = pods.valid.shape[0]
    node_out = jnp.full((P,), -1, jnp.int32).at[order].set(nodes_sorted)
    feas_out = jnp.zeros((P,), bool).at[order].set(feas_sorted)
    return AssignResult(node=node_out, feasible=feas_out, state=final)


def mask_context_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    cls: Array,
    node_name_req: Array,
    valid: Array,
) -> Array:
    """The Filter components that are CONSTANT across a run of same-class
    replicas when the class is self-interaction-free (ops/runs.py): the
    static lattice, inter-pod affinity/anti-affinity (counts only move at
    placed nodes, through terms such a class never reads), hard topology
    spread, spec.nodeName, and pod validity. The run-collapsed engine
    evaluates this once per RUN; pod_mask_row recomposes it per pod."""
    from .lattice import _on

    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    ecfg = cyc.ecfg
    D = cyc.ELD.shape[2] - 1
    aff_ok, anti_ok = affinity_rows(
        cls, classes, terms, cyc.TM, state.CNT, state.HOLD, nodes, D
    )
    interpod_ok = (aff_ok & anti_ok) | ~_on(ecfg.f_interpod)
    spread_ok = spread_row(
        cls, classes, terms, cyc.TM, state.CNT, cyc.ELD,
        cyc.static.node_match[cls], nodes, D,
    ) | ~_on(ecfg.f_spread)
    host_ok = (node_name_req < 0) | (nodes.name_id == node_name_req) \
        | ~_on(ecfg.f_name)
    return cyc.static.mask[cls] & interpod_ok & spread_ok & host_ok & valid


def mask_dynamic_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    cls: Array,
    used: Array,
    ppa: Array, ppw: Array, ppt: Array,
    vol_any: Array, vol_rw: Array,
) -> Array:
    """The Filter components that move as replicas of the SAME class land:
    resources, host ports, volumes — all strictly per-node functions of the
    passed state planes. The run-collapsed engine re-evaluates exactly this
    per admission epoch against synthesized per-node planes; the per-pod
    scan calls it (via pod_mask_row) with the live carry."""
    from .lattice import _on

    nodes, classes = tables.nodes, tables.classes
    ecfg = cyc.ecfg
    rid = classes.rid[cls]
    req_vec = tables.reqs.vec[rid]
    fit = fit_row(req_vec, used, nodes.alloc, nodes.valid) \
        | ~_on(ecfg.f_fit)
    ps = classes.portset[cls]
    psafe = jnp.maximum(ps, 0)
    conflict = port_conflict_row(
        tables.portsets.wild_words[psafe],
        tables.portsets.pair_words[psafe],
        tables.portsets.trip_words[psafe],
        ppa, ppw, ppt,
    )
    port_ok = (ps < 0) | ~conflict | ~_on(ecfg.f_ports)
    vconf_free, vlimit_ok = volume_components_row(
        tables, vol_any, vol_rw, cls)
    vol_ok = (vconf_free | ~_on(ecfg.f_volrestrict)) \
        & (vlimit_ok | ~_on(ecfg.f_vollimits))
    return fit & port_ok & vol_ok


def pod_mask_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    cls: Array,
    node_name_req: Array,
    valid: Array,
) -> Array:
    """Full Filter mask [N] for one pod against a given assume-state — the
    tensor analog of podFitsOnNode (generic_scheduler.go:628-706). Shared by
    the assignment scan and the golden-test / extender surfaces. Each
    component honors its EngineConfig plugin flag (a disabled filter plugin
    never blocks, matching CreateFromKeys composition). Composed from the
    run-constant context half and the per-placement dynamic half — boolean
    conjunction, so the regrouping is exact."""
    return (
        mask_context_row(tables, cyc, state, cls, node_name_req, valid)
        & mask_dynamic_row(tables, cyc, cls, state.used,
                           state.ppa, state.ppw, state.ppt,
                           state.vol_any, state.vol_rw)
    )


class ScoreContext(NamedTuple):
    """The Score components that stay fixed across a self-interaction-free
    replica run: the count/weight-aggregated rows whose inputs (CNT/WSYM at
    terms the class reads) its own placements cannot move."""

    soft_ip: Array    # [N] soft inter-pod affinity, min/max-normalized
    even_soft: Array  # [N] EvenPodsSpread ScheduleAnyway score
    ssel: Array       # [N] SelectorSpread score


def score_context_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    cls: Array,
) -> ScoreContext:
    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    D = cyc.ELD.shape[2] - 1
    soft_ip = soft_affinity_row(cls, classes, terms, state.CNT, nodes, D,
                                TM=cyc.TM, WSYM=state.WSYM)
    even_soft = even_spread_soft_row(
        cls, classes, terms, state.CNT, nodes, cyc.static.node_match[cls], D)
    ssel = selector_spread_row(
        cls, classes, state.CNT, nodes, tables.zone_keys, D)
    return ScoreContext(soft_ip=soft_ip, even_soft=even_soft, ssel=ssel)


def score_combine_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    cls: Array,
    used: Array,
    ctx: ScoreContext,
) -> Array:
    """The exact weighted-sum expression tree of the Score row, parameterized
    by the per-node `used` plane. BOTH engines go through this one function
    — the run-collapsed engine with synthesized used-after-j-replicas planes,
    the scan with the live carry — so the float op sequence (and therefore
    every rounding) is identical by construction, which is what makes the
    argmax chains bit-equal."""
    nodes, classes = tables.nodes, tables.classes
    w = cyc.ecfg
    req_vec = tables.reqs.vec[classes.rid[cls]]
    least, balanced, most = resource_scores_row(req_vec, used, nodes.alloc)
    return (cyc.static.score[cls] + least * w.w_least
            + balanced * w.w_balanced + most * w.w_most
            + ctx.soft_ip * w.w_interpod + ctx.even_soft * w.w_even
            + ctx.ssel * w.w_ssel)


def score_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    cls: Array,
) -> Array:
    """Full Score row [N] for one pod class against a live assume-state —
    prioritizeNodes' weighted sum (generic_scheduler.go:714-869) with the
    EngineConfig carrying per-plugin weights. Shared by all engines and the
    score-matrix surface."""
    return score_combine_row(
        tables, cyc, cls, state.used,
        score_context_row(tables, cyc, state, cls))


def feasible_matrix(
    tables: ClusterTables, cyc: CycleArrays, pods: PodArrays
) -> Array:
    """[P, N] Filter mask for every pending pod against the *initial* state
    (no assignment feedback) — findNodesThatFit (generic_scheduler.go:473) as
    one vmapped tensor, used for golden tests and the extender Filter verb."""
    state = initial_state(tables, cyc)
    return jax.vmap(
        lambda c, nnr, v: pod_mask_row(tables, cyc, state, c, nnr, v)
    )(pods.cls, pods.node_name_req, pods.valid)


class MaskComponents(NamedTuple):
    """Per-predicate [P, N] masks for failure diagnosis — the tensor analog of
    PredicateFailureReason lists (predicates.go error types). Component names
    follow the reference predicate names (algorithm/predicates/error.go)."""

    node_match: Array   # MatchNodeSelector / node affinity
    taints: Array       # PodToleratesNodeTaints (incl. CheckNodeUnschedulable)
    fit: Array          # PodFitsResources
    ports: Array        # PodFitsHostPorts
    affinity: Array     # MatchInterPodAffinity (required affinity half)
    anti: Array         # MatchInterPodAffinity (anti-affinity half)
    spread: Array       # EvenPodsSpread
    host: Array         # PodFitsHost (spec.nodeName)
    volumes: Array      # NoDiskConflict + max-volume-count family


def mask_components(
    tables: ClusterTables, cyc: CycleArrays, pods: PodArrays
) -> MaskComponents:
    """Decomposed feasibility against the initial state, vmapped over pods."""
    state = initial_state(tables, cyc)
    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    D = cyc.ELD.shape[2] - 1

    def row(c, nnr, v):
        req_vec = tables.reqs.vec[classes.rid[c]]
        fit = fit_row(req_vec, state.used, nodes.alloc, nodes.valid)
        ps = classes.portset[c]
        psafe = jnp.maximum(ps, 0)
        conflict = port_conflict_row(
            tables.portsets.wild_words[psafe],
            tables.portsets.pair_words[psafe],
            tables.portsets.trip_words[psafe],
            state.ppa, state.ppw, state.ppt,
        )
        port_ok = (ps < 0) | ~conflict
        aff_ok, anti_ok = affinity_rows(
            c, classes, terms, cyc.TM, state.CNT, state.HOLD, nodes, D
        )
        spread_ok = spread_row(
            c, classes, terms, cyc.TM, state.CNT, cyc.ELD,
            cyc.static.node_match[c], nodes, D,
        )
        host_ok = (nnr < 0) | (nodes.name_id == nnr)
        vol_ok = volume_ok_row(tables, state.vol_any, state.vol_rw, c)
        nm = cyc.static.node_match[c]
        # static.mask = node_match ∧ taint_ok ∧ unsched_pass ∧ class valid;
        # recover the taint/unschedulable part by division
        taints_ok = cyc.static.mask[c] | ~nm
        return (nm & v, taints_ok, fit, port_ok, aff_ok, anti_ok, spread_ok,
                host_ok, vol_ok)

    parts = jax.vmap(row)(pods.cls, pods.node_name_req, pods.valid)
    return MaskComponents(*parts)


def score_matrix(
    tables: ClusterTables, cyc: CycleArrays, pods: PodArrays
) -> Array:
    """[P, N] Score for every pending pod against the *initial* state — the
    tensor analog of prioritizeNodes (generic_scheduler.go:714-869): static
    lattice scores (preferred node affinity, taint PreferNoSchedule) plus
    least-requested/balanced-allocation plus soft inter-pod affinity, all
    weight-1 summed. Infeasible nodes score -inf."""
    state = initial_state(tables, cyc)
    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    D = cyc.ELD.shape[2] - 1

    def row(c, nnr, v):
        mask = pod_mask_row(tables, cyc, state, c, nnr, v)
        return jnp.where(mask, score_row(tables, cyc, state, c), -jnp.inf)

    return jax.vmap(row)(pods.cls, pods.node_name_req, pods.valid)


def initial_state(tables: ClusterTables, cyc: CycleArrays) -> AssignState:
    n = tables.nodes
    return AssignState(
        used=n.used, ppa=n.port_pair_any, ppw=n.port_pair_wild, ppt=n.port_triple,
        CNT=cyc.CNT, HOLD=cyc.HOLD, WSYM=cyc.WSYM,
        vol_any=n.vol_any, vol_rw=n.vol_rw,
    )
