"""Batched assignment: the whole scheduling cycle as one lax.scan on device.

The reference schedules one pod per `scheduleOne` call (scheduler.go:596-763):
snapshot → filter over nodes (16 goroutines) → score → selectHost → assume.
Each pod's placement updates the cache before the next pod is considered —
sequential *semantics* are load-bearing (two pods landing on one node must see
each other's resource usage and affinity counts).

Here the entire pending batch is scheduled in ONE device dispatch: a lax.scan
over pods in queue order (priority desc, creation asc — the activeQ comparator,
internal/queue/scheduling_queue.go:119-138 + util.GetPodPriority). The scan
carry is the assume-cache state: per-node used resources, port bitsets, and the
affinity/spread count tables. Per step: O(N) rows of dynamic checks + gathers
into the precomputed static [SC, N] lattice. This preserves the reference's
sequential assume semantics exactly while amortizing all O(SC·N·…) work outside
the loop.

Deviation (documented in docs/PARITY.md): ties in the max score pick the
lowest node index (deterministic) instead of the reference's reservoir-random
selectHost (generic_scheduler.go:290-311).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..state.arrays import Array, ClusterTables, PodArrays
from .fit import fit_row, resource_scores_row
from .interpod import affinity_rows, soft_affinity_row
from .lattice import CycleArrays
from .ports import port_conflict_row
from .scores import even_spread_soft_row, selector_spread_row
from .topospread import spread_row
from .volumes import volume_components_row, volume_ok_row


class AssignState(NamedTuple):
    used: Array  # [N, R] i32
    ppa: Array   # [N, PWp] u32 — (proto,port) pairs in use (any IP)
    ppw: Array   # [N, PWp] u32 — wildcard-IP pairs in use
    ppt: Array   # [N, PWt] u32 — exact triples in use
    CNT: Array   # [S, N] i32 — per-node term match counts
    HOLD: Array  # [S, N] i32 — per-node anti-term holders
    WSYM: Array  # [S, N] f32 — signed symmetric soft-affinity weights
    vol_any: Array  # [N, VW] u32 — attached volumes (NoDiskConflict/limits)
    vol_rw: Array   # [N, VW] u32 — attached read-write


class AssignResult(NamedTuple):
    node: Array       # [P] i32 — chosen node index, -1 unschedulable
    feasible: Array   # [P] bool
    state: AssignState


def queue_order(pods: PodArrays) -> Array:
    """activeQ pop order: valid first, then priority desc, then creation asc
    (scheduling_queue.go activeQComp → podutil.GetPodPriority + timestamp)."""
    return jnp.lexsort((pods.creation, -pods.priority, ~pods.valid))


def assign_step(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    c: Array,
    p_valid: Array,
    node_name_req: Array,
) -> Tuple[AssignState, Array, Array]:
    """ONE pod's Filter → Score → selectHost → assume against a live state —
    the body of the sequential scan, factored out so the run-collapsed
    engine's per-pod fallback (ops/runs.py) executes the IDENTICAL op
    sequence (bit-equality between the engines is by shared code, not by
    re-derivation). Returns (new state, node index or -1, feasible)."""
    classes = tables.classes
    req_vec = tables.reqs.vec[classes.rid[c]]
    ps = classes.portset[c]
    psafe = jnp.maximum(ps, 0)

    mask = pod_mask_row(tables, cyc, state, c, node_name_req, p_valid)

    # ---- Score row (weighted sum; component weights/enables come from
    #      the traced EngineConfig — generic_scheduler.go:823-832) ----
    score = score_row(tables, cyc, state, c)
    score = jnp.where(mask, score, -jnp.inf)

    choice = jnp.argmax(score)
    feasible = mask.any() & p_valid
    node = jnp.where(feasible, choice, -1)

    # ---- assume: commit to carry (cache.AssumePod analog) ----
    add = jnp.where(feasible, req_vec, 0)
    used = state.used.at[choice].add(add)

    live_ps = feasible & (ps >= 0)
    pw = jnp.where(live_ps, tables.portsets.pair_words[psafe], 0)
    ww = jnp.where(live_ps, tables.portsets.wild_words[psafe], 0)
    tw = jnp.where(live_ps, tables.portsets.trip_words[psafe], 0)
    ppa = state.ppa.at[choice].set(state.ppa[choice] | pw)
    ppw = state.ppw.at[choice].set(state.ppw[choice] | ww)
    ppt = state.ppt.at[choice].set(state.ppt[choice] | tw)

    # affinity/spread counts: this pod now matches its terms at its node
    inc = (cyc.TM[:, c] & feasible).astype(jnp.int32)   # [S]
    CNT = state.CNT.at[:, choice].add(inc)
    inc_h = (cyc.has_anti[c] & feasible).astype(jnp.int32)
    HOLD = state.HOLD.at[:, choice].add(inc_h)
    WSYM = state.WSYM.at[:, choice].add(
        jnp.where(feasible, cyc.WCOLS[:, c], 0.0))

    vs = tables.classes.volset[c]
    live_vs = feasible & (vs >= 0)
    va = jnp.where(live_vs, tables.volsets.any_words[jnp.maximum(vs, 0)], 0)
    vr = jnp.where(live_vs, tables.volsets.rw_words[jnp.maximum(vs, 0)], 0)
    vol_any = state.vol_any.at[choice].set(state.vol_any[choice] | va)
    vol_rw = state.vol_rw.at[choice].set(state.vol_rw[choice] | vr)

    return AssignState(used, ppa, ppw, ppt, CNT, HOLD, WSYM,
                       vol_any, vol_rw), node, feasible


def assign_batch(
    tables: ClusterTables,
    cyc: CycleArrays,
    pods: PodArrays,
    init: AssignState,
) -> AssignResult:
    order = queue_order(pods)

    def step(state: AssignState, idx):
        state, node, feasible = assign_step(
            tables, cyc, state, pods.cls[idx], pods.valid[idx],
            pods.node_name_req[idx])
        return state, (node, feasible)

    final, (nodes_sorted, feas_sorted) = jax.lax.scan(step, init, order)

    P = pods.valid.shape[0]
    node_out = jnp.full((P,), -1, jnp.int32).at[order].set(nodes_sorted)
    feas_out = jnp.zeros((P,), bool).at[order].set(feas_sorted)
    return AssignResult(node=node_out, feasible=feas_out, state=final)


def mask_context_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    cls: Array,
    node_name_req: Array,
    valid: Array,
) -> Array:
    """The Filter components that are CONSTANT across a run of same-class
    replicas when the class is self-interaction-free (ops/runs.py): the
    static lattice, inter-pod affinity/anti-affinity (counts only move at
    placed nodes, through terms such a class never reads), hard topology
    spread, spec.nodeName, and pod validity. The run-collapsed engine
    evaluates this once per RUN; pod_mask_row recomposes it per pod."""
    from .lattice import _on

    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    ecfg = cyc.ecfg
    D = cyc.ELD.shape[2] - 1
    aff_ok, anti_ok = affinity_rows(
        cls, classes, terms, cyc.TM, state.CNT, state.HOLD, nodes, D
    )
    interpod_ok = (aff_ok & anti_ok) | ~_on(ecfg.f_interpod)
    spread_ok = spread_row(
        cls, classes, terms, cyc.TM, state.CNT, cyc.ELD,
        cyc.static.node_match[cls], nodes, D,
    ) | ~_on(ecfg.f_spread)
    host_ok = (node_name_req < 0) | (nodes.name_id == node_name_req) \
        | ~_on(ecfg.f_name)
    return cyc.static.mask[cls] & interpod_ok & spread_ok & host_ok & valid


def fit_plane(tables: ClusterTables, cyc: CycleArrays, cls: Array,
              used: Array) -> Array:
    """PodFitsResources plane [N] incl. the plugin flag — the ONE
    composition shared by the engines' dynamic mask and the explain
    attribution (drift between the two would make reason counts lie)."""
    from .lattice import _on

    req_vec = tables.reqs.vec[tables.classes.rid[cls]]
    return fit_row(req_vec, used, tables.nodes.alloc, tables.nodes.valid) \
        | ~_on(cyc.ecfg.f_fit)


def ports_plane(tables: ClusterTables, cyc: CycleArrays, cls: Array,
                ppa: Array, ppw: Array, ppt: Array) -> Array:
    """PodFitsHostPorts plane [N] incl. the plugin flag (shared, see
    fit_plane)."""
    from .lattice import _on

    ps = tables.classes.portset[cls]
    psafe = jnp.maximum(ps, 0)
    conflict = port_conflict_row(
        tables.portsets.wild_words[psafe],
        tables.portsets.pair_words[psafe],
        tables.portsets.trip_words[psafe],
        ppa, ppw, ppt,
    )
    return (ps < 0) | ~conflict | ~_on(cyc.ecfg.f_ports)


def volumes_plane(tables: ClusterTables, cyc: CycleArrays, cls: Array,
                  vol_any: Array, vol_rw: Array) -> Array:
    """NoDiskConflict + volume-limits plane [N] incl. the plugin flags
    (shared, see fit_plane)."""
    from .lattice import _on

    vconf_free, vlimit_ok = volume_components_row(
        tables, vol_any, vol_rw, cls)
    return (vconf_free | ~_on(cyc.ecfg.f_volrestrict)) \
        & (vlimit_ok | ~_on(cyc.ecfg.f_vollimits))


def mask_dynamic_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    cls: Array,
    used: Array,
    ppa: Array, ppw: Array, ppt: Array,
    vol_any: Array, vol_rw: Array,
) -> Array:
    """The Filter components that move as replicas of the SAME class land:
    resources, host ports, volumes — all strictly per-node functions of the
    passed state planes. The run-collapsed engine re-evaluates exactly this
    per admission epoch against synthesized per-node planes; the per-pod
    scan calls it (via pod_mask_row) with the live carry. Composed from the
    same per-plane helpers the explain attribution decomposes."""
    return (fit_plane(tables, cyc, cls, used)
            & ports_plane(tables, cyc, cls, ppa, ppw, ppt)
            & volumes_plane(tables, cyc, cls, vol_any, vol_rw))


def pod_mask_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    cls: Array,
    node_name_req: Array,
    valid: Array,
) -> Array:
    """Full Filter mask [N] for one pod against a given assume-state — the
    tensor analog of podFitsOnNode (generic_scheduler.go:628-706). Shared by
    the assignment scan and the golden-test / extender surfaces. Each
    component honors its EngineConfig plugin flag (a disabled filter plugin
    never blocks, matching CreateFromKeys composition). Composed from the
    run-constant context half and the per-placement dynamic half — boolean
    conjunction, so the regrouping is exact."""
    return (
        mask_context_row(tables, cyc, state, cls, node_name_req, valid)
        & mask_dynamic_row(tables, cyc, cls, state.used,
                           state.ppa, state.ppw, state.ppt,
                           state.vol_any, state.vol_rw)
    )


class ScoreContext(NamedTuple):
    """The Score components that stay fixed across a self-interaction-free
    replica run: the count/weight-aggregated rows whose inputs (CNT/WSYM at
    terms the class reads) its own placements cannot move."""

    soft_ip: Array    # [N] soft inter-pod affinity, min/max-normalized
    even_soft: Array  # [N] EvenPodsSpread ScheduleAnyway score
    ssel: Array       # [N] SelectorSpread score


def score_context_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    cls: Array,
) -> ScoreContext:
    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    D = cyc.ELD.shape[2] - 1
    soft_ip = soft_affinity_row(cls, classes, terms, state.CNT, nodes, D,
                                TM=cyc.TM, WSYM=state.WSYM)
    even_soft = even_spread_soft_row(
        cls, classes, terms, state.CNT, nodes, cyc.static.node_match[cls], D)
    ssel = selector_spread_row(
        cls, classes, state.CNT, nodes, tables.zone_keys, D)
    return ScoreContext(soft_ip=soft_ip, even_soft=even_soft, ssel=ssel)


def score_combine_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    cls: Array,
    used: Array,
    ctx: ScoreContext,
) -> Array:
    """The exact weighted-sum expression tree of the Score row, parameterized
    by the per-node `used` plane. BOTH engines go through this one function
    — the run-collapsed engine with synthesized used-after-j-replicas planes,
    the scan with the live carry — so the float op sequence (and therefore
    every rounding) is identical by construction, which is what makes the
    argmax chains bit-equal."""
    nodes, classes = tables.nodes, tables.classes
    w = cyc.ecfg
    req_vec = tables.reqs.vec[classes.rid[cls]]
    least, balanced, most = resource_scores_row(req_vec, used, nodes.alloc)
    return (cyc.static.score[cls] + least * w.w_least
            + balanced * w.w_balanced + most * w.w_most
            + ctx.soft_ip * w.w_interpod + ctx.even_soft * w.w_even
            + ctx.ssel * w.w_ssel)


def score_row(
    tables: ClusterTables,
    cyc: CycleArrays,
    state: AssignState,
    cls: Array,
) -> Array:
    """Full Score row [N] for one pod class against a live assume-state —
    prioritizeNodes' weighted sum (generic_scheduler.go:714-869) with the
    EngineConfig carrying per-plugin weights. Shared by all engines and the
    score-matrix surface."""
    return score_combine_row(
        tables, cyc, cls, state.used,
        score_context_row(tables, cyc, state, cls))


def feasible_matrix(
    tables: ClusterTables, cyc: CycleArrays, pods: PodArrays
) -> Array:
    """[P, N] Filter mask for every pending pod against the *initial* state
    (no assignment feedback) — findNodesThatFit (generic_scheduler.go:473) as
    one vmapped tensor, used for golden tests and the extender Filter verb."""
    state = initial_state(tables, cyc)
    return jax.vmap(
        lambda c, nnr, v: pod_mask_row(tables, cyc, state, c, nnr, v)
    )(pods.cls, pods.node_name_req, pods.valid)


class MaskComponents(NamedTuple):
    """Per-predicate [P, N] masks for failure diagnosis — the tensor analog of
    PredicateFailureReason lists (predicates.go error types). Component names
    follow the reference predicate names (algorithm/predicates/error.go)."""

    node_match: Array   # MatchNodeSelector / node affinity
    taints: Array       # PodToleratesNodeTaints (incl. CheckNodeUnschedulable)
    fit: Array          # PodFitsResources
    ports: Array        # PodFitsHostPorts
    affinity: Array     # MatchInterPodAffinity (required affinity half)
    anti: Array         # MatchInterPodAffinity (anti-affinity half)
    spread: Array       # EvenPodsSpread
    host: Array         # PodFitsHost (spec.nodeName)
    volumes: Array      # NoDiskConflict + max-volume-count family


def mask_components(
    tables: ClusterTables, cyc: CycleArrays, pods: PodArrays
) -> MaskComponents:
    """Decomposed feasibility against the initial state, vmapped over pods."""
    state = initial_state(tables, cyc)
    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    D = cyc.ELD.shape[2] - 1

    def row(c, nnr, v):
        req_vec = tables.reqs.vec[classes.rid[c]]
        fit = fit_row(req_vec, state.used, nodes.alloc, nodes.valid)
        ps = classes.portset[c]
        psafe = jnp.maximum(ps, 0)
        conflict = port_conflict_row(
            tables.portsets.wild_words[psafe],
            tables.portsets.pair_words[psafe],
            tables.portsets.trip_words[psafe],
            state.ppa, state.ppw, state.ppt,
        )
        port_ok = (ps < 0) | ~conflict
        aff_ok, anti_ok = affinity_rows(
            c, classes, terms, cyc.TM, state.CNT, state.HOLD, nodes, D
        )
        spread_ok = spread_row(
            c, classes, terms, cyc.TM, state.CNT, cyc.ELD,
            cyc.static.node_match[c], nodes, D,
        )
        host_ok = (nnr < 0) | (nodes.name_id == nnr)
        vol_ok = volume_ok_row(tables, state.vol_any, state.vol_rw, c)
        nm = cyc.static.node_match[c]
        # static.mask = node_match ∧ taint_ok ∧ unsched_pass ∧ class valid;
        # recover the taint/unschedulable part by division
        taints_ok = cyc.static.mask[c] | ~nm
        return (nm & v, taints_ok, fit, port_ok, aff_ok, anti_ok, spread_ok,
                host_ok, vol_ok)

    parts = jax.vmap(row)(pods.cls, pods.node_name_req, pods.valid)
    return MaskComponents(*parts)


# --------------------------------------------------------------------------- #
# decision provenance (ISSUE 10): per-pod unschedulability attribution and
# winning-score decomposition as cheap sum-reductions over the SAME mask/score
# expression trees the engines evaluate — computed inside the wave dispatch
# when KTPU_EXPLAIN is on, byte-for-byte absent otherwise (a static jit flag).
# --------------------------------------------------------------------------- #

#: predicate order of ExplainResult.reasons — kube PredicateFailureReason
#: names rendered by sched/explain.py (algorithm/predicates/error.go)
EXPLAIN_PREDICATES = ("node_match", "taints", "fit", "ports", "affinity",
                      "anti", "spread", "host", "volumes")
#: score-component order of ExplainResult.score_parts (prioritizeNodes'
#: weighted sum, decomposed)
EXPLAIN_SCORE_COMPONENTS = ("static", "least", "balanced", "most",
                            "interpod", "even", "ssel")
#: candidate nodes reported per pod (clamped to N at trace time)
EXPLAIN_TOPK = 3


class ExplainResult(NamedTuple):
    """Per-pod decision attribution for one wave, evaluated against the
    POST-wave assume state (result.state): the "why is this pod still
    pending NOW" answer, not a replay of each scan step. All counts are
    over VALID nodes; invalid (padding) pods zero out."""

    reasons: Array         # [P, 9] i32 — nodes rejected per predicate
    valid_nodes: Array     # [P] i32 — denominator ("0/N nodes are available")
    feasible_nodes: Array  # [P] i32 — nodes passing EVERY predicate
    rejected_any: Array    # [P] i32 — valid_nodes - feasible_nodes
    top_nodes: Array       # [P, K] i32 — best feasible nodes by score (-1 pad)
    top_scores: Array      # [P, K] f32
    score_parts: Array     # [P, 7] f32 — component breakdown at part_node
    part_node: Array       # [P] i32 — chosen node if scheduled, else best
    #                        feasible node, else -1


def _explain_mask_row(tables: ClusterTables, cyc: CycleArrays,
                      state: AssignState, c: Array):
    """The cheap half of attribution for ONE class against `state`: the 8
    class-granular predicate planes reduced to rejected-node counts
    (host/spec.nodeName is per-pod and folded by the caller) plus the
    full-mask [N] row. Every plane honors its EngineConfig plugin flag
    exactly as pod_mask_row/mask_dynamic_row compose it — a disabled
    plugin never rejects, so counts reconcile with the engine's own
    verdicts. This half runs on EVERY explain-on wave (sub-ms at bench
    shapes)."""
    from .lattice import _on

    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    ecfg = cyc.ecfg
    D = cyc.ELD.shape[2] - 1
    nm = cyc.static.node_match[c]
    # static.mask = node_match ∧ taint_ok ∧ unsched_pass ∧ class-valid;
    # recover the taint/unschedulable plane by division (mask_components)
    taints_ok = cyc.static.mask[c] | ~nm
    # dynamic planes through the SAME helpers mask_dynamic_row conjoins —
    # the engines' verdicts and these counts cannot drift apart
    fit = fit_plane(tables, cyc, c, state.used)
    ports_ok = ports_plane(tables, cyc, c, state.ppa, state.ppw, state.ppt)
    vol_ok = volumes_plane(tables, cyc, c, state.vol_any, state.vol_rw)
    # interpod/spread decomposed: mask_context_row conjoins (aff ∧ anti)
    # under one flag — KEEP the flag composition in sync with it
    aff_ok, anti_ok = affinity_rows(
        c, classes, terms, cyc.TM, state.CNT, state.HOLD, nodes, D)
    aff_ok = aff_ok | ~_on(ecfg.f_interpod)
    anti_ok = anti_ok | ~_on(ecfg.f_interpod)
    spread_ok = spread_row(
        c, classes, terms, cyc.TM, state.CNT, cyc.ELD,
        cyc.static.node_match[c], nodes, D,
    ) | ~_on(ecfg.f_spread)
    planes = jnp.stack([nm, taints_ok, fit, ports_ok, aff_ok, anti_ok,
                        spread_ok, vol_ok])            # [8, N]
    nv = nodes.valid
    reasons8 = jnp.sum(nv[None, :] & ~planes, axis=1).astype(jnp.int32)
    mask8 = planes.all(axis=0) & nv
    return reasons8, mask8


def _explain_score_row(tables: ClusterTables, cyc: CycleArrays,
                       state: AssignState, c: Array):
    """The EXPENSIVE half for one class: the composed score row and the
    context score components (soft inter-pod affinity's min/max
    normalization, even-spread, selector-spread — one extra full score
    pass per class, ~an engine wave-iteration's worth of work). Only
    evaluated under the failure-gated branch of explain_assignments."""
    ctxs = score_context_row(tables, cyc, state, c)
    ctx = jnp.stack([ctxs.soft_ip, ctxs.even_soft, ctxs.ssel])  # [3, N]
    score = score_combine_row(tables, cyc, c, state.used, ctxs)
    return score, ctx


def _row_topk(masked, K: int):
    """Top-K (node index, score) of one masked score row — K iterative
    argmax passes with where-iota elimination, NOT lax.top_k: top_k sorts
    the whole row (N log N per row — measured as the bulk of the
    attribution overhead at bench shapes) while K=3 linear maxes keep the
    engines' own argmax tie-break (lowest index wins). Dead slots (score
    -inf: fewer than K feasible nodes) report node -1 / score 0."""
    iota = jnp.arange(masked.shape[0], dtype=jnp.int32)
    tops_l, topi_l = [], []
    cur = masked
    for _ in range(K):
        i = jnp.argmax(cur).astype(jnp.int32)
        tops_l.append(cur[i])
        topi_l.append(i)
        cur = jnp.where(iota == i, -jnp.inf, cur)
    tops = jnp.stack(tops_l)
    topi = jnp.stack(topi_l)
    live = tops > -jnp.inf
    return jnp.where(live, topi, -1), jnp.where(live, tops, 0.0)


def explain_assignments(
    tables: ClusterTables, cyc: CycleArrays, pods: PodArrays,
    result: AssignResult, granularity: str = "class",
) -> ExplainResult:
    """The attribution reduction for one wave, against result.state (the
    post-wave assume state). Two granularities, bit-equal by shared code:

      * "pod"   — the spec: one full row per pod (the scan engine's
                  granularity; cost scales with P·N).
      * "class" — the cheap half evaluates ONCE per interned class (the
                  run-collapsed engine's fan-out; the waves engine shares
                  it — both already think in [SC, N] planes), then per-pod
                  work is pure GATHERS when no spec.nodeName pod is in the
                  batch (a lax.cond keeps the per-pod host fold for
                  batches that actually pin).

    Cost discipline (the <=2% bench budget): the REASON/feasibility
    reductions (the mask planes) always run — they are sum-reductions
    over planes the lattice already materializes, sub-ms. The score
    DECOMPOSITION — candidate ranking and per-component parts, which
    needs one extra full score-context pass per class (an engine
    wave-iteration's worth of work) — runs under a failure-gated
    lax.cond: a wave with nothing to explain (every pod placed) skips
    it, reporting empty candidates and zeroed parts; any wave carrying
    an unschedulable pod pays the full cost, proportional to need.

    Both granularities share `_explain_mask_row`/`_explain_score_row`/
    `_row_topk`/the parts stage, so the outputs are bit-equal — asserted
    by tests/test_explain.py."""
    from .lattice import _on

    state = result.state
    chosen = result.node
    nodes = tables.nodes
    nv = nodes.valid
    SC = tables.classes.valid.shape[0]
    P = pods.valid.shape[0]
    K = min(EXPLAIN_TOPK, int(nv.shape[0]))
    cls_safe = jnp.clip(pods.cls, 0, SC - 1)
    validn_scalar = jnp.sum(nv).astype(jnp.int32)
    i32 = jnp.int32
    any_failed = ((chosen < 0) & pods.valid).any()

    def host_plane(nnr):
        return (nnr < 0) | (nodes.name_id == nnr) | ~_on(cyc.ecfg.f_name)

    def parts_stage(pn, ctx_at):
        """Score decomposition at the explained node: [P]-sized gathers +
        pointwise resource scores (shared by both granularities)."""
        w = cyc.ecfg
        j = jnp.maximum(pn, 0)
        req = tables.reqs.vec[tables.classes.rid[cls_safe]]  # [P, R]
        least, balanced, most = jax.vmap(resource_scores_row)(
            req, state.used[j][:, None, :], nodes.alloc[j][:, None, :])
        parts = jnp.stack([
            cyc.static.score[cls_safe, j],
            least[:, 0] * w.w_least, balanced[:, 0] * w.w_balanced,
            most[:, 0] * w.w_most,
            ctx_at[:, 0] * w.w_interpod, ctx_at[:, 1] * w.w_even,
            ctx_at[:, 2] * w.w_ssel,
        ], axis=1)                                           # [P, 7]
        return jnp.where((pn >= 0)[:, None], parts, 0.0)

    def cheap_score(_):
        # failure-free wave: nothing to rank or decompose
        return (jnp.full((P, K), -1, i32), jnp.zeros((P, K), jnp.float32),
                jnp.zeros((P, len(EXPLAIN_SCORE_COMPONENTS)), jnp.float32),
                jnp.where(chosen >= 0, chosen, -1))

    if granularity == "pod":
        def mrow(c, nnr):
            r8, m8 = _explain_mask_row(tables, cyc, state, c)
            host_ok = host_plane(nnr)
            host_rej = jnp.sum(nv & ~host_ok).astype(i32)
            reasons = jnp.concatenate([r8[:7], host_rej[None], r8[7:]])
            feas = jnp.sum(m8 & host_ok).astype(i32)
            return reasons, feas

        reasons, feas = jax.vmap(mrow)(cls_safe, pods.node_name_req)

        def pod_score(_):
            def row(c, nnr, ch):
                _r8, m8 = _explain_mask_row(tables, cyc, state, c)
                full = m8 & host_plane(nnr)
                sc_row, cx = _explain_score_row(tables, cyc, state, c)
                topn, tops = _row_topk(
                    jnp.where(full, sc_row, -jnp.inf), K)
                pn = jnp.where(ch >= 0, ch, topn[0])
                ctx_at = cx[:, jnp.maximum(pn, 0)]
                return topn, tops, pn, ctx_at

            topn, tops, pn, ctx_at = jax.vmap(row)(
                cls_safe, pods.node_name_req, chosen)
            return topn, tops, parts_stage(pn, ctx_at), pn

        topn, tops, parts, pn = jax.lax.cond(
            any_failed, pod_score, cheap_score, None)
    else:
        r8, m8 = jax.vmap(
            lambda c: _explain_mask_row(tables, cyc, state, c)
        )(jnp.arange(SC, dtype=jnp.int32))
        reasons9_c = jnp.concatenate(
            [r8[:, :7], jnp.zeros((SC, 1), i32), r8[:, 7:]], axis=1)
        feas_c = m8.sum(axis=1).astype(i32)
        any_pinned = ((pods.node_name_req >= 0) & pods.valid).any()

        def gather_mask(_):
            # no pinned pods: the host plane is all-true for every pod, so
            # the class-level reductions ARE the per-pod answers
            return reasons9_c[cls_safe], feas_c[cls_safe]

        def host_mask(_):
            def fin(c, nnr):
                host_ok = host_plane(nnr)
                host_rej = jnp.sum(nv & ~host_ok).astype(i32)
                reasons = jnp.concatenate(
                    [r8[c, :7], host_rej[None], r8[c, 7:]])
                feas = jnp.sum(m8[c] & host_ok).astype(i32)
                return reasons, feas

            return jax.vmap(fin)(cls_safe, pods.node_name_req)

        reasons, feas = jax.lax.cond(any_pinned, host_mask, gather_mask,
                                     None)

        def class_score(_):
            sc_rows, cx = jax.vmap(
                lambda c: _explain_score_row(tables, cyc, state, c)
            )(jnp.arange(SC, dtype=jnp.int32))
            masked_c = jnp.where(m8, sc_rows, -jnp.inf)
            topn_c, tops_c = jax.vmap(
                lambda row: _row_topk(row, K))(masked_c)

            def g(_):
                return topn_c[cls_safe], tops_c[cls_safe]

            def h(_):
                def fin(c, nnr):
                    full = m8[c] & host_plane(nnr)
                    return _row_topk(
                        jnp.where(full, sc_rows[c], -jnp.inf), K)

                return jax.vmap(fin)(cls_safe, pods.node_name_req)

            topn, tops = jax.lax.cond(any_pinned, h, g, None)
            pn = jnp.where(chosen >= 0, chosen, topn[:, 0])
            ctx_at = cx[cls_safe, :, jnp.maximum(pn, 0)]
            return topn, tops, parts_stage(pn, ctx_at), pn

        topn, tops, parts, pn = jax.lax.cond(
            any_failed, class_score, cheap_score, None)

    # invalid (padding) pods zero out across the board
    v = pods.valid
    vi = v.astype(i32)
    return ExplainResult(
        reasons=reasons * vi[:, None],
        valid_nodes=validn_scalar * vi,
        feasible_nodes=feas * vi,
        rejected_any=(validn_scalar - feas) * vi,
        top_nodes=jnp.where(v[:, None], topn, -1),
        top_scores=tops * v[:, None].astype(jnp.float32),
        score_parts=parts * v[:, None].astype(jnp.float32),
        part_node=jnp.where(v, pn, -1),
    )


def score_matrix(
    tables: ClusterTables, cyc: CycleArrays, pods: PodArrays
) -> Array:
    """[P, N] Score for every pending pod against the *initial* state — the
    tensor analog of prioritizeNodes (generic_scheduler.go:714-869): static
    lattice scores (preferred node affinity, taint PreferNoSchedule) plus
    least-requested/balanced-allocation plus soft inter-pod affinity, all
    weight-1 summed. Infeasible nodes score -inf."""
    state = initial_state(tables, cyc)
    nodes, classes, terms = tables.nodes, tables.classes, tables.terms
    D = cyc.ELD.shape[2] - 1

    def row(c, nnr, v):
        mask = pod_mask_row(tables, cyc, state, c, nnr, v)
        return jnp.where(mask, score_row(tables, cyc, state, c), -jnp.inf)

    return jax.vmap(row)(pods.cls, pods.node_name_req, pods.valid)


def initial_state(tables: ClusterTables, cyc: CycleArrays) -> AssignState:
    n = tables.nodes
    return AssignState(
        used=n.used, ppa=n.port_pair_any, ppw=n.port_pair_wild, ppt=n.port_triple,
        CNT=cyc.CNT, HOLD=cyc.HOLD, WSYM=cyc.WSYM,
        vol_any=n.vol_any, vol_rw=n.vol_rw,
    )
