"""Score-parity tensor kernels beyond the resource/affinity basics:

  * symmetric preferred inter-pod affinity weighting — the existing pods'
    PreferredDuringScheduling terms (and hard-affinity symmetric weight)
    pulling/pushing the incoming pod (interpod_affinity.go:119-215);
  * EvenPodsSpread SCORE for ScheduleAnyway constraints
    (priorities/even_pods_spread.go:106,139,175);
  * SelectorSpread — spread pods of the same Service/RC/RS/StatefulSet
    across hosts and zones (priorities/selector_spreading.go:58-165,
    zoneWeighting = 2/3);
  * ImageLocality — favor nodes already holding the pod's container images,
    spread-scaled against node heating (priorities/image_locality.go:39-92).

Everything here is expressed against the same interned TermTable/CNT carry
the predicates use, so the dynamic pieces stay live inside the assignment
loop (assume feedback) and the static pieces fold into the per-cycle lattice.
Pure-Python reference semantics: api/semantics.py (golden-tested).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..state.arrays import (
    Array,
    ClusterTables,
    NodeArrays,
    PodArrays,
    PodClassTable,
    TermTable,
)
from .interpod import domain_agg, domain_of_term

MAX_NODE_SCORE = 100.0

# hardPodAffinitySymmetricWeight default (apis/config/types.go:45-112 →
# DefaultHardPodAffinitySymmetricWeight = 1)
DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1

# image size thresholds (image_locality.go:33-35), converted to KiB
IMG_MIN_KIB = 23 * 1024
IMG_MAX_KIB = 1000 * 1024

# selector_spreading.go:33 — zone score weight when zone info is present
ZONE_WEIGHTING = 2.0 / 3.0


def symmetric_weight_cols(
    classes: PodClassTable, S: int,
    hard_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT,
) -> Array:
    """WCOLS [S, SC] f32: the signed symmetric-preference weight an existing
    pod of class c contributes through term s to any incoming pod that term
    matches: +w for preferred affinity, −w for preferred anti-affinity,
    +hard_weight for REQUIRED affinity terms (interpod_affinity.go:156-185)."""
    SC = classes.valid.shape[0]
    out = jnp.zeros((S, SC), jnp.float32)

    def scatter(term_ids, w):  # [SC, A], [SC, A] → [S, SC]
        s = jnp.maximum(term_ids, 0)
        val = jnp.where(term_ids >= 0, w, 0).astype(jnp.float32)
        add = jnp.zeros((S + 1, SC), jnp.float32)
        add = add.at[
            jnp.where(term_ids >= 0, s, S).T, jnp.arange(SC)[None, :]
        ].add(val.T)
        return add[:S]

    out = out + scatter(classes.paff_terms, classes.paff_w)
    out = out - scatter(classes.panti_terms, classes.panti_w)
    hard = scatter(classes.aff_terms, jnp.ones_like(classes.aff_terms))
    out = out + hard * jnp.asarray(hard_weight, jnp.float32)
    return out * classes.valid[None, :]


def weighted_per_node(WCOLS: Array, pods: PodArrays, N: int) -> Array:
    """WSYM seed [S, N] f32: Σ over existing pods of their class's signed
    symmetric weights, scattered by node — the cycle-start counterpart of
    processExistingPod (interpod_affinity.go:124-185)."""
    per_e = WCOLS[:, jnp.maximum(pods.cls, 0)]  # [S, E]
    on_node = (pods.node_id >= 0) & pods.valid
    per_e = jnp.where(on_node[None, :], per_e, 0.0)
    idx = jnp.where(on_node, pods.node_id, N)
    S = WCOLS.shape[0]
    out = jnp.zeros((S, N + 1), jnp.float32)
    out = out.at[jnp.arange(S)[:, None],
                 jnp.broadcast_to(idx[None, :], per_e.shape)].add(per_e)
    return out[:, :N]


def sym_affinity_contrib(
    cls: Array,
    TM: Array,          # [S, SC]
    WSYM: Array,        # [S, N] live signed weights
    terms: TermTable,
    nodes: NodeArrays,
    D: int,
) -> Array:
    """[N] f32 raw symmetric contribution for one incoming pod: for every term
    s the pod MATCHES (TM[s, cls]), credit every node sharing the topology
    domain of a contributing existing pod (processTerm's fixed-term spreading
    over same-topology nodes, interpod_affinity.go:87-117). Added to the raw
    preferred-affinity counts BEFORE min-max normalization."""
    S = TM.shape[0]
    dom, has_key = domain_of_term(nodes, terms.topo_key)  # [S, N]
    seg = domain_agg(WSYM, dom, D)                        # [S, D+1] (f32 sum)
    per_term = jnp.take_along_axis(seg, jnp.where(dom >= 0, dom, D), axis=1)
    credit = jnp.where(TM[:, cls][:, None] & has_key, per_term, 0.0)
    return credit.sum(0)


def even_spread_soft_row(
    cls: Array,
    classes: PodClassTable,
    terms: TermTable,
    CNT: Array,            # [S, N] live counts
    nodes: NodeArrays,
    node_match_row: Array, # [N] this class's selector/affinity eligibility
    D: int,
) -> Array:
    """[N] f32 0..100: EvenPodsSpread score over ScheduleAnyway constraints
    (even_pods_spread.go:106-227). Raw score per node = Σ matching pods in
    the node's topology domain; normalized inverted (total−raw)/(total−min),
    ineligible nodes (missing key / failing node match) score 0.

    Deviation (docs/PARITY.md): normalization runs over all valid eligible
    nodes, not just the cycle's feasible set — ordering is unaffected."""
    s_ids = classes.tsc_term[cls]                 # [TS]
    s = jnp.maximum(s_ids, 0)
    soft = (s_ids >= 0) & ~classes.tsc_hard[cls]  # [TS]

    dom, has_key = domain_of_term(nodes, terms.topo_key[s])  # [TS, N]
    # counts restricted to nodes eligible for this pod (buildPodTopologySpreadMap
    # checks PodMatchesNodeSelectorAndAffinityTerms on the counted node)
    seg = domain_agg(CNT[s], dom, D, eligible=node_match_row[None, :])
    cnt = jnp.take_along_axis(seg, jnp.where(dom >= 0, dom, D), axis=1)
    raw = jnp.where(soft[:, None] & has_key, cnt, 0).sum(0)  # [N] i32

    elig = (
        node_match_row & nodes.valid
        & (~soft[:, None] | has_key).all(0)  # all soft keys present
    )
    any_soft = soft.any()
    rawf = raw.astype(jnp.float32)
    total = jnp.sum(jnp.where(elig, rawf, 0.0))
    mn = jnp.min(jnp.where(elig, rawf, jnp.inf))
    denom = total - jnp.where(jnp.isinf(mn), 0.0, mn)
    score = jnp.where(
        denom > 0,
        MAX_NODE_SCORE * (total - rawf) / jnp.maximum(denom, 1e-9),
        MAX_NODE_SCORE,
    )
    return jnp.where(any_soft & elig, score, 0.0)


def selector_spread_row(
    cls: Array,
    classes: PodClassTable,
    CNT: Array,          # [S, N]
    nodes: NodeArrays,
    zone_keys: Array,    # [2] i32 topo-key ids, -1 absent
    D: int,
) -> Array:
    """[N] f32 0..100: SelectorSpread (selector_spreading.go:62-165).
    count = matching pods of the pod's owning Services/controllers on each
    node; node score = 100·(maxCount−count)/maxCount, blended 1/3:2/3 with
    the same statistic aggregated by zone when zone labels exist."""
    s_ids = classes.ssel_terms[cls]              # [SS]
    s = jnp.maximum(s_ids, 0)
    active = (s_ids >= 0)[:, None]               # [SS, 1]
    cnt = jnp.where(active, CNT[s], 0).sum(0)    # [N] i32
    cntf = cnt.astype(jnp.float32)
    has_sel = (s_ids >= 0).any()

    valid = nodes.valid
    max_n = jnp.max(jnp.where(valid, cntf, 0.0))
    node_score = jnp.where(
        max_n > 0, MAX_NODE_SCORE * (max_n - cntf) / max_n, MAX_NODE_SCORE)

    # zone aggregation: modern zone label wins, legacy fills the gaps; the
    # two keys' compact domains live in disjoint halves of a 2D+1 bucket
    def zdom_of(kslot):
        k = zone_keys[kslot]
        col = nodes.domain[:, jnp.maximum(k, 0)]
        return jnp.where((k >= 0) & valid, col, -1)

    z0, z1 = zdom_of(0), zdom_of(1)
    zdom = jnp.where(z0 >= 0, z0, jnp.where(z1 >= 0, D + z1, -1))  # [N]
    has_zone = zdom >= 0
    idx = jnp.where(has_zone, zdom, 2 * D)
    zcounts = jnp.zeros((2 * D + 1,), jnp.float32).at[idx].add(
        jnp.where(has_zone, cntf, 0.0))
    zcnt = zcounts[idx]                                   # [N]
    max_z = jnp.max(zcounts[: 2 * D])
    zone_score = jnp.where(
        max_z > 0, MAX_NODE_SCORE * (max_z - zcnt) / max_z, MAX_NODE_SCORE)
    have_zones = has_zone.any()

    blended = jnp.where(
        have_zones & has_zone,
        node_score * (1.0 - ZONE_WEIGHTING) + ZONE_WEIGHTING * zone_score,
        node_score,
    )
    return jnp.where(has_sel & valid, blended, 0.0)


def image_locality_static(tables: ClusterTables) -> Array:
    """[SC, N] f32 0..100: ImageLocality (image_locality.go:39-92). Static per
    cycle — depends only on node image states. sumScore(c, n) =
    Σ_{img ∈ class} present(n, img)·size(img)·spread(img), spread =
    nodesWithImage/totalNodes; clamped to [23MiB, 1000MiB] then scaled."""
    nodes, classes, images = tables.nodes, tables.classes, tables.images
    N = nodes.valid.shape[0]
    img_ids = classes.img_ids                      # [SC, CI]
    safe = jnp.maximum(img_ids, 0)
    word = safe >> 5
    bit = (safe & 31).astype(jnp.uint32)
    words = nodes.img_words[:, word]               # [N, SC, CI]
    bits = ((words >> bit[None, :, :]) & 1).astype(jnp.int32)
    bits = bits * nodes.valid[:, None, None]       # [N, SC, CI]
    present = jnp.transpose(bits.astype(bool), (1, 2, 0)) \
        & (img_ids >= 0)[:, :, None]               # [SC, CI, N]

    total_nodes = jnp.maximum(nodes.valid.sum(), 1).astype(jnp.float32)
    # ImageStateSummary.NumNodes: how many nodes hold the image cluster-wide
    num_nodes = bits.sum(0) * (img_ids >= 0)       # [SC, CI]
    spread = num_nodes.astype(jnp.float32) / total_nodes
    size = images.size_kib[safe].astype(jnp.float32) * (img_ids >= 0)
    scaled = size * spread                          # [SC, CI]
    sums = (present * scaled[:, :, None]).sum(1)    # [SC, N]
    clamped = jnp.clip(sums, IMG_MIN_KIB, IMG_MAX_KIB)
    return (MAX_NODE_SCORE * (clamped - IMG_MIN_KIB)
            / float(IMG_MAX_KIB - IMG_MIN_KIB))
