"""kubernetes_tpu — a TPU-native scheduling framework with the capability
surface of the Kubernetes control plane's scheduler.

Instead of the reference's per-pod, per-node Go loops (pkg/scheduler), the
Filter and Score phases are boolean constraint masks and score tensors over a
(pod-class × node) lattice, evaluated in one XLA dispatch per scheduling cycle;
assignment is a lax.scan that preserves sequential assume semantics.

Layers:
  api/       — object model + executable reference semantics (the oracle)
  state/     — vocab interning, class tables, device arrays, cache
  ops/       — the tensor kernels (Filter masks, Score tensors, assignment)
  sched/     — cycle driver, queue, framework plugin surface
  parallel/  — Mesh/pjit sharding of the lattice across chips
  extender/  — HTTP Scheduler-Extender boundary to stock clusters
  models/    — end-to-end scheduling profiles (flagship entry points)
"""

__version__ = "0.1.0"

from .api.types import (  # noqa: F401
    Affinity,
    HostPort,
    LabelSelector,
    Node,
    NodeSelector,
    NodeSelectorTerm,
    Op,
    Pod,
    PodAffinityTerm,
    Requirement,
    Resources,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOp,
    TopologySpreadConstraint,
    UnsatisfiableAction,
)
from .sched.cycle import BatchScheduler, CycleResult  # noqa: F401
