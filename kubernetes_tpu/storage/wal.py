"""Write-ahead log + snapshot persistence for the MVCC kvstore.

Role of etcd's `wal/` + `snap/` packages under the reference apiserver: every
mutation is framed, CRC'd and appended to a segment file BEFORE it is applied
to the in-memory store, so an apiserver process death loses nothing that was
acknowledged — bindings, Leases, bind intents and (critically) the revision
counter itself all come back on reboot. Both KV backends (native/kvstore.cpp
and PyKV) sit behind one `DurableKV` wrapper writing ONE wal format, so the
dlopen-fallback path produces byte-identical logs.

On-disk layout (`data_dir/`)::

    wal-00000001.log     append-only segment: 16-byte header
                         (magic "KTPUWAL1" + i64 seq) then frames
    snap-<rev 16d>.snap  compacted snapshot: magic "KTPUSNP1" + payload
                         + u32 crc32(payload); written tmp+rename (atomic)

    frame   := u32 len | u32 crc32(payload) | payload
    payload := u8 op | i64 rev | u32 klen | key | u32 vlen | value
    op      := 1 PUT | 2 DELETE | 3 COMPACT (rev = new floor, no key/value)

Durability policy (``KTPU_STORE_DURABILITY``):

    off     append only — no fsync ever (page cache still survives process
            death; only machine death can lose acknowledged writes)
    batch   group commit: a background flusher fsyncs every
            ``KTPU_WAL_FSYNC_INTERVAL`` seconds (default 0.05)
    always  fsync before every acknowledgement

Recovery decision table (`read_segment` / `load_state`):

    clean tail                      replay everything
    torn tail (short frame, or CRC  truncate the file at the bad frame and
    mismatch on the FINAL record    continue — the crash interrupted an
    of the FINAL segment)           unacknowledged append
    mid-log corruption (bad frame   refuse to start (WalCorruptionError):
    with valid bytes after it, or   history is rewritten, replaying past it
    in a non-final segment)         would reissue revisions
    corrupt snapshot                refuse to start (a partial snapshot can
                                    never carry the final name — tmp+rename)

The RV-continuity invariant: recovery seeds the revision counter from the
snapshot header and asserts every replayed record re-earns EXACTLY the
revision it logged. A reissued RV would silently corrupt every watch resume
token in the fleet, so a mismatch is a refuse-to-start corruption error.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import IO, Iterable, List, Optional, Tuple

from kubernetes_tpu.component.metrics import DEFAULT_REGISTRY as _REG
from kubernetes_tpu.utils import faultline

SEG_MAGIC = b"KTPUWAL1"
SNAP_MAGIC = b"KTPUSNP1"
SEG_HEADER_LEN = len(SEG_MAGIC) + 8

OP_PUT = 1
OP_DELETE = 2
OP_COMPACT = 3

_FRAME_HDR = struct.Struct("<II")   # len, crc32(payload)
_PAYLOAD_HDR = struct.Struct("<Bq")  # op, rev
_U32 = struct.Struct("<I")

WAL_APPENDS = _REG.counter(
    "apiserver_storage_wal_appends_total",
    "Records appended to the kvstore write-ahead log, by op "
    "(put, delete, compact)",
    labels=("op",))
WAL_FSYNCS = _REG.counter(
    "apiserver_storage_wal_fsyncs_total",
    "fsync calls issued by the WAL, by trigger (commit = the `always` "
    "policy's per-acknowledgement sync, batch = the group-commit flusher, "
    "rotate, snapshot, dir = directory-entry sync after create/rename)",
    labels=("trigger",))
WAL_SNAPSHOTS = _REG.counter(
    "apiserver_storage_wal_snapshots_total",
    "Compacted snapshots written (each truncates the log: older segments "
    "and snapshots are deleted once the new snapshot is durable)")
RECOVERY_SECONDS = _REG.gauge(
    "apiserver_storage_recovery_seconds",
    "Wall seconds the last boot spent restoring the kvstore from disk "
    "(snapshot load + WAL tail replay)")
RECOVERY_RECORDS = _REG.gauge(
    "apiserver_storage_recovery_records",
    "Records restored by the last boot, by source (snapshot, wal); "
    "source=torn counts tail records discarded by the clean-truncate rule",
    labels=("source",))

_OP_NAMES = {OP_PUT: "put", OP_DELETE: "delete", OP_COMPACT: "compact"}


class WalError(Exception):
    """Base for WAL failures."""


class WalWriteError(WalError):
    """An append could not be made durable (disk full / IO error). The
    in-memory store was NOT mutated — the failed write simply never
    happened, exactly as if the request had been rejected."""


class WalCorruptionError(WalError):
    """Structured refuse-to-start error: the log or snapshot is damaged in
    a way replay cannot safely skip (mid-log corruption, snapshot CRC
    mismatch, or a replayed record that would re-earn a different revision
    than it logged)."""

    def __init__(self, reason: str, path: str = "", offset: int = -1):
        self.reason = reason
        self.path = path
        self.offset = offset
        where = f" at {os.path.basename(path)}" if path else ""
        where += f"+{offset}" if offset >= 0 else ""
        super().__init__(f"wal corruption{where}: {reason}")


@dataclass(frozen=True)
class WalRecord:
    op: int
    rev: int
    key: str
    value: bytes


@dataclass
class RecoveredState:
    """Everything `load_state` pulled off disk, ready to feed a backend."""

    snapshot_rev: int = 0
    snapshot_compacted: int = 0
    snapshot_records: List[Tuple[str, bytes, int, int]] = field(
        default_factory=list)  # (key, value, create_rev, mod_rev)
    wal_records: List[WalRecord] = field(default_factory=list)
    torn_tail_truncated: bool = False
    next_seq: int = 1


# --------------------------------------------------------------------- #
# framing
# --------------------------------------------------------------------- #

def encode_record(op: int, rev: int, key: str, value: bytes) -> bytes:
    kb = key.encode()
    return b"".join((
        _PAYLOAD_HDR.pack(op, rev),
        _U32.pack(len(kb)), kb,
        _U32.pack(len(value)), value,
    ))


def decode_record(payload: bytes) -> WalRecord:
    try:
        op, rev = _PAYLOAD_HDR.unpack_from(payload, 0)
        off = _PAYLOAD_HDR.size
        (klen,) = _U32.unpack_from(payload, off)
        off += 4
        key = payload[off:off + klen].decode()
        off += klen
        (vlen,) = _U32.unpack_from(payload, off)
        off += 4
        value = payload[off:off + vlen]
        if off + vlen != len(payload) or op not in _OP_NAMES:
            raise ValueError("trailing bytes or unknown op")
    except (struct.error, UnicodeDecodeError, ValueError) as e:
        raise WalCorruptionError(f"undecodable record payload: {e}") from None
    return WalRecord(op, rev, key, value)


def frame(payload: bytes) -> bytes:
    return _FRAME_HDR.pack(len(payload), zlib.crc32(payload)) + payload


# --------------------------------------------------------------------- #
# segment / snapshot files
# --------------------------------------------------------------------- #

def _fsync_dir(path: str) -> None:
    """Make directory entries durable. fsync on a file persists its bytes,
    not the name pointing at them: a rename/create is only crash-safe once
    the directory itself is synced."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds; nothing we can do
    try:
        os.fsync(fd)
        WAL_FSYNCS.inc(trigger="dir")
    finally:
        os.close(fd)


def _seg_name(seq: int) -> str:
    return f"wal-{seq:08d}.log"


def _snap_name(rev: int) -> str:
    return f"snap-{rev:016d}.snap"


def list_segments(data_dir: str) -> List[Tuple[int, str]]:
    out = []
    for n in os.listdir(data_dir):
        if n.startswith("wal-") and n.endswith(".log"):
            try:
                out.append((int(n[4:-4]), os.path.join(data_dir, n)))
            except ValueError:
                continue
    return sorted(out)


def list_snapshots(data_dir: str) -> List[Tuple[int, str]]:
    out = []
    for n in os.listdir(data_dir):
        if n.startswith("snap-") and n.endswith(".snap"):
            try:
                out.append((int(n[5:-5]), os.path.join(data_dir, n)))
            except ValueError:
                continue
    return sorted(out)


def read_segment(path: str, final: bool) -> Tuple[List[WalRecord],
                                                  Optional[int]]:
    """Parse one segment per the recovery decision table.

    Returns (records, truncate_at): truncate_at is the byte offset the
    caller must ftruncate the file to when the final record was torn
    (None = clean). Mid-log corruption raises WalCorruptionError."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < SEG_HEADER_LEN or data[:len(SEG_MAGIC)] != SEG_MAGIC:
        if final and len(data) < SEG_HEADER_LEN:
            # a crash between creating the file and writing its header —
            # nothing in it was ever acknowledged
            return [], 0
        raise WalCorruptionError("bad segment header", path=path, offset=0)
    records: List[WalRecord] = []
    off, size = SEG_HEADER_LEN, len(data)
    while off < size:
        def torn_or_corrupt(reason: str, tail: bool):
            # tail = the damage plausibly extends to EOF (an interrupted
            # append). Anything else — or any damage in a non-final
            # segment — is rewritten history: refuse.
            if final and tail:
                return None
            raise WalCorruptionError(reason, path=path, offset=off)

        if size - off < _FRAME_HDR.size:
            torn_or_corrupt("short frame header", tail=True)
            return records, off
        length, crc = _FRAME_HDR.unpack_from(data, off)
        end = off + _FRAME_HDR.size + length
        if end > size:
            torn_or_corrupt(f"frame of {length}B overruns segment",
                            tail=True)
            return records, off
        payload = data[off + _FRAME_HDR.size:end]
        if zlib.crc32(payload) != crc:
            torn_or_corrupt("payload CRC mismatch", tail=(end == size))
            return records, off
        records.append(decode_record(payload))
        off = end
    return records, None


def write_snapshot(data_dir: str, rev: int, compacted: int,
                   records: Iterable[Tuple[str, bytes, int, int]]) -> str:
    """Atomically persist the full keyspace at `rev` (tmp + rename: a
    partial snapshot can never carry the final name, so recovery either
    sees a complete CRC-valid file or none at all)."""
    parts = [struct.pack("<qq", rev, compacted)]
    n = 0
    for key, value, create_rev, mod_rev in records:
        kb = key.encode()
        parts.append(b"".join((
            _U32.pack(len(kb)), kb, _U32.pack(len(value)), value,
            struct.pack("<qq", create_rev, mod_rev))))
        n += 1
    parts.insert(0, struct.pack("<q", n))
    payload = b"".join(parts)
    path = os.path.join(data_dir, _snap_name(rev))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(SNAP_MAGIC + payload + _U32.pack(zlib.crc32(payload)))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # the rename is only durable once the directory entry is — without this
    # a machine death could persist the caller's subsequent unlinks of the
    # old segments while losing the new snapshot: neither survives
    _fsync_dir(data_dir)
    WAL_FSYNCS.inc(trigger="snapshot")
    WAL_SNAPSHOTS.inc()
    return path


def read_snapshot(path: str) -> Tuple[int, int,
                                      List[Tuple[str, bytes, int, int]]]:
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < len(SNAP_MAGIC) + 28 or data[:len(SNAP_MAGIC)] != SNAP_MAGIC:
        raise WalCorruptionError("bad snapshot header", path=path)
    payload, (crc,) = data[len(SNAP_MAGIC):-4], _U32.unpack(data[-4:])
    if zlib.crc32(payload) != crc:
        raise WalCorruptionError("snapshot CRC mismatch", path=path)
    try:
        n, rev, compacted = struct.unpack_from("<qqq", payload, 0)
        off = 24
        records = []
        for _ in range(n):
            (klen,) = _U32.unpack_from(payload, off)
            off += 4
            key = payload[off:off + klen].decode()
            off += klen
            (vlen,) = _U32.unpack_from(payload, off)
            off += 4
            value = payload[off:off + vlen]
            off += vlen
            create_rev, mod_rev = struct.unpack_from("<qq", payload, off)
            off += 16
            records.append((key, value, create_rev, mod_rev))
    except (struct.error, UnicodeDecodeError) as e:
        raise WalCorruptionError(f"undecodable snapshot: {e}",
                                 path=path) from None
    return rev, compacted, records


def load_state(data_dir: str) -> RecoveredState:
    """Read everything recoverable from `data_dir` (no backend touched).

    The `wal.torn@tail` chaos seam fires here: it chops bytes off the final
    segment before parsing, simulating the power cut landing mid-append."""
    st = RecoveredState()
    if not os.path.isdir(data_dir):
        return st
    segments = list_segments(data_dir)
    if segments and faultline.should("wal.torn", "tail"):
        _, last = segments[-1]
        sz = os.path.getsize(last)
        if sz > SEG_HEADER_LEN:
            with open(last, "r+b") as f:
                f.truncate(max(SEG_HEADER_LEN, sz - 7))
    snaps = list_snapshots(data_dir)
    if snaps:
        rev, compacted, records = read_snapshot(snaps[-1][1])
        st.snapshot_rev = rev
        # events at/below the snapshot revision are not persisted: the
        # recovered floor rises to the snapshot itself, so a resume beneath
        # it earns an HONEST 410 instead of a silent gap (etcd compaction
        # semantics); WAL-tail replay rebuilds the ring above it
        st.snapshot_compacted = max(compacted, rev)
        st.snapshot_records = records
    for i, (seq, path) in enumerate(segments):
        final = (i == len(segments) - 1)
        records, truncate_at = read_segment(path, final=final)
        if truncate_at is not None:
            # POSIX truncate EXTENDS a shorter file: a final segment that
            # died before its 16-byte header landed (truncate_at=0) must
            # shrink to empty so the writer rewrites a valid header — padding
            # it to SEG_HEADER_LEN zero bytes would make every subsequent
            # acknowledged append sit behind a corrupt header and brick the
            # NEXT boot
            with open(path, "r+b") as f:
                f.truncate(truncate_at if truncate_at >= SEG_HEADER_LEN
                           else 0)
            st.torn_tail_truncated = True
        st.wal_records.extend(records)
        st.next_seq = seq  # the writer re-opens the final segment for append
    return st


# --------------------------------------------------------------------- #
# writer
# --------------------------------------------------------------------- #

class WalWriter:
    """Append-only segment writer with the off/batch/always fsync policy.

    One writer per store; `append` is called under the DurableKV commit
    lock, so frames never interleave. The `batch` flusher thread group-
    commits via the synced-offset watermark — a sync that another sync
    already covered is skipped."""

    POLICIES = ("off", "batch", "always")

    def __init__(self, data_dir: str, durability: str = "batch",
                 fsync_interval: Optional[float] = None,
                 segment_bytes: Optional[int] = None,
                 start_seq: int = 1):
        if durability not in self.POLICIES:
            raise ValueError(
                f"KTPU_STORE_DURABILITY={durability!r}: want off|batch|always")
        self.data_dir = data_dir
        self.durability = durability
        self._fsync_interval = float(
            fsync_interval if fsync_interval is not None
            else os.environ.get("KTPU_WAL_FSYNC_INTERVAL", "0.05"))
        self._segment_bytes = int(
            segment_bytes if segment_bytes is not None
            else os.environ.get("KTPU_WAL_SEGMENT_BYTES", str(64 << 20)))
        os.makedirs(data_dir, exist_ok=True)
        self._mu = threading.Lock()
        self._f: Optional[IO[bytes]] = None
        self._seq = 0
        self._written = 0   # bytes appended to the current segment
        self._synced = 0    # bytes known durable (group-commit watermark)
        self._closed = False
        self._open_segment(start_seq)
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if durability == "batch":
            self._flusher = threading.Thread(
                target=self._flush_loop, name="wal-flusher", daemon=True)
            self._flusher.start()

    def _open_segment(self, seq: int) -> None:
        path = os.path.join(self.data_dir, _seg_name(seq))
        existed = os.path.exists(path)
        self._f = open(path, "ab")
        if not existed or os.path.getsize(path) < SEG_HEADER_LEN:
            # a partial header (crash between file creation and the 16th
            # byte) is wiped, never appended-after: the header must start
            # at offset 0
            self._f.truncate(0)
            self._f.write(SEG_MAGIC + struct.pack("<q", seq))
            self._f.flush()
            if not existed and self.durability != "off":
                # the file's bytes fsync with the first record; its
                # DIRECTORY ENTRY only becomes durable via the dir fd —
                # without this, machine death after a rotation can lose a
                # whole segment of acknowledged (file-fsynced) records
                _fsync_dir(self.data_dir)
        self._seq = seq
        self._written = self._f.tell()
        self._synced = 0

    def append(self, op: int, rev: int, key: str, value: bytes) -> None:
        """Make one record durable per the policy. Raises WalWriteError
        (nothing written) when the disk is full — the `disk.full@wal` seam
        fires here, BEFORE any bytes land, so the caller's memory state and
        the log can never disagree."""
        if faultline.should("disk.full", "wal"):
            raise WalWriteError("disk full (injected): wal append refused")
        buf = frame(encode_record(op, rev, key, value))
        with self._mu:
            f = self._f
            start = self._written
            try:
                f.write(buf)
                f.flush()
            except OSError as e:
                # a partial append must not survive as a "torn tail" the
                # next boot would silently truncate INTO acknowledged data
                try:
                    f.truncate(start)
                    f.seek(start)
                except OSError:
                    pass
                raise WalWriteError(f"wal append failed: {e}") from None
            self._written = start + len(buf)
            WAL_APPENDS.inc(op=_OP_NAMES.get(op, "?"))
            # record appended (page cache) but not yet fsynced
            faultline.crashpoint("wal:pre_fsync")
            if self.durability == "always":
                self._sync_locked(trigger="commit")
            # record durable (or policy says the flusher owns the sync);
            # the in-memory store has NOT yet applied it
            faultline.crashpoint("wal:post_fsync")
            if self._written >= self._segment_bytes:
                self._rotate_locked()

    def _sync_locked(self, trigger: str) -> None:
        if self._synced >= self._written or self._f is None:
            return  # group commit: someone already synced past us
        os.fsync(self._f.fileno())
        self._synced = self._written
        WAL_FSYNCS.inc(trigger=trigger)

    def sync(self, trigger: str = "batch") -> None:
        with self._mu:
            if not self._closed:
                self._sync_locked(trigger=trigger)

    def _flush_loop(self) -> None:
        while not self._stop.wait(self._fsync_interval):
            try:
                self.sync(trigger="batch")
            except OSError:
                pass  # a failed background sync retries next tick

    def _rotate_locked(self) -> None:
        if self.durability != "off":
            self._sync_locked(trigger="rotate")
        self._f.close()
        self._open_segment(self._seq + 1)

    def snapshot(self, rev: int, compacted: int,
                 records: Iterable[Tuple[str, bytes, int, int]]) -> None:
        """Persist a snapshot and TRUNCATE the log: rotate to a fresh
        segment, then delete every older segment and snapshot — all their
        records are ≤ rev and covered by the new snapshot."""
        with self._mu:
            write_snapshot(self.data_dir, rev, compacted, records)
            self._rotate_locked()
            # snapshot rename + fresh segment creation must BOTH be durable
            # directory entries before any unlink below can land on disk
            _fsync_dir(self.data_dir)
            keep_seq, keep_snap = self._seq, rev
        for seq, path in list_segments(self.data_dir):
            if seq < keep_seq:
                try:
                    os.remove(path)
                except OSError:
                    pass
        for srev, path in list_snapshots(self.data_dir):
            if srev < keep_snap:
                try:
                    os.remove(path)
                except OSError:
                    pass

    def close(self) -> None:
        self._stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=2)
        with self._mu:
            if self._closed:
                return
            self._closed = True
            if self._f is not None:
                try:
                    if self.durability != "off":
                        self._sync_locked(trigger="commit")
                except OSError:
                    pass
                self._f.close()
                self._f = None
