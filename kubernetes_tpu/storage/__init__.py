"""Persistence layer: native MVCC kvstore + storage.Interface.

TPU-native analog of SURVEY.md layer 1 (etcd + the apiserver's etcd3 storage,
staging/src/k8s.io/apiserver/pkg/storage/etcd3/). The store itself is C++
(native/kvstore.cpp) behind a ctypes binding, with a pure-Python fallback.
"""

from kubernetes_tpu.storage.native import (
    CompactedError,
    DurableKV,
    NativeKV,
    PyKV,
    new_kv,
)
from kubernetes_tpu.storage.store import Storage
from kubernetes_tpu.storage.wal import WalCorruptionError, WalWriteError

__all__ = ["CompactedError", "DurableKV", "NativeKV", "PyKV", "new_kv",
           "Storage", "WalCorruptionError", "WalWriteError"]
