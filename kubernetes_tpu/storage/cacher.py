"""Watch cache: the Cacher tier between the KV store and watchers.

Analog of the apiserver's Cacher
(/root/reference/staging/src/k8s.io/apiserver/pkg/storage/cacher/cacher.go:309):
the reference interposes a reflector-fed ring buffer (watchCache, :369-374)
between etcd and the N registered watchers so that

  * each event is decoded ONCE, not once per watcher, and
  * a new watcher resuming from a recent resourceVersion replays its catch-up
    window from memory — storage reads stay independent of watcher count
    (`WatchCache.events_since`); only a resume older than the ring's horizon
    falls through to the backing store (counted in `storage_fallbacks`).

The ring holds already-decoded events `(rev, type, key, obj)` in revision
order. `horizon` is the revision BEFORE the oldest retained event: a resume
from `since >= horizon` is served fully from memory.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, List, NamedTuple, Optional

DEFAULT_CAPACITY = 8192  # ring slots (cacher.go watchCache capacity analog)


class CachedEvent(NamedTuple):
    rev: int
    type: str        # machinery.watch ADDED/MODIFIED/DELETED
    key: str
    obj: Dict[str, Any]  # decoded, resourceVersion set


class WatchCache:
    """Decoded-event ring buffer with a revision horizon."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, horizon: int = 0):
        self._mu = threading.Lock()
        self._ring: Deque[CachedEvent] = deque()
        self._capacity = capacity
        self._horizon = horizon   # rev before the oldest retained event
        self.hits = 0             # catch-ups served from memory
        self.storage_fallbacks = 0  # catch-ups that had to read the store

    @property
    def horizon(self) -> int:
        with self._mu:
            return self._horizon

    def add(self, ev: CachedEvent) -> None:
        with self._mu:
            if len(self._ring) >= self._capacity:
                evicted = self._ring.popleft()
                self._horizon = evicted.rev
            self._ring.append(ev)

    def compact(self, at_rev: int) -> None:
        """Drop every retained event at or below `at_rev` and raise the
        horizon to it — what a sustained storm does to the ring organically
        (old revisions churn out). Resumes below the new horizon fall back
        to storage, where a compacted revision earns its 410."""
        with self._mu:
            while self._ring and self._ring[0].rev <= at_rev:
                self._ring.popleft()
            self._horizon = max(self._horizon, at_rev)

    def events_since(self, since: int, prefix: str) -> Optional[List[CachedEvent]]:
        """Events with rev > since under prefix, from memory — or None when
        `since` predates the ring's horizon (caller falls back to storage)."""
        with self._mu:
            if since < self._horizon:
                self.storage_fallbacks += 1
                return None
            self.hits += 1
            return [e for e in self._ring
                    if e.rev > since and e.key.startswith(prefix)]
