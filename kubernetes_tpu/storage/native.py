"""ctypes binding to the native MVCC kvstore, with auto-build + fallback.

The C++ store (native/kvstore.cpp) plays the role etcd plays under the
reference apiserver (storage/etcd3/store.go). `PyKV` is a pure-Python replica
of the same interface for environments without a C++ toolchain; both are
exercised by the same tests. `DurableKV` wraps EITHER backend with the
write-ahead log + snapshot layer (storage/wal.py) — one wal format, so the
fallback path produces byte-identical logs and recovers into either backend.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from kubernetes_tpu.component.metrics import DEFAULT_REGISTRY as _REG
from kubernetes_tpu.utils import faultline

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")

_log = logging.getLogger("ktpu.storage")

# which kvstore implementation actually carries the control plane — a fleet
# silently degraded to the slow pure-Python path by a toolchain break must be
# visible on a dashboard, not discovered by profiling (ISSUE 19 satellite)
BACKEND_INFO = _REG.gauge(
    "apiserver_storage_backend_info",
    "1 for the kvstore backend this process selected (native = the C++ "
    "store, python = the PyKV fallback); the fallback series carries "
    'reason="build-failed|dlopen-failed|chaos|requested"',
    labels=("backend", "reason"))

EVENT_PUT = 0
EVENT_DELETE = 1
EVENT_CREATE = 2


@dataclass(frozen=True)
class KVRecord:
    key: str
    value: bytes
    create_rev: int
    mod_rev: int


@dataclass(frozen=True)
class KVEvent:
    rev: int
    type: int  # EVENT_PUT | EVENT_DELETE | EVENT_CREATE
    key: str
    value: bytes  # for DELETE: the previous value


class CompactedError(Exception):
    """Watch/list from a revision older than the compaction point."""


_build_error: Optional[str] = None  # why native is unavailable (surfaced
# once by new_kv's backend-visibility log line, never re-raised)


def _build_lib(force: bool = False) -> Optional[str]:
    global _build_error
    so = os.path.join(_NATIVE_DIR, "libkvstore.so")
    if os.path.exists(so) and not force:
        return so
    try:
        cmd = ["make", "-C", _NATIVE_DIR] + (["-B"] if force else [])
        proc = subprocess.run(cmd, check=True, capture_output=True,
                              timeout=120)
        del proc
        if os.path.exists(so):
            return so
        _build_error = "make succeeded but produced no libkvstore.so"
        return None
    except subprocess.CalledProcessError as e:
        tail = (e.stderr or e.stdout or b"")[-300:]
        _build_error = f"make failed rc={e.returncode}: {tail!r}"
        return None
    except Exception as e:  # noqa: BLE001 - toolchain absence, timeout, ...
        _build_error = f"build unavailable: {e!r}"
        return None


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        so = _build_lib()
        if not so:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # a prebuilt .so linked against a NEWER libc than this host
            # (GLIBC_2.34-style version errors) raises at dlopen time, not
            # at build time: rebuild against the local toolchain once, and
            # if that fails too fall back to the pure-Python store instead
            # of poisoning every Store construction with an OSError
            so = _build_lib(force=True)
            if not so:
                return None
            try:
                lib = ctypes.CDLL(so)
            except OSError as e:
                global _build_error
                _build_error = f"dlopen failed after rebuild: {e}"
                return None
        lib.kv_new.restype = ctypes.c_void_p
        lib.kv_free.argtypes = [ctypes.c_void_p]
        for fn, args, res in [
            ("kv_rev", [ctypes.c_void_p], ctypes.c_int64),
            ("kv_compacted_rev", [ctypes.c_void_p], ctypes.c_int64),
            ("kv_put", [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                        ctypes.c_int64], ctypes.c_int64),
            ("kv_txn_put", [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                            ctypes.c_char_p, ctypes.c_int64], ctypes.c_int64),
            ("kv_txn_delete", [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int64], ctypes.c_int64),
            ("kv_get", [ctypes.c_void_p, ctypes.c_char_p,
                        ctypes.POINTER(ctypes.c_char_p),
                        ctypes.POINTER(ctypes.c_int64),
                        ctypes.POINTER(ctypes.c_int64),
                        ctypes.POINTER(ctypes.c_int64)], ctypes.c_int64),
            ("kv_range", [ctypes.c_void_p, ctypes.c_char_p,
                          ctypes.POINTER(ctypes.c_char_p),
                          ctypes.POINTER(ctypes.c_int64),
                          ctypes.POINTER(ctypes.c_int64)], ctypes.c_int64),
            ("kv_count", [ctypes.c_void_p, ctypes.c_char_p], ctypes.c_int64),
            ("kv_events_since", [ctypes.c_void_p, ctypes.c_int64,
                                 ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_int64)], ctypes.c_int64),
            ("kv_wait", [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64],
             ctypes.c_int64),
            ("kv_compact", [ctypes.c_void_p, ctypes.c_int64], ctypes.c_int64),
            ("kv_load", [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                         ctypes.c_int64, ctypes.c_int64, ctypes.c_int64],
             None),
            ("kv_init", [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64],
             None),
            ("kv_buf_free", [ctypes.c_char_p], None),
        ]:
            f = getattr(lib, fn)
            f.argtypes = args
            f.restype = res
        _lib = lib
        return _lib


def _parse_records(buf: bytes) -> List[Tuple[int, int, str, bytes]]:
    """Decode [i64 a][i64 b][i64 klen][key][i64 vlen][val]* records."""
    out = []
    off, n = 0, len(buf)
    while off < n:
        a, b, klen = struct.unpack_from("<qqq", buf, off)
        off += 24
        key = buf[off:off + klen].decode()
        off += klen
        (vlen,) = struct.unpack_from("<q", buf, off)
        off += 8
        val = buf[off:off + vlen]
        off += vlen
        out.append((a, b, key, val))
    return out


class NativeKV:
    """The C++ store. All revisions are int; value payloads are bytes."""

    def __init__(self) -> None:
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native kvstore unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.kv_new())

    def close(self) -> None:
        # Deliberately do NOT kv_free: daemon threads (informer reflectors,
        # watch pumps) may still be inside a C call on this handle; freeing
        # under them is a use-after-free. One store lives per process in
        # production; tests leak a few KB per store instead of segfaulting.
        self._h_closed = True

    def rev(self) -> int:
        return int(self._lib.kv_rev(self._h))

    def compacted_rev(self) -> int:
        return int(self._lib.kv_compacted_rev(self._h))

    def put(self, key: str, value: bytes) -> int:
        return int(self._lib.kv_put(self._h, key.encode(), value, len(value)))

    def txn_put(self, key: str, expected_mod_rev: int, value: bytes) -> int:
        """expected 0=create-only, >0=CAS on mod_rev, -1=unconditional.
        Returns new rev or -1 on condition failure."""
        return int(self._lib.kv_txn_put(self._h, key.encode(),
                                        expected_mod_rev, value, len(value)))

    def txn_delete(self, key: str, expected_mod_rev: int = -1) -> int:
        """Returns new rev, 0 if absent, -1 on condition failure."""
        return int(self._lib.kv_txn_delete(self._h, key.encode(),
                                           expected_mod_rev))

    def get(self, key: str) -> Optional[KVRecord]:
        out = ctypes.c_char_p()
        out_len = ctypes.c_int64()
        crev = ctypes.c_int64()
        mrev = ctypes.c_int64()
        found = self._lib.kv_get(self._h, key.encode(), ctypes.byref(out),
                                 ctypes.byref(out_len), ctypes.byref(crev),
                                 ctypes.byref(mrev))
        if not found:
            return None
        try:
            val = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_buf_free(out)
        return KVRecord(key, val, crev.value, mrev.value)

    def range(self, prefix: str) -> Tuple[List[KVRecord], int]:
        out = ctypes.c_char_p()
        out_len = ctypes.c_int64()
        at_rev = ctypes.c_int64()
        self._lib.kv_range(self._h, prefix.encode(), ctypes.byref(out),
                           ctypes.byref(out_len), ctypes.byref(at_rev))
        try:
            buf = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_buf_free(out)
        recs = [KVRecord(k, v, a, b) for a, b, k, v in _parse_records(buf)]
        return recs, at_rev.value

    def count(self, prefix: str) -> int:
        return int(self._lib.kv_count(self._h, prefix.encode()))

    def events_since(self, since_rev: int, prefix: str = "") -> List[KVEvent]:
        out = ctypes.c_char_p()
        out_len = ctypes.c_int64()
        n = self._lib.kv_events_since(self._h, since_rev, prefix.encode(),
                                      ctypes.byref(out), ctypes.byref(out_len))
        if n < 0:
            raise CompactedError(f"revision {since_rev} already compacted")
        try:
            buf = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_buf_free(out)
        return [KVEvent(rev, typ, k, v) for rev, typ, k, v in _parse_records(buf)]

    def wait(self, rev: int, timeout: float) -> int:
        return int(self._lib.kv_wait(self._h, rev, int(timeout * 1000)))

    def compact(self, at_rev: int) -> int:
        return int(self._lib.kv_compact(self._h, at_rev))

    def load(self, key: str, value: bytes, create_rev: int,
             mod_rev: int) -> None:
        """Snapshot restore: install a record without a rev bump or event."""
        self._lib.kv_load(self._h, key.encode(), value, len(value),
                          create_rev, mod_rev)

    def init_rev(self, rev: int, compacted_rev: int) -> None:
        """Seed rev counter + compaction floor from durable state (recovery
        only — calling this on a live store corrupts MVCC history)."""
        self._lib.kv_init(self._h, rev, compacted_rev)


class PyKV:
    """Pure-Python replica of NativeKV (same interface, same semantics)."""

    MAX_EVENTS = 1 << 20  # mirror NativeKV's cap: bound the log for the
    # process lifetime even when nothing calls compact()

    def __init__(self) -> None:
        self._mu = threading.Condition()
        self._data: dict = {}  # key -> (value, create_rev, mod_rev)
        self._events: List[KVEvent] = []
        self._rev = 0
        self._compacted = 0

    def _trim_locked(self) -> None:
        if len(self._events) > self.MAX_EVENTS:
            drop = len(self._events) - self.MAX_EVENTS
            self._compacted = self._events[drop - 1].rev
            del self._events[:drop]

    def close(self) -> None:
        pass

    def rev(self) -> int:
        with self._mu:
            return self._rev

    def compacted_rev(self) -> int:
        with self._mu:
            return self._compacted

    def put(self, key: str, value: bytes) -> int:
        return self.txn_put(key, -1, value)

    def txn_put(self, key: str, expected_mod_rev: int, value: bytes) -> int:
        with self._mu:
            cur = self._data.get(key)
            if expected_mod_rev == 0 and cur is not None:
                return -1
            if expected_mod_rev > 0 and (cur is None or cur[2] != expected_mod_rev):
                return -1
            self._rev += 1
            create = cur[1] if cur else self._rev
            self._data[key] = (value, create, self._rev)
            self._events.append(KVEvent(
                self._rev, EVENT_PUT if cur else EVENT_CREATE, key, value))
            self._trim_locked()
            self._mu.notify_all()
            return self._rev

    def txn_delete(self, key: str, expected_mod_rev: int = -1) -> int:
        with self._mu:
            cur = self._data.get(key)
            if cur is None:
                return 0
            if expected_mod_rev > 0 and cur[2] != expected_mod_rev:
                return -1
            self._rev += 1
            del self._data[key]
            self._events.append(KVEvent(self._rev, EVENT_DELETE, key, cur[0]))
            self._trim_locked()
            self._mu.notify_all()
            return self._rev

    def get(self, key: str) -> Optional[KVRecord]:
        with self._mu:
            cur = self._data.get(key)
            if cur is None:
                return None
            return KVRecord(key, cur[0], cur[1], cur[2])

    def range(self, prefix: str) -> Tuple[List[KVRecord], int]:
        with self._mu:
            recs = [KVRecord(k, v[0], v[1], v[2])
                    for k, v in sorted(self._data.items())
                    if k.startswith(prefix)]
            return recs, self._rev

    def count(self, prefix: str) -> int:
        with self._mu:
            return sum(1 for k in self._data if k.startswith(prefix))

    def events_since(self, since_rev: int, prefix: str = "") -> List[KVEvent]:
        with self._mu:
            if since_rev < self._compacted:
                raise CompactedError(f"revision {since_rev} already compacted")
            return [e for e in self._events
                    if e.rev > since_rev and e.key.startswith(prefix)]

    def wait(self, rev: int, timeout: float) -> int:
        with self._mu:
            self._mu.wait_for(lambda: self._rev > rev, timeout=timeout)
            return self._rev

    def compact(self, at_rev: int) -> int:
        with self._mu:
            self._events = [e for e in self._events if e.rev > at_rev]
            if at_rev > self._compacted:
                self._compacted = at_rev
            return self._compacted

    def load(self, key: str, value: bytes, create_rev: int,
             mod_rev: int) -> None:
        """Snapshot restore: install a record without a rev bump or event."""
        with self._mu:
            self._data[key] = (value, create_rev, mod_rev)

    def init_rev(self, rev: int, compacted_rev: int) -> None:
        """Seed rev counter + compaction floor from durable state (recovery
        only — calling this on a live store corrupts MVCC history)."""
        with self._mu:
            self._rev = rev
            self._compacted = compacted_rev


class DurableKV:
    """WAL-before-apply wrapper giving either backend crash consistency.

    Every mutation serializes through one commit lock: predict the revision
    the backend will assign (`rev()+1`), pre-check the CAS condition, make
    the record durable (storage/wal.py, per the fsync policy), THEN apply to
    the in-memory backend and assert it earned exactly the predicted
    revision. An acknowledged write is therefore always on disk before it is
    visible — a crash between append and apply re-delivers it on recovery
    (the etcd contract: committed-but-unacked writes may surface after
    reboot; lost acknowledged writes may not).

    Reads delegate straight to the backend (its own lock suffices);
    `events_since`/`wait` keep working unchanged, so the Storage watch pump
    is oblivious to durability.
    """

    def __init__(self, backend, data_dir: str,
                 durability: Optional[str] = None,
                 snapshot_every: Optional[int] = None,
                 segment_bytes: Optional[int] = None):
        from kubernetes_tpu.storage import wal as _wal

        self._wal_mod = _wal
        self._backend = backend
        self.data_dir = data_dir
        self.durability = (
            durability if durability is not None
            else os.environ.get("KTPU_STORE_DURABILITY", "batch"))
        self._snapshot_every = int(
            snapshot_every if snapshot_every is not None
            else os.environ.get("KTPU_WAL_SNAPSHOT_EVERY", "100000"))
        self._mu = threading.RLock()
        t0 = time.perf_counter()
        st = _wal.load_state(data_dir)
        self._recover(st)
        self._wal = _wal.WalWriter(
            data_dir, durability=self.durability,
            segment_bytes=segment_bytes,
            start_seq=max(1, st.next_seq))
        self._since_snapshot = len(st.wal_records)
        self.recovered = (bool(st.snapshot_records) or bool(st.wal_records)
                          or st.snapshot_rev > 0)
        self.torn_tail_truncated = st.torn_tail_truncated
        self.recovery_seconds = time.perf_counter() - t0
        _wal.RECOVERY_SECONDS.set(self.recovery_seconds)
        _wal.RECOVERY_RECORDS.set(len(st.snapshot_records),
                                  source="snapshot")
        _wal.RECOVERY_RECORDS.set(len(st.wal_records), source="wal")
        _wal.RECOVERY_RECORDS.set(1 if st.torn_tail_truncated else 0,
                                  source="torn")
        if self.recovered:
            _log.info(
                "kvstore recovered from %s: snapshot rev=%d (%d records) "
                "+ %d wal records -> rev=%d floor=%d torn_tail=%s in %.3fs",
                data_dir, st.snapshot_rev, len(st.snapshot_records),
                len(st.wal_records), self._backend.rev(),
                self._backend.compacted_rev(), st.torn_tail_truncated,
                self.recovery_seconds)

    def _recover(self, st) -> None:
        wal = self._wal_mod
        b = self._backend
        for key, value, create_rev, mod_rev in st.snapshot_records:
            b.load(key, value, create_rev, mod_rev)
        b.init_rev(st.snapshot_rev, st.snapshot_compacted)
        for rec in st.wal_records:
            if rec.op == wal.OP_COMPACT:
                if rec.rev > b.compacted_rev():
                    b.compact(rec.rev)
                continue
            if rec.rev <= st.snapshot_rev:
                continue  # already inside the snapshot
            if rec.op == wal.OP_PUT:
                got = b.put(rec.key, rec.value)
            else:
                got = b.txn_delete(rec.key, -1)
            if got != rec.rev:
                # RV continuity: the replayed mutation MUST re-earn exactly
                # the revision it logged; anything else means history is
                # rewritten and every resume token in the fleet is a lie
                raise wal.WalCorruptionError(
                    f"replay discontinuity: logged rev {rec.rev} for "
                    f"{wal._OP_NAMES[rec.op]} {rec.key!r} but backend "
                    f"assigned {got}")

    # -- mutations: WAL-before-apply ------------------------------------ #

    def put(self, key: str, value: bytes) -> int:
        return self.txn_put(key, -1, value)

    def txn_put(self, key: str, expected_mod_rev: int, value: bytes) -> int:
        wal = self._wal_mod
        b = self._backend
        with self._mu:
            cur = b.get(key)
            if expected_mod_rev == 0 and cur is not None:
                return -1
            if expected_mod_rev > 0 and (cur is None
                                         or cur.mod_rev != expected_mod_rev):
                return -1
            rev = b.rev() + 1
            self._wal.append(wal.OP_PUT, rev, key, value)
            got = b.txn_put(key, expected_mod_rev, value)
            if got != rev:
                # not an assert: this invariant must hold under python -O
                # too — a skew means the WAL logged one revision while the
                # backend assigned another, corrupting replay and every
                # resume token in the fleet
                raise wal.WalCorruptionError(
                    f"wal/backend rev skew on put {key!r}: "
                    f"logged {rev}, backend assigned {got}")
            # the record is durable AND applied — the site a mid-commit
            # apiserver kill exercises in the cold-restart drill
            faultline.crashpoint("wal:post_append")
            self._maybe_snapshot_locked()
            return rev

    def txn_delete(self, key: str, expected_mod_rev: int = -1) -> int:
        wal = self._wal_mod
        b = self._backend
        with self._mu:
            cur = b.get(key)
            if cur is None:
                return 0
            if expected_mod_rev > 0 and cur.mod_rev != expected_mod_rev:
                return -1
            rev = b.rev() + 1
            self._wal.append(wal.OP_DELETE, rev, key, b"")
            got = b.txn_delete(key, expected_mod_rev)
            if got != rev:
                raise wal.WalCorruptionError(
                    f"wal/backend rev skew on delete {key!r}: "
                    f"logged {rev}, backend assigned {got}")
            faultline.crashpoint("wal:post_append")
            self._maybe_snapshot_locked()
            return rev

    def compact(self, at_rev: int) -> int:
        wal = self._wal_mod
        with self._mu:
            self._wal.append(wal.OP_COMPACT, at_rev, "", b"")
            return self._backend.compact(at_rev)

    def _maybe_snapshot_locked(self) -> None:
        self._since_snapshot += 1
        if self._since_snapshot >= self._snapshot_every:
            self.snapshot()

    def snapshot(self) -> None:
        """Write a full-keyspace snapshot and truncate the log."""
        with self._mu:
            b = self._backend
            recs, at_rev = b.range("")
            self._wal.snapshot(
                at_rev, b.compacted_rev(),
                ((r.key, r.value, r.create_rev, r.mod_rev) for r in recs))
            self._since_snapshot = 0

    # -- reads / plumbing: straight delegation -------------------------- #

    def close(self) -> None:
        self._wal.close()
        self._backend.close()

    def rev(self) -> int:
        return self._backend.rev()

    def compacted_rev(self) -> int:
        return self._backend.compacted_rev()

    def get(self, key: str) -> Optional[KVRecord]:
        return self._backend.get(key)

    def range(self, prefix: str) -> Tuple[List[KVRecord], int]:
        return self._backend.range(prefix)

    def count(self, prefix: str) -> int:
        return self._backend.count(prefix)

    def events_since(self, since_rev: int, prefix: str = "") -> List[KVEvent]:
        return self._backend.events_since(since_rev, prefix)

    def wait(self, rev: int, timeout: float) -> int:
        return self._backend.wait(rev, timeout)


_backend_reported = False


def _report_backend(backend: str, reason: str) -> None:
    """Once per process: which kvstore carries the control plane, and why.
    A toolchain break must not silently demote a fleet to the slow path."""
    global _backend_reported
    if _backend_reported:
        return
    _backend_reported = True
    BACKEND_INFO.set(1, backend=backend, reason=reason)
    if backend == "python":
        _log.warning(
            "kvstore backend: python (PyKV fallback, reason=%s%s) — the "
            "native C++ store is NOT serving this process",
            reason, f"; build error: {_build_error}" if _build_error else "")
    else:
        _log.info("kvstore backend: native (libkvstore.so)")


def new_kv(prefer_native: bool = True, data_dir: Optional[str] = None,
           durability: Optional[str] = None):
    """Factory: native store if buildable, else the Python replica; either
    is wrapped in the WAL/recovery layer when `data_dir` is given."""
    backend = None
    if faultline.should("native.dlopen", "new_kv"):
        # chaos: the .so linked against a newer libc than this host —
        # dlopen fails, the PyKV fallback must carry the store
        backend = PyKV()
        _report_backend("python", "chaos")
    elif prefer_native:
        try:
            backend = NativeKV()
            _report_backend("native", "preferred")
        except RuntimeError:
            pass
    if backend is None:
        backend = PyKV()
        _report_backend(
            "python",
            ("build-failed" if prefer_native else "requested"))
    if data_dir:
        return DurableKV(backend, data_dir, durability=durability)
    return backend
