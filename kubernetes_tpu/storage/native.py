"""ctypes binding to the native MVCC kvstore, with auto-build + fallback.

The C++ store (native/kvstore.cpp) plays the role etcd plays under the
reference apiserver (storage/etcd3/store.go). `PyKV` is a pure-Python replica
of the same interface for environments without a C++ toolchain; both are
exercised by the same tests.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")

EVENT_PUT = 0
EVENT_DELETE = 1
EVENT_CREATE = 2


@dataclass(frozen=True)
class KVRecord:
    key: str
    value: bytes
    create_rev: int
    mod_rev: int


@dataclass(frozen=True)
class KVEvent:
    rev: int
    type: int  # EVENT_PUT | EVENT_DELETE | EVENT_CREATE
    key: str
    value: bytes  # for DELETE: the previous value


class CompactedError(Exception):
    """Watch/list from a revision older than the compaction point."""


def _build_lib(force: bool = False) -> Optional[str]:
    so = os.path.join(_NATIVE_DIR, "libkvstore.so")
    if os.path.exists(so) and not force:
        return so
    try:
        cmd = ["make", "-C", _NATIVE_DIR] + (["-B"] if force else [])
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return so if os.path.exists(so) else None
    except Exception:
        return None


_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lib_lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        so = _build_lib()
        if not so:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # a prebuilt .so linked against a NEWER libc than this host
            # (GLIBC_2.34-style version errors) raises at dlopen time, not
            # at build time: rebuild against the local toolchain once, and
            # if that fails too fall back to the pure-Python store instead
            # of poisoning every Store construction with an OSError
            so = _build_lib(force=True)
            if not so:
                return None
            try:
                lib = ctypes.CDLL(so)
            except OSError:
                return None
        lib.kv_new.restype = ctypes.c_void_p
        lib.kv_free.argtypes = [ctypes.c_void_p]
        for fn, args, res in [
            ("kv_rev", [ctypes.c_void_p], ctypes.c_int64),
            ("kv_compacted_rev", [ctypes.c_void_p], ctypes.c_int64),
            ("kv_put", [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                        ctypes.c_int64], ctypes.c_int64),
            ("kv_txn_put", [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                            ctypes.c_char_p, ctypes.c_int64], ctypes.c_int64),
            ("kv_txn_delete", [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int64], ctypes.c_int64),
            ("kv_get", [ctypes.c_void_p, ctypes.c_char_p,
                        ctypes.POINTER(ctypes.c_char_p),
                        ctypes.POINTER(ctypes.c_int64),
                        ctypes.POINTER(ctypes.c_int64),
                        ctypes.POINTER(ctypes.c_int64)], ctypes.c_int64),
            ("kv_range", [ctypes.c_void_p, ctypes.c_char_p,
                          ctypes.POINTER(ctypes.c_char_p),
                          ctypes.POINTER(ctypes.c_int64),
                          ctypes.POINTER(ctypes.c_int64)], ctypes.c_int64),
            ("kv_count", [ctypes.c_void_p, ctypes.c_char_p], ctypes.c_int64),
            ("kv_events_since", [ctypes.c_void_p, ctypes.c_int64,
                                 ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_int64)], ctypes.c_int64),
            ("kv_wait", [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64],
             ctypes.c_int64),
            ("kv_compact", [ctypes.c_void_p, ctypes.c_int64], ctypes.c_int64),
            ("kv_buf_free", [ctypes.c_char_p], None),
        ]:
            f = getattr(lib, fn)
            f.argtypes = args
            f.restype = res
        _lib = lib
        return _lib


def _parse_records(buf: bytes) -> List[Tuple[int, int, str, bytes]]:
    """Decode [i64 a][i64 b][i64 klen][key][i64 vlen][val]* records."""
    out = []
    off, n = 0, len(buf)
    while off < n:
        a, b, klen = struct.unpack_from("<qqq", buf, off)
        off += 24
        key = buf[off:off + klen].decode()
        off += klen
        (vlen,) = struct.unpack_from("<q", buf, off)
        off += 8
        val = buf[off:off + vlen]
        off += vlen
        out.append((a, b, key, val))
    return out


class NativeKV:
    """The C++ store. All revisions are int; value payloads are bytes."""

    def __init__(self) -> None:
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native kvstore unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.kv_new())

    def close(self) -> None:
        # Deliberately do NOT kv_free: daemon threads (informer reflectors,
        # watch pumps) may still be inside a C call on this handle; freeing
        # under them is a use-after-free. One store lives per process in
        # production; tests leak a few KB per store instead of segfaulting.
        self._h_closed = True

    def rev(self) -> int:
        return int(self._lib.kv_rev(self._h))

    def compacted_rev(self) -> int:
        return int(self._lib.kv_compacted_rev(self._h))

    def put(self, key: str, value: bytes) -> int:
        return int(self._lib.kv_put(self._h, key.encode(), value, len(value)))

    def txn_put(self, key: str, expected_mod_rev: int, value: bytes) -> int:
        """expected 0=create-only, >0=CAS on mod_rev, -1=unconditional.
        Returns new rev or -1 on condition failure."""
        return int(self._lib.kv_txn_put(self._h, key.encode(),
                                        expected_mod_rev, value, len(value)))

    def txn_delete(self, key: str, expected_mod_rev: int = -1) -> int:
        """Returns new rev, 0 if absent, -1 on condition failure."""
        return int(self._lib.kv_txn_delete(self._h, key.encode(),
                                           expected_mod_rev))

    def get(self, key: str) -> Optional[KVRecord]:
        out = ctypes.c_char_p()
        out_len = ctypes.c_int64()
        crev = ctypes.c_int64()
        mrev = ctypes.c_int64()
        found = self._lib.kv_get(self._h, key.encode(), ctypes.byref(out),
                                 ctypes.byref(out_len), ctypes.byref(crev),
                                 ctypes.byref(mrev))
        if not found:
            return None
        try:
            val = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_buf_free(out)
        return KVRecord(key, val, crev.value, mrev.value)

    def range(self, prefix: str) -> Tuple[List[KVRecord], int]:
        out = ctypes.c_char_p()
        out_len = ctypes.c_int64()
        at_rev = ctypes.c_int64()
        self._lib.kv_range(self._h, prefix.encode(), ctypes.byref(out),
                           ctypes.byref(out_len), ctypes.byref(at_rev))
        try:
            buf = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_buf_free(out)
        recs = [KVRecord(k, v, a, b) for a, b, k, v in _parse_records(buf)]
        return recs, at_rev.value

    def count(self, prefix: str) -> int:
        return int(self._lib.kv_count(self._h, prefix.encode()))

    def events_since(self, since_rev: int, prefix: str = "") -> List[KVEvent]:
        out = ctypes.c_char_p()
        out_len = ctypes.c_int64()
        n = self._lib.kv_events_since(self._h, since_rev, prefix.encode(),
                                      ctypes.byref(out), ctypes.byref(out_len))
        if n < 0:
            raise CompactedError(f"revision {since_rev} already compacted")
        try:
            buf = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kv_buf_free(out)
        return [KVEvent(rev, typ, k, v) for rev, typ, k, v in _parse_records(buf)]

    def wait(self, rev: int, timeout: float) -> int:
        return int(self._lib.kv_wait(self._h, rev, int(timeout * 1000)))

    def compact(self, at_rev: int) -> int:
        return int(self._lib.kv_compact(self._h, at_rev))


class PyKV:
    """Pure-Python replica of NativeKV (same interface, same semantics)."""

    MAX_EVENTS = 1 << 20  # mirror NativeKV's cap: bound the log for the
    # process lifetime even when nothing calls compact()

    def __init__(self) -> None:
        self._mu = threading.Condition()
        self._data: dict = {}  # key -> (value, create_rev, mod_rev)
        self._events: List[KVEvent] = []
        self._rev = 0
        self._compacted = 0

    def _trim_locked(self) -> None:
        if len(self._events) > self.MAX_EVENTS:
            drop = len(self._events) - self.MAX_EVENTS
            self._compacted = self._events[drop - 1].rev
            del self._events[:drop]

    def close(self) -> None:
        pass

    def rev(self) -> int:
        with self._mu:
            return self._rev

    def compacted_rev(self) -> int:
        with self._mu:
            return self._compacted

    def put(self, key: str, value: bytes) -> int:
        return self.txn_put(key, -1, value)

    def txn_put(self, key: str, expected_mod_rev: int, value: bytes) -> int:
        with self._mu:
            cur = self._data.get(key)
            if expected_mod_rev == 0 and cur is not None:
                return -1
            if expected_mod_rev > 0 and (cur is None or cur[2] != expected_mod_rev):
                return -1
            self._rev += 1
            create = cur[1] if cur else self._rev
            self._data[key] = (value, create, self._rev)
            self._events.append(KVEvent(
                self._rev, EVENT_PUT if cur else EVENT_CREATE, key, value))
            self._trim_locked()
            self._mu.notify_all()
            return self._rev

    def txn_delete(self, key: str, expected_mod_rev: int = -1) -> int:
        with self._mu:
            cur = self._data.get(key)
            if cur is None:
                return 0
            if expected_mod_rev > 0 and cur[2] != expected_mod_rev:
                return -1
            self._rev += 1
            del self._data[key]
            self._events.append(KVEvent(self._rev, EVENT_DELETE, key, cur[0]))
            self._trim_locked()
            self._mu.notify_all()
            return self._rev

    def get(self, key: str) -> Optional[KVRecord]:
        with self._mu:
            cur = self._data.get(key)
            if cur is None:
                return None
            return KVRecord(key, cur[0], cur[1], cur[2])

    def range(self, prefix: str) -> Tuple[List[KVRecord], int]:
        with self._mu:
            recs = [KVRecord(k, v[0], v[1], v[2])
                    for k, v in sorted(self._data.items())
                    if k.startswith(prefix)]
            return recs, self._rev

    def count(self, prefix: str) -> int:
        with self._mu:
            return sum(1 for k in self._data if k.startswith(prefix))

    def events_since(self, since_rev: int, prefix: str = "") -> List[KVEvent]:
        with self._mu:
            if since_rev < self._compacted:
                raise CompactedError(f"revision {since_rev} already compacted")
            return [e for e in self._events
                    if e.rev > since_rev and e.key.startswith(prefix)]

    def wait(self, rev: int, timeout: float) -> int:
        with self._mu:
            self._mu.wait_for(lambda: self._rev > rev, timeout=timeout)
            return self._rev

    def compact(self, at_rev: int) -> int:
        with self._mu:
            self._events = [e for e in self._events if e.rev > at_rev]
            if at_rev > self._compacted:
                self._compacted = at_rev
            return self._compacted


def new_kv(prefer_native: bool = True):
    """Factory: native store if buildable, else the Python replica."""
    from kubernetes_tpu.utils import faultline

    if faultline.should("native.dlopen", "new_kv"):
        # chaos: the .so linked against a newer libc than this host —
        # dlopen fails, the PyKV fallback must carry the store
        return PyKV()
    if prefer_native:
        try:
            return NativeKV()
        except RuntimeError:
            pass
    return PyKV()
