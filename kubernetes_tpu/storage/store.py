"""storage.Interface: versioned object storage over the MVCC kvstore.

Analog of `staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go`: objects
are JSON-encoded under `/registry/<resource>/[<ns>/]<name>`; as in the
reference, resourceVersion is NOT stored in the value — it is filled from the
record's mod_revision on every read (store.go Versioner). GuaranteedUpdate
retries a CAS on mod_revision (store.go:219-300); Watch delivers events from
a given revision with 410-Gone on compaction. One dispatcher thread pumps kv
events to all registered watchers (role of etcd watch streams + the apiserver
Cacher, storage/cacher/cacher.go:309).

Watch-plane contract (ISSUE 13, the cacher's delivery discipline):

  * every watcher owns a BOUNDED buffer (`KTPU_WATCH_BUFFER`, default 8192);
    a consumer that stops draining is terminated — that ONE stream gets a
    410 "too old resource version" terminal Status (so the client knows to
    resume/relist) and the broadcast loop never blocks or balloons for it
    (cacher.go forgetWatcher);
  * BOOKMARK events carry the dispatched revision on a timer AND immediately
    on every compaction-boundary crossing (`compact_to`), so a quiet
    stream's resume token stays above the compaction floor and reconnects
    resume instead of relisting;
  * `drop_watchers` (the apiserver-restart seam) emits a terminal 503
    Status BEFORE closing each stream — clients resume by resourceVersion
    rather than discovering death by socket EOF and blind-relisting.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.component.metrics import DEFAULT_REGISTRY as _REG
from kubernetes_tpu.machinery import errors, meta
from kubernetes_tpu.machinery import watch as mwatch
from kubernetes_tpu.storage import native
from kubernetes_tpu.storage.cacher import CachedEvent, WatchCache
from kubernetes_tpu.utils import faultline

Obj = Dict[str, Any]
Predicate = Optional[Callable[[Obj], bool]]

# watch-plane delivery telemetry (ISSUE 13): the per-watcher buffer is the
# backpressure boundary — its depth is the early-warning signal, and an
# eviction is the cacher contract actually firing (one deaf consumer paid,
# everyone else's broadcast stayed live)
WATCH_BUFFER_DEPTH = _REG.gauge(
    "watch_buffer_depth",
    "Deepest per-watcher delivery buffer observed at dispatch, by resource",
    labels=("resource",))
WATCH_DEAF_EVICTIONS = _REG.counter(
    "apiserver_watch_deaf_evictions_total",
    "Watch streams terminated with a too-old error because the consumer "
    "stopped draining its bounded buffer (cacher forgetWatcher contract)",
    labels=("resource",))
WATCH_BOOKMARKS_SENT = _REG.counter(
    "apiserver_watch_bookmarks_sent_total",
    "BOOKMARK events sent to opted-in watchers, by trigger "
    "(timer, compaction)",
    labels=("trigger",))


def _parse_watch_buffer(value, default: int = 8192) -> int:
    """Bounds-checked buffer parse (the KTPU_FLIGHT_RING convention):
    garbage falls back to the default, and the result clamps to [1, 2^20]
    — 0/negative would make queue.Queue UNBOUNDED, silently disabling the
    deaf-eviction contract this buffer exists to enforce."""
    try:
        n = int(value)
    except (TypeError, ValueError):
        return default
    return max(1, min(n, 1 << 20))


def _encode(obj: Obj) -> bytes:
    obj = dict(obj)
    md = dict(obj.get("metadata") or {})
    md.pop("resourceVersion", None)
    obj["metadata"] = md
    return json.dumps(obj, separators=(",", ":"), sort_keys=True).encode()


def _decode(data: bytes, rev: int) -> Obj:
    obj = json.loads(data)
    meta.set_resource_version(obj, str(rev))
    return obj


def _resource_of(prefix: str) -> str:
    """`/registry/<group>/<resource>/…` → `<resource>` (metric label
    granularity; registry.Store.key_root shape — `/registry/core/pods/` →
    `pods`). Bare test prefixes like `/registry/pods/` fall back to their
    last segment."""
    parts = prefix.strip("/").split("/")
    if len(parts) >= 3:
        return parts[2]
    return parts[-1] if parts and parts[-1] else "all"


@dataclass
class _Watcher:
    """One registered watch stream: the delivery buffer plus its horizon.

    `since` is the revision at/below which events are before this watcher's
    catch-up replay and must never be re-delivered; `bookmarks` opts the
    stream into BOOKMARK events (allowWatchBookmarks)."""

    prefix: str
    watch: mwatch.Watch
    predicate: Predicate
    since: int
    bookmarks: bool
    resource: str = field(default="")

    def __post_init__(self):
        if not self.resource:
            self.resource = _resource_of(self.prefix)


def _too_old_status(detail: str) -> Obj:
    return errors.new_gone(f"too old resource version: {detail}").status()


class Storage:
    """Object store + watch hub over one KV backend."""

    def __init__(self, kv=None, watch_buffer: Optional[int] = None,
                 bookmark_interval: Optional[float] = None,
                 data_dir: Optional[str] = None,
                 durability: Optional[str] = None):
        # data_dir turns the store durable: the kv is wrapped in the
        # WAL/snapshot layer (storage/wal.py) and recovery has ALREADY run
        # by the time new_kv returns — self.kv.rev() below is the last
        # durable revision, so the pump, the cacher horizon and every RV
        # this process hands out continue the pre-crash sequence
        self.kv = kv if kv is not None else native.new_kv(
            data_dir=data_dir, durability=durability)
        self._watch_mu = threading.Lock()
        self._watchers: List[_Watcher] = []
        self._watch_buffer = _parse_watch_buffer(
            watch_buffer if watch_buffer is not None
            else os.environ.get("KTPU_WATCH_BUFFER"))
        self._bookmark_interval = float(
            bookmark_interval if bookmark_interval is not None
            else os.environ.get("KTPU_WATCH_BOOKMARK_INTERVAL", "10"))
        self._dispatched_rev = self.kv.rev()
        # Cacher tier (storage/cacher.py ⇔ cacher.go:309): the pump decodes
        # each event once into this ring; watcher catch-up replays from it so
        # storage reads stay independent of watcher count
        self.watch_cache = WatchCache(horizon=self._dispatched_rev)
        # resources the depth gauge was last exported for: when a
        # resource's final watcher stops, its series must drop to 0 rather
        # than freeze at the last (typically full-buffer) reading
        self._depth_resources: set = set()
        # watch-plane counters the bench/chaos drills assert against
        self.deaf_evictions = 0
        self.bookmarks_sent = 0
        self.compaction_bookmarks = 0
        self._stop = threading.Event()
        self._pump = threading.Thread(target=self._dispatch_loop,
                                      name="storage-watch-pump", daemon=True)
        self._pump.start()

    def close(self) -> None:
        self._stop.set()
        self._pump.join(timeout=2)
        with self._watch_mu:
            for wr in self._watchers:
                wr.watch.stop()
            self._watchers.clear()
        self.kv.close()

    def drop_watchers(self) -> int:
        """Terminate every registered watch stream (the data survives).
        This is what an apiserver restart looks like from a client: the
        store (etcd) keeps its state, every open watch connection dies, and
        reflectors re-establish. Each stream gets a terminal 503 Status
        FIRST (the reference closes the response with a Status frame), so
        informers resume from their last resourceVersion instead of
        discovering death by socket EOF and falling into the blind-relist
        path. Used by the chaos injector's ``apiserver.restart`` seam;
        returns the number of streams dropped."""
        status = errors.new_service_unavailable(
            "apiserver restarting; watch stream closed").status()
        with self._watch_mu:
            n = len(self._watchers)
            for wr in self._watchers:
                wr.watch.terminate(mwatch.Event(mwatch.ERROR, status))
            self._watchers.clear()
        return n

    @property
    def dispatched_rev(self) -> int:
        """How far the broadcast pump has gotten. A compaction drill that
        wants to move the floor WITHOUT manufacturing a pump gap (events
        destroyed before they were ever broadcast 410 every live watcher)
        compacts at this revision, not the kv head."""
        return self._dispatched_rev

    def live_watchers(self, prefix: str = "") -> int:
        """Registered, not-yet-stopped streams under prefix — the bench's
        `upstream_watches_per_resource` reads this (one mux stream per
        resource for a whole tenant fleet is the acceptance bar)."""
        with self._watch_mu:
            return sum(1 for wr in self._watchers
                       if not wr.watch.stopped
                       and wr.prefix.startswith(prefix))

    # ------------------------------------------------------------------ #
    # CRUD (etcd3 store.go Create:143 / Get:86 / Delete / GuaranteedUpdate:219)
    # ------------------------------------------------------------------ #

    def create(self, key: str, obj: Obj, resource: str = "object") -> Obj:
        rev = self.kv.txn_put(key, 0, _encode(obj))
        if rev < 0:
            raise errors.new_already_exists(resource, meta.name(obj))
        out = meta.deep_copy(obj)
        meta.set_resource_version(out, str(rev))
        return out

    def get(self, key: str, resource: str = "object", name: str = "") -> Obj:
        rec = self.kv.get(key)
        if rec is None:
            raise errors.new_not_found(resource, name or key)
        return _decode(rec.value, rec.mod_rev)

    def list(self, prefix: str, predicate: Predicate = None) -> Tuple[List[Obj], str]:
        recs, at_rev = self.kv.range(prefix)
        items = []
        for rec in recs:
            obj = _decode(rec.value, rec.mod_rev)
            if predicate is None or predicate(obj):
                items.append(obj)
        return items, str(at_rev)

    def count(self, prefix: str) -> int:
        return self.kv.count(prefix)

    def delete(self, key: str, resource: str = "object", name: str = "",
               expected_rv: Optional[str] = None) -> Obj:
        while True:
            rec = self.kv.get(key)
            if rec is None:
                raise errors.new_not_found(resource, name or key)
            if expected_rv is not None and str(rec.mod_rev) != expected_rv:
                raise errors.new_conflict(resource, name or key,
                                          "the object has been modified")
            rv = self.kv.txn_delete(key, rec.mod_rev)
            if rv > 0:
                return _decode(rec.value, rec.mod_rev)
            if rv == 0:
                raise errors.new_not_found(resource, name or key)
            # lost a race with a concurrent update; retry

    def guaranteed_update(self, key: str, update_fn: Callable[[Obj], Obj],
                          resource: str = "object", name: str = "",
                          ignore_not_found: bool = False,
                          expected_rv: Optional[str] = None) -> Obj:
        """Retry loop: read → user transform → CAS write (store.go:219-300).

        update_fn receives a deep copy (with resourceVersion set) and returns
        the new object, or raises to abort.
        """
        chaos_cas = False  # at most one injected conflict per call: the
        # retry loop must converge even under FAULT_SPEC=store.cas_conflict@1.0
        while True:
            rec = self.kv.get(key)
            if rec is None:
                if not ignore_not_found:
                    raise errors.new_not_found(resource, name or key)
                cur: Obj = {}
                cur_mod = 0
            else:
                cur = _decode(rec.value, rec.mod_rev)
                cur_mod = rec.mod_rev
            if (expected_rv is not None and rec is not None
                    and str(rec.mod_rev) != expected_rv):
                # the precondition holds on EVERY iteration, not just the
                # first: when our txn_put loses the CAS race to a
                # concurrent writer, the retry re-reads a revision past
                # the caller's precondition and MUST conflict — retrying
                # with the stale body would silently stomp the winner
                # (observed: a lease renew racing a usurper's claim
                # overwrote it and kept the incumbent leading — the exact
                # window lease fencing closes). etcd3 store.go preconditions
                # are checked per attempt for the same reason.
                raise errors.new_conflict(
                    resource, name or key,
                    "the object has been modified; please apply your changes "
                    "to the latest version and try again")
            if faultline.should("store.latency", "guaranteed_update"):
                # chaos: the storage backend (etcd) is slow — every hit
                # read-transform-write stalls KTPU_SLOW_S. The bind-intent
                # writes and Lease renews ride this path, so the overload
                # drills use it to slow the COMMIT side without touching
                # the watch/ingest side.
                import os as _os
                import time as _time

                _time.sleep(float(_os.environ.get("KTPU_SLOW_S", "0.2")))
            updated = update_fn(meta.deep_copy(cur))
            if not chaos_cas and faultline.should("store.cas_conflict",
                                                  "guaranteed_update"):
                # chaos: behave exactly as if a concurrent writer won the
                # CAS race — skip the put and take the re-read/retry path
                chaos_cas = True
                continue
            rev = self.kv.txn_put(key, cur_mod if cur_mod else 0, _encode(updated))
            if rev > 0:
                out = meta.deep_copy(updated)
                meta.set_resource_version(out, str(rev))
                return out
            # CAS failure → re-read and retry

    # ------------------------------------------------------------------ #
    # Compaction
    # ------------------------------------------------------------------ #

    def compact_to(self, at_rev: int) -> None:
        """A REAL compaction at `at_rev`: the KV history and the cacher ring
        both drop everything at/below it, and every bookmark-opted LIVE
        watcher immediately receives a BOOKMARK carrying a revision ABOVE
        the new floor — the compaction-boundary crossing bookmark. That is
        what turns a later reconnect into a resume instead of a 410 relist:
        a quiet stream's resume token would otherwise sit below the floor
        exactly when the apiserver is busiest (the self-inflicted
        list-storm ISSUE 13 exists to kill)."""
        self.kv.compact(at_rev)
        self.watch_cache.compact(at_rev)
        self._send_bookmarks(trigger="compaction")

    # ------------------------------------------------------------------ #
    # Watch
    # ------------------------------------------------------------------ #

    def watch(self, prefix: str, since_rv: str = "",
              predicate: Predicate = None,
              bookmarks: bool = False,
              buffer: Optional[int] = None) -> mwatch.Watch:
        """Watch events under prefix with revision > since_rv.

        since_rv ""/"0" = from now. Raises Gone(410) if since_rv predates
        compaction — the caller must relist (reflector relist semantics).
        `buffer` bounds this watcher's delivery queue (default
        KTPU_WATCH_BUFFER); a consumer that stops draining it is evicted
        with a too-old terminal error, never allowed to stall the pump.
        """
        if faultline.should("store.compact", "watch"):
            # chaos: a REAL compaction at the current revision — stale
            # resumes below earn a genuine 410, and the dispatch pump's own
            # compaction handling runs against true state, not a mock. The
            # cacher ring compacts with it (a sustained storm churns old
            # revisions out of the window organically).
            self.compact_to(self.kv.rev())
        # per-call buffers go through the same clamp as the ctor/env path:
        # `buffer or ...` would send 0 to the default instead of the
        # documented clamp-to-1, and a negative value would make the queue
        # UNBOUNDED — un-evictable deaf consumers
        w = mwatch.Watch(capacity=_parse_watch_buffer(
            buffer, default=self._watch_buffer))
        wr = _Watcher(prefix=prefix, watch=w, predicate=predicate,
                      since=0, bookmarks=bookmarks)
        with self._watch_mu:
            # "" / "0" = from NOW: the current store revision, regardless of
            # how far the dispatch pump has gotten
            since = int(since_rv) if since_rv not in ("", "0") else self.kv.rev()
            # catch-up: replay history before going live under the same lock
            # the pump uses, so no event is missed or duplicated; the pump
            # delivers everything > max(since, _dispatched_rev). The replay
            # is served from the watch cache whenever `since` is within its
            # horizon — no storage read per watcher (cacher.go:369-374)
            cached = self.watch_cache.events_since(since, prefix)
            if cached is not None:
                for ce in cached:
                    if ce.rev > self._dispatched_rev:
                        break
                    self._deliver(wr, ce)
            else:
                try:
                    history = self.kv.events_since(since, prefix)
                except native.CompactedError:
                    raise errors.new_gone(
                        f"too old resource version: {since} "
                        f"(compacted at {self.kv.compacted_rev()})")
                for ev in history:
                    if ev.rev > self._dispatched_rev:
                        break  # the pump will deliver the rest
                    self._deliver(wr, self._to_cached(ev))
            wr.since = max(since, self._dispatched_rev)
            self._watchers.append(wr)
        return w

    @staticmethod
    def _to_cached(ev: native.KVEvent) -> CachedEvent:
        typ = {native.EVENT_CREATE: mwatch.ADDED,
               native.EVENT_PUT: mwatch.MODIFIED,
               native.EVENT_DELETE: mwatch.DELETED}[ev.type]
        return CachedEvent(rev=ev.rev, type=typ, key=ev.key,
                           obj=_decode(ev.value, ev.rev))

    def _deliver(self, wr: _Watcher, ce: CachedEvent,
                 timeout: float = 0.0) -> None:
        if wr.predicate is not None and not wr.predicate(ce.obj):
            return
        w = wr.watch
        if w.stopped:
            return
        # watchers receive a copy so one consumer's mutation can't leak into
        # another's view of the shared decoded event
        obj = meta.deep_copy(ce.obj)
        # non-blocking from the dispatcher: a watcher that cannot keep up is
        # terminated with a too-old terminal error — it alone pays, and the
        # event path for everyone else never stalls (cacher.go
        # forgetWatcher). The terminal Status survives the full buffer
        # (machinery/watch.Watch.terminate), so a slow-but-alive consumer
        # drains its backlog and THEN learns it must resume/relist.
        if not w.send(mwatch.Event(ce.type, obj), timeout=timeout):
            self._evict_if_deaf(wr, at_rev=ce.rev)

    def _evict_if_deaf(self, wr: _Watcher, at_rev: int) -> None:
        """A failed send is a DEAF eviction only when the buffer actually
        overflowed (Watch.overflowed); a consumer that closed its own
        stream a moment before the send gets neither a bogus too-old
        terminal nor a tick on the eviction metric."""
        w = wr.watch
        if not w.overflowed:
            return
        w.terminate(mwatch.Event(
            mwatch.ERROR,
            _too_old_status(f"{at_rev} (watcher evicted: delivery "
                            f"buffer of {w.capacity} exhausted)")))
        self.deaf_evictions += 1
        WATCH_DEAF_EVICTIONS.inc(resource=wr.resource)

    def _send_bookmarks(self, trigger: str = "timer") -> None:
        with self._watch_mu:
            for wr in self._watchers:
                if wr.bookmarks and not wr.watch.stopped:
                    # never below the watcher's own horizon (a bookmark at
                    # the pump's lagging revision would hand a resuming
                    # reflector an RV it has already consumed past,
                    # replaying duplicates) and never ABOVE the pump's
                    # dispatched revision: advertising the compaction
                    # floor itself when it outran the pump would hand out
                    # a resume token that silently skips events destroyed
                    # before they were ever broadcast. For a compaction at
                    # <= dispatched_rev (compact_to's contract for the
                    # seam and drills) this value already sits at/above
                    # the new floor, which is what makes the reconnect a
                    # resume; a floor beyond the pump leaves tokens below
                    # it, and the next resume earns its honest 410.
                    rv = max(wr.since, self._dispatched_rev)
                    if wr.watch.send(mwatch.Event(mwatch.BOOKMARK, {
                            "kind": "Bookmark", "apiVersion": "v1",
                            "metadata": {"resourceVersion": str(rv)}}),
                            timeout=0):
                        self.bookmarks_sent += 1
                        if trigger == "compaction":
                            self.compaction_bookmarks += 1
                        WATCH_BOOKMARKS_SENT.inc(trigger=trigger)
                    else:
                        # a bookmark landing on a FULL buffer is the same
                        # deaf consumer _deliver evicts — it must get the
                        # same too-old terminal + metric, not a silent
                        # stop that reads as a clean EOF
                        self._evict_if_deaf(wr, at_rev=rv)

    def _export_depths(self) -> None:
        """Deepest live delivery buffer per resource → watch_buffer_depth.
        Called from the pump with the watch lock held."""
        deepest: Dict[str, int] = {}
        for wr in self._watchers:
            if not wr.watch.stopped:
                d = wr.watch.depth()
                if d >= deepest.get(wr.resource, -1):
                    deepest[wr.resource] = d
        for res in self._depth_resources - set(deepest):
            WATCH_BUFFER_DEPTH.set(0, resource=res)
        self._depth_resources = set(deepest)
        for res, d in deepest.items():
            WATCH_BUFFER_DEPTH.set(d, resource=res)

    def _dispatch_loop(self) -> None:
        last_bm = time.monotonic()
        while not self._stop.is_set():
            rev = self.kv.wait(self._dispatched_rev, timeout=0.25)
            if faultline.should("watch.compact", "floor"):
                # chaos (ISSUE 13): a compaction storm hitting mid-stream —
                # a REAL compaction at the pump's own dispatched revision
                # (already-broadcast history only: compacting the kv head
                # would destroy events the pump hasn't read and force the
                # fell-behind 410 on everyone), with the boundary-crossing
                # bookmark broadcast that keeps LIVE opted-in streams
                # resumable. The drill asserts resumes, not relists,
                # survive this.
                self.compact_to(self._dispatched_rev)
            if time.monotonic() - last_bm >= self._bookmark_interval:
                last_bm = time.monotonic()
                self._send_bookmarks(trigger="timer")
            if rev <= self._dispatched_rev:
                continue
            try:
                events = self.kv.events_since(self._dispatched_rev, "")
            except native.CompactedError:
                # the pump fell behind compaction: watchers have an
                # unrecoverable gap — error them all out so clients relist
                # (the reference terminates such watchers, cacher.go)
                with self._watch_mu:
                    gone = errors.new_gone(
                        "watch events compacted away; relist required")
                    for wr in self._watchers:
                        wr.watch.terminate(
                            mwatch.Event(mwatch.ERROR, gone.status()))
                    self._watchers.clear()
                    self._dispatched_rev = self.kv.rev()
                    # the compacted-away events never reached the ring: the
                    # cache has a GAP, so its window must restart at now —
                    # otherwise a later resume would be served an incomplete
                    # history instead of falling through to a 410
                    self.watch_cache = WatchCache(
                        horizon=self._dispatched_rev)
                continue
            with self._watch_mu:
                cached = [self._to_cached(ev) for ev in events]  # decode ONCE
                for ce in cached:
                    self.watch_cache.add(ce)
                live = []
                for wr in self._watchers:
                    if wr.watch.stopped:
                        continue
                    for ce in cached:
                        if ce.rev > wr.since and ce.key.startswith(wr.prefix):
                            self._deliver(wr, ce)
                    if not wr.watch.stopped:
                        live.append(wr)
                self._watchers = live
                self._export_depths()
                if events:
                    self._dispatched_rev = max(e.rev for e in events)
