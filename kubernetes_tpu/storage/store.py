"""storage.Interface: versioned object storage over the MVCC kvstore.

Analog of `staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go`: objects
are JSON-encoded under `/registry/<resource>/[<ns>/]<name>`; as in the
reference, resourceVersion is NOT stored in the value — it is filled from the
record's mod_revision on every read (store.go Versioner). GuaranteedUpdate
retries a CAS on mod_revision (store.go:219-300); Watch delivers events from
a given revision with 410-Gone on compaction. One dispatcher thread pumps kv
events to all registered watchers (role of etcd watch streams + the apiserver
Cacher, storage/cacher/cacher.go:309).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.machinery import errors, meta
from kubernetes_tpu.machinery import watch as mwatch
from kubernetes_tpu.storage import native
from kubernetes_tpu.storage.cacher import CachedEvent, WatchCache
from kubernetes_tpu.utils import faultline

Obj = Dict[str, Any]
Predicate = Optional[Callable[[Obj], bool]]


def _encode(obj: Obj) -> bytes:
    obj = dict(obj)
    md = dict(obj.get("metadata") or {})
    md.pop("resourceVersion", None)
    obj["metadata"] = md
    return json.dumps(obj, separators=(",", ":"), sort_keys=True).encode()


def _decode(data: bytes, rev: int) -> Obj:
    obj = json.loads(data)
    meta.set_resource_version(obj, str(rev))
    return obj


class Storage:
    """Object store + watch hub over one KV backend."""

    def __init__(self, kv=None):
        self.kv = kv if kv is not None else native.new_kv()
        self._watch_mu = threading.Lock()
        # (prefix, watch, predicate, since_rev, bookmarks): events <=
        # since_rev are before this watcher's horizon and never delivered;
        # `bookmarks` watchers additionally receive periodic BOOKMARK
        # events carrying the dispatched revision (WatchBookmarks,
        # cacher.go bookmark timer) so reflectors resume from recent RVs
        # after quiet disconnects instead of falling into a 410 relist
        self._watchers: List[Tuple[str, mwatch.Watch, Predicate, int,
                                   bool]] = []
        self._bookmark_interval = float(os.environ.get(
            "KTPU_WATCH_BOOKMARK_INTERVAL", "10"))
        self._dispatched_rev = self.kv.rev()
        # Cacher tier (storage/cacher.py ⇔ cacher.go:309): the pump decodes
        # each event once into this ring; watcher catch-up replays from it so
        # storage reads stay independent of watcher count
        self.watch_cache = WatchCache(horizon=self._dispatched_rev)
        self._stop = threading.Event()
        self._pump = threading.Thread(target=self._dispatch_loop,
                                      name="storage-watch-pump", daemon=True)
        self._pump.start()

    def close(self) -> None:
        self._stop.set()
        self._pump.join(timeout=2)
        with self._watch_mu:
            for _, w, _, _, _ in self._watchers:
                w.stop()
            self._watchers.clear()
        self.kv.close()

    def drop_watchers(self) -> int:
        """Terminate every registered watch stream (the data survives).
        This is what an apiserver restart looks like from a client: the
        store (etcd) keeps its state, every open watch connection dies, and
        reflectors must re-establish/relist. Used by the chaos injector's
        ``apiserver.restart`` seam; returns the number of streams dropped."""
        with self._watch_mu:
            n = len(self._watchers)
            for _, w, _, _, _ in self._watchers:
                w.stop()
            self._watchers.clear()
        return n

    # ------------------------------------------------------------------ #
    # CRUD (etcd3 store.go Create:143 / Get:86 / Delete / GuaranteedUpdate:219)
    # ------------------------------------------------------------------ #

    def create(self, key: str, obj: Obj, resource: str = "object") -> Obj:
        rev = self.kv.txn_put(key, 0, _encode(obj))
        if rev < 0:
            raise errors.new_already_exists(resource, meta.name(obj))
        out = meta.deep_copy(obj)
        meta.set_resource_version(out, str(rev))
        return out

    def get(self, key: str, resource: str = "object", name: str = "") -> Obj:
        rec = self.kv.get(key)
        if rec is None:
            raise errors.new_not_found(resource, name or key)
        return _decode(rec.value, rec.mod_rev)

    def list(self, prefix: str, predicate: Predicate = None) -> Tuple[List[Obj], str]:
        recs, at_rev = self.kv.range(prefix)
        items = []
        for rec in recs:
            obj = _decode(rec.value, rec.mod_rev)
            if predicate is None or predicate(obj):
                items.append(obj)
        return items, str(at_rev)

    def count(self, prefix: str) -> int:
        return self.kv.count(prefix)

    def delete(self, key: str, resource: str = "object", name: str = "",
               expected_rv: Optional[str] = None) -> Obj:
        while True:
            rec = self.kv.get(key)
            if rec is None:
                raise errors.new_not_found(resource, name or key)
            if expected_rv is not None and str(rec.mod_rev) != expected_rv:
                raise errors.new_conflict(resource, name or key,
                                          "the object has been modified")
            rv = self.kv.txn_delete(key, rec.mod_rev)
            if rv > 0:
                return _decode(rec.value, rec.mod_rev)
            if rv == 0:
                raise errors.new_not_found(resource, name or key)
            # lost a race with a concurrent update; retry

    def guaranteed_update(self, key: str, update_fn: Callable[[Obj], Obj],
                          resource: str = "object", name: str = "",
                          ignore_not_found: bool = False,
                          expected_rv: Optional[str] = None) -> Obj:
        """Retry loop: read → user transform → CAS write (store.go:219-300).

        update_fn receives a deep copy (with resourceVersion set) and returns
        the new object, or raises to abort.
        """
        chaos_cas = False  # at most one injected conflict per call: the
        # retry loop must converge even under FAULT_SPEC=store.cas_conflict@1.0
        while True:
            rec = self.kv.get(key)
            if rec is None:
                if not ignore_not_found:
                    raise errors.new_not_found(resource, name or key)
                cur: Obj = {}
                cur_mod = 0
            else:
                cur = _decode(rec.value, rec.mod_rev)
                cur_mod = rec.mod_rev
            if (expected_rv is not None and rec is not None
                    and str(rec.mod_rev) != expected_rv):
                # the precondition holds on EVERY iteration, not just the
                # first: when our txn_put loses the CAS race to a
                # concurrent writer, the retry re-reads a revision past
                # the caller's precondition and MUST conflict — retrying
                # with the stale body would silently stomp the winner
                # (observed: a lease renew racing a usurper's claim
                # overwrote it and kept the incumbent leading — the exact
                # window lease fencing closes). etcd3 store.go preconditions
                # are checked per attempt for the same reason.
                raise errors.new_conflict(
                    resource, name or key,
                    "the object has been modified; please apply your changes "
                    "to the latest version and try again")
            if faultline.should("store.latency", "guaranteed_update"):
                # chaos: the storage backend (etcd) is slow — every hit
                # read-transform-write stalls KTPU_SLOW_S. The bind-intent
                # writes and Lease renews ride this path, so the overload
                # drills use it to slow the COMMIT side without touching
                # the watch/ingest side.
                import os as _os
                import time as _time

                _time.sleep(float(_os.environ.get("KTPU_SLOW_S", "0.2")))
            updated = update_fn(meta.deep_copy(cur))
            if not chaos_cas and faultline.should("store.cas_conflict",
                                                  "guaranteed_update"):
                # chaos: behave exactly as if a concurrent writer won the
                # CAS race — skip the put and take the re-read/retry path
                chaos_cas = True
                continue
            rev = self.kv.txn_put(key, cur_mod if cur_mod else 0, _encode(updated))
            if rev > 0:
                out = meta.deep_copy(updated)
                meta.set_resource_version(out, str(rev))
                return out
            # CAS failure → re-read and retry

    # ------------------------------------------------------------------ #
    # Watch
    # ------------------------------------------------------------------ #

    def watch(self, prefix: str, since_rv: str = "",
              predicate: Predicate = None,
              bookmarks: bool = False) -> mwatch.Watch:
        """Watch events under prefix with revision > since_rv.

        since_rv ""/"0" = from now. Raises Gone(410) if since_rv predates
        compaction — the caller must relist (reflector relist semantics).
        """
        if faultline.should("store.compact", "watch"):
            # chaos: a REAL compaction at the current revision — stale
            # resumes below earn a genuine 410, and the dispatch pump's own
            # compaction handling runs against true state, not a mock. The
            # cacher ring compacts with it (a sustained storm churns old
            # revisions out of the window organically).
            at = self.kv.rev()
            self.kv.compact(at)
            self.watch_cache.compact(at)
        w = mwatch.Watch(capacity=8192)
        with self._watch_mu:
            # "" / "0" = from NOW: the current store revision, regardless of
            # how far the dispatch pump has gotten
            since = int(since_rv) if since_rv not in ("", "0") else self.kv.rev()
            # catch-up: replay history before going live under the same lock
            # the pump uses, so no event is missed or duplicated; the pump
            # delivers everything > max(since, _dispatched_rev). The replay
            # is served from the watch cache whenever `since` is within its
            # horizon — no storage read per watcher (cacher.go:369-374)
            cached = self.watch_cache.events_since(since, prefix)
            if cached is not None:
                for ce in cached:
                    if ce.rev > self._dispatched_rev:
                        break
                    self._deliver(w, ce, predicate)
            else:
                try:
                    history = self.kv.events_since(since, prefix)
                except native.CompactedError:
                    raise errors.new_gone(
                        f"too old resource version: {since} "
                        f"(compacted at {self.kv.compacted_rev()})")
                for ev in history:
                    if ev.rev > self._dispatched_rev:
                        break  # the pump will deliver the rest
                    self._send(w, ev, predicate)
            self._watchers.append((prefix, w, predicate,
                                   max(since, self._dispatched_rev),
                                   bookmarks))
        return w

    @staticmethod
    def _to_cached(ev: native.KVEvent) -> CachedEvent:
        typ = {native.EVENT_CREATE: mwatch.ADDED,
               native.EVENT_PUT: mwatch.MODIFIED,
               native.EVENT_DELETE: mwatch.DELETED}[ev.type]
        return CachedEvent(rev=ev.rev, type=typ, key=ev.key,
                           obj=_decode(ev.value, ev.rev))

    @classmethod
    def _send(cls, w: mwatch.Watch, ev: native.KVEvent, predicate: Predicate,
              timeout: float = 0.0) -> None:
        cls._deliver(w, cls._to_cached(ev), predicate, timeout)

    @staticmethod
    def _deliver(w: mwatch.Watch, ce: CachedEvent, predicate: Predicate,
                 timeout: float = 0.0) -> None:
        if predicate is not None and not predicate(ce.obj):
            return
        # watchers receive a copy so one consumer's mutation can't leak into
        # another's view of the shared decoded event
        obj = meta.deep_copy(ce.obj)
        # non-blocking from the dispatcher: a watcher that cannot keep up is
        # terminated (send stops it on Full), never allowed to stall the
        # event path for everyone else (cacher.go forgetWatcher semantics)
        w.send(mwatch.Event(ce.type, obj), timeout=timeout)

    def _send_bookmarks(self) -> None:
        with self._watch_mu:
            for _, w, _, since, bm in self._watchers:
                if bm and not w.stopped:
                    # never below the watcher's own horizon: a bookmark at
                    # the pump's (possibly lagging) revision would hand a
                    # resuming reflector an RV it has already consumed past,
                    # replaying duplicates (the cacher's bookmark path
                    # guarantees the same monotonicity)
                    rv = max(since, self._dispatched_rev)
                    w.send(mwatch.Event(mwatch.BOOKMARK, {
                        "kind": "Bookmark", "apiVersion": "v1",
                        "metadata": {"resourceVersion": str(rv)}}),
                        timeout=0)

    def _dispatch_loop(self) -> None:
        last_bm = time.monotonic()
        while not self._stop.is_set():
            rev = self.kv.wait(self._dispatched_rev, timeout=0.25)
            if time.monotonic() - last_bm >= self._bookmark_interval:
                last_bm = time.monotonic()
                self._send_bookmarks()
            if rev <= self._dispatched_rev:
                continue
            try:
                events = self.kv.events_since(self._dispatched_rev, "")
            except native.CompactedError:
                # the pump fell behind compaction: watchers have an
                # unrecoverable gap — error them all out so clients relist
                # (the reference terminates such watchers, cacher.go)
                with self._watch_mu:
                    gone = errors.new_gone(
                        "watch events compacted away; relist required")
                    for _, w, _, _, _ in self._watchers:
                        w.send(mwatch.Event(mwatch.ERROR, gone.status()),
                               timeout=0)
                        w.stop()
                    self._watchers.clear()
                    self._dispatched_rev = self.kv.rev()
                    # the compacted-away events never reached the ring: the
                    # cache has a GAP, so its window must restart at now —
                    # otherwise a later resume would be served an incomplete
                    # history instead of falling through to a 410
                    self.watch_cache = WatchCache(
                        horizon=self._dispatched_rev)
                continue
            with self._watch_mu:
                cached = [self._to_cached(ev) for ev in events]  # decode ONCE
                for ce in cached:
                    self.watch_cache.add(ce)
                live = []
                for prefix, w, pred, since, bm in self._watchers:
                    if w.stopped:
                        continue
                    live.append((prefix, w, pred, since, bm))
                    for ce in cached:
                        if ce.rev > since and ce.key.startswith(prefix):
                            self._deliver(w, ce, pred)
                self._watchers = live
                if events:
                    self._dispatched_rev = max(e.rev for e in events)
