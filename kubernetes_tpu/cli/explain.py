"""kubectl explain: field documentation walked from a schema tree.

The reference resolves `kubectl explain pods.spec.containers` against the
server's OpenAPI document (staging/src/k8s.io/kubectl/pkg/cmd/explain +
pkg/explain field-path walker). Here the same dotted-path walk runs over
(a) a built-in doc tree for the core kinds this framework serves, and
(b) a CRD's openAPIV3Schema for custom resources — so `explain` answers
for every resource the apiserver can store.

Doc nodes are {"doc": str, "type": str, "fields": {name: node}}.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

Node = Dict[str, Any]


def _n(doc: str, typ: str = "Object", **fields: Node) -> Node:
    return {"doc": doc, "type": typ, "fields": fields}


_META = _n(
    "Standard object metadata (metav1.ObjectMeta).",
    "Object",
    name=_n("Unique name within a namespace.", "string"),
    namespace=_n("Namespace scoping the object (default: \"default\").",
                 "string"),
    labels=_n("String keys/values for organizing and selecting objects.",
              "map[string]string"),
    annotations=_n("Unstructured metadata for tools and extensions.",
                   "map[string]string"),
    uid=_n("System-generated unique identifier.", "string"),
    resourceVersion=_n("Opaque version for optimistic concurrency.",
                       "string"),
)

_RESOURCES_REQ = _n(
    "Compute resources required by this container.",
    "Object",
    requests=_n("Minimum resources the scheduler reserves "
                "(cpu/memory/ephemeral-storage/extended).",
                "map[string]Quantity"),
    limits=_n("Maximum resources the kubelet enforces.",
              "map[string]Quantity"),
)

_CONTAINER = _n(
    "A single container to run in the pod.",
    "Object",
    name=_n("Container name, unique within the pod.", "string"),
    image=_n("Container image reference.", "string"),
    resources=_RESOURCES_REQ,
    ports=_n("Ports to expose; hostPort reserves the port on the node "
             "(PodFitsHostPorts).", "[]Object"),
)

_AFFINITY = _n(
    "Scheduling affinity: node affinity, pod affinity/anti-affinity.",
    "Object",
    nodeAffinity=_n("Constrains nodes by label (MatchNodeSelector / "
                    "NodeAffinity priority).", "Object"),
    podAffinity=_n("Attracts to nodes whose topology domain runs matching "
                   "pods (MatchInterPodAffinity).", "Object"),
    podAntiAffinity=_n("Repels from domains running matching pods.",
                       "Object"),
)

_POD_SPEC = _n(
    "Specification of the desired pod behavior.",
    "Object",
    containers=_CONTAINER | {"type": "[]Object"},
    initContainers=_n("Run to completion before containers start; "
                      "resources take the per-resource max.", "[]Object"),
    nodeName=_n("Target node; set by the scheduler via Binding.", "string"),
    nodeSelector=_n("Node labels that must match (PodMatchNodeSelector).",
                    "map[string]string"),
    affinity=_AFFINITY,
    tolerations=_n("Taints this pod tolerates "
                   "(PodToleratesNodeTaints).", "[]Object"),
    topologySpreadConstraints=_n(
        "Even spreading across topology domains (EvenPodsSpread).",
        "[]Object"),
    priority=_n("Scheduling priority; higher preempts lower.", "integer"),
    priorityClassName=_n("Resolves to spec.priority via PriorityClass.",
                         "string"),
    schedulerName=_n("Which scheduler handles this pod.", "string"),
    restartPolicy=_n("Always | OnFailure | Never.", "string"),
    overhead=_n("Pod-level resource overhead added to requests "
                "(PodOverhead).", "map[string]Quantity"),
)

_TREE: Dict[str, Node] = {
    "pods": _n(
        "A group of containers scheduled onto one node.",
        "Object",
        metadata=_META,
        spec=_POD_SPEC,
        status=_n("Observed pod state, written by the kubelet.", "Object",
                  phase=_n("Pending | Running | Succeeded | Failed.",
                           "string"),
                  podIP=_n("IP assigned by the runtime sandbox.", "string"),
                  conditions=_n("PodScheduled / Ready / ContainersReady.",
                                "[]Object")),
    ),
    "nodes": _n(
        "A worker machine registered with the control plane.",
        "Object",
        metadata=_META,
        spec=_n("Node configuration.", "Object",
                unschedulable=_n("Cordon flag (CheckNodeUnschedulable).",
                                 "boolean"),
                taints=_n("Repel pods without matching tolerations.",
                          "[]Object"),
                podCIDR=_n("Per-node pod address range (nodeipam).",
                           "string")),
        status=_n("Reported by the kubelet.", "Object",
                  capacity=_n("Total resources on the node.",
                              "map[string]Quantity"),
                  allocatable=_n("Resources available to pods "
                                 "(PodFitsResources).",
                                 "map[string]Quantity"),
                  conditions=_n("Ready and pressure conditions; heartbeat "
                                "target.", "[]Object"),
                  images=_n("Images present (ImageLocality score).",
                            "[]Object")),
    ),
    "services": _n(
        "A named virtual IP load-balancing to selected pods.",
        "Object",
        metadata=_META,
        spec=_n("Service behavior.", "Object",
                selector=_n("Pods backing this service "
                            "(Endpoints/EndpointSlice source).",
                            "map[string]string"),
                ports=_n("Exposed port mappings.", "[]Object")),
    ),
    "deployments": _n(
        "Declarative rollout management for ReplicaSets.",
        "Object",
        metadata=_META,
        spec=_n("Desired deployment state.", "Object",
                replicas=_n("Desired pod count.", "integer"),
                selector=_n("Pods owned by this deployment.", "Object"),
                template=_n("Pod template; hash-suffixed per revision.",
                            "Object"),
                strategy=_n("RollingUpdate | Recreate.", "Object")),
        status=_n("Rollout progress.", "Object",
                  readyReplicas=_n("Pods passing readiness.", "integer"),
                  updatedReplicas=_n("Pods at the newest template.",
                                     "integer")),
    ),
}


def _from_openapi(schema: Dict[str, Any], doc: str = "") -> Node:
    """Lift an OpenAPI schema subtree (CRD openAPIV3Schema, or a served
    /openapi/v2 definition) into a doc node. Arrays descend into items so
    `pods.spec.containers.resources` keeps walking."""
    typ = schema.get("type", "Object")
    if typ == "array":
        items = schema.get("items") or {}
        inner = _from_openapi(items)
        return {
            "doc": schema.get("description", doc) or inner["doc"],
            "type": f"[]{inner['type']}",
            "fields": inner["fields"],
        }
    return {
        "doc": schema.get("description", doc) or "<no description>",
        "type": typ,
        "fields": {k: _from_openapi(v)
                   for k, v in (schema.get("properties") or {}).items()},
    }


def explain_text(resource: str, group: str, version: str,
                 field_path: List[str],
                 crd_schema: Optional[Dict[str, Any]] = None,
                 node: Optional[Node] = None,
                 ) -> Optional[str]:
    """Render the explain output for `resource[.field...]`, or None if the
    path does not resolve. `node` carries a pre-resolved doc tree (the
    served-OpenAPI path); crd_schema lifts a raw openAPIV3Schema; the
    built-in tree is the in-process fallback."""
    if node is not None:
        pass
    elif crd_schema is not None:
        node = _from_openapi(crd_schema, f"Custom resource {resource}")
        node["fields"].setdefault("metadata", _META)
    else:
        node = _TREE.get(resource)
    if node is None:
        return None
    walked = [resource]
    for seg in field_path:
        node = (node.get("fields") or {}).get(seg)
        if node is None:
            return None
        walked.append(seg)
    gv = f"{group}/{version}" if group else version
    lines = [f"KIND:     {resource}",
             f"VERSION:  {gv}", "",
             f"FIELD:    {'.'.join(walked)} <{node['type']}>"
             if field_path else f"RESOURCE: {resource} <{node['type']}>",
             "",
             "DESCRIPTION:",
             f"     {node['doc']}"]
    fields = node.get("fields") or {}
    if fields:
        lines += ["", "FIELDS:"]
        for name in sorted(fields):
            child = fields[name]
            lines.append(f"   {name}\t<{child['type']}>")
            lines.append(f"     {child['doc']}")
    return "\n".join(lines) + "\n"
