"""`python -m kubernetes_tpu.cli ...` — kubectl verbs, plus `cluster up`."""

import sys

from kubernetes_tpu.cli.cluster import cluster_main
from kubernetes_tpu.cli.kubectl import main

if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "cluster":
        sys.exit(cluster_main(sys.argv[2:]))
    sys.exit(main())
