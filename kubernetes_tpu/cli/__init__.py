"""CLI + cluster bootstrap.

TPU-native analog of SURVEY.md layer 10 (`staging/src/k8s.io/kubectl`,
`cmd/kubeadm`).
"""

from kubernetes_tpu.cli.cluster import Cluster, ClusterConfig
from kubernetes_tpu.cli.kubectl import Kubectl, main

__all__ = ["Cluster", "ClusterConfig", "Kubectl", "main"]
