"""kubectl: the CLI verbs against the REST API.

Analog of `staging/src/k8s.io/kubectl` (get/describe/create/apply/delete/
scale/cordon/drain/label/taint/api-resources/version) over the same REST
paths, with table printers and -o json|yaml|name|wide. Entry point:
`python -m kubernetes_tpu.cli <verb> ...`.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

import yaml

from kubernetes_tpu.api.types import parse_cpu_milli, parse_mem_kib
from kubernetes_tpu.client.rest import Client
from kubernetes_tpu.machinery import errors, meta

Obj = Dict[str, Any]


# --------------------------------------------------------------------------- #
# printers (kubectl's printers.HumanReadablePrinter, abbreviated columns)
# --------------------------------------------------------------------------- #


def _age(obj: Obj) -> str:
    return obj.get("metadata", {}).get("creationTimestamp", "")[-9:-1] or "?"


_COLUMNS = {
    "pods": (("NAME", lambda o: meta.name(o)),
             ("READY", lambda o: _pod_ready(o)),
             ("STATUS", lambda o: o.get("status", {}).get("phase", "")),
             ("NODE", lambda o: o.get("spec", {}).get("nodeName", "<none>"))),
    "nodes": (("NAME", lambda o: meta.name(o)),
              ("STATUS", lambda o: _node_status(o)),
              ("TAINTS", lambda o: str(len(o.get("spec", {})
                                          .get("taints", []) or []))),
              ("CPU", lambda o: o.get("status", {}).get("capacity", {})
               .get("cpu", "?"))),
    "deployments": (("NAME", lambda o: meta.name(o)),
                    ("READY", lambda o: f"{o.get('status', {}).get('readyReplicas', 0)}"
                                        f"/{o.get('spec', {}).get('replicas', 0)}"),
                    ("UP-TO-DATE", lambda o: str(o.get("status", {})
                                                 .get("updatedReplicas", 0))),
                    ("AVAILABLE", lambda o: str(o.get("status", {})
                                                .get("availableReplicas", 0)))),
    "services": (("NAME", lambda o: meta.name(o)),
                 ("TYPE", lambda o: o.get("spec", {}).get("type", "")),
                 ("CLUSTER-IP", lambda o: o.get("spec", {})
                  .get("clusterIP", "<auto>")),
                 ("PORTS", lambda o: ",".join(
                     f"{p.get('port')}/{p.get('protocol', 'TCP')}"
                     for p in o.get("spec", {}).get("ports", []) or []))),
}

_DEFAULT_COLUMNS = (("NAME", lambda o: meta.name(o)),
                    ("AGE", _age))


def _pod_ready(o: Obj) -> str:
    cs = o.get("status", {}).get("containerStatuses", []) or []
    total = len(o.get("spec", {}).get("containers", []) or [])
    ready = sum(1 for c in cs if c.get("ready"))
    return f"{ready}/{total}"


def _node_status(o: Obj) -> str:
    status = "NotReady"
    for c in o.get("status", {}).get("conditions", []) or []:
        if c.get("type") == "Ready":
            status = {"True": "Ready", "False": "NotReady"}.get(
                c.get("status"), "Unknown")
    if o.get("spec", {}).get("unschedulable"):
        status += ",SchedulingDisabled"
    return status


def render_rows(header: List[str], rows: List[List[str]],
                out=sys.stdout) -> None:
    """Column-aligned table text (the HumanReadablePrinter's layout)."""
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              if rows else len(header[i]) for i in range(len(header))]
    for r in [header] + rows:
        out.write("  ".join(v.ljust(w)
                            for v, w in zip(r, widths)).rstrip() + "\n")


def print_table(resource: str, items: List[Obj], namespaced: bool,
                all_namespaces: bool, out=sys.stdout) -> None:
    cols = list(_COLUMNS.get(resource, _DEFAULT_COLUMNS))
    if all_namespaces and namespaced:
        cols.insert(0, ("NAMESPACE", lambda o: meta.namespace(o)))
    render_rows([h for h, _ in cols],
                [[fn(o) for _, fn in cols] for o in items], out)


def print_obj(obj: Obj, fmt: str, out=sys.stdout) -> None:
    if fmt == "json":
        json.dump(obj, out, indent=2)
        out.write("\n")
    elif fmt == "yaml":
        yaml.safe_dump(obj, out, sort_keys=False)
    elif fmt == "name":
        out.write(f"{obj.get('kind', '').lower()}/{meta.name(obj)}\n")


def describe(obj: Obj, out=sys.stdout) -> None:
    out.write(f"Name:         {meta.name(obj)}\n")
    if meta.namespace(obj):
        out.write(f"Namespace:    {meta.namespace(obj)}\n")
    if meta.labels_of(obj):
        out.write(f"Labels:       "
                  f"{','.join(f'{k}={v}' for k, v in sorted(meta.labels_of(obj).items()))}\n")
    out.write(f"UID:          {meta.uid(obj)}\n")
    for section in ("spec", "status"):
        if obj.get(section):
            out.write(f"{section.capitalize()}:\n")
            dumped = yaml.safe_dump(obj[section], sort_keys=False)
            for line in dumped.splitlines():
                out.write(f"  {line}\n")


# --------------------------------------------------------------------------- #
# command implementation
# --------------------------------------------------------------------------- #


class Kubectl:
    def __init__(self, client: Client, out=sys.stdout, err=sys.stderr):
        self.client = client
        self.out = out
        self.err = err
        # discovery is static within one invocation: sweep once, reuse
        # (kubectl's CachedDiscoveryClient)
        self._discovery: Optional[List[tuple]] = None

    def _rc(self, resource: str):
        """Resolve short names through the server's discovery."""
        rc = getattr(self.client, resource, None)
        if rc is not None:
            return rc
        for group, version, r in self._discovered_resources():
            if resource in ((r["name"],) + tuple(r.get("shortNames", []))) \
                    or resource == r["kind"].lower() \
                    or resource == r["name"].rstrip("s"):
                return self.client.resource(group, version, r["name"],
                                            r.get("namespaced", True))
        raise errors.new_bad_request(
            f'the server doesn\'t have a resource type "{resource}"')

    def _group_versions(self):
        yield "", "v1"
        groups = self.client.transport.request("GET", "/apis", {}, None)
        for g in groups.get("groups", []):
            for v in g.get("versions", []):
                yield g["name"], v["version"]

    def _discovered_resources(self) -> List[tuple]:
        """[(group, version, APIResource dict)] — one sweep per invocation."""
        if self._discovery is None:
            out = []
            for group, version in self._group_versions():
                rl = self.client.transport.request(
                    "GET",
                    f"/apis/{group}/{version}" if group else f"/api/{version}",
                    {}, None)
                for r in rl.get("resources", []):
                    out.append((group, version, r))
            self._discovery = out
        return self._discovery

    # -- verbs -------------------------------------------------------------- #

    def get(self, resource: str, name: str = "", namespace: str = "default",
            all_namespaces: bool = False, selector: str = "",
            output: str = "") -> int:
        rc = self._rc(resource)
        if name:
            obj = rc.get(name, namespace if rc.namespaced else "")
            if output in ("", "wide"):
                print_table(rc.resource, [obj], rc.namespaced, False, self.out)
            else:
                print_obj(obj, output, self.out)
            return 0
        ns = "" if (all_namespaces or not rc.namespaced) else namespace
        lst = rc.list(ns, label_selector=selector)
        items = lst.get("items", [])
        if output == "json":
            print_obj(lst, "json", self.out)
        elif output == "yaml":
            print_obj(lst, "yaml", self.out)
        elif output == "name":
            for o in items:
                print_obj(o, "name", self.out)
        else:
            print_table(rc.resource, items, rc.namespaced, all_namespaces,
                        self.out)
        return 0

    def describe_cmd(self, resource: str, name: str,
                     namespace: str = "default") -> int:
        rc = self._rc(resource)
        describe(rc.get(name, namespace if rc.namespaced else ""), self.out)
        return 0

    def _load_manifests(self, path: str) -> List[Obj]:
        if path == "-":
            docs = list(yaml.safe_load_all(sys.stdin.read()))
        else:
            with open(path) as f:
                docs = list(yaml.safe_load_all(f.read()))
        return [d for d in docs if d]

    def _rc_for_obj(self, obj: Obj):
        group, version, kind = meta.gvk(obj)
        for g, v, r in self._discovered_resources():
            if g == group and r["kind"] == kind and "/" not in r["name"]:
                return self.client.resource(g, v, r["name"],
                                            r.get("namespaced", True))
        raise errors.new_bad_request(f"no resource mapping for kind {kind!r}")

    def create(self, filename: str, namespace: str = "default") -> int:
        for obj in self._load_manifests(filename):
            rc = self._rc_for_obj(obj)
            out = rc.create(obj, namespace if rc.namespaced else "")
            self.out.write(f"{out.get('kind', '').lower()}/"
                           f"{meta.name(out)} created\n")
        return 0

    def apply(self, filename: str, namespace: str = "default") -> int:
        """Three-way apply (apply.go): the patch body is the full desired
        state PLUS deletions computed against the live object's
        last-applied-configuration annotation — removing a container/env
        entry (or a map key) from the manifest removes it from the live
        object, while fields set by controllers stay. Built-ins patch with
        strategic merge; custom resources fall back to 3-way JSON merge
        (lists replace wholesale) when the server answers 415."""
        import json as _json

        from kubernetes_tpu.machinery.strategicpatch import (
            LAST_APPLIED_ANNOTATION, apply_patch_body)

        for obj in self._load_manifests(filename):
            rc = self._rc_for_obj(obj)
            ns = meta.namespace(obj) or namespace
            desired = {k: v for k, v in meta.deep_copy(obj).items()
                       if k != "status"}
            record = _json.dumps(desired, sort_keys=True,
                                 separators=(",", ":"))
            meta.ensure_meta(desired).setdefault("annotations", {})[
                LAST_APPLIED_ANNOTATION] = record
            try:
                live = rc.get(meta.name(obj), ns if rc.namespaced else "")
                try:
                    last = _json.loads(
                        (live.get("metadata", {}).get("annotations") or {})
                        .get(LAST_APPLIED_ANNOTATION, "") or "{}")
                except _json.JSONDecodeError:
                    last = {}
                body = apply_patch_body(last if isinstance(last, dict)
                                        else {}, desired)
                try:
                    rc.patch(meta.name(obj), body,
                             ns if rc.namespaced else "",
                             patch_type="strategic")
                except errors.StatusError as e:
                    if e.code != 415:
                        raise
                    body = apply_patch_body(
                        last if isinstance(last, dict) else {},
                        desired, merge_lists=False)
                    rc.patch(meta.name(obj), body,
                             ns if rc.namespaced else "")
                self.out.write(f"{obj.get('kind', '').lower()}/"
                               f"{meta.name(obj)} configured\n")
            except errors.StatusError as e:
                if not errors.is_not_found(e):
                    raise
                rc.create(desired, ns if rc.namespaced else "")
                self.out.write(f"{obj.get('kind', '').lower()}/"
                               f"{meta.name(obj)} created\n")
        return 0

    def patch_cmd(self, resource: str, name: str, patch_str: str,
                  patch_type: str = "strategic",
                  namespace: str = "default") -> int:
        """kubectl patch (staging/src/k8s.io/kubectl/pkg/cmd/patch): apply a
        strategic-merge (default), RFC 7386 merge, or RFC 6902 json patch
        through the server's PATCH dialects (apiserver/registry.py patch —
        machinery/strategicpatch.py implements all three)."""
        rc = self._rc(resource)
        try:
            body = json.loads(patch_str)
        except json.JSONDecodeError:
            # kubectl accepts YAML patch bodies too (-p 'spec:\n  replicas: 3')
            try:
                body = yaml.safe_load(patch_str)
            except yaml.YAMLError:
                raise errors.new_bad_request(
                    f"unable to parse patch {patch_str!r}: not JSON or YAML"
                ) from None
        if patch_type == "json":
            if not isinstance(body, list):
                raise errors.new_bad_request(
                    "a json patch body must be an array of operations")
        elif not isinstance(body, dict):
            raise errors.new_bad_request(
                f"a {patch_type} patch body must be a JSON object")
        rc.patch(name, body, namespace if rc.namespaced else "",
                 patch_type=patch_type)
        self.out.write(f"{rc.resource.rstrip('s')}/{name} patched\n")
        return 0

    def delete(self, resource: str, name: str,
               namespace: str = "default") -> int:
        rc = self._rc(resource)
        rc.delete(name, namespace if rc.namespaced else "")
        self.out.write(f"{rc.resource.rstrip('s')} \"{name}\" deleted\n")
        return 0

    def scale(self, resource: str, name: str, replicas: int,
              namespace: str = "default") -> int:
        rc = self._rc(resource)
        rc.put_scale(name, replicas, namespace)
        self.out.write(f"{rc.resource.rstrip('s')}/{name} scaled\n")
        return 0

    def cordon(self, node: str, on: bool = True) -> int:
        self.client.nodes.patch(node, {"spec": {"unschedulable": on or None}},
                                namespace="")
        self.out.write(f"node/{node} {'cordoned' if on else 'uncordoned'}\n")
        return 0

    def drain(self, node: str) -> int:
        self.cordon(node, True)
        pods = self.client.pods.list(
            "", field_selector=f"spec.nodeName={node}")["items"]
        for p in pods:
            ref = meta.controller_ref(p)
            if ref and ref.get("kind") == "DaemonSet":
                continue  # kubectl drain --ignore-daemonsets default
            try:
                self.client.pods.evict(meta.name(p), meta.namespace(p))
                self.out.write(f"pod/{meta.name(p)} evicted\n")
            except errors.StatusError as e:
                self.err.write(f"error evicting pod {meta.name(p)}: "
                               f"{e.message}\n")
        self.out.write(f"node/{node} drained\n")
        return 0

    def label(self, resource: str, name: str, kv: List[str],
              namespace: str = "default") -> int:
        rc = self._rc(resource)
        patch: Dict[str, Any] = {}
        for pair in kv:
            # only `key-` (no '=') is a removal; a VALUE ending in '-' is
            # a legitimate assignment (kubectl parseLabels)
            if "=" not in pair and pair.endswith("-"):
                patch[pair[:-1]] = None
            else:
                k, _, v = pair.partition("=")
                patch[k] = v
        rc.patch(name, {"metadata": {"labels": patch}},
                 namespace if rc.namespaced else "")
        self.out.write(f"{rc.resource.rstrip('s')}/{name} labeled\n")
        return 0

    def taint(self, node: str, spec: str) -> int:
        """kubectl taint nodes n1 key=value:NoSchedule (or key:NoSchedule-)."""
        cur = self.client.nodes.get(node, "")
        taints = [t for t in cur.get("spec", {}).get("taints", []) or []]
        if spec.endswith("-"):
            body = spec[:-1]
            key = body.split("=")[0].split(":")[0]
            taints = [t for t in taints if t.get("key") != key]
            action = "untainted"
        else:
            kv, _, effect = spec.rpartition(":")
            key, _, value = kv.partition("=")
            taints = [t for t in taints if t.get("key") != key]
            taints.append({"key": key, "value": value, "effect": effect})
            action = "tainted"
        cur.setdefault("spec", {})["taints"] = taints
        self.client.nodes.update(cur, "")
        self.out.write(f"node/{node} {action}\n")
        return 0

    def diff(self, filename: str, namespace: str = "default") -> int:
        """kubectl diff (staging/src/k8s.io/kubectl/pkg/cmd/diff): show what
        apply WOULD change, without changing it. The merged result is
        computed with the server's own JSON-merge semantics
        (apiserver/registry.py `_merge_patch` — the reference does this as
        a server-side dry-run apply) and printed as a unified diff of live
        vs merged. Exit code 1 when differences exist, 0 when none — the
        reference's contract."""
        import difflib

        from kubernetes_tpu.apiserver.registry import _merge_patch

        changed = False
        for obj in self._load_manifests(filename):
            rc = self._rc_for_obj(obj)
            ns = (meta.namespace(obj) or namespace) if rc.namespaced else ""
            name = meta.name(obj)
            desired = {k: v for k, v in obj.items() if k != "status"}
            try:
                live = rc.get(name, ns)
                merged = _merge_patch(meta.deep_copy(live), desired)
            except errors.StatusError as e:
                if not errors.is_not_found(e):
                    raise
                live, merged = {}, desired  # would be created
            def strip(o: Obj) -> Obj:
                o = meta.deep_copy(o)
                md = o.get("metadata", {})
                for k in ("resourceVersion", "uid", "creationTimestamp",
                          "generation"):
                    md.pop(k, None)
                return o
            a = json.dumps(strip(live), indent=2, sort_keys=True)
            b = json.dumps(strip(merged), indent=2, sort_keys=True)
            if a == b:
                continue
            changed = True
            tag = f"{obj.get('kind', '').lower()}/{name}"
            self.out.write("".join(difflib.unified_diff(
                a.splitlines(keepends=True), b.splitlines(keepends=True),
                fromfile=f"live/{tag}", tofile=f"merged/{tag}")))
            # json.dumps never ends in a newline, so the diff's final line
            # is always unterminated
            self.out.write("\n")
        return 1 if changed else 0

    def explain(self, path: str) -> int:
        """kubectl explain (staging/src/k8s.io/kubectl/pkg/cmd/explain):
        walk a dotted field path through the SERVED OpenAPI document
        (/openapi/v2 — the same walk the reference does), falling back to
        the in-process doc trees only if the server has no /openapi/v2."""
        from kubernetes_tpu.cli.explain import (
            _META, _from_openapi, explain_text)

        segs = path.split(".")
        rc = self._rc(segs[0])
        node = None
        try:
            doc = self.client.transport.request("GET", "/openapi/v2",
                                                {}, None)
        except Exception:  # noqa: BLE001 — older server: in-process docs
            doc = None
        if isinstance(doc, dict) and doc.get("definitions"):
            from kubernetes_tpu.apiserver.openapi import find_definition

            schema = find_definition(doc, rc.group, rc.version,
                                     resource=rc.resource)
            if schema is not None:
                node = _from_openapi(schema)
                node["fields"].setdefault("metadata", _META)
        if node is None and rc.group not in ("", "apps", "batch", "policy"):
            # find_definition's kind→plural match is naive (irregular
            # plurals miss), and an older server may serve no /openapi/v2
            # at all: fetch the CRD's schema by its exact stored name
            try:
                crd = self.client.customresourcedefinitions.get(
                    f"{rc.resource}.{rc.group}", "")
                versions = crd.get("spec", {}).get("versions") or []
                v = next((x for x in versions
                          if x.get("name") == rc.version), None) \
                    or (versions[0] if versions else None)
                crd_schema = ((v or {}).get("schema") or {}).get(
                    "openAPIV3Schema") or (crd.get("spec", {})
                                           .get("validation") or {}).get(
                                               "openAPIV3Schema")
                if crd_schema is not None:
                    node = _from_openapi(
                        crd_schema, f"Custom resource {rc.resource}")
                    node["fields"].setdefault("metadata", _META)
            except errors.StatusError:
                pass
        text = explain_text(rc.resource, rc.group, rc.version, segs[1:],
                            node=node)
        if text is None:
            self.err.write(f"error: field {'.'.join(segs)!r} does not "
                           "exist\n")
            return 1
        self.out.write(text)
        return 0

    def _deployment_rses(self, name: str, ns: str):
        from kubernetes_tpu.controllers.workloads import rs_revision

        d = self.client.deployments.get(name, ns)
        uid = d["metadata"]["uid"]
        rses = [rs for rs in self.client.replicasets.list(ns)["items"]
                if any(o.get("uid") == uid and o.get("controller")
                       for o in rs["metadata"].get("ownerReferences", []))]
        return d, sorted(rses, key=rs_revision), rs_revision

    def rollout(self, subverb: str, target: str,
                namespace: str = "default", to_revision: int = 0,
                timeout: float = 60.0) -> int:
        """kubectl rollout status|restart|history|undo for deployments
        (staging/src/k8s.io/kubectl/pkg/cmd/rollout + polymorphichelpers):
        status polls the observed rollout, restart stamps the template's
        restartedAt annotation, history lists ReplicaSet revisions, undo
        re-applies a previous revision's template (becoming the newest
        revision)."""
        res, _, name = target.partition("/")
        if res not in ("deployment", "deployments", "deploy") or not name:
            self.err.write("error: rollout supports deployment/<name>\n")
            return 1
        if subverb == "status":
            import time as _time

            deadline = _time.monotonic() + timeout
            while _time.monotonic() < deadline:
                d = self.client.deployments.get(name, namespace)
                want = int(d["spec"].get("replicas", 1))
                st = d.get("status", {})
                if (st.get("observedGeneration", 0)
                        >= d["metadata"].get("generation", 0)
                        and st.get("updatedReplicas", 0) == want
                        and st.get("readyReplicas", 0) == want
                        and st.get("replicas", 0) == want):
                    self.out.write(
                        f'deployment "{name}" successfully rolled out\n')
                    return 0
                _time.sleep(0.2)
            self.err.write(f'error: deployment "{name}" did not roll out '
                           f"within {timeout:g}s\n")
            return 1
        if subverb == "restart":
            stamp = meta.now_rfc3339()
            self.client.deployments.patch(name, {"spec": {"template": {
                "metadata": {"annotations": {
                    "kubectl.kubernetes.io/restartedAt": stamp}}}}},
                namespace)
            self.out.write(f"deployment.apps/{name} restarted\n")
            return 0
        if subverb == "history":
            _, rses, rev = self._deployment_rses(name, namespace)
            self.out.write("REVISION  CHANGE-CAUSE\n")
            for rs in rses:
                cause = (rs["metadata"].get("annotations") or {}).get(
                    "kubernetes.io/change-cause", "<none>")
                self.out.write(f"{rev(rs)}         {cause}\n")
            return 0
        if subverb == "undo":
            d, rses, rev = self._deployment_rses(name, namespace)
            if to_revision:
                target_rs = next((rs for rs in rses
                                  if rev(rs) == to_revision), None)
                if target_rs is None:
                    self.err.write(f"error: unable to find revision "
                                   f"{to_revision} of deployment "
                                   f"{name!r}\n")
                    return 1
            else:
                if len(rses) < 2:
                    self.err.write("error: no rollout history found\n")
                    return 1
                target_rs = rses[-2]  # previous revision
            tmpl = meta.deep_copy(target_rs["spec"]["template"])
            tmpl.get("metadata", {}).get("labels", {}).pop(
                "pod-template-hash", None)
            # full-object PUT, not a merge patch: the server's RFC 7386
            # merge cannot REMOVE template fields added after the target
            # revision (annotations, env, labels), which would leave a
            # hybrid spec matching neither revision
            for _ in range(5):
                cur = self.client.deployments.get(name, namespace)
                cur["spec"]["template"] = meta.deep_copy(tmpl)
                try:
                    self.client.deployments.update(cur, namespace)
                    break
                except errors.StatusError as e:
                    if not errors.is_conflict(e):
                        raise
            else:
                self.err.write("error: rollback write kept conflicting; "
                               "retry\n")
                return 1
            self.out.write(f"deployment.apps/{name} rolled back\n")
            return 0
        self.err.write(f"error: unknown rollout subcommand {subverb!r}\n")
        return 1

    def top(self, kind: str, namespace: str = "default") -> int:
        """kubectl top pods|nodes (staging/src/k8s.io/kubectl top_*.go):
        reads the aggregated resource-metrics API the metrics-server
        publishes (component/metrics_server.py)."""
        if kind not in ("pods", "nodes", "pod", "node", "po", "no"):
            self.err.write(f"error: unknown resource {kind!r}\n")
            return 1
        nodes = kind.startswith("no")
        try:
            rc = self.client.resource("metrics.k8s.io", "v1beta1",
                                      "nodes" if nodes else "pods",
                                      not nodes)
            items = rc.list("" if nodes else namespace).get("items", [])
        except errors.StatusError as e:
            if errors.is_not_found(e):
                # the group genuinely isn't served (no metrics-server);
                # RBAC denials / server errors surface as themselves
                self.err.write("error: Metrics API not available\n")
                return 1
            raise
        rows = []
        for m in items:
            if nodes:
                usage = m.get("usage", {})
            else:
                cpu = sum(parse_cpu_milli(
                    (c.get("usage") or {}).get("cpu", 0))
                    for c in m.get("containers", []))
                memk = sum(parse_mem_kib(
                    (c.get("usage") or {}).get("memory", 0))
                    for c in m.get("containers", []))
                usage = {"cpu": f"{cpu}m", "memory": f"{memk}Ki"}
            rows.append([meta.name(m), str(usage.get("cpu", "0")),
                         str(usage.get("memory", "0"))])
        render_rows(["NAME", "CPU(cores)", "MEMORY(bytes)"], rows, self.out)
        return 0

    def api_resources(self) -> int:
        self.out.write("NAME  SHORTNAMES  APIGROUP  NAMESPACED  KIND\n")
        for group, _, r in self._discovered_resources():
            if "/" in r["name"]:
                continue
            self.out.write(
                f"{r['name']}  {','.join(r.get('shortNames', []))}  "
                f"{group}  {r.get('namespaced', True)}  {r['kind']}\n")
        return 0

    def version(self) -> int:
        v = self.client.version()
        self.out.write(f"Server Version: {v.get('gitVersion', '?')}\n")
        return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kubectl",
                                description="kubernetes-tpu CLI")
    p.add_argument("-s", "--server", default="http://127.0.0.1:6443")
    p.add_argument("-n", "--namespace", default="default")
    sub = p.add_subparsers(dest="verb", required=True)

    g = sub.add_parser("get")
    g.add_argument("resource")
    g.add_argument("name", nargs="?", default="")
    g.add_argument("-A", "--all-namespaces", action="store_true")
    g.add_argument("-l", "--selector", default="")
    g.add_argument("-o", "--output", default="",
                   choices=["", "json", "yaml", "name", "wide"])

    d = sub.add_parser("describe")
    d.add_argument("resource")
    d.add_argument("name")

    for verb in ("create", "apply", "diff"):
        c = sub.add_parser(verb)
        c.add_argument("-f", "--filename", required=True)

    ex = sub.add_parser("explain")
    ex.add_argument("path", help="resource[.field.field...]")

    tp = sub.add_parser("top")
    tp.add_argument("kind", help="pods|nodes")

    ro = sub.add_parser("rollout")
    ro.add_argument("subverb", choices=["status", "restart", "history",
                                        "undo"])
    ro.add_argument("target", help="deployment/<name>")
    ro.add_argument("--to-revision", type=int, default=0)
    ro.add_argument("--timeout", type=float, default=60.0)

    pa = sub.add_parser("patch")
    pa.add_argument("resource")
    pa.add_argument("name")
    pa.add_argument("-p", "--patch", required=True)
    pa.add_argument("--type", default="strategic", dest="patch_type",
                    choices=["strategic", "merge", "json"])

    de = sub.add_parser("delete")
    de.add_argument("resource")
    de.add_argument("name")

    sc = sub.add_parser("scale")
    sc.add_argument("resource_slash_name")
    sc.add_argument("--replicas", type=int, required=True)

    for verb in ("cordon", "uncordon", "drain"):
        cn = sub.add_parser(verb)
        cn.add_argument("node")

    lb = sub.add_parser("label")
    lb.add_argument("resource")
    lb.add_argument("name")
    lb.add_argument("kv", nargs="+")

    tn = sub.add_parser("taint")
    tn.add_argument("nodes_literal")  # "nodes"
    tn.add_argument("node")
    tn.add_argument("spec")

    sub.add_parser("api-resources")
    sub.add_parser("version")
    return p


def main(argv: Optional[List[str]] = None, client: Optional[Client] = None,
         out=sys.stdout, err=sys.stderr) -> int:
    args = build_parser().parse_args(argv)
    cl = client or Client.http(args.server)
    k = Kubectl(cl, out=out, err=err)
    try:
        if args.verb == "get":
            return k.get(args.resource, args.name, args.namespace,
                         args.all_namespaces, args.selector, args.output)
        if args.verb == "describe":
            return k.describe_cmd(args.resource, args.name, args.namespace)
        if args.verb == "create":
            return k.create(args.filename, args.namespace)
        if args.verb == "apply":
            return k.apply(args.filename, args.namespace)
        if args.verb == "diff":
            return k.diff(args.filename, args.namespace)
        if args.verb == "explain":
            return k.explain(args.path)
        if args.verb == "top":
            return k.top(args.kind, args.namespace)
        if args.verb == "rollout":
            return k.rollout(args.subverb, args.target, args.namespace,
                             to_revision=args.to_revision,
                             timeout=args.timeout)
        if args.verb == "patch":
            return k.patch_cmd(args.resource, args.name, args.patch,
                               args.patch_type, args.namespace)
        if args.verb == "delete":
            return k.delete(args.resource, args.name, args.namespace)
        if args.verb == "scale":
            res, _, name = args.resource_slash_name.partition("/")
            return k.scale(res, name, args.replicas, args.namespace)
        if args.verb == "cordon":
            return k.cordon(args.node, True)
        if args.verb == "uncordon":
            return k.cordon(args.node, False)
        if args.verb == "drain":
            return k.drain(args.node)
        if args.verb == "label":
            return k.label(args.resource, args.name, args.kv, args.namespace)
        if args.verb == "taint":
            return k.taint(args.node, args.spec)
        if args.verb == "api-resources":
            return k.api_resources()
        if args.verb == "version":
            return k.version()
    except errors.StatusError as e:
        err.write(f"Error from server ({e.reason}): {e.message}\n")
        # kubectl diff reserves rc 1 for "differences found"; errors are >1
        return 2 if args.verb == "diff" else 1
    return 0
