"""Cluster lifecycle: the kubeadm workflow for the TPU-native control plane.

Analog of `cmd/kubeadm` phases reduced to what a single-process control
plane needs:

  init  (`up`)    storage → apiserver (+HTTP gateway) → scheduler →
                  controller-manager → optional hollow nodes, in dependency
                  order (cmd/kubeadm/app/cmd/init.go phase runner).
  join  (`join`)  add worker nodes to a RUNNING cluster over its URL —
                  the kubeadm-join flow with hollow kubelets standing in
                  for real ones (cmd/kubeadm/app/cmd/join.go).
  reset (`down`)  tear everything down in reverse order.

A KubeSchedulerConfiguration file/dict flows through `scheduler_config`
into the scheduler exactly as `--config` does for the reference binary.
`python -m kubernetes_tpu.cli cluster up` serves until interrupted.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.apiserver import APIServer, HTTPGateway
from kubernetes_tpu.client import Client
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.sched.server import SchedulerServer


@dataclass
class ClusterConfig:
    """The kubeadm ClusterConfiguration analog."""

    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral
    hollow_nodes: int = 0
    hollow_capacity: Dict[str, str] = field(default_factory=lambda: {
        "cpu": "8", "memory": "16Gi", "pods": "110"})
    leader_elect: bool = False
    controllers: Optional[List[str]] = None
    scheduler_name: str = "default-scheduler"
    # KubeSchedulerConfiguration: a path, YAML/JSON string, or dict
    # (sched/config.py load_config) — the kube-scheduler --config analog
    scheduler_config: Optional[object] = None


class Cluster:
    """All control-plane components in one process (the integration-test /
    local-dev topology; each component still talks REST through the gateway
    so the process boundary semantics hold)."""

    def __init__(self, config: Optional[ClusterConfig] = None):
        self.config = config or ClusterConfig()
        self.api: Optional[APIServer] = None
        self.gateway: Optional[HTTPGateway] = None
        self.client: Optional[Client] = None
        self.scheduler: Optional[SchedulerServer] = None
        self.manager: Optional[ControllerManager] = None
        self.hollow: Optional[HollowCluster] = None
        self._joined: List[HollowCluster] = []

    # -- phases (kubeadm init workflow) ------------------------------------- #

    def up(self) -> "Cluster":
        cfg = self.config
        self.api = APIServer()
        self.gateway = HTTPGateway(self.api, host=cfg.host,
                                   port=cfg.port).start()
        self.client = Client.http(self.gateway.url)
        self.scheduler = SchedulerServer(
            self.client,
            scheduler_name=cfg.scheduler_name,
            leader_elect=cfg.leader_elect,
            config=cfg.scheduler_config).start()
        self.manager = ControllerManager(
            self.client, controllers=cfg.controllers,
            leader_elect=cfg.leader_elect).start()
        if cfg.hollow_nodes:
            self.hollow = HollowCluster(
                self.client, cfg.hollow_nodes,
                capacity=cfg.hollow_capacity).start()
        return self

    def join(self, n_nodes: int = 1, name_prefix: Optional[str] = None,
             capacity: Optional[Dict[str, str]] = None) -> "HollowCluster":
        """kubeadm join: register n worker nodes against the running control
        plane (a fresh client over the public URL — the same wire path an
        out-of-process kubelet would take). Each join batch gets a unique
        default prefix so repeated joins ADD nodes instead of re-registering
        the previous batch's names."""
        if self.gateway is None:
            raise RuntimeError("cluster is not up")
        if name_prefix is None:
            name_prefix = f"joined-node-b{len(self._joined)}"
        extra = HollowCluster(
            Client.http(self.gateway.url), n_nodes,
            name_prefix=name_prefix,
            capacity=capacity or self.config.hollow_capacity).start()
        self._joined.append(extra)
        return extra

    def down(self) -> None:
        for extra in reversed(self._joined):
            extra.stop()
        self._joined.clear()
        for c in (self.hollow, self.manager, self.scheduler):
            if c is not None:
                c.stop()
        if self.gateway is not None:
            self.gateway.stop()
        if self.api is not None:
            self.api.close()

    @property
    def url(self) -> str:
        return self.gateway.url if self.gateway else ""

    def __enter__(self) -> "Cluster":
        return self.up()

    def __exit__(self, *exc) -> None:
        self.down()


def cluster_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="cluster")
    p.add_argument("action", choices=["up"])
    p.add_argument("--port", type=int, default=6443)
    p.add_argument("--hollow-nodes", type=int, default=0)
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--scheduler-config", default=None,
                   help="KubeSchedulerConfiguration file (YAML/JSON)")
    args = p.parse_args(argv)
    cluster = Cluster(ClusterConfig(port=args.port,
                                    hollow_nodes=args.hollow_nodes,
                                    leader_elect=args.leader_elect,
                                    scheduler_config=args.scheduler_config)).up()
    print(f"control plane ready at {cluster.url} "
          f"({args.hollow_nodes} hollow nodes)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        cluster.down()
    return 0
