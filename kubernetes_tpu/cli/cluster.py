"""Cluster bootstrap: kubeadm-init for the TPU-native control plane.

Analog of `cmd/kubeadm` phases reduced to what a single-process control
plane needs: bring up storage → apiserver (+HTTP gateway) → scheduler →
controller-manager → (optionally) hollow nodes, in dependency order, with
clean teardown. `python -m kubernetes_tpu.cli cluster up` serves until
interrupted.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.apiserver import APIServer, HTTPGateway
from kubernetes_tpu.client import Client
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.sched.server import SchedulerServer


@dataclass
class ClusterConfig:
    """The kubeadm ClusterConfiguration analog."""

    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral
    hollow_nodes: int = 0
    hollow_capacity: Dict[str, str] = field(default_factory=lambda: {
        "cpu": "8", "memory": "16Gi", "pods": "110"})
    leader_elect: bool = False
    controllers: Optional[List[str]] = None
    scheduler_name: str = "default-scheduler"


class Cluster:
    """All control-plane components in one process (the integration-test /
    local-dev topology; each component still talks REST through the gateway
    so the process boundary semantics hold)."""

    def __init__(self, config: Optional[ClusterConfig] = None):
        self.config = config or ClusterConfig()
        self.api: Optional[APIServer] = None
        self.gateway: Optional[HTTPGateway] = None
        self.client: Optional[Client] = None
        self.scheduler: Optional[SchedulerServer] = None
        self.manager: Optional[ControllerManager] = None
        self.hollow: Optional[HollowCluster] = None

    # -- phases (kubeadm init workflow) ------------------------------------- #

    def up(self) -> "Cluster":
        cfg = self.config
        self.api = APIServer()
        self.gateway = HTTPGateway(self.api, host=cfg.host,
                                   port=cfg.port).start()
        self.client = Client.http(self.gateway.url)
        self.scheduler = SchedulerServer(
            self.client, scheduler_name=cfg.scheduler_name,
            leader_elect=cfg.leader_elect).start()
        self.manager = ControllerManager(
            self.client, controllers=cfg.controllers,
            leader_elect=cfg.leader_elect).start()
        if cfg.hollow_nodes:
            self.hollow = HollowCluster(
                self.client, cfg.hollow_nodes,
                capacity=cfg.hollow_capacity).start()
        return self

    def down(self) -> None:
        for c in (self.hollow, self.manager, self.scheduler):
            if c is not None:
                c.stop()
        if self.gateway is not None:
            self.gateway.stop()
        if self.api is not None:
            self.api.close()

    @property
    def url(self) -> str:
        return self.gateway.url if self.gateway else ""

    def __enter__(self) -> "Cluster":
        return self.up()

    def __exit__(self, *exc) -> None:
        self.down()


def cluster_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="cluster")
    p.add_argument("action", choices=["up"])
    p.add_argument("--port", type=int, default=6443)
    p.add_argument("--hollow-nodes", type=int, default=0)
    p.add_argument("--leader-elect", action="store_true")
    args = p.parse_args(argv)
    cluster = Cluster(ClusterConfig(port=args.port,
                                    hollow_nodes=args.hollow_nodes,
                                    leader_elect=args.leader_elect)).up()
    print(f"control plane ready at {cluster.url} "
          f"({args.hollow_nodes} hollow nodes)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        cluster.down()
    return 0
