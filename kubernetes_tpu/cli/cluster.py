"""Cluster lifecycle: the kubeadm workflow for the TPU-native control plane.

Analog of `cmd/kubeadm` phases reduced to what a single-process control
plane needs:

  init  (`up`)    storage → apiserver (+HTTP gateway) → scheduler →
                  controller-manager → optional hollow nodes, in dependency
                  order (cmd/kubeadm/app/cmd/init.go phase runner).
  join  (`join`)  add worker nodes to a RUNNING cluster over its URL —
                  the kubeadm-join flow with hollow kubelets standing in
                  for real ones (cmd/kubeadm/app/cmd/join.go).
  reset (`down`)  tear everything down in reverse order.

A KubeSchedulerConfiguration file/dict flows through `scheduler_config`
into the scheduler exactly as `--config` does for the reference binary.
`python -m kubernetes_tpu.cli cluster up` serves until interrupted.
"""

from __future__ import annotations

import base64
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.apiserver import APIServer, HTTPGateway
from kubernetes_tpu.client import Client
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.sched.server import SchedulerServer


@dataclass
class ClusterConfig:
    """The kubeadm ClusterConfiguration analog."""

    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral
    hollow_nodes: int = 0
    hollow_capacity: Dict[str, str] = field(default_factory=lambda: {
        "cpu": "8", "memory": "16Gi", "pods": "110"})
    leader_elect: bool = False
    controllers: Optional[List[str]] = None
    # authenticated=True puts an AuthGate on the gateway: components use a
    # minted admin token, and joiners' bootstrap tokens are VALIDATED by
    # the BootstrapTokenAuthenticator chain (the kubeadm topology; off by
    # default to keep the open integration-test surface)
    authenticated: bool = False
    scheduler_name: str = "default-scheduler"
    # KubeSchedulerConfiguration: a path, YAML/JSON string, or dict
    # (sched/config.py load_config) — the kube-scheduler --config analog
    scheduler_config: Optional[object] = None


def _parse_version(v: str):
    """'v1.17.0-tpu.1' → (1, 17, 0); None if unparseable."""
    core = v.lstrip("v").split("-")[0]
    try:
        parts = [int(x) for x in core.split(".")[:3]]
        while len(parts) < 3:
            parts.append(0)
        return tuple(parts)
    except ValueError:
        return None


def _skew_allows(cur: str, target: str):
    """kubeadm's version-skew policy (phases/upgrade/policy.go): no
    downgrades, at most one minor-version jump."""
    c, t = _parse_version(cur), _parse_version(target)
    if c is None or t is None:
        return False, f"unparseable version: {cur!r} -> {target!r}"
    if t < c:
        return False, f"downgrade {cur} -> {target} is not supported"
    if t[0] != c[0]:
        return False, f"major version change {cur} -> {target} not supported"
    if t[1] > c[1] + 1:
        return False, (f"cannot skip minor versions: {cur} -> {target} "
                       "(one minor at a time)")
    return True, ""


class Cluster:
    """All control-plane components in one process (the integration-test /
    local-dev topology; each component still talks REST through the gateway
    so the process boundary semantics hold)."""

    def __init__(self, config: Optional[ClusterConfig] = None):
        self.config = config or ClusterConfig()
        self.api: Optional[APIServer] = None
        self.gateway: Optional[HTTPGateway] = None
        self.client: Optional[Client] = None
        self.scheduler: Optional[SchedulerServer] = None
        self.manager: Optional[ControllerManager] = None
        self.hollow: Optional[HollowCluster] = None
        self._joined: List[HollowCluster] = []
        self.bootstrap_token: str = ""
        self.admin_token: str = ""
        self.node_credentials: Dict[str, Dict[str, bytes]] = {}

    # -- phases (kubeadm init workflow) ------------------------------------- #

    def up(self) -> "Cluster":
        cfg = self.config
        self.api = APIServer()
        auth_gate = None
        self.admin_token = ""
        if cfg.authenticated:
            import secrets as pysecrets

            from kubernetes_tpu.apiserver.auth import (
                AuthGate, RBACAuthorizer, TokenAuthenticator)
            from kubernetes_tpu.controllers.certificates import (
                BootstrapTokenAuthenticator)

            self.admin_token = pysecrets.token_hex(16)
            ta = TokenAuthenticator()
            ta.add(self.admin_token, "kubernetes-admin",
                   ("system:masters",))
            ta.chain.append(BootstrapTokenAuthenticator(self.api))
            # RBAC at the gateway: without an authorizer every
            # authenticated identity — including a joiner's bootstrap
            # token — had unrestricted access (e.g. GET of the kube-system
            # cluster-ca Secret holding the CA private key). The reference
            # confines system:bootstrappers to posting/collecting CSRs;
            # _seed_rbac_policy writes the same confinement
            self._seed_rbac_policy()
            auth_gate = AuthGate(authenticator=ta,
                                 authorizer=RBACAuthorizer(self.api),
                                 allow_anonymous=False)
        self.gateway = HTTPGateway(self.api, host=cfg.host, port=cfg.port,
                                   auth_gate=auth_gate).start()
        self.client = Client.http(self.gateway.url,
                                  token=self.admin_token)
        self.scheduler = SchedulerServer(
            self.client,
            scheduler_name=cfg.scheduler_name,
            leader_elect=cfg.leader_elect,
            config=cfg.scheduler_config).start()
        self.manager = ControllerManager(
            self.client, controllers=cfg.controllers,
            leader_elect=cfg.leader_elect).start()
        # bootstrap-token phase (kubeadm init phase bootstrap-token): mint
        # the token joiners authenticate with; the CSR controllers serve
        # the other half of TLS bootstrap
        from kubernetes_tpu.controllers.certificates import (
            make_bootstrap_token)
        from kubernetes_tpu.machinery import errors as merrors

        self.bootstrap_token, secret = make_bootstrap_token()
        try:
            self.client.secrets.create(secret, "kube-system")
        except merrors.StatusError as e:
            if not merrors.is_already_exists(e):
                raise
        if cfg.authenticated:
            # kube-public/cluster-info (kubeadm init phase bootstrap-token):
            # the CA CERTIFICATE published where bootstrappers may read it —
            # under RBAC they can no longer GET the kube-system cluster-ca
            # Secret (which also holds the CA private key)
            self._publish_cluster_info()
        if cfg.hollow_nodes:
            self.hollow = HollowCluster(
                self.client, cfg.hollow_nodes,
                capacity=cfg.hollow_capacity).start()
        return self

    def _seed_rbac_policy(self) -> None:
        """Write the authenticated topology's RBAC policy straight into
        storage (before the gateway opens): system:masters is cluster-admin,
        and system:bootstrappers gets EXACTLY the reference's
        system:node-bootstrapper surface — CSR create/get/list/watch plus a
        read of kube-public/cluster-info — so a leaked bootstrap token can
        request a node certificate but cannot read the CA private key, list
        Secrets, or touch workloads."""
        from kubernetes_tpu.controllers.certificates import BOOTSTRAP_GROUP

        g = "rbac.authorization.k8s.io"
        gv = f"{g}/v1"
        objs = [
            ("clusterroles", "", {
                "apiVersion": gv, "kind": "ClusterRole",
                "metadata": {"name": "cluster-admin"},
                "rules": [
                    {"verbs": ["*"], "apiGroups": ["*"],
                     "resources": ["*"]},
                    {"verbs": ["*"], "nonResourceURLs": ["*"]},
                ]}),
            ("clusterrolebindings", "", {
                "apiVersion": gv, "kind": "ClusterRoleBinding",
                "metadata": {"name": "cluster-admin"},
                "subjects": [{"kind": "Group", "name": "system:masters"}],
                "roleRef": {"kind": "ClusterRole", "name": "cluster-admin"}}),
            ("clusterroles", "", {
                "apiVersion": gv, "kind": "ClusterRole",
                "metadata": {"name": "system:node-bootstrapper"},
                "rules": [
                    {"verbs": ["create", "get", "list", "watch"],
                     "apiGroups": ["certificates.k8s.io"],
                     "resources": ["certificatesigningrequests"]},
                ]}),
            ("clusterrolebindings", "", {
                "apiVersion": gv, "kind": "ClusterRoleBinding",
                "metadata": {"name": "kubeadm:node-bootstrappers"},
                "subjects": [{"kind": "Group", "name": BOOTSTRAP_GROUP}],
                "roleRef": {"kind": "ClusterRole",
                            "name": "system:node-bootstrapper"}}),
            ("roles", "kube-public", {
                "apiVersion": gv, "kind": "Role",
                "metadata": {"name": "kubeadm:bootstrap-signer-clusterinfo",
                             "namespace": "kube-public"},
                "rules": [{"verbs": ["get"], "apiGroups": [""],
                           "resources": ["configmaps"],
                           "resourceNames": ["cluster-info"]}]}),
            ("rolebindings", "kube-public", {
                "apiVersion": gv, "kind": "RoleBinding",
                "metadata": {"name": "kubeadm:bootstrap-signer-clusterinfo",
                             "namespace": "kube-public"},
                "subjects": [{"kind": "Group", "name": BOOTSTRAP_GROUP}],
                "roleRef": {"kind": "Role",
                            "name": "kubeadm:bootstrap-signer-clusterinfo"}}),
        ]
        from kubernetes_tpu.machinery import errors as merrors

        for resource, ns, obj in objs:
            try:
                self.api.store(g, resource).create(ns, obj)
            except merrors.StatusError as e:
                if not merrors.is_already_exists(e):
                    raise

    def _publish_cluster_info(self) -> None:
        """kube-public/cluster-info: the CA certificate + a minimal
        kubeconfig, readable by bootstrappers (and signed per usable token
        by the BootstrapSignerController when it runs)."""
        import json as _json

        from kubernetes_tpu.controllers.certificates import _shared_ca
        from kubernetes_tpu.machinery import errors as merrors

        try:
            ca_pem = _shared_ca(self.client).ca_pem().decode()
        except ImportError:
            # no `cryptography` in this environment: there is no CA to
            # publish (CSR signing is equally unavailable) — skip the
            # ConfigMap rather than fail the whole control-plane bringup
            return
        kubeconfig = _json.dumps({
            "apiVersion": "v1", "kind": "Config",
            "clusters": [{"name": "", "cluster": {
                "server": self.gateway.url if self.gateway else "",
                "certificate-authority-data": base64.b64encode(
                    ca_pem.encode()).decode()}}]})
        cm = {"apiVersion": "v1", "kind": "ConfigMap",
              "metadata": {"name": "cluster-info",
                           "namespace": "kube-public"},
              "data": {"ca.crt": ca_pem, "kubeconfig": kubeconfig}}
        try:
            self.client.configmaps.create(cm, "kube-public")
        except merrors.StatusError as e:
            if not merrors.is_already_exists(e):
                raise

    def join(self, n_nodes: int = 1, name_prefix: Optional[str] = None,
             capacity: Optional[Dict[str, str]] = None) -> "HollowCluster":
        """kubeadm join: each worker runs TLS BOOTSTRAP first — authenticate
        with the init-minted bootstrap token, post a node-client CSR, wait
        for the approve/sign controllers to issue a CA-signed X.509
        identity (phases/kubelet TLS bootstrap) — then registers against
        the control plane over the public URL. Issued credentials land in
        `self.node_credentials[name]` = {key, cert, ca} PEM bytes. Each
        join batch gets a unique default prefix so repeated joins ADD
        nodes instead of re-registering the previous batch's names."""
        from kubernetes_tpu.controllers.certificates import (
            BOOTSTRAP_GROUP, collect_node_identity, post_node_csr)

        if self.gateway is None:
            raise RuntimeError("cluster is not up")
        if name_prefix is None:
            name_prefix = f"joined-node-b{len(self._joined)}"
        # TLS bootstrap requires the approve/sign controllers; a manager
        # configured without them (custom controller subsets are a
        # supported topology) joins token-only, as before
        roster = set(self.manager.controllers) if self.manager else set()
        if {"csrsigning", "csrapproving"} <= roster:
            join_client = Client.http(self.gateway.url,
                                      token=self.bootstrap_token)
            tid = self.bootstrap_token.partition(".")[0]
            # post every CSR first, THEN collect: the approve/sign
            # round-trips overlap across the batch instead of serializing
            keys = {}
            for i in range(n_nodes):
                name = f"{name_prefix}-{i}"
                keys[name] = post_node_csr(
                    join_client, name,
                    username=f"system:bootstrap:{tid}",
                    groups=[BOOTSTRAP_GROUP])
            for name, key_pem in keys.items():
                self.node_credentials[name] = collect_node_identity(
                    join_client, name, key_pem)
        extra = HollowCluster(
            Client.http(self.gateway.url, token=self.admin_token), n_nodes,
            name_prefix=name_prefix,
            capacity=capacity or self.config.hollow_capacity).start()
        self._joined.append(extra)
        return extra

    # -- upgrade (cmd/kubeadm/app/phases/upgrade) --------------------------- #

    def _stored_cluster_config(self) -> Dict:
        """The kubeadm-config ConfigMap in kube-system — where kubeadm
        persists ClusterConfiguration (incl. kubernetesVersion)."""
        try:
            return self.client.configmaps.get("kubeadm-config", "kube-system")
        except Exception:  # noqa: BLE001 — absent on pre-upgrade clusters
            return {}

    def current_version(self) -> str:
        cm = self._stored_cluster_config()
        stored = (cm.get("data") or {}).get("kubernetesVersion", "")
        if stored:
            return stored
        return self.client.version().get("gitVersion", "")

    def upgrade_plan(self, target: str) -> Dict:
        """`kubeadm upgrade plan`: health + skew preflight, no mutation
        (phases/upgrade/plan.go: current/target versions, component health,
        per-node kubelet versions)."""
        cur = self.current_version()
        components = {
            "apiserver": self._healthz(),
            "scheduler": self.scheduler is not None,
            "controller-manager": self.manager is not None,
        }
        nodes = []
        for n in self.client.nodes.list("").get("items", []):
            ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                        for c in n.get("status", {}).get("conditions", []))
            nodes.append({
                "name": n["metadata"]["name"], "ready": ready,
                "kubeletVersion": n.get("status", {})
                .get("nodeInfo", {}).get("kubeletVersion", "")})
        ok, reason = _skew_allows(cur, target)
        return {"currentVersion": cur, "targetVersion": target,
                "components": components, "nodes": nodes,
                "canUpgrade": ok and all(components.values()),
                "reason": reason if not ok else "", }

    def _healthz(self) -> bool:
        try:
            return self.client.transport.request(
                "GET", "/healthz", {}, None) is not None
        except Exception:  # noqa: BLE001
            return False

    def upgrade_apply(self, target: str) -> Dict:
        """`kubeadm upgrade apply <target>`: preflight → ComponentConfig
        migration → control-plane restart (scheduler, then controller
        manager, against the same durable storage — no placement loss) →
        record the new version in kubeadm-config. Each phase is recorded
        the way kubeadm's phase runner reports them."""
        phases: List[str] = []
        plan = self.upgrade_plan(target)
        if not plan["canUpgrade"]:
            raise RuntimeError(
                f"preflight failed: {plan.get('reason') or plan['components']}")
        phases.append("preflight")

        # config migration: the scheduler config must still load under the
        # new version (phases/upgrade/postupgrade.go ComponentConfig check)
        if self.config.scheduler_config is not None:
            from kubernetes_tpu.sched.config import load_config

            load_config(self.config.scheduler_config)
        phases.append("config")

        # control plane, one component at a time; the apiserver (storage)
        # stays up throughout, as in a real rolling control-plane upgrade
        self.scheduler.stop()
        self.scheduler = SchedulerServer(
            self.client, scheduler_name=self.config.scheduler_name,
            leader_elect=self.config.leader_elect,
            config=self.config.scheduler_config).start()
        phases.append("control-plane/scheduler")
        self.manager.stop()
        self.manager = ControllerManager(
            self.client, controllers=self.config.controllers,
            leader_elect=self.config.leader_elect).start()
        phases.append("control-plane/controller-manager")

        # persist the new ClusterConfiguration version (uploadconfig phase)
        cm = self._stored_cluster_config()
        if cm:
            cm.setdefault("data", {})["kubernetesVersion"] = target
            self.client.configmaps.update(cm, "kube-system")
        else:
            self.client.configmaps.create(
                {"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "kubeadm-config",
                              "namespace": "kube-system"},
                 "data": {"kubernetesVersion": target}}, "kube-system")
        phases.append("upload-config")

        if not self._healthz():
            raise RuntimeError("post-upgrade health check failed")
        phases.append("health")
        return {"from": plan["currentVersion"], "to": target,
                "phases": phases}

    def down(self) -> None:
        for extra in reversed(self._joined):
            extra.stop()
        self._joined.clear()
        for c in (self.hollow, self.manager, self.scheduler):
            if c is not None:
                c.stop()
        if self.gateway is not None:
            self.gateway.stop()
        if self.api is not None:
            self.api.close()

    @property
    def url(self) -> str:
        return self.gateway.url if self.gateway else ""

    def __enter__(self) -> "Cluster":
        return self.up()

    def __exit__(self, *exc) -> None:
        self.down()


def cluster_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="cluster")
    p.add_argument("action", choices=["up"])
    p.add_argument("--port", type=int, default=6443)
    p.add_argument("--hollow-nodes", type=int, default=0)
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--scheduler-config", default=None,
                   help="KubeSchedulerConfiguration file (YAML/JSON)")
    args = p.parse_args(argv)
    cluster = Cluster(ClusterConfig(port=args.port,
                                    hollow_nodes=args.hollow_nodes,
                                    leader_elect=args.leader_elect,
                                    scheduler_config=args.scheduler_config)).up()
    print(f"control plane ready at {cluster.url} "
          f"({args.hollow_nodes} hollow nodes)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        cluster.down()
    return 0
