"""Scheduling-framework plugin interfaces — the tensor-native re-design of
framework/v1alpha1/interface.go.

The reference defines 11 extension points with per-(pod,node) Go callbacks
(QueueSort :201, PreFilter :210-221, Filter :242, PostFilter :263, Score
:273-282, Reserve :299, PreBind :308, PostBind :317, Unreserve :330, Permit
:339, Bind :352). On TPU the device-evaluated points (PreFilter/Filter/Score)
are *batched*: a plugin contributes a whole ``[P, N]`` mask or score tensor to
the fused cycle computation instead of being called P×N times. The host-side
lifecycle points (QueueSort, Reserve, Permit, PreBind, Bind, PostBind,
Unreserve) keep per-pod semantics — they guard the commit path, which is
host-side by nature (API writes, volume attach, external coordination).

Scores obey the reference's contract: each Score plugin produces values in
[MinNodeScore, MaxNodeScore] = [0, 100] (interface.go:86-90), multiplied by
the plugin's weight and summed (framework.go:391-… RunScorePlugins).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, NamedTuple, Optional, Protocol, runtime_checkable

from ..api.types import Pod

MAX_NODE_SCORE = 100  # interface.go:87
MIN_NODE_SCORE = 0    # interface.go:90


class Code(enum.IntEnum):
    """Status codes (interface.go:53-79)."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


@dataclass(frozen=True)
class Status:
    """interface.go:97-… Status. None is treated as Success everywhere, same
    as the reference's nil-status convention."""

    code: Code = Code.SUCCESS
    message: str = ""

    @property
    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    @property
    def is_unschedulable(self) -> bool:
        return self.code in (Code.UNSCHEDULABLE, Code.UNSCHEDULABLE_AND_UNRESOLVABLE)


SUCCESS = Status()


class CycleState:
    """Per-scheduling-cycle key-value scratchpad (cycle_state.go). Plugins
    stash cross-extension-point data here; `clone()` supports the preemption
    what-if path the same way the reference's CycleState.Clone does."""

    def __init__(self) -> None:
        self._data: Dict[str, Any] = {}

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def read(self, key: str) -> Any:
        if key not in self._data:
            raise KeyError(f"no cycle-state entry for {key!r}")
        return self._data[key]

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        c._data = dict(self._data)
        return c


class TensorContext(NamedTuple):
    """What a device-evaluated plugin sees: the encoded cluster + the per-cycle
    precompute. All fields are device arrays/pytrees; plugin tensor hooks run
    under jit inside the fused cycle computation. `components` carries the
    per-predicate mask decomposition computed once and shared by every in-tree
    filter plugin (XLA CSE makes re-derivation free, but sharing keeps the
    trace small)."""

    tables: Any           # state.arrays.ClusterTables
    cyc: Any              # ops.lattice.CycleArrays
    pending: Any          # state.arrays.PodArrays
    components: Any = None  # ops.assign.MaskComponents


class Plugin:
    """interface.go:165. `name` doubles as the registry key."""

    name: str = "Plugin"


@runtime_checkable
class QueueSortPlugin(Protocol):
    """interface.go:201. less(a, b) orders the active queue."""

    def less(self, a: "QueuedPodInfo", b: "QueuedPodInfo") -> bool: ...


@dataclass(frozen=True)
class QueuedPodInfo:
    """The comparator's view of a queued pod (queue.PodInfo analog)."""

    pod: Pod
    timestamp: float = 0.0


class PreFilterPlugin(Plugin):
    """interface.go:210-221. Batched: contribute per-cycle precompute into
    CycleState before the device dispatch (GetPredicateMetadata analog)."""

    def pre_filter(self, state: CycleState, pods: list) -> Optional[Status]:
        return None


class FilterPlugin(Plugin):
    """interface.go:242. Batched: return a [P, N] bool mask (True = feasible).
    Runs under jit; must be traceable jax code over the TensorContext."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    """interface.go:263. Informational pass over the filter outcome (receives
    the combined [P, N] mask on host)."""

    def post_filter(self, state: CycleState, pods: list, mask) -> Optional[Status]:
        return None


class ScorePlugin(Plugin):
    """interface.go:273-282. Batched: return a [P, N] f32 score in [0, 100]
    (already normalized — the NormalizeScore extension folds into this hook)."""

    weight: int = 1

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        raise NotImplementedError


class ReservePlugin(Plugin):
    """interface.go:299. Host-side, at assume time."""

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        return None


class UnreservePlugin(Plugin):
    """interface.go:330. Host-side rollback; must be idempotent."""

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        return None


class PermitPlugin(Plugin):
    """interface.go:339. Return SUCCESS, UNSCHEDULABLE (reject), or WAIT with
    a timeout (waiting_pods_map analog)."""

    def permit(self, state: CycleState, pod: Pod, node_name: str
               ) -> tuple[Optional[Status], float]:
        """Returns (status, timeout_seconds); timeout only meaningful for WAIT."""
        return None, 0.0


class PreBindPlugin(Plugin):
    """interface.go:308."""

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        return None


class BindPlugin(Plugin):
    """interface.go:352. Return SKIP to pass to the next bind plugin."""

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Optional[Status]:
        return None


class PostBindPlugin(Plugin):
    """interface.go:317. Informational."""

    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        return None
