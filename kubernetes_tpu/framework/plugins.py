"""In-tree framework plugins — the tensor re-expression of
pkg/scheduler/framework/plugins/* wrapping the lattice ops.

Each filter plugin selects its per-predicate component from the shared
MaskComponents decomposition (computed once per fused cycle); each score
plugin returns a 0..100-normalized [P, N] tensor. Plugin names match the
reference's registry keys (framework/plugins/default_registry.go:57) so
Plugins configs written for the reference map 1:1.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.assign import mask_components
from ..ops.fit import resource_scores_row
from ..ops.interpod import soft_affinity_row
from ..ops.lattice import build_cycle
from ..ops.scores import (
    even_spread_soft_row,
    image_locality_static,
    selector_spread_row,
)
from .interface import (
    CycleState,
    FilterPlugin,
    Plugin,
    ScorePlugin,
    TensorContext,
)
from .runtime import Framework, Plugins, PluginSet, Registry


def build_context(tables, existing, pending, uk, ev, D) -> TensorContext:
    """Assemble the TensorContext for one fused cycle (PreFilter device half:
    build_cycle = GetPredicateMetadata analog, metadata.go:334)."""
    cyc = build_cycle(tables, existing, uk, ev, D)
    ctx = TensorContext(tables=tables, cyc=cyc, pending=pending)
    comp = mask_components(tables, cyc, pending)
    return ctx._replace(components=comp)


# --------------------------------------------------------------------------- #
# Filter plugins (framework/plugins/<dir>; predicates.go semantics)
# --------------------------------------------------------------------------- #


class NodeResourcesFit(FilterPlugin):
    """noderesources/fit.go — PodFitsResources (predicates.go:789)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.fit


class NodeAffinity(FilterPlugin):
    """nodeaffinity/ — PodMatchNodeSelector (predicates.go:914): spec.nodeSelector
    ∧ required node affinity."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.node_match


class NodeName(FilterPlugin):
    """nodename/ — PodFitsHost (predicates.go:926)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.host


class NodePorts(FilterPlugin):
    """nodeports/ — PodFitsHostPorts (predicates.go:1104)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.ports


class TaintToleration(FilterPlugin, ScorePlugin):
    """tainttoleration/ — PodToleratesNodeTaints (predicates.go:1543) filter +
    PreferNoSchedule-counting score (taint_toleration.go)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.taints

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        return ctx.cyc.static.taint_score[ctx.pending.cls]


class NodeUnschedulable(FilterPlugin):
    """nodeunschedulable/ — CheckNodeUnschedulable (predicates.go:1522).
    Evaluated jointly with taints in the lattice (spec.unschedulable is the
    synthetic node.kubernetes.io/unschedulable taint); the shared component
    keeps both names live for config parity."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.taints


class VolumeRestrictions(FilterPlugin):
    """volumerestrictions/ — NoDiskConflict (predicates.go:156-221)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.volumes


class NodeVolumeLimits(FilterPlugin):
    """nodevolumelimits/ — the max-volume-count family
    (csi_volume_predicate.go:89; shares the fused volumes component with
    VolumeRestrictions — both are exact subsets of it)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.volumes


class InterPodAffinity(FilterPlugin, ScorePlugin):
    """interpodaffinity/ — MatchInterPodAffinity (predicates.go:1212) filter +
    soft (anti)affinity score (interpod_affinity.go:119-215)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.affinity & ctx.components.anti

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables, cyc = ctx.tables, ctx.cyc
        D = cyc.ELD.shape[2] - 1
        return jax.vmap(
            lambda c: soft_affinity_row(
                c, tables.classes, tables.terms, cyc.CNT, tables.nodes, D,
                TM=cyc.TM, WSYM=cyc.WSYM)
        )(ctx.pending.cls)


class PodTopologySpread(FilterPlugin, ScorePlugin):
    """podtopologyspread/ — EvenPodsSpreadPredicate (predicates.go:1643)
    filter + the ScheduleAnyway score (even_pods_spread.go:106-227)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.spread

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables, cyc = ctx.tables, ctx.cyc
        D = cyc.ELD.shape[2] - 1
        return jax.vmap(
            lambda c: even_spread_soft_row(
                c, tables.classes, tables.terms, cyc.CNT, tables.nodes,
                cyc.static.node_match[c], D)
        )(ctx.pending.cls)


class SelectorSpread(ScorePlugin):
    """defaultpodtopologyspread/ — SelectorSpread across hosts and zones
    (priorities/selector_spreading.go:62-165; Pod.spread_selectors carries the
    Service/RC/RS/StatefulSet owner selectors the reference resolves via
    listers)."""

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables, cyc = ctx.tables, ctx.cyc
        D = cyc.ELD.shape[2] - 1
        return jax.vmap(
            lambda c: selector_spread_row(
                c, tables.classes, cyc.CNT, tables.nodes, tables.zone_keys, D)
        )(ctx.pending.cls)


class ImageLocality(ScorePlugin):
    """imagelocality/ — spread-scaled image-size score
    (priorities/image_locality.go:39-92)."""

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        return ctx.cyc.static.img_score[ctx.pending.cls]


class NodeLabel(ScorePlugin):
    """nodelabel/ — presence/absence label preferences
    (priorities/node_label.go:46-71). Config: {"present": [...keys],
    "absent": [...keys]}; score = 100 × hits / #prefs."""

    def __init__(self, present=(), absent=()):
        self.present = tuple(present)
        self.absent = tuple(absent)

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        nodes = ctx.tables.nodes
        P = ctx.pending.valid.shape[0]
        N = nodes.valid.shape[0]
        prefs = len(self.present) + len(self.absent)
        if prefs == 0:
            return jnp.zeros((P, N), jnp.float32)
        # label-key ids resolved host-side by the config wiring
        # (SchedulerServer interns self.present/self.absent into
        # _present_ids/_absent_ids). A 'present' key missing from the vocab
        # can match no node; an 'absent' key missing from the vocab is
        # absent from every node — both handled without touching the -1
        # padding in label_keys.
        hits = jnp.zeros((N,), jnp.float32)
        for kid in getattr(self, "_present_ids", ()):
            if kid >= 0:
                hits = hits + (nodes.label_keys == kid).any(-1)
        for kid in getattr(self, "_absent_ids", ()):
            if kid >= 0:
                hits = hits + ~((nodes.label_keys == kid).any(-1))
            else:
                hits = hits + 1.0
        score = 100.0 * hits / prefs
        return jnp.broadcast_to(score[None, :], (P, N))


class RequestedToCapacityRatio(ScorePlugin):
    """requestedtocapacityratio/ — broken-linear utilization shape
    (priorities/requested_to_capacity_ratio.go:30-146). Config: shape points
    [(utilization%, score)], default [(0,100),(100,0)] = least-utilized."""

    def __init__(self, shape=((0, 100), (100, 0))):
        # accept both the reference arg format [{"utilization": u, "score" : s}]
        # and plain (u, s) pairs
        pts = []
        for p in shape:
            if isinstance(p, dict):
                pts.append((float(p["utilization"]), float(p["score"])))
            else:
                pts.append((float(p[0]), float(p[1])))
        self.shape = tuple(pts)

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables = ctx.tables

        xs = jnp.array([p[0] for p in self.shape], jnp.float32)
        ys = jnp.array([p[1] for p in self.shape], jnp.float32)

        def row(c):
            req_vec = tables.reqs.vec[tables.classes.rid[c]]
            total = tables.nodes.used + req_vec[None, :]
            cap = tables.nodes.alloc

            def util(t, cp):
                return jnp.where(
                    cp > 0,
                    100.0 * t.astype(jnp.float32)
                    / jnp.maximum(cp.astype(jnp.float32), 1.0),
                    0.0)

            def eval_shape(u):
                # buildBrokenLinearFunction: clamp below/above, interpolate
                u = jnp.clip(u, xs[0], xs[-1])
                return jnp.interp(u, xs, ys)

            s_cpu = eval_shape(util(total[:, 0], cap[:, 0]))
            s_mem = eval_shape(util(total[:, 1], cap[:, 1]))
            return (s_cpu + s_mem) / 2.0

        return jax.vmap(row)(ctx.pending.cls)


class ResourceLimits(ScorePlugin):
    """noderesources/resource_limits.go — tie-break score 1 when the node can
    satisfy the pod's cpu or memory LIMITS, else 0 (feature-gated off by
    default in the reference, kube_features.go ResourceLimitsPriorityFunction)."""

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables = ctx.tables
        classes = tables.classes

        def row(c):
            lim = classes.lim_rid[c]
            vec = tables.reqs.vec[jnp.maximum(lim, 0)]
            cap = tables.nodes.alloc
            cpu_ok = (vec[0] > 0) & (cap[:, 0] > 0) & (vec[0] <= cap[:, 0])
            mem_ok = (vec[1] > 0) & (cap[:, 1] > 0) & (vec[1] <= cap[:, 1])
            return jnp.where((lim >= 0) & (cpu_ok | mem_ok), 1.0, 0.0)

        return jax.vmap(row)(ctx.pending.cls)


# --------------------------------------------------------------------------- #
# Score plugins
# --------------------------------------------------------------------------- #


class _ResourceScoreBase(ScorePlugin):
    _index = 0  # 0 = least, 1 = balanced, 2 = most

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables = ctx.tables

        def row(c):
            req_vec = tables.reqs.vec[tables.classes.rid[c]]
            return resource_scores_row(req_vec, tables.nodes.used, tables.nodes.alloc)

        triple = jax.vmap(row)(ctx.pending.cls)
        return triple[self._index]


class NodeResourcesLeastAllocated(_ResourceScoreBase):
    """noderesources/least_allocated.go — spread by free capacity."""

    _index = 0


class NodeResourcesBalancedAllocation(_ResourceScoreBase):
    """noderesources/balanced_allocation.go — minimize cpu/mem fraction skew."""

    _index = 1


class NodeResourcesMostAllocated(_ResourceScoreBase):
    """noderesources/most_allocated.go — bin packing: (total/cap)×100 averaged
    over cpu+memory (most_requested.go:52-70); shares resource_scores_row with
    least/balanced so the formula lives once."""

    _index = 2


class NodePreferAvoidPods(ScorePlugin):
    """nodepreferavoidpods/ — nodes annotated avoid-pods score 0, others 100
    (node_prefer_avoid_pods.go). The annotation rides NodeArrays.avoid
    (encoded from scheduler.alpha.kubernetes.io/preferAvoidPods).
    Deviation: the reference applies the avoidance only to pods controlled
    by an RC/RS (checks the controllerRef kind); here every pod avoids the
    node — the annotation's operational intent (drain-ish bias) at class
    granularity."""

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        avoid = ctx.tables.nodes.avoid
        N = ctx.tables.nodes.valid.shape[0]
        P = ctx.pending.valid.shape[0]
        return jnp.broadcast_to(
            jnp.where(avoid[None, :], 0.0, 100.0), (P, N)).astype(jnp.float32)


class NodeAffinityScore(ScorePlugin):
    """nodeaffinity preferred terms score (priorities/node_affinity.go:34)."""

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        return ctx.cyc.static.pref_score[ctx.pending.cls]


# --------------------------------------------------------------------------- #
# registry + defaults (default_registry.go:57 NewDefaultRegistry)
# --------------------------------------------------------------------------- #


# score plugins whose semantics are compiled INTO the fused engines via
# EngineConfig weights (ops/lattice.py); anything else configured at the
# score point reaches the fused path as a per-class bias matrix
# (extra_score_plugins → sched/cycle.py)
FUSED_SCORE_PLUGINS = frozenset({
    "NodeResourcesLeastAllocated", "NodeResourcesBalancedAllocation",
    "NodeResourcesMostAllocated", "NodeAffinityScore", "TaintToleration",
    "InterPodAffinity", "PodTopologySpread", "SelectorSpread", "ImageLocality",
    # registry alias for SelectorSpread (default_registry.go keeps both
    # names); it must not leak into the class-pure extras path — its score
    # depends on in-cycle placements
    "DefaultPodTopologySpread",
})


class Coscheduling(Plugin):
    """Gang scheduling on the Permit machinery — the host per-pod analog of
    the device gang engine (ops/gang.py). Semantics follow the out-of-tree
    sig-scheduling coscheduling plugin (the reference ships none in-tree:
    the Permit wait/allow surface at framework/v1alpha1/interface.go:339 +
    waiting_pods_map.go IS its extension hook for exactly this):

      * Reserve tracks a group's assumed members;
      * Permit WAITs each member (with `timeout`) until the group's
        minMember count is reserved, then the arriving member ALLOWs every
        waiting sibling (allow_waiting_pod) and proceeds itself;
      * the waiting-map timeout rejecting a parked member unreserves it —
        a group that never fills releases everything it held.

    Wiring: the Scheduler auto-wires `on_release` (its complete_waiting) and
    `bound_count` (its cache's group_bound_count) when this plugin is in the
    permit set — see Scheduler.__init__; tests exercising the framework
    standalone can leave both unset and quorum falls back to the plugin's
    own reservation ledger."""

    name = "Coscheduling"

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout
        self.handle = None        # Framework runtime (allow_waiting_pod)
        self.on_release = None    # Scheduler.complete_waiting
        self.groups: dict = {}    # group key → authoritative minMember
        self.bound_count = None   # callable: group key → assumed+bound members
        self._reserved: dict = {}  # group key → in-flight reserved pod keys

    def register_group(self, key: str, min_member: int) -> None:
        """PodGroup object registration (overrides pod-carried hints)."""
        self.groups[key] = int(min_member)

    def _min_member(self, gk: str, pod) -> int:
        return self.groups.get(gk) or max(pod.min_member, 1)

    def reserve(self, state, pod, node_name):
        gk = pod.group_key
        if gk:
            self._reserved.setdefault(gk, set()).add(pod.key)
        return None

    def unreserve(self, state, pod, node_name):
        gk = pod.group_key
        if gk:
            self._reserved.get(gk, set()).discard(pod.key)

    def permit(self, state, pod, node_name):
        from .interface import Code, Status

        gk = pod.group_key
        if not gk:
            return None, 0.0
        # quorum: members assumed in the cache (covers every reserved member
        # — assume precedes Reserve — PLUS members bound in earlier cycles,
        # and self-heals when group pods are deleted). The plugin's own
        # ledger is the fallback for cache-less standalone use.
        if self.bound_count is not None:
            have = int(self.bound_count(gk))
        else:
            have = len(self._reserved.get(gk, ()))
        if have >= self._min_member(gk, pod):
            # quorum reached: release every waiting sibling, admit this one,
            # and retire the group's in-flight ledger (released members are
            # bound from here on — bound_count keeps counting them)
            waiting = [k for k in self._reserved.pop(gk, ()) if k != pod.key]
            if self.handle is not None:
                for key in waiting:
                    if self.handle.allow_waiting_pod(key, self.name) and \
                            self.on_release is not None:
                        self.on_release(key)
            return None, 0.0
        return Status(Code.WAIT, f"gang {gk}: {have}/"
                      f"{self._min_member(gk, pod)} members reserved"), \
            self.timeout


def extra_score_plugins(framework) -> tuple:
    """(plugin, weight) pairs for configured score plugins OUTSIDE the fused
    set — NodeLabel, RequestedToCapacityRatio, ResourceLimits,
    NodePreferAvoidPods, or any custom registration. These are class-pure
    (their scores depend only on (class, node), not on in-cycle placement),
    so the fused dispatch evaluates them once per cycle as a [SC, N] bias
    added to the static score lattice."""
    if framework is None:
        return ()
    return tuple(
        (pl, float(getattr(pl, "weight", 1)))
        for pl in framework.score_plugins
        if getattr(pl, "name", type(pl).__name__) not in FUSED_SCORE_PLUGINS
    )


def _make_node_label(cfg: dict) -> "NodeLabel":
    """NodeLabel needs vocab ids for its configured label keys; the config
    loader resolves them (present_ids/absent_ids). String keys are kept for
    introspection."""
    p = NodeLabel(present=cfg.get("present", ()), absent=cfg.get("absent", ()))
    p._present_ids = tuple(cfg.get("present_ids", ()))
    p._absent_ids = tuple(cfg.get("absent_ids", ()))
    return p


def default_registry() -> Registry:
    return {
        "NodeResourcesFit": lambda cfg: NodeResourcesFit(),
        "NodeAffinity": lambda cfg: NodeAffinity(),
        "NodeName": lambda cfg: NodeName(),
        "NodePorts": lambda cfg: NodePorts(),
        "NodeUnschedulable": lambda cfg: NodeUnschedulable(),
        "TaintToleration": lambda cfg: TaintToleration(),
        "InterPodAffinity": lambda cfg: InterPodAffinity(),
        "PodTopologySpread": lambda cfg: PodTopologySpread(),
        "NodeResourcesLeastAllocated": lambda cfg: NodeResourcesLeastAllocated(),
        "NodeResourcesBalancedAllocation": lambda cfg: NodeResourcesBalancedAllocation(),
        "NodeResourcesMostAllocated": lambda cfg: NodeResourcesMostAllocated(),
        "NodePreferAvoidPods": lambda cfg: NodePreferAvoidPods(),
        "NodeAffinityScore": lambda cfg: NodeAffinityScore(),
        "VolumeRestrictions": lambda cfg: VolumeRestrictions(),
        "NodeVolumeLimits": lambda cfg: NodeVolumeLimits(),
        "SelectorSpread": lambda cfg: SelectorSpread(),
        "DefaultPodTopologySpread": lambda cfg: SelectorSpread(),
        "ImageLocality": lambda cfg: ImageLocality(),
        "NodeLabel": lambda cfg: _make_node_label(cfg or {}),
        "RequestedToCapacityRatio": lambda cfg: RequestedToCapacityRatio(
            shape=(cfg or {}).get("shape", ((0, 100), (100, 0)))),
        "NodeResourcesResourceLimits": lambda cfg: ResourceLimits(),
        "Coscheduling": lambda cfg: Coscheduling(
            timeout=float((cfg or {}).get("permitWaitingTimeSeconds", 30.0))),
    }


def default_plugins() -> Plugins:
    """The default provider's plugin set (algorithmprovider/defaults +
    default_registry.go ConfigProducer mapping)."""
    return Plugins(
        filter=PluginSet(enabled=[
            "NodeUnschedulable", "NodeName", "NodePorts", "NodeAffinity",
            "NodeResourcesFit", "TaintToleration", "InterPodAffinity",
            "PodTopologySpread", "VolumeRestrictions", "NodeVolumeLimits",
        ]),
        score=PluginSet(enabled=[
            "NodeResourcesLeastAllocated", "NodeResourcesBalancedAllocation",
            "NodeAffinityScore", "TaintToleration", "InterPodAffinity",
            "PodTopologySpread", "SelectorSpread", "ImageLocality",
        ]),
    )


def default_framework(
    plugins: Optional[Plugins] = None,
    plugin_config: Optional[dict] = None,
    score_weights: Optional[dict] = None,
) -> Framework:
    return Framework(
        registry=default_registry(),
        plugins=plugins or default_plugins(),
        plugin_config=plugin_config,
        score_weights=score_weights,
    )
