"""In-tree framework plugins — the tensor re-expression of
pkg/scheduler/framework/plugins/* wrapping the lattice ops.

Each filter plugin selects its per-predicate component from the shared
MaskComponents decomposition (computed once per fused cycle); each score
plugin returns a 0..100-normalized [P, N] tensor. Plugin names match the
reference's registry keys (framework/plugins/default_registry.go:57) so
Plugins configs written for the reference map 1:1.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.assign import mask_components
from ..ops.fit import resource_scores_row
from ..ops.interpod import soft_affinity_row
from ..ops.lattice import build_cycle
from .interface import (
    CycleState,
    FilterPlugin,
    Plugin,
    ScorePlugin,
    TensorContext,
)
from .runtime import Framework, Plugins, PluginSet, Registry


def build_context(tables, existing, pending, uk, ev, D) -> TensorContext:
    """Assemble the TensorContext for one fused cycle (PreFilter device half:
    build_cycle = GetPredicateMetadata analog, metadata.go:334)."""
    cyc = build_cycle(tables, existing, uk, ev, D)
    ctx = TensorContext(tables=tables, cyc=cyc, pending=pending)
    comp = mask_components(tables, cyc, pending)
    return ctx._replace(components=comp)


# --------------------------------------------------------------------------- #
# Filter plugins (framework/plugins/<dir>; predicates.go semantics)
# --------------------------------------------------------------------------- #


class NodeResourcesFit(FilterPlugin):
    """noderesources/fit.go — PodFitsResources (predicates.go:789)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.fit


class NodeAffinity(FilterPlugin):
    """nodeaffinity/ — PodMatchNodeSelector (predicates.go:914): spec.nodeSelector
    ∧ required node affinity."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.node_match


class NodeName(FilterPlugin):
    """nodename/ — PodFitsHost (predicates.go:926)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.host


class NodePorts(FilterPlugin):
    """nodeports/ — PodFitsHostPorts (predicates.go:1104)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.ports


class TaintToleration(FilterPlugin, ScorePlugin):
    """tainttoleration/ — PodToleratesNodeTaints (predicates.go:1543) filter +
    PreferNoSchedule-counting score (taint_toleration.go)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.taints

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        return ctx.cyc.static.taint_score[ctx.pending.cls]


class NodeUnschedulable(FilterPlugin):
    """nodeunschedulable/ — CheckNodeUnschedulable (predicates.go:1522).
    Evaluated jointly with taints in the lattice (spec.unschedulable is the
    synthetic node.kubernetes.io/unschedulable taint); the shared component
    keeps both names live for config parity."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.taints


class InterPodAffinity(FilterPlugin, ScorePlugin):
    """interpodaffinity/ — MatchInterPodAffinity (predicates.go:1212) filter +
    soft (anti)affinity score (interpod_affinity.go:119-215)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.affinity & ctx.components.anti

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables, cyc = ctx.tables, ctx.cyc
        D = cyc.ELD.shape[2] - 1
        return jax.vmap(
            lambda c: soft_affinity_row(
                c, tables.classes, tables.terms, cyc.CNT, tables.nodes, D)
        )(ctx.pending.cls)


class PodTopologySpread(FilterPlugin):
    """podtopologyspread/ — EvenPodsSpreadPredicate (predicates.go:1643)."""

    def filter_mask(self, state: CycleState, ctx: TensorContext):
        return ctx.components.spread


# --------------------------------------------------------------------------- #
# Score plugins
# --------------------------------------------------------------------------- #


class _ResourceScoreBase(ScorePlugin):
    _index = 0  # 0 = least, 1 = balanced

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables = ctx.tables

        def row(c):
            req_vec = tables.reqs.vec[tables.classes.rid[c]]
            return resource_scores_row(req_vec, tables.nodes.used, tables.nodes.alloc)

        pair = jax.vmap(row)(ctx.pending.cls)
        return pair[self._index]


class NodeResourcesLeastAllocated(_ResourceScoreBase):
    """noderesources/least_allocated.go — spread by free capacity."""

    _index = 0


class NodeResourcesBalancedAllocation(_ResourceScoreBase):
    """noderesources/balanced_allocation.go — minimize cpu/mem fraction skew."""

    _index = 1


class NodeResourcesMostAllocated(ScorePlugin):
    """noderesources/most_allocated.go — bin-packing: (total/cap)×100 averaged
    over cpu+memory (most_requested.go:60 semantics)."""

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        tables = ctx.tables

        def row(c):
            req_vec = tables.reqs.vec[tables.classes.rid[c]]
            total = tables.nodes.used + req_vec[None, :]
            cap = tables.nodes.alloc
            def frac(t, cp):
                f = t.astype(jnp.float32) / jnp.maximum(cp.astype(jnp.float32), 1.0)
                return jnp.where((cp > 0) & (t <= cp), f * 100.0, 0.0)
            return (frac(total[:, 0], cap[:, 0]) + frac(total[:, 1], cap[:, 1])) / 2.0

        return jax.vmap(row)(ctx.pending.cls)


class NodePreferAvoidPods(ScorePlugin):
    """nodepreferavoidpods/ — nodes annotated avoid-pods score 0, others 100
    (node_prefer_avoid_pods.go). The annotation rides NodeArrays.avoid."""

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        avoid = getattr(ctx.tables.nodes, "avoid", None)
        N = ctx.tables.nodes.valid.shape[0]
        P = ctx.pending.valid.shape[0]
        if avoid is None:
            return jnp.full((P, N), 100.0, jnp.float32)
        return jnp.broadcast_to(
            jnp.where(avoid[None, :], 0.0, 100.0), (P, N)).astype(jnp.float32)


class NodeAffinityScore(ScorePlugin):
    """nodeaffinity preferred terms score (priorities/node_affinity.go:34)."""

    def score_matrix(self, state: CycleState, ctx: TensorContext):
        return ctx.cyc.static.pref_score[ctx.pending.cls]


# --------------------------------------------------------------------------- #
# registry + defaults (default_registry.go:57 NewDefaultRegistry)
# --------------------------------------------------------------------------- #


def default_registry() -> Registry:
    return {
        "NodeResourcesFit": lambda cfg: NodeResourcesFit(),
        "NodeAffinity": lambda cfg: NodeAffinity(),
        "NodeName": lambda cfg: NodeName(),
        "NodePorts": lambda cfg: NodePorts(),
        "NodeUnschedulable": lambda cfg: NodeUnschedulable(),
        "TaintToleration": lambda cfg: TaintToleration(),
        "InterPodAffinity": lambda cfg: InterPodAffinity(),
        "PodTopologySpread": lambda cfg: PodTopologySpread(),
        "NodeResourcesLeastAllocated": lambda cfg: NodeResourcesLeastAllocated(),
        "NodeResourcesBalancedAllocation": lambda cfg: NodeResourcesBalancedAllocation(),
        "NodeResourcesMostAllocated": lambda cfg: NodeResourcesMostAllocated(),
        "NodePreferAvoidPods": lambda cfg: NodePreferAvoidPods(),
        "NodeAffinityScore": lambda cfg: NodeAffinityScore(),
    }


def default_plugins() -> Plugins:
    """The default provider's plugin set (algorithmprovider/defaults +
    default_registry.go ConfigProducer mapping)."""
    return Plugins(
        filter=PluginSet(enabled=[
            "NodeUnschedulable", "NodeName", "NodePorts", "NodeAffinity",
            "NodeResourcesFit", "TaintToleration", "InterPodAffinity",
            "PodTopologySpread",
        ]),
        score=PluginSet(enabled=[
            "NodeResourcesLeastAllocated", "NodeResourcesBalancedAllocation",
            "NodeAffinityScore", "TaintToleration", "InterPodAffinity",
        ]),
    )


def default_framework(
    plugins: Optional[Plugins] = None,
    plugin_config: Optional[dict] = None,
    score_weights: Optional[dict] = None,
) -> Framework:
    return Framework(
        registry=default_registry(),
        plugins=plugins or default_plugins(),
        plugin_config=plugin_config,
        score_weights=score_weights,
    )
