"""Framework runner — framework/v1alpha1/framework.go re-designed for batched
device evaluation.

RunFilterPlugins (framework.go:339) becomes one jit-fused AND over every
enabled plugin's [P, N] mask; RunScorePlugins (:391 — parallel per plugin,
normalize, weight, sum) becomes one fused weighted sum of [P, N] score
tensors. The host lifecycle points (Reserve/Permit/PreBind/Bind/PostBind/
Unreserve, :299-563) run per pod on the commit path, including the
waiting-pods map with Permit timeouts (waiting_pods_map.go).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..api.types import Pod
from .interface import (
    BindPlugin,
    Code,
    CycleState,
    FilterPlugin,
    PermitPlugin,
    Plugin,
    PostBindPlugin,
    PostFilterPlugin,
    PreBindPlugin,
    PreFilterPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
    SUCCESS,
    TensorContext,
    UnreservePlugin,
)


@dataclass
class PluginSet:
    """apis/config Plugins entry: enabled plugin names (+ weight for Score)."""

    enabled: List[str] = field(default_factory=list)
    # filters default plugins during merge_plugins(); "*" drops all defaults.
    # On a hand-built Plugins it filters exact names from `enabled`.
    disabled: List[str] = field(default_factory=list)


@dataclass
class Plugins:
    """Which plugins run at each extension point (apis/config/types.go:160
    Plugins struct, one PluginSet per point)."""

    pre_filter: PluginSet = field(default_factory=PluginSet)
    filter: PluginSet = field(default_factory=PluginSet)
    post_filter: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    reserve: PluginSet = field(default_factory=PluginSet)
    permit: PluginSet = field(default_factory=PluginSet)
    pre_bind: PluginSet = field(default_factory=PluginSet)
    bind: PluginSet = field(default_factory=PluginSet)
    post_bind: PluginSet = field(default_factory=PluginSet)
    unreserve: PluginSet = field(default_factory=PluginSet)

    POINTS = ("pre_filter", "filter", "post_filter", "score", "reserve",
              "permit", "pre_bind", "bind", "post_bind", "unreserve")


def merge_plugins(defaults: Plugins, custom: Plugins) -> Plugins:
    """Reference profile merging (apis/config Plugins.Apply): per extension
    point, custom.disabled filters the defaults ("*" drops them all), then
    custom.enabled is appended in order."""
    out = Plugins()
    for point in Plugins.POINTS:
        d: PluginSet = getattr(defaults, point)
        c: PluginSet = getattr(custom, point)
        if "*" in c.disabled:
            base: List[str] = []
        else:
            base = [n for n in d.enabled if n not in set(c.disabled)]
        merged = base + [n for n in c.enabled if n not in base]
        setattr(out, point, PluginSet(enabled=merged))
    return out


# factory: (args: dict) -> Plugin instance
Registry = Dict[str, Callable[[dict], Plugin]]


@dataclass
class _WaitingPod:
    """waiting_pods_map.go WaitingPod: a pod parked by a Permit WAIT."""

    pod: Pod
    node_name: str
    state: CycleState
    deadline: float
    pending_plugins: set  # plugin names still to allow


class Framework:
    """framework.go:96 framework struct + NewFramework (:145)."""

    def __init__(
        self,
        registry: Registry,
        plugins: Plugins,
        plugin_config: Optional[Dict[str, dict]] = None,
        score_weights: Optional[Dict[str, int]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.registry = dict(registry)
        self.plugins_config = plugins
        self.clock = clock
        cfg = plugin_config or {}

        instances: Dict[str, Plugin] = {}

        def get(name: str) -> Plugin:
            if name not in instances:
                if name not in self.registry:
                    raise KeyError(f"plugin {name!r} is not registered")
                instances[name] = self.registry[name](cfg.get(name, {}))
                instances[name].name = name
            return instances[name]

        def pick(ps: PluginSet) -> List[Plugin]:
            # ps.disabled is resolved against defaults by merge_plugins();
            # here it still filters exact names so a hand-built Plugins
            # behaves as documented
            return [get(n) for n in ps.enabled if n not in set(ps.disabled)]

        self.pre_filter_plugins: List[PreFilterPlugin] = pick(plugins.pre_filter)
        self.filter_plugins: List[FilterPlugin] = pick(plugins.filter)
        self.post_filter_plugins: List[PostFilterPlugin] = pick(plugins.post_filter)
        self.score_plugins: List[ScorePlugin] = pick(plugins.score)
        self.reserve_plugins: List[ReservePlugin] = pick(plugins.reserve)
        self.permit_plugins: List[PermitPlugin] = pick(plugins.permit)
        self.pre_bind_plugins: List[PreBindPlugin] = pick(plugins.pre_bind)
        self.bind_plugins: List[BindPlugin] = pick(plugins.bind)
        self.post_bind_plugins: List[PostBindPlugin] = pick(plugins.post_bind)
        self.unreserve_plugins: List[UnreservePlugin] = pick(plugins.unreserve)

        for p in self.score_plugins:
            w = (score_weights or {}).get(p.name, getattr(p, "weight", 1))
            if w <= 0:
                raise ValueError(f"score plugin {p.name} has non-positive weight {w}")
            p.weight = w

        # plugins declaring a `handle` slot get the framework itself — the
        # FrameworkHandle injection (framework.go:145 NewFramework passes the
        # handle to every factory; Coscheduling uses it to allow waiters)
        for p in instances.values():
            if hasattr(p, "handle") and p.handle is None:
                p.handle = self

        self._waiting: Dict[str, _WaitingPod] = {}
        self._wmu = threading.Lock()

    # ------------------------------------------------------------------ #
    # device-evaluated points (run inside the fused jit computation)
    # ------------------------------------------------------------------ #

    def run_pre_filter_plugins(self, state: CycleState, pods: list) -> Optional[Status]:
        """framework.go:260 RunPreFilterPlugins — host-side per-cycle
        precompute; an error status aborts the cycle."""
        for p in self.pre_filter_plugins:
            st = p.pre_filter(state, pods)
            if st is not None and not st.is_success:
                return Status(st.code, f"prefilter plugin {p.name}: {st.message}")
        return None

    def run_filter_plugins(self, state: CycleState, ctx: TensorContext):
        """framework.go:339 RunFilterPlugins — AND of [P, N] masks. Must be
        called under jit (from the fused cycle fn)."""
        mask = None
        for p in self.filter_plugins:
            m = p.filter_mask(state, ctx)
            mask = m if mask is None else (mask & m)
        if mask is None:
            P = ctx.pending.valid.shape[0]
            N = ctx.tables.nodes.valid.shape[0]
            mask = jnp.ones((P, N), bool)
        return mask & ctx.pending.valid[:, None] & ctx.tables.nodes.valid[None, :]

    def run_score_plugins(self, state: CycleState, ctx: TensorContext):
        """framework.go:391 RunScorePlugins — Σ weight × normalized [P, N]."""
        P = ctx.pending.valid.shape[0]
        N = ctx.tables.nodes.valid.shape[0]
        total = jnp.zeros((P, N), jnp.float32)
        for p in self.score_plugins:
            total = total + p.weight * p.score_matrix(state, ctx).astype(jnp.float32)
        return total

    def run_post_filter_plugins(self, state: CycleState, pods: list, mask) -> Optional[Status]:
        for p in self.post_filter_plugins:
            st = p.post_filter(state, pods, mask)
            if st is not None and not st.is_success:
                return Status(st.code, f"postfilter plugin {p.name}: {st.message}")
        return None

    # ------------------------------------------------------------------ #
    # host lifecycle points (commit path)
    # ------------------------------------------------------------------ #

    def run_reserve_plugins(self, state: CycleState, pod: Pod, node: str) -> Optional[Status]:
        for p in self.reserve_plugins:
            st = p.reserve(state, pod, node)
            if st is not None and not st.is_success:
                return Status(Code.ERROR, f"reserve plugin {p.name}: {st.message}")
        return None

    def run_unreserve_plugins(self, state: CycleState, pod: Pod, node: str) -> None:
        for p in self.unreserve_plugins:
            p.unreserve(state, pod, node)

    def run_permit_plugins(self, state: CycleState, pod: Pod, node: str) -> Status:
        """framework.go:553 RunPermitPlugins: reject wins; any WAIT parks the
        pod in the waiting map with the max timeout."""
        pending: set = set()
        timeout = 0.0
        for p in self.permit_plugins:
            st, t = p.permit(state, pod, node)
            if st is None or st.is_success:
                continue
            if st.code == Code.WAIT:
                pending.add(p.name)
                timeout = max(timeout, t)
            else:
                return Status(Code.UNSCHEDULABLE,
                              f"pod rejected by permit plugin {p.name}: {st.message}")
        if pending:
            with self._wmu:
                self._waiting[pod.key] = _WaitingPod(
                    pod=pod, node_name=node, state=state,
                    deadline=self.clock() + timeout, pending_plugins=pending,
                )
            return Status(Code.WAIT, f"waiting on permit plugins {sorted(pending)}")
        return SUCCESS

    def run_pre_bind_plugins(self, state: CycleState, pod: Pod, node: str) -> Optional[Status]:
        for p in self.pre_bind_plugins:
            st = p.pre_bind(state, pod, node)
            if st is not None and not st.is_success:
                return Status(Code.ERROR, f"prebind plugin {p.name}: {st.message}")
        return None

    def run_bind_plugins(self, state: CycleState, pod: Pod, node: str) -> Status:
        """framework.go:487 RunBindPlugins: first non-SKIP result wins."""
        if not self.bind_plugins:
            return Status(Code.SKIP)
        for p in self.bind_plugins:
            st = p.bind(state, pod, node)
            if st is not None and st.code == Code.SKIP:
                continue
            if st is not None and not st.is_success:
                return Status(Code.ERROR, f"bind plugin {p.name}: {st.message}")
            return SUCCESS
        return Status(Code.SKIP)

    def run_post_bind_plugins(self, state: CycleState, pod: Pod, node: str) -> None:
        for p in self.post_bind_plugins:
            p.post_bind(state, pod, node)

    # ------------------------------------------------------------------ #
    # waiting pods (waiting_pods_map.go)
    # ------------------------------------------------------------------ #

    def waiting_pods(self) -> List[Pod]:
        with self._wmu:
            return [w.pod for w in self._waiting.values()]

    def allow_waiting_pod(self, key: str, plugin: str) -> bool:
        """A permit plugin allows the pod; when no plugins remain pending the
        pod is released (caller completes the bind). Returns released?"""
        with self._wmu:
            w = self._waiting.get(key)
            if w is None:
                return False
            w.pending_plugins.discard(plugin)
            if not w.pending_plugins:
                del self._waiting[key]
                return True
            return False

    def reject_waiting_pod(self, key: str) -> Optional[Pod]:
        with self._wmu:
            w = self._waiting.pop(key, None)
            return w.pod if w else None

    def pop_waiting(self, key: str) -> Optional[_WaitingPod]:
        with self._wmu:
            return self._waiting.pop(key, None)

    def expire_waiting(self, now: float) -> List[_WaitingPod]:
        """Timed-out waiting pods are rejected (waiting_pods_map timeout)."""
        out = []
        with self._wmu:
            for key in list(self._waiting):
                if now >= self._waiting[key].deadline:
                    out.append(self._waiting.pop(key))
        return out

    def has_filter_plugins(self) -> bool:
        return bool(self.filter_plugins)

    def has_score_plugins(self) -> bool:
        return bool(self.score_plugins)
