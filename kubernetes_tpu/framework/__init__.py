"""Scheduling-framework plugin runtime (framework/v1alpha1 re-designed for
batched device evaluation) + in-tree plugins + default registry."""

from .interface import (
    Code,
    CycleState,
    FilterPlugin,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
    PermitPlugin,
    Plugin,
    PostBindPlugin,
    PreBindPlugin,
    PreFilterPlugin,
    BindPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
    SUCCESS,
    TensorContext,
    UnreservePlugin,
)
from .plugins import (
    build_context,
    default_framework,
    default_plugins,
    default_registry,
)
from .runtime import Framework, PluginSet, Plugins, Registry, merge_plugins

__all__ = [
    "Code", "CycleState", "FilterPlugin", "MAX_NODE_SCORE", "MIN_NODE_SCORE",
    "PermitPlugin", "Plugin", "PostBindPlugin", "PreBindPlugin",
    "PreFilterPlugin", "BindPlugin", "ReservePlugin", "ScorePlugin", "Status",
    "SUCCESS", "TensorContext", "UnreservePlugin", "build_context",
    "default_framework", "default_plugins", "default_registry", "Framework",
    "PluginSet", "Plugins", "Registry", "merge_plugins",
]
