"""Kubelet volume manager — `pkg/kubelet/volumemanager/volume_manager.go`
reduced to its control contract:

  * WaitForAttachAndMount: a pod with attach-requiring volumes does not
    start containers until the attach/detach controller has marked every
    one of them attached to this node (node.status.volumesAttached);
  * volumesInUse: the kubelet REPORTS the volumes its pods hold
    (kubelet_node_status.go setNodeVolumesInUseStatus) — the controller
    reads that to defer detach until unmount (safe detach);
  * mount bookkeeping: mounted volumes release at pod teardown, which is
    what makes the in-use report shrink and the deferred detach proceed.

There is no real filesystem to mount (FakeCRI runtime) — "mounted" is the
bookkeeping state the control protocol needs, same stance as PARITY #9.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Set, Tuple

from kubernetes_tpu.volume.names import attachable_volume_ids

Obj = Dict


class VolumeManager:
    def __init__(self):
        self._mu = threading.Lock()
        self._mounted: Dict[str, List[str]] = {}  # pod uid → volume names
        # the latest view of node.status.volumesAttached, fed by the
        # kubelet's heartbeat read of its own Node object
        self._attached: Set[str] = set()

    def note_attached(self, node_status: Obj) -> None:
        with self._mu:
            self._attached = {
                v.get("name", "") for v in
                (node_status or {}).get("volumesAttached", []) or []}

    def wait_for_attach_and_mount(self, pod: Obj) -> Tuple[bool, List[str]]:
        """Can this pod's containers start? Returns (ok, missing)."""
        need = attachable_volume_ids(pod)
        if not need:
            return True, []
        with self._mu:
            missing = [v for v in need if v not in self._attached]
        return not missing, missing

    def mark_mounted(self, pod_uid: str, pod: Obj) -> None:
        vols = attachable_volume_ids(pod)
        if vols:
            with self._mu:
                self._mounted[pod_uid] = vols

    def unmount(self, pod_uid: str) -> None:
        with self._mu:
            self._mounted.pop(pod_uid, None)

    def in_use(self) -> List[str]:
        with self._mu:
            return sorted({v for vols in self._mounted.values()
                           for v in vols})
