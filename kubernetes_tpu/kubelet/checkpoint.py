"""Checkpoint manager: CRC-checksummed local state files.

Analog of `pkg/kubelet/checkpointmanager/checkpoint_manager.go` +
`checksum/checksum.go`: each checkpoint is JSON + a CRC of its payload;
corrupt files are detected and rejected on restore.
"""

from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import Any, Dict, List, Optional


class CorruptCheckpointError(Exception):
    pass


class CheckpointManager:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_")
        return os.path.join(self.directory, f"{safe}.json")

    def create_checkpoint(self, key: str, data: Any) -> None:
        payload = json.dumps(data, sort_keys=True, separators=(",", ":"))
        doc = {"data": payload, "checksum": zlib.crc32(payload.encode())}
        # atomic write (tempfile + rename), as the reference's file store does
        fd, tmp = tempfile.mkstemp(dir=self.directory)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self._path(key))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def get_checkpoint(self, key: str) -> Optional[Any]:
        try:
            with open(self._path(key)) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return None
        except json.JSONDecodeError as e:
            raise CorruptCheckpointError(str(e))
        payload = doc.get("data", "")
        if zlib.crc32(payload.encode()) != doc.get("checksum"):
            raise CorruptCheckpointError(f"checksum mismatch for {key}")
        return json.loads(payload)

    def remove_checkpoint(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def list_checkpoints(self) -> List[str]:
        return sorted(p[:-5] for p in os.listdir(self.directory)
                      if p.endswith(".json"))
