"""Fake CRI runtime: the container-runtime process boundary, in-process.

Analog of the CRI gRPC surface the kubelet drives
(`staging/src/k8s.io/cri-api/` RuntimeService) backed by the fake runtime
kubemark uses (`cmd/kubemark/hollow-node.go` wires kubelet to
`containertest.FakeRuntime`-family fakes). Sandboxes and containers are
state machines on the host clock; a policy decides whether containers run
forever (hollow service pods) or exit (job pods).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

SANDBOX_READY = "SANDBOX_READY"
SANDBOX_NOTREADY = "SANDBOX_NOTREADY"

CONTAINER_CREATED = "CONTAINER_CREATED"
CONTAINER_RUNNING = "CONTAINER_RUNNING"
CONTAINER_EXITED = "CONTAINER_EXITED"


@dataclass
class FakeContainer:
    id: str
    name: str
    image: str
    sandbox_id: str
    state: str = CONTAINER_CREATED
    exit_code: int = 0
    started_at: float = 0.0
    finished_at: float = 0.0
    # None = run forever; else exit with (code) after (seconds)
    exit_after: Optional[float] = None


@dataclass
class FakeSandbox:
    id: str
    pod_name: str
    pod_namespace: str
    pod_uid: str
    ip: str
    state: str = SANDBOX_READY
    containers: Dict[str, FakeContainer] = field(default_factory=dict)


class FakeCRI:
    """RuntimeService + ImageService double. Thread-safe; time-driven."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 ip_prefix: str = "10.88"):
        self._mu = threading.Lock()
        self.clock = clock
        self.sandboxes: Dict[str, FakeSandbox] = {}
        self.images: Dict[str, int] = {}
        self._ip_seq = 0
        self.ip_prefix = ip_prefix
        # policy hook: containers whose image matches return exit_after secs
        self.exit_policy: Callable[[str], Optional[float]] = lambda image: None
        # stats hook (ListContainerStats): image → (cpu milli, memory bytes);
        # the fake's stand-in for cadvisor-fed usage, overridable per test
        self.usage_policy: Callable[[str], tuple] = \
            lambda image: (100, 64 << 20)
        # probe hook: (image, kind) → bool; the fake's stand-in for
        # exec/http/tcp probe outcomes ("readiness" | "liveness")
        self.probe_policy: Callable[[str, str], bool] = \
            lambda image, kind: True
        # ImageService accounting (images dict holds name → sizeBytes):
        # size_policy sizes newly-pulled images; last-used times feed the
        # image GC manager's LRU ordering; imagefs capacity bounds usage
        self.size_policy: Callable[[str], int] = lambda image: 256 << 20
        self.image_last_used: Dict[str, float] = {}
        self.image_fs_capacity: int = 100 << 30

    # -- RuntimeService ----------------------------------------------------- #

    def run_pod_sandbox(self, pod_name: str, pod_namespace: str,
                        pod_uid: str) -> str:
        with self._mu:
            sid = f"sandbox-{uuid.uuid4().hex[:12]}"
            self._ip_seq += 1
            ip = f"{self.ip_prefix}.{(self._ip_seq >> 8) & 255}.{self._ip_seq & 255}"
            self.sandboxes[sid] = FakeSandbox(sid, pod_name, pod_namespace,
                                              pod_uid, ip)
            return sid

    def stop_pod_sandbox(self, sid: str) -> None:
        with self._mu:
            sb = self.sandboxes.get(sid)
            if sb is None:
                return
            sb.state = SANDBOX_NOTREADY
            now = self.clock()
            for c in sb.containers.values():
                if c.state == CONTAINER_RUNNING:
                    c.state = CONTAINER_EXITED
                    c.exit_code = 137  # SIGKILL, like a real stop
                    c.finished_at = now

    def remove_pod_sandbox(self, sid: str) -> None:
        with self._mu:
            self.sandboxes.pop(sid, None)

    def create_container(self, sid: str, name: str, image: str) -> str:
        with self._mu:
            sb = self.sandboxes[sid]
            cid = f"container-{uuid.uuid4().hex[:12]}"
            self._pull_locked(image)
            self.image_last_used[image] = self.clock()
            sb.containers[cid] = FakeContainer(
                cid, name, image, sid, exit_after=self.exit_policy(image))
            return cid

    def start_container(self, cid: str) -> None:
        with self._mu:
            c = self._container(cid)
            c.state = CONTAINER_RUNNING
            c.started_at = self.clock()

    def stop_container(self, cid: str, exit_code: int = 137) -> None:
        with self._mu:
            c = self._container(cid)
            if c.state == CONTAINER_RUNNING:
                c.state = CONTAINER_EXITED
                c.exit_code = exit_code
                c.finished_at = self.clock()

    def remove_container(self, cid: str) -> None:
        with self._mu:
            for sb in self.sandboxes.values():
                sb.containers.pop(cid, None)

    def _container(self, cid: str) -> FakeContainer:
        for sb in self.sandboxes.values():
            if cid in sb.containers:
                return sb.containers[cid]
        raise KeyError(cid)

    def container_status(self, cid: str) -> Optional[FakeContainer]:
        """Thread-safe snapshot read for status computation."""
        with self._mu:
            try:
                c = self._container(cid)
            except KeyError:
                return None
            return FakeContainer(c.id, c.name, c.image, c.sandbox_id, c.state,
                                 c.exit_code, c.started_at, c.finished_at,
                                 c.exit_after)

    def sandbox_for_pod(self, pod_uid: str) -> Optional[FakeSandbox]:
        with self._mu:
            for sb in self.sandboxes.values():
                if sb.pod_uid == pod_uid and sb.state == SANDBOX_READY:
                    return sb
            return None

    def probe(self, cid: str, kind: str) -> bool:
        """One probe attempt against a container (the prober's exec/http/tcp
        check collapsed to the policy hook). Non-running containers fail."""
        c = self.container_status(cid)
        if c is None or c.state != CONTAINER_RUNNING:
            return False
        return bool(self.probe_policy(c.image, kind))

    # -- ImageService (api.proto ImageService) ------------------------------ #

    def _pull_locked(self, image: str) -> None:
        if image not in self.images:
            self.images[image] = int(self.size_policy(image))

    def pull_image(self, image: str) -> None:
        """PullImage: materialize the image on the node's imagefs."""
        with self._mu:
            self._pull_locked(image)
            self.image_last_used[image] = self.clock()

    def list_images(self) -> List[dict]:
        """ListImages: name/size/lastUsed, plus whether any container
        (running or not) still references the image — GC exempts those
        (image_gc_manager.go detectImages imagesInUse)."""
        with self._mu:
            in_use = {c.image for sb in self.sandboxes.values()
                      for c in sb.containers.values()}
            return [{"name": name, "sizeBytes": size,
                     "lastUsed": self.image_last_used.get(name, 0.0),
                     "inUse": name in in_use}
                    for name, size in self.images.items()]

    def remove_image(self, image: str) -> None:
        with self._mu:
            self.images.pop(image, None)
            self.image_last_used.pop(image, None)

    def image_fs_info(self) -> dict:
        """ImageFsInfo: capacity/used bytes of the image filesystem — the
        signal both the image GC thresholds and the nodefs eviction signal
        read."""
        with self._mu:
            return {"capacityBytes": self.image_fs_capacity,
                    "usedBytes": sum(self.images.values())}

    def list_stats(self) -> List[dict]:
        """ListContainerStats (api.proto RuntimeService): per-running-container
        cpu/memory usage, synthesized by `usage_policy` — the source the
        kubelet's resource-metrics endpoint aggregates from."""
        out: List[dict] = []
        with self._mu:
            for sb in self.sandboxes.values():
                for c in sb.containers.values():
                    if c.state != CONTAINER_RUNNING:
                        continue
                    cpu, mem = self.usage_policy(c.image)
                    out.append({
                        "containerId": c.id, "name": c.name,
                        "podUid": sb.pod_uid, "podName": sb.pod_name,
                        "podNamespace": sb.pod_namespace,
                        "cpuMilli": int(cpu), "memoryBytes": int(mem),
                    })
        return out

    # -- the PLEG source: advance clocks, report states --------------------- #

    def tick(self) -> List[str]:
        """Advance container lifecycles; returns ids that changed state
        (what the real PLEG derives by relisting the runtime)."""
        changed: List[str] = []
        now = self.clock()
        with self._mu:
            for sb in self.sandboxes.values():
                for c in sb.containers.values():
                    if (c.state == CONTAINER_RUNNING
                            and c.exit_after is not None
                            and now - c.started_at >= c.exit_after):
                        c.state = CONTAINER_EXITED
                        c.exit_code = 0
                        c.finished_at = now
                        changed.append(c.id)
        return changed
