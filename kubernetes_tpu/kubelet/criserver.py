"""CRI as a real process boundary: RuntimeService/ImageService over a
Unix-domain socket.

The reference's kubelet↔runtime split is gRPC on a unix socket
(`staging/src/k8s.io/cri-api/pkg/apis/runtime/v1alpha2/api.proto` —
RuntimeService: RunPodSandbox/StopPodSandbox/RemovePodSandbox/
CreateContainer/StartContainer/StopContainer/RemoveContainer/
ContainerStatus/ListPodSandbox/ListContainerStats/Status/Version;
ImageService: ListImages/PullImage/...; wired in
`pkg/kubelet/remote/remote_runtime.go`). grpc/protoc codegen is not
available in this image, so the wire here is length-prefixed JSON frames
(4-byte big-endian size + UTF-8 body) carrying `{"method", "params"}` →
`{"result"}` | `{"error"}` — the same verb set, the same process boundary,
a simpler codec.

Three pieces:

* `CRIServer` — hosts any runtime object with the `FakeCRI` method surface
  behind the socket (thread-per-connection accept loop).
* `RemoteCRI` — the kubelet-side client (`remote_runtime.go` analog): one
  persistent connection, reconnect-once-per-call on failure, raising
  `CRIError` when the runtime is unreachable so the kubelet's sync loops
  degrade instead of dying (fault injection: kill the runtime process, the
  node keeps heartbeating, pods resync when it returns).
* `python -m kubernetes_tpu.kubelet.criserver --socket PATH` — a standalone
  runtime process (the containerd/dockershim seat), so kubelet and runtime
  genuinely live in different processes.

Fake-only verbs, documented as such: `Tick` (drives the PLEG relist clock —
the fake's time wheel) and `SetExitRules` (the containertest-style injection
hook: image-substring → exit-after-seconds), both consumed by the test
harness the way kubemark wires containertest fakes
(`cmd/kubemark/hollow-node.go`).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from kubernetes_tpu.kubelet.cri import FakeCRI, FakeContainer, FakeSandbox


class CRIError(RuntimeError):
    """Runtime unreachable or the verb failed server-side (the analog of a
    gRPC transport/status error from remote_runtime.go)."""


# ---------------------------------------------------------------------- #
# framing
# ---------------------------------------------------------------------- #

def _send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    body = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Dict[str, Any]:
    (size,) = struct.unpack(">I", _recv_exact(sock, 4))
    if size > (64 << 20):
        raise ConnectionError(f"oversized frame: {size}")
    return json.loads(_recv_exact(sock, size))


def _container_wire(c: FakeContainer) -> Dict[str, Any]:
    return {"id": c.id, "name": c.name, "image": c.image,
            "sandboxId": c.sandbox_id, "state": c.state,
            "exitCode": c.exit_code, "startedAt": c.started_at,
            "finishedAt": c.finished_at, "exitAfter": c.exit_after}


def _sandbox_wire(sb: FakeSandbox) -> Dict[str, Any]:
    return {"id": sb.id, "podName": sb.pod_name,
            "podNamespace": sb.pod_namespace, "podUid": sb.pod_uid,
            "ip": sb.ip, "state": sb.state}


# ---------------------------------------------------------------------- #
# server
# ---------------------------------------------------------------------- #

class CRIServer:
    """Serves a runtime (FakeCRI surface) on a unix socket."""

    def __init__(self, runtime: FakeCRI, socket_path: str):
        self.runtime = runtime
        self.socket_path = socket_path
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # verb table: CRI rpc name → handler(params) → result
    def _handle(self, method: str, p: Dict[str, Any]) -> Any:
        rt = self.runtime
        if method == "Version":
            return {"runtimeName": "ktpu-fakecri",
                    "runtimeApiVersion": "v1alpha2",
                    "runtimeVersion": "0.1"}
        if method == "Status":
            return {"conditions": [
                {"type": "RuntimeReady", "status": True},
                {"type": "NetworkReady", "status": True}]}
        if method == "RunPodSandbox":
            return {"podSandboxId": rt.run_pod_sandbox(
                p["podName"], p["podNamespace"], p["podUid"])}
        if method == "StopPodSandbox":
            rt.stop_pod_sandbox(p["podSandboxId"])
            return {}
        if method == "RemovePodSandbox":
            rt.remove_pod_sandbox(p["podSandboxId"])
            return {}
        if method == "ListPodSandbox":
            uid = (p.get("filter") or {}).get("podUid")
            with rt._mu:
                sbs = [_sandbox_wire(sb) for sb in rt.sandboxes.values()
                       if uid is None or sb.pod_uid == uid]
            return {"items": sbs}
        if method == "CreateContainer":
            return {"containerId": rt.create_container(
                p["podSandboxId"], p["name"], p["image"])}
        if method == "StartContainer":
            rt.start_container(p["containerId"])
            return {}
        if method == "StopContainer":
            rt.stop_container(p["containerId"], p.get("exitCode", 137))
            return {}
        if method == "RemoveContainer":
            rt.remove_container(p["containerId"])
            return {}
        if method == "ContainerStatus":
            c = rt.container_status(p["containerId"])
            return {"status": _container_wire(c) if c is not None else None}
        if method == "ListImages":
            return {"images": rt.list_images()}
        if method == "PullImage":
            rt.pull_image(p["image"])
            return {}
        if method == "RemoveImage":
            rt.remove_image(p["image"])
            return {}
        if method == "ImageFsInfo":
            return rt.image_fs_info()
        if method == "ListContainerStats":
            return {"stats": rt.list_stats()}
        if method == "Probe":  # the prober's check, policy-backed (fake)
            return {"ok": rt.probe(p["containerId"], p["kind"])}
        if method == "Tick":  # fake-only: PLEG relist clock
            return {"changed": rt.tick()}
        if method == "SetExitRules":  # fake-only: containertest injection
            rules: List[Tuple[str, float]] = [
                (r[0], float(r[1])) for r in p.get("rules", [])]

            def policy(image: str) -> Optional[float]:
                for substr, secs in rules:
                    if substr in image:
                        return secs
                return None

            rt.exit_policy = policy
            return {}
        raise CRIError(f"unimplemented verb: {method}")

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                req = _recv_frame(conn)
                try:
                    result = self._handle(req.get("method", ""),
                                          req.get("params", {}) or {})
                    _send_frame(conn, {"result": result})
                except (ConnectionError, BrokenPipeError):
                    raise
                except Exception as e:  # noqa: BLE001 — verb errors go on
                    # the wire as status, the transport stays up (gRPC status
                    # vs transport failure)
                    _send_frame(conn, {"error": f"{type(e).__name__}: {e}"})
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            conn.close()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="cri-conn")
            t.start()
            self._threads.append(t)

    def start(self) -> "CRIServer":
        try:
            os.unlink(self.socket_path)
        except FileNotFoundError:
            pass
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(64)
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="cri-accept")
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass


# ---------------------------------------------------------------------- #
# client (kubelet side)
# ---------------------------------------------------------------------- #

class RemoteCRI:
    """Duck-type drop-in for FakeCRI that dials the socket per verb —
    `pkg/kubelet/remote/remote_runtime.go`'s seat. One persistent
    connection under a lock; one reconnect attempt per call."""

    def __init__(self, socket_path: str, timeout: float = 5.0):
        self.socket_path = socket_path
        self.timeout = timeout
        self._mu = threading.Lock()
        self._conn: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self.socket_path)
        return s

    def _call(self, method: str, **params: Any) -> Any:
        req = {"method": method, "params": params}
        with self._mu:
            for attempt in (0, 1):
                fresh = sent = False
                try:
                    if self._conn is None:
                        self._conn = self._connect()
                        fresh = True
                    _send_frame(self._conn, req)
                    sent = True
                    resp = _recv_frame(self._conn)
                    break
                except (OSError, ConnectionError, json.JSONDecodeError) as e:
                    if self._conn is not None:
                        try:
                            self._conn.close()
                        except OSError:
                            pass
                        self._conn = None
                    # at-most-once: retransmit ONLY when the request cannot
                    # have reached the runtime — a stale reused connection
                    # failing at send time. A failure after a successful
                    # send (recv/timeout) may have executed server-side;
                    # resending RunPodSandbox/CreateContainer there would
                    # duplicate sandboxes (gRPC semantics: transport retry,
                    # never application retry).
                    if sent or fresh or attempt:
                        raise CRIError(
                            f"runtime unreachable at {self.socket_path}: {e}")
        if "error" in resp:
            raise CRIError(resp["error"])
        return resp.get("result")

    def close(self) -> None:
        with self._mu:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None

    # -- FakeCRI method surface ------------------------------------------ #

    def run_pod_sandbox(self, pod_name: str, pod_namespace: str,
                        pod_uid: str) -> str:
        return self._call("RunPodSandbox", podName=pod_name,
                          podNamespace=pod_namespace,
                          podUid=pod_uid)["podSandboxId"]

    def stop_pod_sandbox(self, sid: str) -> None:
        self._call("StopPodSandbox", podSandboxId=sid)

    def remove_pod_sandbox(self, sid: str) -> None:
        self._call("RemovePodSandbox", podSandboxId=sid)

    def create_container(self, sid: str, name: str, image: str) -> str:
        return self._call("CreateContainer", podSandboxId=sid, name=name,
                          image=image)["containerId"]

    def start_container(self, cid: str) -> None:
        self._call("StartContainer", containerId=cid)

    def stop_container(self, cid: str, exit_code: int = 137) -> None:
        self._call("StopContainer", containerId=cid, exitCode=exit_code)

    def remove_container(self, cid: str) -> None:
        self._call("RemoveContainer", containerId=cid)

    def container_status(self, cid: str) -> Optional[FakeContainer]:
        w = self._call("ContainerStatus", containerId=cid)["status"]
        if w is None:
            return None
        return FakeContainer(
            id=w["id"], name=w["name"], image=w["image"],
            sandbox_id=w["sandboxId"], state=w["state"],
            exit_code=w["exitCode"], started_at=w["startedAt"],
            finished_at=w["finishedAt"], exit_after=w["exitAfter"])

    def sandbox_for_pod(self, pod_uid: str) -> Optional[FakeSandbox]:
        items = self._call("ListPodSandbox",
                           filter={"podUid": pod_uid})["items"]
        for w in items:
            if w["state"] == "SANDBOX_READY":
                return FakeSandbox(
                    id=w["id"], pod_name=w["podName"],
                    pod_namespace=w["podNamespace"], pod_uid=w["podUid"],
                    ip=w["ip"], state=w["state"])
        return None

    def tick(self) -> List[str]:
        return self._call("Tick")["changed"]

    def list_stats(self) -> List[Dict[str, Any]]:
        return self._call("ListContainerStats")["stats"]

    def probe(self, cid: str, kind: str) -> bool:
        return self._call("Probe", containerId=cid, kind=kind)["ok"]

    def version(self) -> Dict[str, Any]:
        return self._call("Version")

    # -- ImageService -------------------------------------------------- #

    def pull_image(self, image: str) -> None:
        self._call("PullImage", image=image)

    def list_images(self) -> List[Dict[str, Any]]:
        return self._call("ListImages")["images"]

    def remove_image(self, image: str) -> None:
        self._call("RemoveImage", image=image)

    def image_fs_info(self) -> Dict[str, Any]:
        return self._call("ImageFsInfo")

    def set_exit_rules(self, rules: List[Tuple[str, float]]) -> None:
        self._call("SetExitRules", rules=[list(r) for r in rules])


def main(argv: Optional[List[str]] = None) -> None:
    """Standalone runtime process: the containerd seat on the other side of
    the boundary."""
    ap = argparse.ArgumentParser(prog="ktpu-cri-runtime")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--exit-rule", action="append", default=[],
                    metavar="SUBSTR=SECONDS",
                    help="containers whose image contains SUBSTR exit 0 "
                         "after SECONDS")
    args = ap.parse_args(argv)
    rt = FakeCRI()
    rules = []
    for r in args.exit_rule:
        substr, _, secs = r.partition("=")
        rules.append((substr, float(secs)))
    if rules:
        rt.exit_policy = lambda image: next(
            (s for sub, s in rules if sub in image), None)
    srv = CRIServer(rt, args.socket).start()
    stop = threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()


if __name__ == "__main__":
    main()
