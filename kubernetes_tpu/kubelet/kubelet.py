"""The node agent: syncLoop → pod workers → CRI; status + heartbeats.

Analog of `pkg/kubelet/kubelet.go`: `Run` (:1395) registers the node and
starts the loops; `syncLoop`/`syncLoopIteration` (:1818,:1892) select over
the pod config source (here: a watch on pods bound to this node), PLEG
events, and housekeeping ticks; `syncPod` (:1478) drives the CRI. Status
writes go through a status manager that dedupes; node heartbeats ride the
Ready condition + a kube-node-lease Lease, which the nodelifecycle
controller consumes.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from kubernetes_tpu.client.informers import SharedInformer
from kubernetes_tpu.kubelet.checkpoint import CheckpointManager
from kubernetes_tpu.kubelet.cri import (
    CONTAINER_CREATED,
    CONTAINER_EXITED,
    CONTAINER_RUNNING,
    FakeCRI,
)
from kubernetes_tpu.kubelet.criserver import CRIError
from kubernetes_tpu.machinery import errors, meta

Obj = Dict[str, Any]


class Kubelet:
    """One node agent. `hollow=True` is the kubemark configuration: fake CRI,
    real everything else (hollow-node.go)."""

    def __init__(self, client, node_name: str,
                 capacity: Optional[Dict[str, str]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 cri: Optional[FakeCRI] = None,
                 heartbeat_interval: float = 10.0,
                 housekeeping_interval: float = 0.5,
                 checkpoint_dir: Optional[str] = None,
                 eviction_hard: Optional[Dict[str, str]] = None,
                 eviction_soft: Optional[Dict[str, str]] = None,
                 eviction_soft_grace_period: Optional[Dict[str, str]] = None,
                 system_reserved: Optional[Dict[str, str]] = None,
                 kube_reserved: Optional[Dict[str, str]] = None,
                 image_gc_high_percent: int = 85,
                 image_gc_low_percent: int = 80,
                 image_gc_period: float = 10.0,
                 clock=time.time):
        from kubernetes_tpu.kubelet.cm import (
            ContainerManager, DevicePluginManager, ImageGCManager)
        from kubernetes_tpu.kubelet.volumemanager import VolumeManager

        self.client = client
        self.node_name = node_name
        self.capacity = capacity or {"cpu": "8", "memory": "16Gi",
                                     "pods": "110"}
        self.labels = dict(labels or {})
        self.labels.setdefault("kubernetes.io/hostname", node_name)
        self.cri = cri or FakeCRI()
        self.heartbeat_interval = heartbeat_interval
        self.housekeeping_interval = housekeeping_interval
        self.clock = clock
        self.checkpoints = CheckpointManager(checkpoint_dir) \
            if checkpoint_dir else None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._informer: Optional[SharedInformer] = None
        self._status_mu = threading.Lock()
        self._last_status: Dict[str, Obj] = {}  # pod key → last written status
        # serializes syncPod across the informer and housekeeping threads
        # (the reference gives each pod a single worker goroutine)
        self._pod_mu = threading.Lock()
        self._sandbox_by_uid: Dict[str, str] = {}
        self._containers_by_uid: Dict[str, List[str]] = {}
        # teardowns that failed because the runtime was unreachable: the pod
        # is already gone from the API (no more informer events), so the
        # housekeeping loop owns the retry
        self._pending_teardowns: Dict[str, Obj] = {}
        # prober manager (pkg/kubelet/prober): per-(uid, container, kind)
        # consecutive-count state; readiness gates the Ready condition,
        # liveness failure restarts the container
        self._probe_state: Dict[tuple, Dict[str, float]] = {}
        self._restart_counts: Dict[tuple, int] = {}
        self._container_started: Dict[str, float] = {}
        # eviction manager (pkg/kubelet/eviction/eviction_manager.go):
        # evictionHard thresholds, e.g. {"memory.available": "1Gi"} — when
        # this node's CRI-reported memory usage leaves less available than
        # the threshold, MemoryPressure goes True (+ NoSchedule taint) and
        # pods are evicted lowest-priority-first until below threshold
        self.eviction_hard = dict(eviction_hard or {})
        # soft thresholds must hold CONTINUOUSLY for their grace period
        # before acting (eviction/helpers.go thresholdsMetGracePeriod);
        # observation start times live in _soft_observed_since
        self.eviction_soft = dict(eviction_soft or {})
        self.eviction_soft_grace = dict(eviction_soft_grace_period or {})
        self._soft_observed_since: Dict[str, float] = {}
        self.under_memory_pressure = False
        self.under_disk_pressure = False
        # uids this kubelet evicted: blocks resync-resurrection while the
        # Failed status propagates through the watch (cleared at teardown)
        self._evicted: set = set()
        self._pending_evict_writes: Dict[str, tuple] = {}  # uid → (pod, res)
        # container manager (kubelet/cm.py): node allocatable = capacity -
        # reservations, and the canAdmitPod gate _sync_pod runs before a
        # sandbox exists. Rejected uids behave like evicted ones: no
        # resurrection while the Failed status propagates.
        self.container_manager = ContainerManager(
            self.capacity, system_reserved, kube_reserved)
        self._rejected: set = set()
        self._pending_reject_writes: Dict[str, tuple] = {}
        self.image_gc = ImageGCManager(self.cri, image_gc_high_percent,
                                       image_gc_low_percent)
        self._image_gc_period = image_gc_period
        self._last_image_gc = 0.0
        # device plugins (cm/devicemanager) + volume manager
        # (kubelet/volumemanager): device capacity rides the heartbeat,
        # admission allocates concrete device ids, the attach gate holds
        # containers until the controller attaches, volumesInUse is OUR
        # report
        self.device_manager = DevicePluginManager()
        self.volume_manager = VolumeManager()

    # ------------------------------------------------------------------ #
    # node registration + heartbeat (kubelet_node_status.go)
    # ------------------------------------------------------------------ #

    def register_node(self) -> None:
        node = {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": self.node_name, "labels": dict(self.labels)},
            "spec": {},
            "status": {
                "capacity": self._capacity_with_devices(),
                "allocatable": {**self.container_manager.allocatable(),
                                **self._device_capacity()},
                "conditions": [self._ready_condition()],
                "nodeInfo": {"kubeletVersion": "v1.17.0-tpu.1"},
                "addresses": [{"type": "Hostname",
                               "address": self.node_name}],
            },
        }
        try:
            self.client.nodes.create(node)
        except errors.StatusError as e:
            if not errors.is_already_exists(e):
                raise
            # re-registration keeps the existing object, refreshes status
            self._heartbeat()

    def _device_capacity(self) -> Dict[str, str]:
        return {res: str(n)
                for res, n in self.device_manager.capacity().items()}

    def _capacity_with_devices(self) -> Dict[str, str]:
        return {**self.capacity, **self._device_capacity()}

    def _ready_condition(self) -> Obj:
        return {"type": "Ready", "status": "True", "reason": "KubeletReady",
                "heartbeatUnix": self.clock(),
                "lastHeartbeatTime": meta.now_rfc3339()}

    def _heartbeat(self) -> None:
        try:
            node = self.client.nodes.get(self.node_name, "")
            conds = [c for c in node.get("status", {}).get("conditions", [])
                     if c.get("type") not in ("Ready", "MemoryPressure",
                                              "DiskPressure")]
            conds.append(self._ready_condition())
            thresholds = {**self.eviction_hard, **self.eviction_soft}
            if "memory.available" in thresholds:
                # the eviction manager's verdict rides the heartbeat
                # (kubelet_node_status.go setNodeMemoryPressureCondition)
                conds.append({
                    "type": "MemoryPressure",
                    "status": "True" if self.under_memory_pressure
                    else "False",
                    "reason": "KubeletHasInsufficientMemory"
                    if self.under_memory_pressure
                    else "KubeletHasSufficientMemory"})
            if "nodefs.available" in thresholds:
                conds.append({
                    "type": "DiskPressure",
                    "status": "True" if self.under_disk_pressure
                    else "False",
                    "reason": "KubeletHasDiskPressure"
                    if self.under_disk_pressure
                    else "KubeletHasNoDiskPressure"})
            node.setdefault("status", {})["conditions"] = conds
            node["status"]["capacity"] = self._capacity_with_devices()
            node["status"]["allocatable"] = {
                **self.container_manager.allocatable(),
                **self._device_capacity()}
            # volume manager halves of the attach/detach protocol: learn
            # what the controller attached, report what we hold mounted
            self.volume_manager.note_attached(node.get("status", {}))
            node["status"]["volumesInUse"] = self.volume_manager.in_use()
            self.client.nodes.update_status(node, "")
        except errors.StatusError:
            pass
        # node lease (kube-node-lease), the cheap heartbeat path
        lease = {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": self.node_name,
                         "namespace": "kube-node-lease"},
            "spec": {"holderIdentity": self.node_name,
                     "renewTime": self.clock(),
                     "leaseDurationSeconds": 40}}
        try:
            cur = self.client.leases.get(self.node_name, "kube-node-lease")
            cur["spec"] = lease["spec"]
            self.client.leases.update(cur, "kube-node-lease")
        except errors.StatusError:
            try:
                self.client.leases.create(lease, "kube-node-lease")
            except errors.StatusError:
                pass

    # ------------------------------------------------------------------ #
    # syncLoop (kubelet.go:1818): pod source + PLEG + housekeeping
    # ------------------------------------------------------------------ #

    def start(self) -> "Kubelet":
        self.register_node()
        self._informer = SharedInformer(
            self.client.pods,
            field_selector=f"spec.nodeName={self.node_name}")
        self._informer.add_handlers(
            on_add=self._pod_changed,
            on_update=lambda o, n: self._pod_changed(n),
            on_delete=self._pod_deleted)
        self._informer.start()
        self._informer.wait_for_sync()
        for target, name, period in (
                (self._heartbeat_loop, "heartbeat", None),
                (self._housekeeping_loop, "housekeeping", None)):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"kubelet-{self.node_name}-{name}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._informer is not None:
            self._informer.stop()
        for t in self._threads:
            t.join(timeout=2)

    def _heartbeat_loop(self) -> None:
        self._heartbeat()
        while not self._stop.wait(self.heartbeat_interval):
            self._heartbeat()

    def _housekeeping_loop(self) -> None:
        """PLEG relist + pod reconciliation (syncLoopIteration's 1 s/2 s
        housekeeping + PLEG channels collapsed into one tick). The loop body
        is guarded — a raising sync must not kill the node's PLEG forever."""
        while not self._stop.wait(self.housekeeping_interval):
            try:
                self.cri.tick()
                # reconcile every pod each tick, not only on CRI changes: a
                # conflicted status write would otherwise never retry (the
                # status dedupe map makes the no-change case free)
                for pod in list(self._informer.lister.list()):
                    self._pod_changed(pod)
                with self._pod_mu:
                    parked = list(self._pending_teardowns.values())
                    evict_writes = list(self._pending_evict_writes.items())
                for pod in parked:
                    self._pod_deleted(pod)
                for uid, (pod, resource) in evict_writes:
                    if self._write_evicted_status(pod, resource):
                        with self._pod_mu:
                            self._pending_evict_writes.pop(uid, None)
                with self._pod_mu:
                    reject_writes = list(
                        self._pending_reject_writes.items())
                for uid, (pod, reason, message) in reject_writes:
                    if self._write_failed_status(pod, reason, message):
                        with self._pod_mu:
                            self._pending_reject_writes.pop(uid, None)
                if self.eviction_hard or self.eviction_soft:
                    self._check_eviction()
                now = self.clock()
                if now - self._last_image_gc >= self._image_gc_period:
                    self._last_image_gc = now
                    self.image_gc.garbage_collect()
            except Exception:  # noqa: BLE001 — node loops never die
                pass

    # ------------------------------------------------------------------ #
    # syncPod (kubelet.go:1478) — one pod's reconcile against the CRI
    # ------------------------------------------------------------------ #

    def _pod_changed(self, pod: Obj) -> None:
        try:
            self._sync_pod(pod)
        except CRIError:
            # runtime down (the socket boundary, kubelet/criserver.py): the
            # reference kubelet logs the sync error and retries on the next
            # housekeeping/PLEG tick — the node must not die with its runtime
            pass

    def _sync_pod(self, pod: Obj) -> None:
        if meta.is_being_deleted(pod):
            self._teardown(pod, deleted_from_api=False)
            return
        uid = meta.uid(pod)
        phase = pod.get("status", {}).get("phase", "")
        if phase in ("Succeeded", "Failed") or uid in self._evicted \
                or uid in self._rejected:
            return
        with self._pod_mu:
            if uid in self._evicted or uid in self._rejected:
                # re-checked UNDER the lock: a sync that passed the outer
                # guard while _evict_pod held the lock must not recreate
                # the sandbox it just destroyed
                return
            sid = self._sandbox_by_uid.get(uid)
            if sid is None:
                # canAdmitPod (kubelet.go HandlePodAdditions): the NODE
                # enforces allocatable against already-admitted pods —
                # the scheduler's arithmetic is advisory (stale caches,
                # static pods, competing schedulers can all overcommit)
                active = [p for p in self._informer.lister.list()
                          if meta.uid(p) in self._sandbox_by_uid
                          and meta.uid(p) not in self._evicted
                          and p.get("status", {}).get("phase", "")
                          not in ("Succeeded", "Failed")] \
                    if self._informer else []
                ok, reason, message = self.container_manager.admit(
                    pod, active)
                if ok:
                    # device-plugin resources allocate CONCRETE device ids
                    # at admission (devicemanager Allocate) — exhaustion
                    # rejects like any other resource
                    from kubernetes_tpu.kubelet.cm import (
                        pod_extended_requests)

                    plugin_caps = self.device_manager.capacity()
                    dev_req = {r: n for r, n in
                               pod_extended_requests(pod).items()
                               if r in plugin_caps}
                    if dev_req and not self.device_manager.allocate(
                            uid, dev_req):
                        ok = False
                        worst = sorted(dev_req)[0]
                        reason = f"OutOf{worst}"
                        message = (f"Node didn't have enough resource: "
                                   f"{worst} (device plugin)")
                if not ok:
                    # rejectPod: no sandbox is ever created; the Failed
                    # status (reason OutOfcpu/OutOfmemory/OutOfpods)
                    # writes outside the lock, housekeeping re-drives it
                    self._rejected.add(uid)
                    self._pending_reject_writes[uid] = (pod, reason,
                                                        message)
                    rejection = (pod, reason, message)
                else:
                    rejection = None
                    sid = self.cri.run_pod_sandbox(meta.name(pod),
                                                   meta.namespace(pod), uid)
                    # recorded IMMEDIATELY so a CRIError later in this
                    # sync leaves resumable bookkeeping, never a leaked
                    # sandbox
                    self._sandbox_by_uid[uid] = sid
                    self._containers_by_uid[uid] = []
            else:
                rejection = None
        if rejection is not None:
            if self._write_failed_status(*rejection):
                with self._pod_mu:
                    self._pending_reject_writes.pop(uid, None)
            return
        with self._pod_mu:
            if uid in self._evicted or self._sandbox_by_uid.get(uid) is None:
                return
            # volumesInUse marks BEFORE the attach gate and UNDER the pod
            # lock (reference order: markVolumesInUse precedes mounting):
            # the in-use report must cover a pod still WAITING for its
            # attach, or a delete between heartbeats detaches under an
            # active mount; and marking after the evicted/sandbox check
            # means a concurrent teardown's unmount can't be overwritten
            # by a stale sync (permanent attach leak otherwise)
            self.volume_manager.mark_mounted(uid, pod)
            # WaitForAttachAndMount: containers hold until the attach/
            # detach controller attached every attach-requiring volume;
            # housekeeping retries the sync
            ok_vols, _missing = \
                self.volume_manager.wait_for_attach_and_mount(pod)
            if not ok_vols:
                return
            sid = self._sandbox_by_uid[uid]
            cids = self._containers_by_uid.setdefault(uid, [])
            spec_containers = pod.get("spec", {}).get("containers", []) or []
            # resume container creation where a partial sync stopped (the
            # runtime died mid-loop): containers are created in spec order,
            # so the tail beyond len(cids) is exactly what's missing
            created = False
            for c in spec_containers[len(cids):]:
                cid = self.cri.create_container(sid, c.get("name", "c"),
                                                c.get("image", ""))
                cids.append(cid)
                created = True
                self.cri.start_container(cid)
                self._container_started[cid] = self.clock()
            if created and self.checkpoints:
                self.checkpoints.create_checkpoint(
                    f"pod-{uid}", {"sandbox": sid, "containers": list(cids)})
            if not created:
                self._restart_failed_containers(pod, uid)
            self._run_probes(pod, uid, cids)
        self._write_status(pod)

    # ------------------------------------------------------------------ #
    # eviction manager (pkg/kubelet/eviction/eviction_manager.go)
    # ------------------------------------------------------------------ #

    @staticmethod
    def _parse_threshold(value: str, capacity_bytes: int) -> float:
        """Threshold quantity: absolute ("1Gi") or percentage of capacity
        ("10%") — both forms the reference accepts (eviction/api/types)."""
        from kubernetes_tpu.api.types import parse_mem_kib

        value = str(value).strip()
        if value.endswith("%"):
            return capacity_bytes * float(value[:-1]) / 100.0
        return parse_mem_kib(value) * 1024.0

    @staticmethod
    def _parse_grace(value: str) -> float:
        """Duration string: '90s', '1m30s', '2h' (metav1.Duration subset)."""
        import re as _re

        total = 0.0
        # ms before m/s: the alternation is first-match (500ms ≠ 500 min)
        for num, unit in _re.findall(r"([0-9.]+)(ms|h|m|s)", str(value)):
            total += float(num) * {"h": 3600.0, "m": 60.0, "s": 1.0,
                                   "ms": 0.001}[unit]
        return total

    def _signal_under_pressure(self, signal: str, avail: float,
                               cap: float, now: float) -> bool:
        """Hard threshold: immediate. Soft threshold: only after holding
        continuously for its grace period."""
        hard = self.eviction_hard.get(signal)
        if hard and avail < self._parse_threshold(hard, int(cap)):
            return True
        soft = self.eviction_soft.get(signal)
        if soft and avail < self._parse_threshold(soft, int(cap)):
            since = self._soft_observed_since.setdefault(signal, now)
            grace = self._parse_grace(
                self.eviction_soft_grace.get(signal, "0s"))
            return now - since >= grace
        self._soft_observed_since.pop(signal, None)
        return False

    def _check_eviction(self) -> None:
        """synchronize() analog over two signals: memory.available (CRI
        container stats) and nodefs.available (imagefs). Under memory
        pressure, evict the rankMemoryPressure victim; under disk
        pressure, reclaim node-level resources FIRST (delete unused
        images — eviction_manager.go reclaimNodeLevelResources) and evict
        only if that does not clear the signal. Conditions ride the
        heartbeat; nodelifecycle converts them to NoSchedule taints. One
        stats snapshot feeds both the availability sum and the ranking,
        so the verdict and the victim come from the same observation."""
        from kubernetes_tpu.api.types import parse_mem_kib

        now = self.clock()
        with self._pod_mu:
            uids = set(self._sandbox_by_uid)
        usage: Dict[str, int] = {}
        for s in self.cri.list_stats():
            uid = s.get("podUid", "")
            if uid in uids:
                usage[uid] = usage.get(uid, 0) + s["memoryBytes"]
        cap_b = parse_mem_kib(self.capacity.get("memory", "0")) * 1024
        avail = cap_b - sum(usage.values())

        # nodefs.available over the image filesystem (the only fs here)
        disk_signals = ("nodefs.available" in self.eviction_hard
                        or "nodefs.available" in self.eviction_soft)
        if disk_signals:
            try:
                fs = self.cri.image_fs_info()
            except Exception:  # noqa: BLE001 — runtime down: skip this tick
                fs = None
            if fs is not None:
                fs_cap = int(fs.get("capacityBytes", 0))
                fs_avail = fs_cap - int(fs.get("usedBytes", 0))
                under_disk = self._signal_under_pressure(
                    "nodefs.available", fs_avail, fs_cap, now)
                if under_disk:
                    # reclaim node-level resources first: delete unused
                    # images, then re-measure before evicting anything.
                    # Same runtime-down policy as the first probe: a
                    # CRIError mid-reclaim skips the DISK verdict for this
                    # tick but must not abort the memory check below.
                    try:
                        self.image_gc.delete_unused_images()
                        fs = self.cri.image_fs_info()
                        fs_avail = int(fs.get("capacityBytes", 0)) - \
                            int(fs.get("usedBytes", 0))
                        under_disk = self._signal_under_pressure(
                            "nodefs.available", fs_avail, fs_cap, now)
                        self.under_disk_pressure = under_disk
                    except Exception:  # noqa: BLE001
                        pass
                else:
                    self.under_disk_pressure = under_disk

        mem_pressure = self._signal_under_pressure(
            "memory.available", avail, cap_b, now)
        self.under_memory_pressure = mem_pressure
        if mem_pressure:
            starved = "memory"
        elif self.under_disk_pressure:
            # disk pressure unresolved by image reclaim: evict one pod.
            # FakeCRI models no per-pod disk usage (PARITY #9b), so the
            # memory ranking below doubles as the disk ranking.
            starved = "ephemeral-storage"
        else:
            return
        from kubernetes_tpu.kubelet.cm import pod_requests

        victims = []
        for pod in self._informer.lister.list() if self._informer else []:
            phase = pod.get("status", {}).get("phase", "")
            uid = meta.uid(pod)
            if phase in ("Succeeded", "Failed") or uid in self._evicted:
                continue
            if uid not in usage:
                continue
            # rankMemoryPressure (eviction/helpers.go): pods whose usage
            # EXCEEDS their request evict first, then lower priority, then
            # the largest usage-over-request
            _, req_kib = pod_requests(pod)
            over = usage[uid] - req_kib * 1024
            victims.append((0 if over > 0 else 1,
                            int(pod.get("spec", {}).get("priority", 0) or 0),
                            -over, meta.namespaced_key(pod), pod))
        if not victims:
            return
        # key excludes the pod dict: rank ties must not fall through to
        # (unorderable) dict comparison
        victims.sort(key=lambda v: v[:4])
        self._evict_pod(victims[0][4], resource=starved)

    def _evict_pod(self, pod: Obj, resource: str = "memory") -> None:
        """Kill the pod's containers and report Failed/Evicted — the
        reference's evictPod (the object survives in Failed state; a
        controller replaces it elsewhere). The uid is marked evicted so a
        stale lister copy (watch lag) cannot resurrect the sandbox before
        the Failed status round-trips."""
        uid = meta.uid(pod)
        with self._pod_mu:
            self._evicted.add(uid)
            sid = self._sandbox_by_uid.pop(uid, None)
            cids = self._containers_by_uid.pop(uid, [])
            for cid in cids:
                self._container_started.pop(cid, None)
            for d in (self._probe_state, self._restart_counts):
                for k in [k for k in d if k[0] == uid]:
                    del d[k]
        self.device_manager.deallocate(uid)
        self.volume_manager.unmount(uid)
        if sid is not None:
            try:
                self.cri.stop_pod_sandbox(sid)
                self.cri.remove_pod_sandbox(sid)
            except CRIError:
                pass
        if not self._write_evicted_status(pod, resource):
            # parked: the housekeeping loop re-drives the write until it
            # lands — the sandbox is already gone, so the pod must not be
            # left reporting Running forever
            with self._pod_mu:
                self._pending_evict_writes[meta.uid(pod)] = (pod, resource)

    def _write_evicted_status(self, pod: Obj,
                              resource: str = "memory") -> bool:
        return self._write_failed_status(
            pod, "Evicted", f"The node was low on resource: {resource}.")

    def _write_failed_status(self, pod: Obj, reason: str,
                             message: str) -> bool:
        for _ in range(5):  # CAS-retry: informer status writes race this
            try:
                cur = self.client.pods.get(meta.name(pod),
                                           meta.namespace(pod))
                cur["status"] = {**cur.get("status", {}),
                                 "phase": "Failed", "reason": reason,
                                 "message": message}
                self.client.pods.update_status(cur, meta.namespace(pod))
                return True
            except errors.StatusError as e:
                if errors.is_not_found(e):
                    return True  # gone from the API — nothing left to mark
                if not errors.is_conflict(e):
                    # transient server error (500, auth, ...): park and let
                    # housekeeping retry — only NotFound means done
                    return False
            except Exception:  # noqa: BLE001 - transport error: park, retry
                return False
        return False

    # ------------------------------------------------------------------ #
    # prober manager (pkg/kubelet/prober/prober_manager.go): readiness
    # results gate the Ready condition; liveness failure past the
    # threshold restarts the container (worker.go doProbe)
    # ------------------------------------------------------------------ #

    def _run_probes(self, pod: Obj, uid: str, cids: List[str]) -> None:
        spec_containers = pod.get("spec", {}).get("containers", []) or []
        now = self.clock()
        for c, cid in zip(spec_containers, cids):
            status = self.cri.container_status(cid)
            if status is None or status.state != CONTAINER_RUNNING:
                # the reference stops probe workers for terminated
                # containers — restartPolicy, not liveness, owns their fate
                continue
            for kind in ("readiness", "liveness"):
                probe = c.get(f"{kind}Probe")
                if not probe:
                    continue
                key = (uid, c.get("name", "c"), kind)
                st = self._probe_state.setdefault(
                    key, {"ok": False, "fails": 0, "passes": 0, "last": 0.0})
                delay = float(probe.get("initialDelaySeconds", 0) or 0)
                period = float(probe.get("periodSeconds", 10) or 10)
                started = self._container_started.get(cid, now)
                if now - started < delay or now - st["last"] < period:
                    continue
                st["last"] = now
                ok = self.cri.probe(cid, kind)
                if ok:
                    st["passes"] += 1
                    st["fails"] = 0
                    if st["passes"] >= int(probe.get("successThreshold", 1)
                                           or 1):
                        st["ok"] = True
                else:
                    st["fails"] += 1
                    st["passes"] = 0
                    if st["fails"] >= int(probe.get("failureThreshold", 3)
                                          or 3):
                        st["ok"] = False
                        if kind == "liveness":
                            # the kubelet KILLS on liveness failure; whether
                            # it restarts is restartPolicy's call
                            # (kuberuntime_manager computePodActions:
                            # Never → the container stays terminated and
                            # the pod settles via getPhase)
                            self.cri.stop_container(cid, 137)
                            st.update(fails=0, passes=0)
                            policy = pod.get("spec", {}).get(
                                "restartPolicy", "Always")
                            if policy != "Never":
                                self._restart_container(uid, c.get(
                                    "name", "c"), cid, now)

    def _ready_gate(self, uid: str, name: str, pod: Obj) -> bool:
        """Readiness verdict for one container: True unless a readinessProbe
        is defined and has not (yet) passed."""
        for c in pod.get("spec", {}).get("containers", []) or []:
            if c.get("name", "c") == name and c.get("readinessProbe"):
                return bool(self._probe_state.get(
                    (uid, name, "readiness"), {}).get("ok", False))
        return True

    def _restart_container(self, uid: str, name: str, cid: str,
                           now: float) -> None:
        """The single restart chokepoint: starts the container and does the
        bookkeeping EVERY restart needs — count it, restamp the start time
        (initialDelaySeconds measures from here), and drop the readiness
        verdict (a restarted container is not ready until its probe passes
        again)."""
        self.cri.start_container(cid)
        rkey = (uid, name)
        self._restart_counts[rkey] = self._restart_counts.get(rkey, 0) + 1
        self._container_started[cid] = now
        self._probe_state.pop((uid, name, "readiness"), None)

    def _restart_failed_containers(self, pod: Obj, uid: str) -> None:
        """Container restarts per restartPolicy (SyncPod's computePodActions):
        Always restarts any exit; OnFailure restarts nonzero exits."""
        policy = pod.get("spec", {}).get("restartPolicy", "Always")
        for cid in self._containers_by_uid.get(uid, []):
            c = self.cri.container_status(cid)
            if c is None:
                continue
            if c.state == CONTAINER_CREATED:
                # created but never started (a partial sync lost the start):
                # repaired regardless of restartPolicy — this is first
                # start, not a restart, so no bookkeeping
                self.cri.start_container(cid)
            elif c.state == CONTAINER_EXITED and policy != "Never" and (
                    policy == "Always" or c.exit_code != 0):
                self._restart_container(uid, c.name, cid, self.clock())

    def _pod_deleted(self, pod: Obj) -> None:
        try:
            self._teardown(pod, deleted_from_api=True)
        except CRIError:
            pass  # parked in _pending_teardowns; housekeeping retries

    def _teardown(self, pod: Obj, deleted_from_api: bool) -> None:
        uid = meta.uid(pod)
        with self._pod_mu:
            sid = self._sandbox_by_uid.get(uid)
        if sid is not None:
            try:
                self.cri.stop_pod_sandbox(sid)
                self.cri.remove_pod_sandbox(sid)
            except CRIError:
                # keep the bookkeeping: the sandbox is still running on the
                # far side, and only this map can find it again — park the
                # pod so the housekeeping loop retries the teardown
                with self._pod_mu:
                    self._pending_teardowns[uid] = pod
                raise
        with self._pod_mu:
            for cid in self._containers_by_uid.get(uid, []):
                self._container_started.pop(cid, None)
            self._sandbox_by_uid.pop(uid, None)
            self._containers_by_uid.pop(uid, None)
            self._pending_teardowns.pop(uid, None)
            self._pending_evict_writes.pop(uid, None)
            self._evicted.discard(uid)
            self._rejected.discard(uid)
            self._pending_reject_writes.pop(uid, None)
            for d in (self._probe_state, self._restart_counts):
                for k in [k for k in d if k[0] == uid]:
                    del d[k]
        self.device_manager.deallocate(uid)
        self.volume_manager.unmount(uid)
        with self._status_mu:
            self._last_status.pop(meta.namespaced_key(pod), None)
        if self.checkpoints:
            self.checkpoints.remove_checkpoint(f"pod-{uid}")
        if not deleted_from_api and meta.is_being_deleted(pod):
            # confirm graceful deletion (the kubelet's final delete with
            # grace 0 once containers are down, status_manager.go)
            try:
                self.client.pods.delete(meta.name(pod), meta.namespace(pod))
            except errors.StatusError:
                pass

    # ------------------------------------------------------------------ #
    # stats (pkg/kubelet/server/stats /stats/summary): the scrape surface
    # the resource-metrics pipeline aggregates from
    # ------------------------------------------------------------------ #

    def stats_summary(self) -> Obj:
        """Per-pod cpu/memory usage from the CRI (ListContainerStats),
        summed across containers and tagged with this node — the
        /stats/summary payload metrics-server scrapes."""
        try:
            stats = self.cri.list_stats()
        except CRIError:
            return {"node": self.node_name, "pods": []}
        by_pod: Dict[tuple, Obj] = {}
        for s in stats:
            key = (s["podNamespace"], s["podName"])
            agg = by_pod.setdefault(key, {
                "namespace": s["podNamespace"], "name": s["podName"],
                "uid": s.get("podUid", ""), "cpuMilli": 0, "memoryBytes": 0,
                "containers": []})
            agg["cpuMilli"] += s["cpuMilli"]
            agg["memoryBytes"] += s["memoryBytes"]
            agg["containers"].append({"name": s["name"],
                                      "cpuMilli": s["cpuMilli"],
                                      "memoryBytes": s["memoryBytes"]})
        return {"node": self.node_name, "pods": list(by_pod.values())}

    # ------------------------------------------------------------------ #
    # status manager (pkg/kubelet/status): compute + dedupe + write
    # ------------------------------------------------------------------ #

    def _compute_status(self, pod: Obj) -> Obj:
        uid = meta.uid(pod)
        sb = self.cri.sandbox_for_pod(uid)
        cids = self._containers_by_uid.get(uid, [])
        statuses = []
        n_running = n_succeeded = n_failed = 0
        for cid in cids:
            c = self.cri.container_status(cid)
            if c is None:
                continue
            restarts = self._restart_counts.get((uid, c.name), 0)
            if c.state == CONTAINER_RUNNING:
                n_running += 1
                statuses.append({
                    "name": c.name,
                    # readiness probes gate Ready (prober results manager)
                    "ready": self._ready_gate(uid, c.name, pod),
                    "state": {"running": {}},
                    "restartCount": restarts, "image": c.image})
            elif c.state == CONTAINER_EXITED:
                if c.exit_code == 0:
                    n_succeeded += 1
                else:
                    n_failed += 1
                statuses.append({"name": c.name, "ready": False,
                                 "state": {"terminated":
                                           {"exitCode": c.exit_code}},
                                 "restartCount": restarts, "image": c.image})
        # PodPhase rules (pkg/kubelet/kubelet_pods.go getPhase): all
        # succeeded → Succeeded; any failed with restartPolicy Never →
        # Failed; otherwise Running while anything runs or will restart
        total = len(cids)
        policy = pod.get("spec", {}).get("restartPolicy", "Always")
        if total and n_succeeded == total:
            phase = "Succeeded"
        elif n_failed and policy == "Never":
            phase = "Failed"
        elif n_running or (n_failed and policy == "OnFailure"):
            # failed-under-OnFailure counts as Running: the kubelet restarts
            # the container (see _restart_failed_containers)
            phase = "Running"
        else:
            phase = "Pending"
        # pod Ready requires every container running AND readiness-passing
        # (status_manager GeneratePodReadyCondition)
        ready = (phase == "Running" and n_running == total
                 and all(s.get("ready", False) for s in statuses))
        return {
            "phase": phase,
            "podIP": sb.ip if sb else "",
            "hostIP": self.node_name,
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Ready", "status": "True" if ready else "False"},
                {"type": "ContainersReady",
                 "status": "True" if ready else "False"},
            ],
            "containerStatuses": statuses,
            "startTime": pod.get("status", {}).get("startTime")
            or meta.now_rfc3339(),
        }

    def _write_status(self, pod: Obj) -> None:
        key = meta.namespaced_key(pod)
        status = self._compute_status(pod)
        with self._status_mu:
            if self._last_status.get(key) == status:
                return
        cur = meta.deep_copy(pod)
        # keep scheduler-written conditions (PodScheduled) that we restate
        cur["status"] = {**pod.get("status", {}), **status}
        try:
            self.client.pods.update_status(cur, meta.namespace(pod))
        except errors.StatusError:
            return  # NOT cached: a failed write must be retried next sync
        with self._status_mu:
            self._last_status[key] = status
