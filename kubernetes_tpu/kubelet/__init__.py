"""Node agent: kubelet, fake CRI, checkpoint manager.

TPU-native analog of SURVEY.md layer 8 (`pkg/kubelet`, `cmd/kubelet`,
`staging/src/k8s.io/cri-api`).
"""

from kubernetes_tpu.kubelet.checkpoint import (
    CheckpointManager,
    CorruptCheckpointError,
)
from kubernetes_tpu.kubelet.cri import (
    CONTAINER_CREATED,
    CONTAINER_EXITED,
    CONTAINER_RUNNING,
    FakeCRI,
)
from kubernetes_tpu.kubelet.kubelet import Kubelet

__all__ = ["CheckpointManager", "CorruptCheckpointError",
           "CONTAINER_CREATED", "CONTAINER_EXITED", "CONTAINER_RUNNING",
           "FakeCRI", "Kubelet"]
