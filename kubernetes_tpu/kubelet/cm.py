"""Container manager + image GC — the kubelet's on-node resource seat.

The scheduler's arithmetic is advisory; the NODE enforces. This module is
the analog of:

  * `pkg/kubelet/cm/container_manager_linux.go` — node allocatable
    (capacity minus system/kube reservations) and the admission gate the
    kubelet runs before starting a pod (`kubelet.go canAdmitPod` →
    `pkg/kubelet/lifecycle/predicate.go GeneralPredicates`), with the
    OutOfcpu/OutOfmemory/OutOfpods rejection reasons;
  * `pkg/kubelet/qos/policy.go` — QoS classification (Guaranteed /
    Burstable / BestEffort), which orders eviction;
  * `pkg/kubelet/images/image_gc_manager.go:83` — high/low watermark image
    garbage collection over the runtime's image store, LRU, in-use exempt.

There are no real cgroups here (no containers — FakeCRI stands in for the
runtime), so "enforcement" means the admission ledger: a pod whose
requests do not fit into allocatable minus the sum of admitted pods'
requests is REJECTED with phase Failed — exactly the reference's behavior
when a static pod or a stale-scheduler binding lands on a full node.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import parse_cpu_milli, parse_mem_kib

Obj = Dict[str, Any]


def pod_requests(pod: Obj) -> Tuple[int, int]:
    """Effective (milliCPU, memKiB) request — max over init containers vs
    sum over app containers (resource_helpers.go PodRequestsAndLimits)."""
    cpu = mem = 0
    spec = pod.get("spec", {}) or {}
    for c in spec.get("containers", []) or []:
        req = (c.get("resources", {}) or {}).get("requests", {}) or {}
        cpu += parse_cpu_milli(str(req.get("cpu", "0") or "0"))
        mem += parse_mem_kib(str(req.get("memory", "0") or "0"))
    for c in spec.get("initContainers", []) or []:
        req = (c.get("resources", {}) or {}).get("requests", {}) or {}
        cpu = max(cpu, parse_cpu_milli(str(req.get("cpu", "0") or "0")))
        mem = max(mem, parse_mem_kib(str(req.get("memory", "0") or "0")))
    return cpu, mem


def pod_qos(pod: Obj) -> str:
    """qos.GetPodQOS: Guaranteed when every container's requests == limits
    for both cpu+memory and they are set; BestEffort when no container
    sets any request/limit; Burstable otherwise."""
    spec = pod.get("spec", {}) or {}
    containers = (spec.get("containers", []) or []) + \
        (spec.get("initContainers", []) or [])
    any_set = False
    guaranteed = bool(containers)
    for c in containers:
        res = c.get("resources", {}) or {}
        req = res.get("requests", {}) or {}
        lim = res.get("limits", {}) or {}
        if req or lim:
            any_set = True
        for key in ("cpu", "memory"):
            if not lim.get(key) or req.get(key, lim.get(key)) != lim[key]:
                guaranteed = False
    if not any_set:
        return "BestEffort"
    return "Guaranteed" if guaranteed else "Burstable"


class ContainerManager:
    """Node allocatable + the canAdmitPod gate."""

    def __init__(self, capacity: Dict[str, str],
                 system_reserved: Optional[Dict[str, str]] = None,
                 kube_reserved: Optional[Dict[str, str]] = None):
        self.capacity = dict(capacity)
        self.system_reserved = dict(system_reserved or {})
        self.kube_reserved = dict(kube_reserved or {})

    def _reserved(self, key: str) -> int:
        parse = parse_cpu_milli if key == "cpu" else parse_mem_kib
        return sum(parse(str(r.get(key, "0") or "0"))
                   for r in (self.system_reserved, self.kube_reserved))

    def allocatable(self) -> Dict[str, str]:
        """Capacity minus reservations (GetNodeAllocatableReservation) —
        what the node REPORTS, and what admission enforces."""
        out = dict(self.capacity)
        cpu = parse_cpu_milli(str(self.capacity.get("cpu", "0"))) \
            - self._reserved("cpu")
        mem = parse_mem_kib(str(self.capacity.get("memory", "0"))) \
            - self._reserved("memory")
        out["cpu"] = f"{max(cpu, 0)}m"
        out["memory"] = f"{max(mem, 0)}Ki"
        return out

    def admit(self, pod: Obj, active_pods: List[Obj]) -> Tuple[bool, str,
                                                               str]:
        """canAdmitPod: fit `pod` into allocatable minus the admitted pods'
        requests. Returns (ok, reason, message); reasons are the
        reference's OutOfcpu / OutOfmemory / OutOfpods
        (lifecycle/predicate.go → ... AdmissionFailureHandler)."""
        alloc = self.allocatable()
        alloc_cpu = parse_cpu_milli(str(alloc.get("cpu", "0")))
        alloc_mem = parse_mem_kib(str(alloc.get("memory", "0")))
        alloc_pods = int(alloc.get("pods", 110) or 110)
        used_cpu = used_mem = 0
        for p in active_pods:
            c, m = pod_requests(p)
            used_cpu += c
            used_mem += m
        cpu, mem = pod_requests(pod)
        if len(active_pods) + 1 > alloc_pods:
            return (False, "OutOfpods",
                    f"Node didn't have enough capacity: pods, requested: 1, "
                    f"used: {len(active_pods)}, capacity: {alloc_pods}")
        if used_cpu + cpu > alloc_cpu:
            return (False, "OutOfcpu",
                    f"Node didn't have enough resource: cpu, requested: "
                    f"{cpu}, used: {used_cpu}, capacity: {alloc_cpu}")
        if used_mem + mem > alloc_mem:
            return (False, "OutOfmemory",
                    f"Node didn't have enough resource: memory, requested: "
                    f"{mem}Ki, used: {used_mem}Ki, capacity: {alloc_mem}Ki")
        return True, "", ""


def pod_extended_requests(pod: Obj) -> Dict[str, int]:
    """Integer requests for non-core resources (device-plugin resources
    like example.com/tpu, extended resources generally)."""
    out: Dict[str, int] = {}
    for c in (pod.get("spec", {}) or {}).get("containers", []) or []:
        req = (c.get("resources", {}) or {}).get("requests", {}) or {}
        for name, qty in req.items():
            if name in ("cpu", "memory", "ephemeral-storage", "pods"):
                continue
            try:
                n = int(str(qty))
            except ValueError:
                continue  # extended resources are integral by definition
            if n > 0:    # negative requests are invalid — never count them
                out[name] = out.get(name, 0) + n
    return out


class DevicePluginManager:
    """The device-plugin seat (`pkg/kubelet/cm/devicemanager/manager.go`):
    plugins register a resource name with concrete device IDs; the kubelet
    advertises healthy counts as node capacity, admission counts requests
    against them, and admitted containers get SPECIFIC device ids
    allocated (the Allocate RPC) — released when the pod leaves."""

    def __init__(self):
        import threading

        self._mu = threading.Lock()
        #: resource → {device_id: healthy}
        self._devices: Dict[str, Dict[str, bool]] = {}
        #: pod uid → {resource: [device ids]}
        self._allocations: Dict[str, Dict[str, List[str]]] = {}

    def register(self, resource: str, device_ids: List[str]) -> None:
        """Plugin registration (ListAndWatch's initial inventory)."""
        with self._mu:
            self._devices[resource] = {d: True for d in device_ids}

    def set_health(self, resource: str, device_id: str,
                   healthy: bool) -> None:
        """A plugin reporting device health (ListAndWatch updates):
        unhealthy devices leave capacity and are never allocated."""
        with self._mu:
            devs = self._devices.get(resource)
            if devs is not None and device_id in devs:
                devs[device_id] = healthy

    def capacity(self) -> Dict[str, int]:
        with self._mu:
            return {res: sum(1 for ok in devs.values() if ok)
                    for res, devs in self._devices.items()}

    def _used_locked(self, resource: str) -> set:
        return {d for alloc in self._allocations.values()
                for d in alloc.get(resource, [])}

    def available(self) -> Dict[str, int]:
        with self._mu:
            out = {}
            for res, devs in self._devices.items():
                used = self._used_locked(res)
                out[res] = sum(1 for d, ok in devs.items()
                               if ok and d not in used)
            return out

    def allocate(self, pod_uid: str, requests: Dict[str, int]) -> bool:
        """Allocate concrete devices for every requested resource, or
        nothing (all-or-nothing, as the reference's Allocate). Idempotent
        per pod: a re-admission after a failed sync (CRIError retry path)
        reuses the pod's existing allocation instead of counting it as
        someone else's and spuriously rejecting."""
        with self._mu:
            mine = self._allocations.get(pod_uid, {})
            plan: Dict[str, List[str]] = {}
            for res, want in requests.items():
                if want <= 0:
                    continue  # negative/zero requests allocate nothing
                if res not in self._devices:
                    return False
                if len(mine.get(res, [])) >= want:
                    plan[res] = mine[res][:want]
                    continue
                used = self._used_locked(res) - set(mine.get(res, []))
                free = [d for d, ok in self._devices[res].items()
                        if ok and d not in used]
                if len(free) < want:
                    return False
                plan[res] = free[:want]
            if plan:
                self._allocations[pod_uid] = plan
            return True

    def deallocate(self, pod_uid: str) -> None:
        with self._mu:
            self._allocations.pop(pod_uid, None)

    def allocations(self, pod_uid: str) -> Dict[str, List[str]]:
        """The devices a pod holds (the PodResources API surface)."""
        with self._mu:
            return {r: list(ds) for r, ds in
                    self._allocations.get(pod_uid, {}).items()}


class ImageGCManager:
    """High/low watermark GC over the runtime's image store
    (image_gc_manager.go:83 ImageGCPolicy + realImageGCManager
    GarbageCollect/freeSpace): above the high threshold, delete unused
    images oldest-last-used first until usage is below the low threshold;
    images referenced by any container are exempt; images younger than
    min_age are skipped."""

    def __init__(self, cri, high_threshold_percent: int = 85,
                 low_threshold_percent: int = 80, min_age: float = 0.0,
                 clock=None):
        self.cri = cri
        self.high = high_threshold_percent
        self.low = low_threshold_percent
        self.min_age = min_age
        # a socket-backed CRIClient has no clock; monotonic matches the
        # FakeCRI default
        self.clock = clock or getattr(cri, "clock", time.monotonic)
        self.last_freed_bytes = 0

    def delete_unused_images(self) -> int:
        """Delete EVERY unused image regardless of thresholds — what the
        eviction manager's reclaimNodeLevelResources calls
        (eviction_manager.go → imageGC.DeleteUnusedImages). Returns bytes
        freed."""
        freed = 0
        for img in self.cri.list_images():
            if not img.get("inUse"):
                self.cri.remove_image(img["name"])
                freed += int(img.get("sizeBytes", 0))
        return freed

    def garbage_collect(self) -> int:
        """One GC pass; returns bytes freed (0 when below the high mark)."""
        fs = self.cri.image_fs_info()
        capacity = max(int(fs.get("capacityBytes", 0)), 1)
        used = int(fs.get("usedBytes", 0))
        usage_pct = 100 * used / capacity
        self.last_freed_bytes = 0
        if usage_pct <= self.high:
            return 0
        target = capacity * self.low // 100
        to_free = used - target
        now = self.clock()
        candidates = sorted(
            (img for img in self.cri.list_images()
             if not img.get("inUse")
             and now - float(img.get("lastUsed", 0.0)) >= self.min_age),
            key=lambda i: float(i.get("lastUsed", 0.0)))
        freed = 0
        for img in candidates:
            if freed >= to_free:
                break
            self.cri.remove_image(img["name"])
            freed += int(img.get("sizeBytes", 0))
        self.last_freed_bytes = freed
        return freed
