"""Metrics: Prometheus-style registry with text exposition.

Analog of `staging/src/k8s.io/component-base/metrics` (the Prometheus
client wrapper every binary shares): Counter/Gauge/Histogram vectors with
label sets, a process-wide default registry, and the text format served at
/metrics (`pkg/scheduler/metrics/metrics.go` registers into exactly this).

Concurrency contract (audited for ISSUE 7 — the serving loop, the
supervisor's watchdog worker, the background prober, the prewarmer's
compile thread and the consistency sweeper all touch these concurrently):
every read AND write of a metric's state happens under that metric's own
`_mu`, so increments are never lost (tests/test_telemetry.py hammers this).
Lock ordering is registry → metric only (`expose_text` holds the registry
lock while each metric exposes under its own); metric methods never take
the registry lock, so the ordering cannot invert.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0)


def escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping (text exposition format
    spec: backslash, double-quote and line-feed MUST be escaped — a tenant
    name or pod key containing any of them would otherwise corrupt the
    whole exposition for every scraper)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def escape_help(text: str) -> str:
    """HELP-line escaping per the exposition format: backslash and
    line-feed only (quotes are legal in HELP text)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str]):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._mu = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return tuple(labels.get(n, "") for n in self.label_names)

    def _header(self) -> List[str]:
        """Conformant `# HELP` / `# TYPE` preamble (HELP skipped when the
        help text is empty — the format allows absence, not a blank)."""
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {escape_help(self.help)}")
        out.append(f"# TYPE {self.name} {self.TYPE}")
        return out

    @staticmethod
    def _fmt_labels(names: Sequence[str], values: Sequence[str],
                    extra: str = "") -> str:
        pairs = [f'{n}="{escape_label_value(v)}"'
                 for n, v in zip(names, values)]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""


class Counter(_Metric):
    TYPE = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._mu:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._mu:
            return self._values.get(self._key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label combination (tests/bench assert aggregate
        outcomes — e.g. `drf_clamped >= 1` across all tenants — without
        enumerating the label space)."""
        with self._mu:
            return sum(self._values.values())

    def expose(self) -> List[str]:
        with self._mu:
            out = self._header()
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}"
                           f"{self._fmt_labels(self.label_names, k)} {v}")
            if not self._values and not self.label_names:
                # scalar metrics expose 0 before first touch; labeled vectors
                # must NOT emit a bogus unlabeled series
                out.append(f"{self.name} 0")
            return out


class Gauge(Counter):
    TYPE = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._mu:
            self._values[self._key(labels)] = value

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(self, name, help_, label_names=(),
                 buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, **labels) -> None:
        # counts are stored PER BUCKET (non-cumulative) and accumulated at
        # expose/quantile time: observe is on the per-pod hot path (the
        # e2e latency histogram fires once per Binding), and a Python loop
        # over every bucket per observation was a measurable slice of the
        # telemetry overhead budget — one bisect is not
        with self._mu:
            k = self._key(labels)
            counts = self._counts.setdefault(k, [0] * len(self.buckets))
            i = bisect.bisect_left(self.buckets, value)
            if i < len(counts):
                counts[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def observe_many(self, values: Sequence[float], **labels) -> None:
        """Batch observe: one lock acquisition (and one dict resolve) for a
        whole wave's samples. The e2e latency histogram fires once per
        Binding — thousands of times per bulk wave, and the micro-wave
        regime multiplies the wave count on top — and the per-call
        lock+lookup overhead of `observe` was a measurable slice of the
        ≤2% telemetry budget at that rate."""
        if not values:
            return
        bl = self.buckets
        nb = len(bl)
        bis = bisect.bisect_left
        with self._mu:
            k = self._key(labels)
            counts = self._counts.setdefault(k, [0] * nb)
            s = 0.0
            for v in values:
                i = bis(bl, v)
                if i < nb:
                    counts[i] += 1
                s += v
            self._sums[k] = self._sums.get(k, 0.0) + s
            self._totals[k] = self._totals.get(k, 0) + len(values)

    def count(self, **labels) -> int:
        with self._mu:
            return self._totals.get(self._key(labels), 0)

    def sum_value(self, **labels) -> float:
        with self._mu:
            return self._sums.get(self._key(labels), 0.0)

    def quantile(self, q: float, **labels) -> float:
        """Approximate quantile from bucket boundaries (for tests/SLO checks;
        Prometheus computes this server-side with histogram_quantile)."""
        with self._mu:
            k = self._key(labels)
            total = self._totals.get(k, 0)
            if not total:
                return 0.0
            target = q * total
            acc = 0
            for i, b in enumerate(self.buckets):
                acc += self._counts[k][i]
                if acc >= target:
                    return b
            return float("inf")

    def expose(self) -> List[str]:
        with self._mu:
            out = self._header()
            for k in sorted(self._totals):
                acc = 0
                for i, b in enumerate(self.buckets):
                    # no backslashes inside f-string expressions: that is a
                    # Python ≥3.12 feature and this tree must import on 3.10
                    le = 'le="%s"' % b
                    acc += self._counts[k][i]  # cumulative le semantics
                    out.append(
                        f"{self.name}_bucket"
                        f"{self._fmt_labels(self.label_names, k, le)}"
                        f" {acc}")
                le_inf = 'le="+Inf"'
                out.append(f"{self.name}_bucket"
                           f"{self._fmt_labels(self.label_names, k, le_inf)}"
                           f" {self._totals[k]}")
                out.append(f"{self.name}_sum"
                           f"{self._fmt_labels(self.label_names, k)}"
                           f" {self._sums[k]}")
                out.append(f"{self.name}_count"
                           f"{self._fmt_labels(self.label_names, k)}"
                           f" {self._totals[k]}")
            return out


class Registry:
    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._mu:
            # idempotent by name (MustRegister panics; we return the existing
            # collector so module reloads in tests stay cheap)
            return self._metrics.setdefault(metric.name, metric)

    def counter(self, name, help_="", labels=()) -> Counter:
        return self.register(Counter(name, help_, labels))  # type: ignore

    def gauge(self, name, help_="", labels=()) -> Gauge:
        return self.register(Gauge(name, help_, labels))  # type: ignore

    def histogram(self, name, help_="", labels=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_, labels, buckets))  # type: ignore

    def expose_text(self) -> str:
        with self._mu:
            lines: List[str] = []
            for m in self._metrics.values():
                lines.extend(m.expose())
            return "\n".join(lines) + "\n"


DEFAULT_REGISTRY = Registry()
