"""Feature gates.

Analog of `staging/src/k8s.io/component-base/featuregate` +
`pkg/features/kube_features.go`: named alpha/beta/GA switches parsed from
`--feature-gates=A=true,B=false` strings, queried process-wide.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    pre_release: str = ALPHA
    locked_to_default: bool = False  # GA features that can no longer change


class FeatureGate:
    def __init__(self, known: Dict[str, FeatureSpec]):
        self._mu = threading.Lock()
        self._known = dict(known)
        self._enabled: Dict[str, bool] = {}

    def enabled(self, name: str) -> bool:
        with self._mu:
            if name in self._enabled:
                return self._enabled[name]
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            return spec.default

    def set(self, name: str, value: bool) -> None:
        with self._mu:
            spec = self._known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            if spec.locked_to_default and value != spec.default:
                raise ValueError(f"feature {name} is locked to "
                                 f"{spec.default}")
            self._enabled[name] = value

    def set_from_map(self, overrides: Dict[str, bool]) -> None:
        for k, v in overrides.items():
            self.set(k, v)

    def parse(self, s: str) -> None:
        """--feature-gates=A=true,B=false."""
        for part in s.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, val = part.partition("=")
            self.set(name.strip(), val.strip().lower() in ("true", "1", "t"))

    def known(self) -> Dict[str, FeatureSpec]:
        with self._mu:
            return dict(self._known)


# The gates the reference ships that map onto capabilities we implement
# (pkg/features/kube_features.go; EvenPodsSpread:477 is the headline one)
DEFAULT_FEATURE_GATES = FeatureGate({
    "EvenPodsSpread": FeatureSpec(default=True, pre_release=BETA),
    "TaintBasedEvictions": FeatureSpec(default=True, pre_release=BETA),
    "NodeLease": FeatureSpec(default=True, pre_release=BETA),
    "ScheduleDaemonSetPods": FeatureSpec(default=True, pre_release=BETA),
    "PodPriority": FeatureSpec(default=True, pre_release=GA,
                               locked_to_default=True),
    "VolumeScheduling": FeatureSpec(default=True, pre_release=GA,
                                    locked_to_default=True),
    # TPU-native additions
    "TPUBatchScheduling": FeatureSpec(default=True, pre_release=BETA),
    "TPUPreemption": FeatureSpec(default=True, pre_release=BETA),
})
