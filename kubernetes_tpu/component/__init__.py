"""Shared component infrastructure: metrics, feature gates, tracing, version.

TPU-native analog of SURVEY.md layer 11
(`staging/src/k8s.io/component-base`).
"""

from kubernetes_tpu.component.featuregate import (
    ALPHA,
    BETA,
    DEFAULT_FEATURE_GATES,
    FeatureGate,
    FeatureSpec,
    GA,
)
from kubernetes_tpu.component.metrics import (
    Counter,
    DEFAULT_REGISTRY,
    Gauge,
    Histogram,
    Registry,
)
from kubernetes_tpu.component.trace import Trace, device_step_marker

VERSION = {"gitVersion": "v1.17.0-tpu.1", "major": "1", "minor": "17+",
           "platform": "jax/xla-tpu"}

__all__ = ["ALPHA", "BETA", "Counter", "DEFAULT_FEATURE_GATES",
           "DEFAULT_REGISTRY", "FeatureGate", "FeatureSpec", "GA", "Gauge",
           "Histogram", "Registry", "Trace", "VERSION",
           "device_step_marker"]
