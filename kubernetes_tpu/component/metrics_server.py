"""metrics-server: the resource-metrics API (metrics.k8s.io/v1beta1),
served through the aggregation layer.

The reference's HPA never reads kubelet stats directly: the kubelet serves
/stats/summary, the out-of-tree metrics-server scrapes every node, and the
aggregator exposes the result as PodMetrics/NodeMetrics under
`metrics.k8s.io` (an APIService), which the HPA's metrics client queries
(`pkg/controller/podautoscaler/horizontal.go:96` via
`pkg/controller/podautoscaler/metrics`). This module fills the
metrics-server seat:

  * scrapes a set of kubelets' `stats_summary()` on an interval,
  * registers APIService `v1beta1.metrics.k8s.io` with an in-process
    backend (apiserver/aggregator.py `register_local_backend` — the same
    deviation family as PARITY #13: backends are in-process handles, not
    cluster-IP HTTPS endpoints),
  * serves GET /apis/metrics.k8s.io/v1beta1/{namespaces/{ns}/}pods[/{name}]
    and /nodes[/{name}] in the reference wire shape
    (PodMetrics.containers[].usage {cpu: "Nm", memory: "NKi"}).

So the pipeline is the reference's, end to end: CRI ListContainerStats →
kubelet stats_summary → metrics-server scrape → aggregated API → HPA
metrics client (controllers/autoscale.py ResourceMetricsProvider).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.apiserver import aggregator
from kubernetes_tpu.machinery import errors, meta

Obj = Dict[str, Any]

APISERVICE_NAME = "v1beta1.metrics.k8s.io"
GROUP = "metrics.k8s.io"
VERSION = "v1beta1"


class MetricsServer:
    """Scrape loop + aggregated-API backend."""

    def __init__(self, client,
                 kubelets: Sequence = (),
                 scrape_interval: float = 2.0,
                 clock: Callable[[], float] = time.time):
        self.client = client
        self._kubelets = list(kubelets)
        self.scrape_interval = scrape_interval
        self.clock = clock
        self._mu = threading.Lock()
        # (ns, pod) → PodMetrics;  node → NodeMetrics
        self._pods: Dict[Tuple[str, str], Obj] = {}
        self._nodes: Dict[str, Obj] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_kubelet(self, kubelet) -> None:
        with self._mu:
            self._kubelets.append(kubelet)

    # -- scrape ---------------------------------------------------------- #

    def scrape_once(self) -> None:
        now = meta.now_rfc3339()
        pods: Dict[Tuple[str, str], Obj] = {}
        nodes: Dict[str, Obj] = {}
        with self._mu:
            kubelets = list(self._kubelets)
        for k in kubelets:
            try:
                summary = k.stats_summary()
            except Exception:  # noqa: BLE001 — a dead node skips a window
                continue
            node_cpu = node_mem = 0
            for p in summary.get("pods", []):
                node_cpu += p["cpuMilli"]
                node_mem += p["memoryBytes"]
                pods[(p["namespace"], p["name"])] = {
                    "kind": "PodMetrics",
                    "apiVersion": f"{GROUP}/{VERSION}",
                    "metadata": {"name": p["name"],
                                 "namespace": p["namespace"]},
                    "timestamp": now,
                    "window": f"{self.scrape_interval:g}s",
                    "containers": [
                        {"name": c["name"],
                         "usage": {"cpu": f'{c["cpuMilli"]}m',
                                   "memory":
                                   f'{c["memoryBytes"] // 1024}Ki'}}
                        for c in p.get("containers", [])],
                }
            nodes[summary.get("node", "")] = {
                "kind": "NodeMetrics",
                "apiVersion": f"{GROUP}/{VERSION}",
                "metadata": {"name": summary.get("node", "")},
                "timestamp": now,
                "window": f"{self.scrape_interval:g}s",
                "usage": {"cpu": f"{node_cpu}m",
                          "memory": f"{node_mem // 1024}Ki"},
            }
        with self._mu:
            self._pods = pods
            self._nodes = nodes

    def _loop(self) -> None:
        self.scrape_once()
        while not self._stop.wait(self.scrape_interval):
            self.scrape_once()

    # -- aggregated-API surface ------------------------------------------ #

    def _handle(self, method: str, path: str, query: Dict[str, str],
                body: Optional[Obj]) -> Tuple[int, Obj]:
        if method != "GET":
            raise errors.new_method_not_supported("podmetrics", method)
        parts = [p for p in path.split("/") if p]
        # /apis/metrics.k8s.io/v1beta1/...
        rest = parts[3:]
        ns = ""
        if rest and rest[0] == "namespaces" and len(rest) >= 2:
            ns, rest = rest[1], rest[2:]
        kind = rest[0] if rest else ""
        name = rest[1] if len(rest) > 1 else ""
        with self._mu:
            if kind == "nodes":
                if name:
                    m = self._nodes.get(name)
                    if m is None:
                        raise errors.new_not_found("nodes.metrics.k8s.io",
                                                   name)
                    return 200, m
                return 200, {"kind": "NodeMetricsList",
                             "apiVersion": f"{GROUP}/{VERSION}",
                             "items": sorted(self._nodes.values(),
                                             key=lambda m:
                                             meta.name(m))}
            if kind == "pods":
                if name:
                    m = self._pods.get((ns or "default", name))
                    if m is None:
                        raise errors.new_not_found("pods.metrics.k8s.io",
                                                   name)
                    return 200, m
                items = [m for (pns, _), m in self._pods.items()
                         if not ns or pns == ns]
                return 200, {"kind": "PodMetricsList",
                             "apiVersion": f"{GROUP}/{VERSION}",
                             "items": sorted(items,
                                             key=lambda m: meta.name(m))}
        raise errors.new_not_found("metrics.k8s.io", kind)

    # -- lifecycle ------------------------------------------------------- #

    def install(self) -> "MetricsServer":
        """Register the APIService + in-process backend (the kubectl-visible
        face of metrics-server)."""
        aggregator.register_local_backend(APISERVICE_NAME, self._handle)
        svc = {"apiVersion": "apiregistration.k8s.io/v1",
               "kind": "APIService",
               "metadata": {"name": APISERVICE_NAME},
               "spec": {"group": GROUP, "version": VERSION,
                        "groupPriorityMinimum": 100, "versionPriority": 100}}
        try:
            self.client.resource("apiregistration.k8s.io", "v1",
                                 "apiservices", False).create(svc, "")
        except errors.StatusError as e:
            if not errors.is_already_exists(e):
                raise
        return self

    def start(self) -> "MetricsServer":
        self.install()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metrics-server-scrape")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        aggregator.unregister_local_backend(APISERVICE_NAME)
        try:
            self.client.resource("apiregistration.k8s.io", "v1",
                                 "apiservices", False).delete(
                                     APISERVICE_NAME, "")
        except errors.StatusError:
            pass
