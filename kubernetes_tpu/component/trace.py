"""Operation tracing: spans with steps + slow-op logging.

Analog of `vendor/k8s.io/utils/trace/trace.go` (utiltrace) as used by the
scheduler (`core/generic_scheduler.go:188-217` Step/LogIfLong): a Trace
collects timed steps; if the whole operation exceeds a threshold, the steps
are emitted so slow cycles are explainable. Also the hook point for JAX
profiler ranges on device-dispatch steps.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")


#: default LogIfLong threshold (the reference's 100ms scheduler trace bound)
DEFAULT_THRESHOLD = 0.1


class Trace:
    def __init__(self, name: str, clock: Callable[[], float] = time.monotonic,
                 threshold: float = DEFAULT_THRESHOLD, **fields):
        self.name = name
        self.fields = fields
        self.clock = clock
        self.threshold = threshold
        self.start = clock()
        self.steps: List[Tuple[float, str]] = []
        self._ended: Optional[float] = None

    def step(self, msg: str) -> None:
        self.steps.append((self.clock(), msg))

    def duration(self) -> float:
        return (self._ended or self.clock()) - self.start

    def log_if_long(self, threshold: float,
                    sink: Optional[Callable[[str], None]] = None) -> bool:
        """utiltrace.LogIfLong: emit the step timeline when total > threshold.
        Returns True if it logged."""
        self._ended = self.clock()
        total = self.duration()
        if total < threshold:
            return False
        emit = sink or (lambda s: logger.warning("%s", s))
        fs = ",".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" ({fs}) took {total * 1000:.1f}ms '
                 f"(threshold {threshold * 1000:.0f}ms):"]
        prev = self.start
        for ts, msg in self.steps:
            lines.append(f"  +{(ts - prev) * 1000:.1f}ms {msg}")
            prev = ts
        emit("\n".join(lines))
        return True

    def __enter__(self) -> "Trace":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # exiting on an exception: the operation's failure path already
        # reports (and the timeline would blame the step that happened to
        # be open when the raise unwound) — only log clean slow exits
        if exc_type is None:
            self.log_if_long(self.threshold)


def device_step_marker(name: str):
    """JAX profiler named scope for device-dispatch steps — shows up in TPU
    profiler timelines (the jax.profiler analog of the reference's pprof)."""
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — profiling must never break the op
        import contextlib
        return contextlib.nullcontext()
