"""Tenant stacking: K per-tenant ClusterTables behind one leading axis.

The fleet's layout invariant is that every tenant's encoded cluster shares
ONE capacity shape — the fleet bucket — so a single vmap'd program serves
all of them. That bucket is the field-wise union of the tenants' Dims
(`fleet_dims`), fed back into every tenant's cache snapshot as `base_dims`:
`state/cache.py` seeds its capacity growth from the union, so when ANY
tenant grows an axis, every other tenant's next snapshot pads up to match.
Padding semantics are exactly the ones `parallel/mesh.py:pad_node_tables`
already proves for the node axis — unoccupied slots are inert rows
(valid=False, zero capacity, -1 ids) that no engine can admit a pod onto —
applied here by the encoder's own bucketed staging, one axis at a time.

`FleetStack` keeps the STACKED trees resident on device (optionally sharded
across a fleet mesh — 1-D: each chip owns whole tenants, no collectives;
2-D `(TENANT_AXIS, NODE_AXIS)`: each tenant's node planes additionally
split across a device row, with cross-row argmax/psum inserted by GSPMD
exactly as the single-cluster node mesh proves): a tenant whose snapshot object
changed since the last tick scatters its row through the SAME donated-patch
path the mesh-resident single-cluster snapshot uses
(`state/cache.py:_patch_resident`); unchanged tenants cost nothing, and the
mesh steady state (every tenant changed) takes one sharded full restack
instead of replicating the whole stack to every device as patch operands.
"""

from __future__ import annotations

from dataclasses import replace
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..state.dims import Dims

# floor on the padded tenant axis: K buckets to a multiple of the fleet
# mesh (or stays exact single-device), so the stacked shape signature is
# stable as tenants join
RC_TENANT_MIN = 1


def fleet_dims(tenant_dims: Sequence[Dims],
               base: Optional[Dims] = None) -> Dims:
    """The shared fleet bucket: field-wise union of every tenant's Dims
    (and the configured floor). `has_node_name` is cleared — it is a
    per-tick routing fact the server re-derives, not a capacity."""
    d = base or Dims()
    for td in tenant_dims:
        d = d.union(td)
    return replace(d, has_node_name=False)


def stack_blocks(blocks: Sequence[Tuple]):
    """Stack per-tenant pytrees (tables, pending, existing, (uk, ev)) into
    one tree with a leading tenant axis on every leaf."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def empty_tenant_block(d: Dims):
    """An inert PAD tenant: an empty cluster at the fleet bucket — every
    node row invalid, every pending/existing slot invalid, so it can never
    admit a pod (the tenant-axis analog of pad_node_tables' inert rows).
    Pads K up to the fleet mesh's divisibility requirement."""
    from ..state.arrays import ClusterTables
    from ..state.encode import Encoder

    enc = Encoder()
    tables = ClusterTables(
        nodes=enc.empty_node_arrays(d),
        reqs=enc.build_req_table(d),
        labelsets=enc.build_labelset_table(d),
        nterms=enc.build_nterm_table(d),
        tolsets=enc.build_tolset_table(d),
        portsets=enc.build_portset_table(d),
        terms=enc.build_term_table(d),
        classes=enc.build_class_table(d),
        images=enc.build_image_table(d),
        zone_keys=enc.build_zone_keys(),
        volsets=enc.build_volset_table(d),
        drv_masks=enc.build_drv_masks(d),
    )
    pending = enc.build_pod_arrays([], d, capacity=d.P)
    existing = enc.build_pod_arrays([], d, capacity=d.E)
    return (tables, pending, existing,
            (jnp.int32(0), jnp.int32(0)))


def abstract_fleet_args(d: Dims, K: int, mesh=None):
    """ShapeDtypeStruct pytrees for one `fleet/cycle.py:_fleet_cycle_impl`
    call: the single-cluster abstract args (sched/prewarm.py — shapes and
    pytree structure BY CONSTRUCTION the live ones) with a leading tenant
    axis of K prepended, plus the [K] quota vector and the shared traced
    scalars. With a tenant-axis `mesh`, every stacked leaf carries the
    fleet sharding (leading axis split) and the scalars replicate — the
    AOT compile produces the same GSPMD placement the live fleet path
    dispatches."""
    from ..ops.lattice import default_engine_config
    from ..sched.prewarm import abstract_cycle_args

    (tables, pending, keys, existing, _hw, _ecfg,
     _gang) = abstract_cycle_args(d)
    sh = rep = None
    tables_sh = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.mesh import fleet_sharding, fleet_shardings

        sh = fleet_sharding(mesh)
        rep = NamedSharding(mesh, PartitionSpec())
        # the stacked node planes shard (TENANT_AXIS, NODE_AXIS) on a 2-D
        # mesh; fleet_shardings is the SAME helper shard_fleet places
        # with, so AOT input shardings cannot drift from the live stack
        tables_sh = fleet_shardings(tables, mesh)

    stack = lambda t: jax.tree.map(
        lambda a: jax.ShapeDtypeStruct((K,) + a.shape, a.dtype,
                                       sharding=sh), t)
    if tables_sh is not None:
        stack_tables = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct((K,) + a.shape, a.dtype,
                                              sharding=s),
            tables, tables_sh)
    else:
        stack_tables = stack(tables)
    vec = lambda dt: jax.ShapeDtypeStruct((K,), dt, sharding=sh)
    scalar_f32 = jax.ShapeDtypeStruct((), jnp.float32, sharding=rep)
    return (stack_tables, stack(pending),
            (vec(jnp.int32), vec(jnp.int32)), stack(existing),
            vec(jnp.float32), scalar_f32,
            jax.tree.map(lambda _: scalar_f32, default_engine_config()))


class FleetStack:
    """The resident stacked fleet state and its per-tenant patch path.

    `refresh` compares each tenant's Snapshot by object identity (the cache
    returns the SAME object when nothing changed — generation, pending set,
    placement all equal), so idle tenants cost zero device work per tick;
    changed tenants scatter their row into the resident stacked tree via
    the donated patch path (`state/cache.py:_patch_resident` — XLA
    aliases the update in place, and the is_deleted assert proves it).
    Shape changes (the fleet bucket grew, a tenant joined) rebuild the
    whole stack — the fleet analog of the cache's full-snapshot path."""

    def __init__(self, mesh=None):
        # fleet jax Mesh (parallel/mesh.py): 1-D tenant axis, 2-D
        # tenant × node-shard, or None (single device)
        self.mesh = mesh
        self.block = None           # (tables, pending, existing, (uk, ev))
        self.dims: Optional[Dims] = None
        self.K = 0                  # padded leading dim (the stack's K)
        self.live = 0               # live (unpadded) tenant count
        self._snaps: List = []
        self._keys_host: List[Tuple[int, int]] = []
        # accounting mirrors the cache's resident-state counters; the
        # failure counter uses the cache's NAME so _patch_resident (the one
        # shared donation check, gated by KTPU_MESH_DONATION_STRICT for
        # fleet and single-cluster alike) can bump it duck-typed
        self.full_restacks = 0
        self.donated_patches = 0
        self.resident_donation_failures = 0

    @property
    def donation_failures(self) -> int:
        return self.resident_donation_failures

    def _put(self, tree):
        if self.mesh is not None:
            from ..parallel.mesh import shard_fleet

            return shard_fleet(tree, self.mesh)
        return jax.device_put(tree)

    def _put_rep(self, tree):
        """Patch operands (row indices + single-tenant rows) replicate
        across the fleet mesh; GSPMD routes the scatter to the owning
        shard."""
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(
                tree, NamedSharding(self.mesh, PartitionSpec()))
        return jax.device_put(tree)

    def invalidate(self) -> None:
        """Drop the resident stacked tree WITHOUT touching its buffers.
        Called when they may still be held by an abandoned dispatch's
        zombie worker, or live on a lost backend: donating (or even
        scattering onto) such buffers would corrupt an in-flight read or
        dispatch onto dead hardware — the next refresh full-restacks onto
        fresh buffers instead (the fleet analog of the cache's
        `_dispatch_inflight` copy gate and degraded-mode re-encode)."""
        self.block = None
        self.dims = None
        self._snaps = []
        self._keys_host = []

    def padded_k(self, live: int) -> int:
        """K pads to the TENANT-AXIS width of the mesh (not the flat device
        count — on a 2-D mesh each tenant row spans node-shard chips)."""
        if self.mesh is None:
            return max(live, RC_TENANT_MIN)
        from ..parallel.mesh import fleet_mesh_shape, padded_tenant_count

        kt, _ = fleet_mesh_shape(self.mesh)
        return padded_tenant_count(max(live, RC_TENANT_MIN), kt)

    def _node_shards(self) -> int:
        if self.mesh is None:
            return 1
        from ..parallel.mesh import fleet_mesh_shape

        return fleet_mesh_shape(self.mesh)[1]

    def _node_pad(self, block):
        """Pad the stacked tables' per-tenant node axis to the node-shard
        width (2-D mesh, directly-constructed shapes only — the server
        grows the fleet bucket so the serving path never pads here)."""
        kn = self._node_shards()
        if kn <= 1:
            return block
        from ..parallel.mesh import pad_fleet_node_tables

        return (pad_fleet_node_tables(block[0], kn),) + tuple(block[1:])

    def refresh(self, snaps: Sequence, keys: Sequence[Tuple], d: Dims):
        """Bring the resident stack current with this tick's per-tenant
        snapshots. Returns the padded tenant count K of the stacked tree."""
        live = len(snaps)
        Kp = self.padded_k(live)
        keys_host = [(int(uk), int(ev)) for uk, ev in keys]
        base = replace(d, has_node_name=False)
        kn = self._node_shards()
        # a bucket N that doesn't divide the node-shard row can't take the
        # shape-stable patch path (resident rows are node-padded, staging
        # rows are not) — restack with per-tenant inert node padding
        n_padded = kn > 1 and int(d.N) % kn != 0
        if (self.block is None or self.dims != base or self.K != Kp
                or self.live != live or n_padded):
            blocks = [(s.tables, s.pending, s.existing, k)
                      for s, k in zip(snaps, keys)]
            if Kp > live:
                pad = empty_tenant_block(d)
                blocks.extend([pad] * (Kp - live))
            self.block = self._put(self._node_pad(stack_blocks(blocks)))
            self.dims = base
            self.K = Kp
            self.live = live
            self.full_restacks += 1
        else:
            from ..state.cache import _patch_resident

            changed = [
                (k, snap, kh)
                for k, (snap, kh) in enumerate(zip(snaps, keys_host))
                if not (snap is self._snaps[k]
                        and kh == self._keys_host[k])]
            if (self.mesh is not None and changed
                    and len(changed) == live):
                # mesh steady state: EVERY tenant changed, so the patch
                # operands ARE the whole fleet state — and _put_rep
                # replicates them, uploading the full state once PER
                # DEVICE before the scatter. A sharded full restack
                # uploads it exactly once, split across the shards.
                blocks = [(s.tables, s.pending, s.existing, k)
                          for s, k in zip(snaps, keys)]
                if Kp > live:
                    blocks.extend([empty_tenant_block(d)] * (Kp - live))
                self.block = self._put(stack_blocks(blocks))
                self.full_restacks += 1
            elif changed:
                # ONE batched scatter for every changed tenant: in steady
                # state all K tenants pop a fresh batch each tick, and K
                # sequential single-row dispatches would put K host-device
                # round-trips on the hot path in front of the cycle.
                # The changed count is bucketed (cache._pad_patch: pad by
                # repeating the first entry — the repeated .set of
                # identical rows is idempotent) so the patch kernel
                # compiles once per power-of-two changed-tenant count, not
                # once per distinct count between 1 and K
                from ..state.cache import _pad_patch
                from ..state.dims import bucket as _bucket

                kb = _bucket(len(changed))
                padded = list(changed) + [changed[0]] * (kb - len(changed))
                rows = stack_blocks([
                    (snap.tables, snap.pending, snap.existing,
                     (jnp.int32(kh[0]), jnp.int32(kh[1])))
                    for _, snap, kh in padded])
                idx = self._put_rep(jnp.asarray(_pad_patch(
                    [k for k, _, _ in changed], kb), jnp.int32))
                rows = self._put_rep(rows)
                before = self.resident_donation_failures
                self.block = _patch_resident(self.block, idx, rows,
                                             donate=True, cache=self)
                if self.resident_donation_failures == before:
                    self.donated_patches += len(changed)
        self._snaps = list(snaps)
        self._keys_host = keys_host
        return self.K

    # convenience accessors for the dispatch layer
    @property
    def tables(self):
        return self.block[0]

    @property
    def pending(self):
        return self.block[1]

    @property
    def existing(self):
        return self.block[2]

    @property
    def keys(self):
        return self.block[3]
