"""The fleet tick: K tenant cycles as ONE vmap'd XLA dispatch.

The single-cluster cycle body (ops/lattice.py build_cycle → assignment
engine, the exact sequence `sched/cycle.py:_schedule_batch_impl` traces) is
vmapped over the leading tenant axis of the stacked tables. Tenants are
independent by construction — no collective crosses the tenant axis — so on
a tenant-axis mesh (parallel/mesh.py TENANT_AXIS) each chip evaluates its
own tenants and the dispatch count per tick is exactly one, which is the
budget the fleet bench stage enforces (`fleet_dispatches_per_tick=1`).

The DRF quota clamp (fleet/quota.py) runs INSIDE the same program — a pure
pre-mask on `pending.valid` — so quota enforcement costs no extra dispatch
and per-tenant placements stay bit-equal to a solo run under the same clamp
(vmap of these engines is element-wise exact; the bit-equality suite in
tests/test_fleet.py holds the line).

Engines: 'waves' (default), 'scan', and 'runs' — the run-collapsed engine's
static scan bound `rc` is shared across the stack (the max of the tenants'
RunPlans; masking merges/shrinks runs, never splits, so a shared upper
bound is sound for every tenant). Gang-bearing tenant batches are NOT
vmapped (group-atomic admission runs host rejection rounds); the server
routes those tenants through their own single-cluster wave.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.assign import assign_batch, initial_state
from ..ops.lattice import build_cycle, default_engine_config
from .quota import drf_admission_row

Array = jnp.ndarray


class FleetResult(NamedTuple):
    """One fleet tick's device outputs, all [K, …]."""

    node: Array      # [K, P] i32 chosen node row per tenant, -1 none
    feasible: Array  # [K, P] bool
    admitted: Array  # [K, P] bool — the DRF pre-mask (valid ∧ under-quota);
                     # valid ∧ ¬admitted pods were quota-clamped this tick
                     # (requeue promptly, no failure verdict)
    share: Array     # [K] f32 pre-tick dominant share per tenant
    dom: Array       # [K, P] f32 per-pod dominant demand (violation check)


def fleet_signature(K: int) -> int:
    """The tenant-stack signature that flows into every prewarm executable
    key (sched/prewarm.py `fleet=` slot): the padded stack width. Presence
    alone isolates fleet Compileds from single-cluster ones."""
    return int(K)


@functools.partial(jax.jit, static_argnums=(3, 5, 9, 10))
def _fleet_cycle_impl(
    tables,          # stacked ClusterTables [K, …]
    pending,         # stacked PodArrays [K, P]
    keys,            # (uk [K], ev [K]) per-tenant interned taint-key ids
    D: int,
    existing,        # stacked PodArrays [K, E]
    engine: str,
    quota,           # [K] f32 DRF quota fraction per tenant
    hard_weight=1.0,
    ecfg=None,
    rc: int = 0,
    explain: bool = False,
):
    from ..ops.runs import assign_runs
    from ..ops.waves import assign_waves

    def body(t, pe, ky, ex, q):
        uk, ev = ky
        cyc = build_cycle(t, ex, uk, ev, D, hard_weight, ecfg)
        admitted, share, dom = drf_admission_row(t, pe, q)
        clamped = pe._replace(valid=admitted)
        init = initial_state(t, cyc)
        if engine == "scan":
            res = assign_batch(t, cyc, clamped, init)
        elif engine == "runs":
            res = assign_runs(t, cyc, clamped, init, rc)
        else:
            res = assign_waves(t, cyc, clamped, init)
        exp = None
        if explain:
            # ISSUE 10: fleet mode attributes PER TENANT inside the same
            # vmap'd dispatch — the class-collapsed reduction per tenant
            # row (quota-clamped pods carry valid=False and zero out; the
            # commit loop requeues them before ever reading attribution)
            from ..ops.assign import explain_assignments

            exp = explain_assignments(t, cyc, clamped, res,
                                      granularity="class")
        return res.node, res.feasible, admitted, share, dom, exp

    node, feas, admitted, share, dom, exp = jax.vmap(body)(
        tables, pending, keys, existing, quota)
    res = FleetResult(node=node, feasible=feas, admitted=admitted,
                      share=share, dom=dom)
    return (res, exp) if explain else res


def dispatch_fleet(tables, pending, keys, D, existing, engine, quota,
                   hard_weight: float = 1.0, ecfg=None, rc: int = 0,
                   dims=None, prewarmer=None, mesh=None,
                   explain: bool = False):
    """The fleet analog of sched/cycle.py `_schedule_batch`: normalize the
    traced config scalars, probe the prewarmer for an AOT executable under
    the FLEET key (dims, engine, rc, fleet=K, mesh) — a single-cluster
    Compiled can never answer, the key slot forbids it — and fall through
    to the ordinary jit. With `explain` (ISSUE 10, KTPU_EXPLAIN) the
    prewarmed executables are bypassed (they were compiled without the
    attribution tail) and the result is (FleetResult, stacked [K, …]
    ExplainResult)."""
    from ..ops.lattice import strong_engine_config

    K = int(quota.shape[0])
    ecfg = strong_engine_config(ecfg) if ecfg is not None \
        else default_engine_config()
    hw = jnp.float32(hard_weight)
    if prewarmer is not None and dims is not None and not explain:
        compiled = prewarmer.lookup(dims, engine, (), False, mesh=mesh,
                                    rc=rc, fleet=fleet_signature(K))
        if compiled is not None:
            try:
                return FleetResult(*compiled(tables, pending, keys,
                                             existing, quota, hw, ecfg))
            except TypeError:
                pass  # aval/pytree drift — take the ordinary jit path
    return _fleet_cycle_impl(tables, pending, keys, D, existing, engine,
                             quota, hw, ecfg, rc, explain)
