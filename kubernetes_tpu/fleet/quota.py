"""Dominant-resource-fairness quotas as tensor ops over the stacked batch.

The fairness-as-policy framing (Gavel, PAPERS.md) re-expressed over the
existing mask/score lattice: a tenant's quota is a fraction of its own
cluster's capacity, its *dominant share* is the max over resource dims of
used/capacity (the DRF dominant resource), and admission is clamped so one
tick can never push a tenant past its quota — a tenant at quota contributes
inert rows this tick, exactly as an invalid pod would.

The clamp is a PURE PRE-MASK on `pending.valid`, computed inside the fleet
dispatch (fleet/cycle.py) from the same stacked capacity/usage planes the
engines read: downstream, the engines see a smaller valid set and nothing
else, so per-tenant placements are bit-equal to running that tenant alone
under the same clamp — the property tests/test_fleet.py enforces.

The per-pod shape of the clamp is a prefix waterfill in queue order
(priority desc, creation asc — ops/assign.py queue_order): pod i admits iff
the tenant's pre-tick dominant share plus the cumulative dominant demand of
pods 0..i stays ≤ quota. A tenant under quota admits exactly the prefix its
headroom funds; a tenant at/over quota admits nothing with nonzero demand.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ..ops.assign import queue_order
from ..state.arrays import ClusterTables, PodArrays

Array = jnp.ndarray

# slack on the quota comparison: float32 shares accumulate over the prefix
# cumsum, and a tenant sitting EXACTLY at quota must not flap on the last
# ulp of a sum
DRF_EPS = 1e-6


def capacity_usage_planes(tables: ClusterTables) -> Tuple[Array, Array]:
    """Per-resource totals over the tenant's LIVE nodes: ([R] capacity,
    [R] used), float32 (KiB sums overflow int32 at ~60 nodes of 64Gi; the
    shares these feed are ratios, where float32 is plenty)."""
    nodes = tables.nodes
    live = nodes.valid[:, None]
    cap = jnp.where(live, nodes.alloc, 0).astype(jnp.float32).sum(axis=0)
    used = jnp.where(live, nodes.used, 0).astype(jnp.float32).sum(axis=0)
    return cap, used


def dominant_share(tables: ClusterTables) -> Array:
    """The DRF dominant share: max over resource dims of used/capacity,
    0 where the tenant has no capacity at all (an empty/pad tenant)."""
    cap, used = capacity_usage_planes(tables)
    safe = jnp.maximum(cap, 1.0)
    return jnp.max(jnp.where(cap > 0, used / safe, 0.0))


def drf_admission_row(tables: ClusterTables, pending: PodArrays,
                      quota: Array) -> Tuple[Array, Array, Array]:
    """One tenant's DRF clamp: (admission mask [P], pre-tick dominant
    share [], per-pod dominant demand [P]). vmapped over the tenant axis
    by fleet/cycle.py; callable standalone (K-free) for goldens and for
    the single-tenant reference run the bit-equality suite compares
    against."""
    cap, used = capacity_usage_planes(tables)
    safe = jnp.maximum(cap, 1.0)
    live = cap > 0
    # XLA CSEs the repeated capacity reduction inside the fleet program,
    # so sharing the helper costs nothing
    share = dominant_share(tables)

    rid = jnp.maximum(tables.classes.rid[jnp.maximum(pending.cls, 0)], 0)
    req = tables.reqs.vec[rid].astype(jnp.float32)          # [P, R]
    dom = jnp.max(jnp.where(live[None, :], req / safe[None, :], 0.0),
                  axis=1)                                    # [P]

    # prefix waterfill in queue order: the clamp admits exactly the pods
    # the wave would pop first — so clamping commutes with the engines'
    # own ordering and the tick stays bit-equal to a solo run
    order = queue_order(pending)
    dom_sorted = jnp.where(pending.valid[order], dom[order], 0.0)
    cum = jnp.cumsum(dom_sorted)
    ok_sorted = share + cum <= quota + DRF_EPS
    ok = jnp.zeros_like(pending.valid).at[order].set(ok_sorted)
    return pending.valid & ok, share, dom


def violation_headroom(share: Array, dom: Array, admitted: Array,
                       quota: Array, xp=jnp) -> Array:
    """Per-tenant DRF invariant check, computed from the dispatch's own
    outputs: the admitted prefix's total dominant demand must fit the
    tenant's remaining headroom. True = violated (the budget the fleet
    bench enforces to zero). Shapes: share/quota [K], dom/admitted [K, P].

    `xp` picks the array module: the fleet commit loop passes numpy so the
    check runs as pure host arithmetic on already-fetched outputs — a jnp
    call there would dispatch on the DEFAULT backend, which mid-degraded-
    tick may be the dead one (the hazard sched/cycle.py documents)."""
    demand = xp.where(admitted, dom, 0.0).sum(axis=-1)
    headroom = xp.maximum(quota - share, 0.0)
    return demand > headroom + DRF_EPS
