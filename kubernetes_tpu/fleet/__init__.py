"""Fleet serving: one resident scheduler, K virtual tenant clusters.

The mesh-resident snapshot + bucket/prewarm machinery (sched/, state/,
parallel/) serves ONE cluster. This package multiplexes K tenant clusters
onto that machinery: per-tenant `ClusterTables` stack into a leading tenant
axis (`tables.py`), one `vmap` of the existing cycle body evaluates every
tenant in a single XLA dispatch per tick (`cycle.py`), dominant-resource-
fairness quotas clamp admission as tensor ops over the stacked batch
(`quota.py`), and `server.py` owns the per-tenant caches/queues/ledgers and
the commit loop. See docs/FLEET.md.
"""

from .server import FleetServer, FleetTenant, FleetTickStats, tenant_ledger

__all__ = ["FleetServer", "FleetTenant", "FleetTickStats", "tenant_ledger"]
