"""FleetServer: K virtual tenant clusters behind one resident scheduler.

Ownership model (the "one resident scheduler" of ROADMAP item 1):

  * ONE supervisor — every fleet dispatch runs under the watchdog/fallback
    ladder (sched/supervisor.py), keyed by the fleet signature.
  * ONE prewarmer — the stacked executable AOT-compiles under the fleet
    key slot (sched/prewarm.py `fleet=`), so a K-tenant Compiled and a
    single-cluster one can never cross.
  * ONE event-ingest surface — callers route watch events to
    `tenant(name).on_pod_add(...)` etc.; a production informer set routes
    by tenant label on one watch stream (docs/FLEET.md).
  * K per-tenant Schedulers — each tenant keeps its OWN cache, queue,
    encoder, BindIntentLedger and fencing token. The intent namespace is
    `/registry/ktpu.io/bindintents/<tenant>/<sched>/…` (`tenant_ledger`),
    so one tenant's crash replay or fenced takeover cannot touch another
    tenant's binds; `recover()` replays each tenant's ledger through its
    own Scheduler, PR 4's machinery instantiated per tenant.

A `tick()` is the fleet analog of `Scheduler.schedule_pending`: pump every
tenant's queue, pop per-tenant batches, snapshot each tenant at the SHARED
fleet bucket (fleet/tables.py `fleet_dims` — state/cache.py grows every
tenant up to the union), refresh the resident stack (donated per-tenant
row patches), then ONE vmap'd dispatch with the DRF clamp in-graph
(fleet/cycle.py), and finally the per-tenant commit loops — intent write →
assume → fenced bind → retire, through each tenant's own Scheduler.

Chaos: the `tenant.storm@<tenant>` seam (utils/faultline.py) simulates a
per-tenant watch storm — that tenant's snapshot is invalidated (full
re-encode next tick) and its batch requeues promptly; only ITS CycleStats
degrade, which the chaos suite asserts from metrics, not logs.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..sched.scheduler import CycleStats, Scheduler
from ..state.dims import Dims
from ..utils import faultline
from .cycle import dispatch_fleet, fleet_signature
from .quota import violation_headroom
from .tables import FleetStack, fleet_dims


class _TenantIngest:
    """The v1-dict → typed conversion shim between one tenant's mux routes
    and its Scheduler — the per-tenant half of SchedulerServer's event
    handlers (eventhandlers.go), minus everything the fleet owns."""

    def __init__(self, tenant: "FleetTenant"):
        # imports resolved ONCE here, not per event: these handlers sit on
        # the storm-rate ingest hot path (10k ev/s across the routes), and
        # a function-local import is a sys.modules lookup per call
        from ..api.v1 import node_from_v1, pod_from_v1
        from ..machinery import meta
        from ..sched.server import apply_pod_update_v1, pod_schedulable_v1

        self.tenant = tenant
        self._seq = 0
        self._pod_from_v1 = pod_from_v1
        self._node_from_v1 = node_from_v1
        self._meta_name = meta.name
        self._pod_schedulable_v1 = pod_schedulable_v1
        self._apply_pod_update_v1 = apply_pod_update_v1

    def _to_pod(self, obj):
        p = self._pod_from_v1(obj)
        self._seq += 1
        p.creation_index = self._seq
        return p

    # every handler holds the tenant's ingest lock — the per-tenant
    # "event handlers vs waves" serialization SchedulerServer._mu provides
    # for the single-cluster path (multi-step cache/queue transitions must
    # not interleave with the tick's pop/commit on the same tenant)

    def on_pod_add(self, obj) -> None:
        if self._pod_schedulable_v1(obj):
            with self.tenant.ingest_mu:
                self.tenant.on_pod_add(self._to_pod(obj))

    def on_pod_update(self, old, new) -> None:
        # the SAME transition logic as SchedulerServer's informer handler
        # (sched/server.apply_pod_update_v1) — one definition, two ingest
        # paths that cannot drift
        with self.tenant.ingest_mu:
            self._apply_pod_update_v1(self.tenant.sched, old, new,
                                      self._to_pod)

    def on_pod_delete(self, obj) -> None:
        with self.tenant.ingest_mu:
            self.tenant.on_pod_delete(self._pod_from_v1(obj))

    def on_node_add(self, obj) -> None:
        with self.tenant.ingest_mu:
            self.tenant.on_node_add(self._node_from_v1(obj))

    def on_node_update(self, old, new) -> None:
        with self.tenant.ingest_mu:
            self.tenant.on_node_update(self._node_from_v1(new))

    def on_node_delete(self, obj) -> None:
        with self.tenant.ingest_mu:
            self.tenant.on_node_delete(self._meta_name(obj))


class FleetWatchPlane:
    """ISSUE 13: ONE multiplexed watch stream per resource for the whole
    fleet. Two `WatchMux`es (pods, nodes) each own a single bookmark-
    resumable SharedInformer; every tenant gets a bounded route keyed by
    the tenant label. K tenants therefore put exactly 2 watch streams on
    the apiserver — not 2×K — and a disruption costs at most one resume
    (or, beneath the compaction floor, ONE relist) fleet-wide.

    A mux-stream death does not drop ticks: tenants keep scheduling from
    cached state while `tenant_staleness_seconds` grows; `maintain()`
    (called from FleetServer.tick) narrates the death, revives the stream
    (restart-as-resume), and the staleness decays back to ~0."""

    def __init__(self, server: "FleetServer", client,
                 tenant_label: Optional[str] = None, namespace: str = "",
                 buffer: int = 4096, auto_revive: bool = True):
        from ..client.informers import SharedInformer
        from ..client.watchmux import TENANT_LABEL, WatchMux

        self.server = server
        self.client = client
        self.tenant_label = tenant_label or TENANT_LABEL
        self.auto_revive = auto_revive
        self.pod_mux = WatchMux(
            SharedInformer(client.pods, namespace=namespace),
            tenant_label=self.tenant_label, buffer=buffer, name="pods")
        self.node_mux = WatchMux(
            SharedInformer(client.nodes),
            tenant_label=self.tenant_label, buffer=buffer, name="nodes")
        self._ingests: Dict[str, _TenantIngest] = {}
        self.mux_failovers = 0       # deaths maintain() recovered from
        self.max_staleness = 0.0     # worst staleness ever exported
        self._dead_noted: set = set()  # mux_die narration latch (edge-
        self._started = False          # triggered, not per-tick spam)

    @property
    def muxes(self):
        return (self.pod_mux, self.node_mux)

    def add_route(self, tenant: "FleetTenant") -> None:
        ing = _TenantIngest(tenant)
        self._ingests[tenant.name] = ing
        self.pod_mux.route(tenant.name, on_add=ing.on_pod_add,
                           on_update=ing.on_pod_update,
                           on_delete=ing.on_pod_delete)
        self.node_mux.route(tenant.name, on_add=ing.on_node_add,
                            on_update=ing.on_node_update,
                            on_delete=ing.on_node_delete)

    def start(self) -> "FleetWatchPlane":
        for t in self.server.tenants.values():
            if t.name not in self._ingests:
                self.add_route(t)
        for m in self.muxes:
            m.start()
        for m in self.muxes:
            if not m.wait_for_sync(30.0):
                # a sync timeout must not read as a healthy start: the
                # fleet would tick against empty tenant caches with
                # nothing distinguishing that from a quiet cluster —
                # narrate it (flight-recorder visible, same channel as
                # mux_die) and let staleness carry the ongoing signal
                self.server.telemetry.note_supervisor_event(
                    "mux_unsynced",
                    f"{m.name}: initial list+watch did not sync within "
                    "30s; ticking against unsynced caches until it does")
        self._started = True
        return self

    def stop(self) -> None:
        # a deliberate stop must not read as a death: maintain() guards on
        # _started, so clearing it keeps the next tick from auto-reviving
        # muxes whose route drain threads have already exited (events
        # would flow upstream into silently no-op'ing routes — staleness
        # ~0 while every tenant cache is frozen)
        self._started = False
        for m in self.muxes:
            m.stop()
        if self.server.watch_plane is self:
            # make attach_watch_plane's "stop() it first" instruction
            # actually work: a stopped plane detaches itself
            self.server.watch_plane = None

    def staleness(self) -> float:
        """Seconds since the LEAST-recently-heard-from upstream stream —
        bookmarks count, so a healthy quiet fleet sits near the bookmark
        interval's remainder, never growing."""
        now = time.monotonic()
        return max(0.0, now - min(m.last_signal for m in self.muxes))

    def tenant_staleness(self) -> Dict[str, float]:
        """Per-tenant staleness: the upstream-stream staleness, PLUS a
        route-local penalty for any tenant whose route still has
        undelivered backlog (a stalled consumer is behind even when the
        upstream is live — its serving state is only as fresh as the last
        event it actually applied)."""
        now = time.monotonic()
        fleet = self.staleness()
        out: Dict[str, float] = {}
        # snapshot: a late add_tenant() -> add_route() inserts into
        # _ingests from another thread mid-tick; iterating the live dict
        # would RuntimeError out of the fleet tick
        for name in list(self._ingests):
            stale = fleet
            for m in self.muxes:
                r = m.routes.get(name)
                if r is not None and r.depth() > 0:
                    stale = max(stale, now - r.last_event)
            out[name] = max(0.0, stale)
        return out

    def maintain(self) -> float:
        """Per-tick upkeep: export staleness, revive dead streams. Returns
        the worst staleness exported (pre-revive, so the tick that
        discovers a death records how stale its serving state actually
        was)."""
        from ..sched.metrics import observe_tenant_staleness

        if not self._started:
            return 0.0
        per_tenant = self.tenant_staleness()
        stale = max(per_tenant.values(), default=self.staleness())
        self.max_staleness = max(self.max_staleness, stale)
        observe_tenant_staleness(per_tenant)
        for m in self.muxes:
            if not m.alive:
                # edge-triggered narration: with auto_revive=False a dead
                # stream stays dead across ticks, and a per-tick mux_die
                # would flood every wave record with duplicates — the
                # staleness gauge carries the ongoing signal, the event
                # marks the death
                if m.name not in self._dead_noted:
                    self._dead_noted.add(m.name)
                    self.server.telemetry.note_supervisor_event(
                        "mux_die", f"{m.name}: stream dead, serving cached "
                        f"state ({stale:.1f}s stale)")
                if self.auto_revive:
                    try:
                        m.revive()
                    except RuntimeError as e:
                        # a wedged informer thread (start()'s bounded
                        # re-join expired) must not turn into a fleet-wide
                        # tick exception — "ticks are never dropped for a
                        # watch outage": narrate, keep serving cached
                        # state, retry the revive next tick
                        self.server.telemetry.note_supervisor_event(
                            "mux_revive_failed", f"{m.name}: {e}")
                        continue
                    self.mux_failovers += 1
                    self._dead_noted.discard(m.name)
                    self.server.telemetry.note_supervisor_event(
                        "mux_revive",
                        f"{m.name}: resumed (relists={m.informer.relists}, "
                        f"resumes={m.informer.resumes})")
            else:
                self._dead_noted.discard(m.name)
        return stale

    def stats(self) -> Dict[str, object]:
        return {
            "upstream_watches_per_resource": 1,
            "mux_failovers": self.mux_failovers,
            "max_staleness_seconds": round(self.max_staleness, 3),
            "pods": self.pod_mux.stats(),
            "nodes": self.node_mux.stats(),
        }


def tenant_ledger(storage, tenant: str,
                  scheduler_name: str = "default-scheduler"):
    """A per-tenant BindIntentLedger: intents live under
    `/registry/ktpu.io/bindintents/<tenant>/<scheduler>/…` — disjoint
    prefixes per tenant, so replay/unretired listings are tenant-scoped by
    construction and a takeover of one tenant never reads (or retires)
    another's records."""
    from ..sched.ledger import BindIntentLedger

    return BindIntentLedger(storage,
                            scheduler_name=f"{tenant}/{scheduler_name}")


class FleetTenant:
    """One virtual cluster: a full Scheduler whose DISPATCH the fleet owns.
    The wrapped Scheduler contributes its cache/queue/encoder, the commit
    path (`_commit`, `_write_intent`/`_retire_intent`), intent replay
    (`recover`) and the event handlers — everything except the device
    cycle, which `FleetServer.tick` runs stacked."""

    def __init__(self, name: str, binder, quota: float = 1.0,
                 ledger=None, fence_source=None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.quota = float(quota)
        # mesh=0 pins single-device state: fleet residency/sharding happens
        # at the STACK level (fleet/tables.py), never per tenant
        self.sched = Scheduler(binder=binder, ledger=ledger,
                               fence_source=fence_source, mesh=0,
                               clock=clock)
        # the fleet's prewarmer owns compile-ahead; the per-tenant one
        # would warm single-cluster programs nobody dispatches
        self.sched.prewarmer.enabled = False
        if self.sched.governor is not None:
            # per-tenant governor series label by TENANT, not the shared
            # scheduler name — every tenant writes the same registry, and
            # tenant B's NORMAL must not overwrite A's live brownout
            self.sched.governor.name = name
            self.sched.governor.breaker.name = name
        self.storm_ticks = 0
        # serializes THIS tenant's event ingest (watch-plane route threads)
        # against the tick's mutating phases on the same tenant — the
        # per-tenant analog of SchedulerServer._mu ("event handlers vs
        # waves"): multi-step cache/queue transitions on either side must
        # not interleave. One lock per tenant, so ingest for tenant A never
        # stalls behind tenant B's commit loop.
        self.ingest_mu = threading.Lock()

    # -- event-ingest passthrough (the informer routing surface) -- #

    def on_pod_add(self, pod):
        self.sched.on_pod_add(pod)

    def on_pod_update(self, old, new):
        self.sched.on_pod_update(old, new)

    def on_pod_delete(self, pod):
        self.sched.on_pod_delete(pod)

    def on_node_add(self, node):
        self.sched.on_node_add(node)

    def on_node_update(self, node):
        self.sched.on_node_update(node)

    def on_node_delete(self, name):
        self.sched.on_node_delete(name)


@dataclass
class FleetTickStats:
    """One tick's outcome, per tenant plus the fleet-wide invariants the
    bench budgets enforce."""

    per_tenant: Dict[str, CycleStats] = field(default_factory=dict)
    dispatches: int = 0               # XLA dispatches this tick (budget:
                                      # one per ENGINE GROUP — 1 for a
                                      # uniform-engine fleet)
    engine_groups: int = 0            # distinct per-tenant engines this tick
    drf_violations: int = 0           # tenants whose admitted demand broke
                                      # their headroom (budget: 0)
    drf_clamped: int = 0              # pods deferred by the quota pre-mask
                                      # (per-tenant attribution lives on
                                      # CycleStats.drf_clamped → the
                                      # tenant-labelled DRF_CLAMPED metric)
    cross_tenant_placements: int = 0  # placements onto a node row outside
                                      # the tenant's own cluster (budget: 0)
    tick_seconds: float = 0.0
    staleness_seconds: float = 0.0    # watch-plane staleness at tick start
                                      # (0.0 when no watch plane attached)

    @property
    def scheduled(self) -> int:
        return sum(s.scheduled for s in self.per_tenant.values())

    @property
    def attempted(self) -> int:
        return sum(s.attempted for s in self.per_tenant.values())


class FleetServer:
    """One resident scheduler serving K virtual tenant clusters per vmap'd
    tick. See the module docstring for the ownership model."""

    #: the engines a per-tenant config may name (the lattice the
    #: single-cluster KTPU_ASSIGN knob normalizes into)
    ENGINES = ("waves", "runs", "scan")

    def __init__(self, batch_size: int = 1024,
                 base_dims: Optional[Dims] = None, mesh=None,
                 node_shards: Optional[int] = None,
                 engines: Optional[Dict[str, str]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 scheduler_name: str = "default-scheduler",
                 storage=None):
        from ..sched.prewarm import BucketPrewarmer
        from ..sched.supervisor import DispatchSupervisor
        from ..utils.envparse import env_int

        self.batch_size = batch_size
        self.clock = clock
        self.scheduler_name = scheduler_name
        self.storage = storage
        # per-tenant engine config: tenants grouped by engine run as
        # sub-dispatches of the same tick (one vmap'd dispatch per GROUP);
        # unlisted tenants follow the fleet default (KTPU_ASSIGN). Unlike
        # the env knob — which normalizes garbage to "waves" — an explicit
        # config naming an unknown engine is a caller bug and raises.
        engines = dict(engines or {})
        bad = {n: e for n, e in engines.items() if e not in self.ENGINES}
        if bad:
            raise ValueError(
                f"unknown engine(s) in per-tenant config: {bad!r} — "
                f"valid engines: {self.ENGINES}")
        self.engines: Dict[str, str] = engines
        if node_shards is None:
            node_shards = env_int("KTPU_FLEET_NODE_SHARDS", 1, 1, 64)
        self.node_shards = int(node_shards)
        self.mesh, self.mesh_state = self._make_fleet_mesh(
            mesh, self.node_shards)
        self.prewarmer = BucketPrewarmer()
        self.supervisor = DispatchSupervisor(prewarmer=self.prewarmer,
                                             mesh_state=self.mesh_state)
        self.prewarmer.supervisor = self.supervisor
        # fleet-level flight recorder (sched/telemetry.py): per-tick phase
        # spans + per-TENANT stats on each record; storms and abandoned
        # dispatches auto-dump. Per-pod e2e latency stays per tenant (each
        # FleetTenant's Scheduler owns its tracker/commit path).
        from ..sched.telemetry import SchedulerTelemetry

        self.telemetry = SchedulerTelemetry(name="fleet")
        self.supervisor.event_sink = self.telemetry.note_supervisor_event
        # one resident FleetStack PER ENGINE GROUP, created lazily — a
        # uniform-engine fleet (the common case) holds exactly one
        self.stacks: Dict[str, FleetStack] = {}
        self._fleet_dims: Dims = replace(base_dims or Dims(),
                                         has_node_name=False)
        self.tenants: Dict[str, FleetTenant] = {}
        # cumulative fleet-wide invariants (bench reads these)
        self.ticks = 0
        self.total_drf_violations = 0
        self.total_cross_tenant = 0
        self.total_drf_clamped = 0
        self.max_dispatches_per_tick = 0
        self.max_engine_groups = 1
        self._super_epoch = self._supervisor_epoch()
        # re-admission rewarm must target the FLEET mesh's executable key.
        # With a fleet-mode MeshState attached the supervisor reforms the
        # (possibly 2-D) fleet mesh itself — the degrade→reform ladder under
        # the 2-D signature; the provider remains the fallback for an
        # adopted raw Mesh object (no MeshState to reform).
        self.supervisor.mesh_provider = lambda: self.mesh
        # ISSUE 13: the shared watch plane (attach_watch_plane) — one
        # multiplexed, bookmark-resumable stream per resource for all K
        # tenants, maintained (staleness export + dead-stream revive)
        # from every tick
        self.watch_plane: Optional[FleetWatchPlane] = None

    def _supervisor_epoch(self):
        """Changes whenever a primary dispatch hung/failed or the backend
        was re-admitted — i.e. whenever a zombie worker might still hold
        the resident stacked buffers."""
        st = self.supervisor.stats
        return (st.degraded_cycles, st.abandoned, st.recoveries)

    @staticmethod
    def _make_fleet_mesh(mesh, node_shards: int = 1):
        """→ (mesh, mesh_state). An int/str request builds a fleet-mode
        MeshState (pow2 width, the degrade→reform ladder owns the mesh from
        then on); a raw Mesh object is adopted as-is with no state to
        reform. Garbage values clamp to "no mesh" — single-device serving —
        instead of crashing int()."""
        if mesh is None or mesh == 0:
            return None, None
        from jax.sharding import Mesh

        from ..parallel.mesh import MeshState
        from ..utils.envparse import clamped_int

        if isinstance(mesh, Mesh):
            return mesh, None
        n = clamped_int(mesh, 0, 0, 4096)
        if n <= 1:
            return None, None
        ns = clamped_int(node_shards, 1, 1, 64)
        state = MeshState(n, fleet_node_shards=ns)
        if state.mesh is None:
            return None, None
        return state.mesh, state

    # ------------------------------------------------------------------ #
    # per-engine-group residency
    # ------------------------------------------------------------------ #

    def _engine_for(self, name: str) -> str:
        from ..sched.cycle import _engine

        return self.engines.get(name) or _engine()

    def _stack_for(self, engine: str) -> FleetStack:
        st = self.stacks.get(engine)
        if st is None:
            st = self.stacks[engine] = FleetStack(mesh=self.mesh)
        return st

    @property
    def stack(self) -> FleetStack:
        """The default-engine group's stack — THE stack of a
        uniform-engine fleet (back-compat accessor for tests/bench
        reading restack/donation counters)."""
        from ..sched.cycle import _engine

        if len(self.stacks) == 1:
            return next(iter(self.stacks.values()))
        return self._stack_for(_engine())

    def _invalidate_stacks(self) -> None:
        for st in self.stacks.values():
            st.invalidate()

    def _node_shard_width(self) -> int:
        if self.mesh is None:
            return 1
        from ..parallel.mesh import fleet_mesh_shape

        return fleet_mesh_shape(self.mesh)[1]

    def _sync_mesh(self) -> None:
        """Adopt the MeshState's current mesh (degrade dropped it; reform
        rebuilt it — possibly narrower, always a FRESH object). Every
        group stack re-homes and full-restacks onto the new placement."""
        if self.mesh_state is None or self.mesh_state.mesh is self.mesh:
            return
        self.mesh = self.mesh_state.mesh
        for st in self.stacks.values():
            st.mesh = self.mesh
            st.invalidate()

    # ------------------------------------------------------------------ #
    # tenant lifecycle
    # ------------------------------------------------------------------ #

    def add_tenant(self, name: str, binder=None, quota: float = 1.0,
                   ledger=None, fence_source=None) -> FleetTenant:
        if name in self.tenants:
            raise ValueError(f"tenant {name!r} already registered")
        if binder is None:
            from ..sched.scheduler import RecordingBinder

            binder = RecordingBinder()
        if ledger is None and self.storage is not None:
            ledger = tenant_ledger(self.storage, name, self.scheduler_name)
        t = FleetTenant(name, binder, quota=quota, ledger=ledger,
                        fence_source=fence_source, clock=self.clock)
        self.tenants[name] = t
        if self.watch_plane is not None:
            # a late tenant joins the EXISTING streams: its routes resync
            # from the mux indexers — the apiserver sees no new watch
            self.watch_plane.add_route(t)
        return t

    def tenant(self, name: str) -> FleetTenant:
        return self.tenants[name]

    def attach_watch_plane(self, client, tenant_label: Optional[str] = None,
                           namespace: str = "", buffer: int = 4096,
                           auto_revive: bool = True,
                           start: bool = True) -> FleetWatchPlane:
        """Wire the fleet to a live apiserver through ONE multiplexed watch
        stream per resource (ISSUE 13). Registers a route per existing
        tenant; tenants added later join the same streams."""
        if self.watch_plane is not None:
            # silently replacing a live plane would leave the old one's
            # informer + route threads running — double ingest per event
            # and 2 leaked upstream streams, the exact amplification this
            # subsystem exists to kill
            raise ValueError("a watch plane is already attached; stop() "
                             "it first")
        self.watch_plane = FleetWatchPlane(
            self, client, tenant_label=tenant_label, namespace=namespace,
            buffer=buffer, auto_revive=auto_revive)
        if start:
            self.watch_plane.start()
        return self.watch_plane

    def recover(self, now: Optional[float] = None) -> Dict[str, object]:
        """Startup/takeover reconciliation, per tenant through its OWN
        ledger namespace — tenant A's replay can complete/release only
        entries under A's prefix; B's intents are not even listed."""
        out = {}
        for name, t in self.tenants.items():
            with t.ingest_mu:
                out[name] = t.sched.recover(now=now)
        return out

    # ------------------------------------------------------------------ #
    # the fleet tick
    # ------------------------------------------------------------------ #

    def _snapshot_round(self, tlist, batches):
        """Snapshot every tenant at the shared fleet bucket, growing the
        bucket (and re-snapshotting) until all tenants agree — convergence
        is ≤2 passes in practice (one tenant grew, everyone follows)."""
        from ..sched.cycle import snapshot_with_keys

        snaps: Dict[str, object] = {}
        keys: Dict[str, Tuple] = {}
        kn = self._node_shard_width()
        for _ in range(4):
            for t in tlist:
                pending = [p for p, _ in batches[t.name]]
                snaps[t.name], keys[t.name] = snapshot_with_keys(
                    t.sched.cache, t.sched.encoder, pending,
                    self._fleet_dims,
                    device=self.supervisor.snapshot_device())
            union = fleet_dims([snaps[t.name].dims for t in tlist],
                               base=self._fleet_dims)
            if kn > 1:
                # 2-D mesh: the bucket's node axis must divide the
                # node-shard row so the stacked [K, N, …] planes shard
                # without padding. grown_for keeps N pow2 (≤256) or a
                # ≥32-multiple above, so a pow2 row width makes this a
                # no-op in the steady state; the guard covers raw shapes.
                from ..parallel.mesh import padded_node_count

                union = replace(union, N=padded_node_count(union.N, kn))
            if all(replace(snaps[t.name].dims, has_node_name=False)
                   == union for t in tlist):
                self._fleet_dims = union
                return snaps, keys
            self._fleet_dims = union
        raise RuntimeError("fleet bucket did not converge in 4 passes")

    def micro_pass(self, now: Optional[float] = None,
                   tick: Optional[FleetTickStats] = None
                   ) -> Dict[str, CycleStats]:
        """Streaming micro-admission across the fleet (ISSUE 18): each
        micro-ready tenant admits its fresh-delta lane through ITS OWN
        scheduler — own snapshot, own governor/breaker, own ledger
        namespace — under its ingest lock, so per-tenant isolation is
        structural, not asserted. Tenants with mixed/deep/empty backlogs
        are untouched; those pods ride the stacked bulk dispatch.

        Rides the top of every tick; a server loop may ALSO call it
        between ticks for sub-tick admission latency. When `tick` is
        given, each tenant's micro outcome is merged into its per-tenant
        stats so the tenant-labelled metrics (TENANT_ADMITTED et al.)
        and the flight-recorder fleet record count streamed admissions."""
        now = self.clock() if now is None else now
        out: Dict[str, CycleStats] = {}
        for t in list(self.tenants.values()):
            if not t.sched.microwave:
                continue
            with t.ingest_mu:
                st = t.sched.schedule_micro(now)
            if not st.micro:
                continue
            out[t.name] = st
            agg = tick.per_tenant.get(t.name) if tick is not None else None
            if agg is not None:
                agg.attempted += st.attempted
                agg.scheduled += st.scheduled
                agg.unschedulable += st.unschedulable
                agg.bind_errors += st.bind_errors
                agg.aborted += st.aborted
                agg.requeued += st.requeued
                agg.shed += st.shed
                agg.micro += st.micro
                agg.assignments.update(st.assignments)
                agg.failed_keys.extend(st.failed_keys)
        return out

    def tick(self, now: Optional[float] = None) -> FleetTickStats:
        now = self.clock() if now is None else now
        t0 = time.perf_counter()
        tick = FleetTickStats()
        tlist = list(self.tenants.values())
        if not tlist:
            return tick
        for t in tlist:
            tick.per_tenant[t.name] = CycleStats()
        span = self.telemetry.wave_span("fleet-tick")
        # streaming micro-admission interleave (ISSUE 18) before the
        # stacked bulk dispatch — no-op for every tenant unless its
        # scheduler opted in (KTPU_MICROWAVE) and its lane is micro-ready
        if self.micro_pass(now, tick=tick):
            span.mark("micro")
        if self.watch_plane is not None:
            # watch-plane upkeep rides the tick: staleness export first
            # (a dead stream's tick records HOW stale it served), then the
            # dead-stream revive — ticks are never dropped for a watch
            # outage, they degrade to cached state with a visible metric
            tick.staleness_seconds = self.watch_plane.maintain()

        # ---- pump + storm seam + governed pop ---- #
        # each tenant's pop phase holds ITS ingest lock (handlers-vs-waves,
        # per tenant): a route thread's multi-step transition can't
        # interleave with the pump/pop on the same tenant's queue
        batches: Dict[str, List] = {}
        for t in tlist:
            with t.ingest_mu:
                s = t.sched
                st = tick.per_tenant[t.name]
                s.queue.pump(now)
                s.cache.cleanup(now)
                if faultline.should("tenant.storm", t.name):
                    # injected per-tenant watch storm: the tenant's resident
                    # encoding is no longer trusted (full re-encode next tick)
                    # and this tick admits nothing for it — purely ITS
                    # degradation, the other tenants' rows are untouched. The
                    # "storm" event makes this a flight-recorder dump trigger:
                    # the degraded tick is explainable from the artifact.
                    t.storm_ticks += 1
                    st.degraded += 1
                    self.telemetry.note_supervisor_event("storm", t.name)
                    s.cache.invalidate_snapshot()
                    batches[t.name] = []
                    continue
                # per-TENANT overload governor (sched/overload.py): one
                # tenant's storm sheds/pauses only that tenant — composing
                # with the DRF clamp, which bounds a tenant's SHARE while the
                # governor bounds the control plane's own burn for it
                gov = s.governor
                decision = None
                pop_limit = self.batch_size
                if gov is not None:
                    decision = gov.begin_wave(now, s.queue.depths())
                    if decision.release_deferred:
                        released = s.queue.release_deferred(now)
                        if released:
                            self.telemetry.note_supervisor_event(
                                "deferred_release",
                                f"{t.name}: {released} pods re-admitted")
                    if not decision.dispatch_allowed:
                        st.commit_paused += 1
                        batches[t.name] = []
                        continue
                    if decision.wave_limit:
                        pop_limit = min(pop_limit, decision.wave_limit)
                batch = s.queue.pop_batch(pop_limit, now=now)
                if decision is not None and decision.shed_below is not None \
                        and batch:
                    kept = []
                    shed_n = 0
                    for pod, attempts in batch:
                        if pod.priority < decision.shed_below \
                                and s.queue.park_deferred(pod, attempts,
                                                          now=now):
                            shed_n += 1
                        else:
                            kept.append((pod, attempts))
                    batch = kept
                    if shed_n:
                        st.shed += shed_n
                        gov.note_shed(shed_n)
                batches[t.name] = batch
                # += : a micro_pass admission above already counted here
                st.attempted += len(batch)
        span.mark("pump")

        from ..sched.supervisor import DispatchAbandonedError

        # batches are popped: from here to the dispatch result, EVERY
        # failure path must hand them back to their queues — losing them
        # is the one thing a scheduler may never do
        try:
            out, snaps = self._dispatch_tick(tlist, batches, tick, now,
                                             span)
        except DispatchAbandonedError:
            # the abandoned worker's zombie thread may still hold (or be
            # executing on) the resident stacked buffers — never donate or
            # scatter onto them again; the next healthy tick full-restacks.
            # Earlier engine groups' (uncommitted) results are discarded
            # with the requeue: every popped pod goes back to its queue.
            self._invalidate_stacks()
            self._requeue_batches(tlist, batches, tick, now)
            span.mark("requeue")
            tick.tick_seconds = time.perf_counter() - t0
            self._finish_tick(tick, span)
            return tick
        except Exception:
            # any other post-pop failure (bucket non-convergence, a
            # donation assert in the stack refresh, an unexpected dispatch
            # error): requeue everything, drop the possibly half-patched
            # stacks, and re-raise for visibility
            self._invalidate_stacks()
            self._requeue_batches(tlist, batches, tick, now)
            span.mark("requeue")
            tick.tick_seconds = time.perf_counter() - t0
            self._finish_tick(tick, span)
            raise

        self._commit_tick(out, batches, snaps, tick, now)
        span.mark("bind-commit")
        tick.tick_seconds = time.perf_counter() - t0
        # per-tenant governor feedback: the shared tick's wall time is
        # every tenant's deadline signal (commit outcomes already fed the
        # breakers from each tenant's own _commit)
        for t in tlist:
            if t.sched.governor is not None:
                t.sched.governor.end_wave(
                    now, tick.per_tenant[t.name].attempted,
                    tick.tick_seconds)
        self._finish_tick(tick, span)
        return tick

    @staticmethod
    def _pad_quota(tlist, width: int) -> List[float]:
        """Pad tenants carry quota 0.0: with zero capacity their share and
        demand are zero, so they can neither admit nor flag — the ONE
        definition every consumer (primary dispatch, fallback re-encode,
        violation check) must agree on."""
        return [t.quota for t in tlist] + [0.0] * (width - len(tlist))

    @staticmethod
    def _requeue_batches(tlist, batches, tick, now) -> None:
        """Hand every still-unconsumed popped batch back to its tenant's
        queue (prompt retry, no failure verdict) — solo-routed and stormed
        tenants' batches are already empty lists here."""
        for t in tlist:
            with t.ingest_mu:
                st = tick.per_tenant[t.name]
                for pod, attempts in batches[t.name]:
                    st.aborted += 1
                    st.requeued += 1
                    t.sched.queue.add_prompt_retry(pod, attempts=attempts,
                                                   now=now)

    def _dispatch_tick(self, tlist, batches, tick, now, span):
        """Everything between the batch pop and the device results: the
        snapshot convergence round, solo routing, per-engine-group resident
        stack refresh and ONE vmap'd dispatch per engine group (exactly one
        for a uniform-engine fleet). Raises propagate to tick()'s requeue
        guard — this method never loses a popped pod."""
        # adopt a reformed/dropped mesh BEFORE snapshotting: the bucket's
        # node-shard divisibility and the stacks' placement follow it
        self._sync_mesh()
        snaps, keys = self._snapshot_round(tlist, batches)
        span.mark("snapshot")

        # ---- tenants the vmap cannot express run their own single-
        # cluster wave (counted as extra dispatches; the fleet budget
        # shape carries neither): gang-bearing batches (group-atomic
        # admission needs host rejection rounds) and nodeName-pinned
        # batches (routing one tenant's pin through the shared program
        # would downgrade EVERY tenant to the sequential scan engine —
        # exactly the cross-tenant interference the fleet forbids) ---- #
        solo_ran = False
        for t in tlist:
            # the solo wave is this tenant's whole cycle, held under its
            # ingest lock exactly like SchedulerServer.run_one_wave holds
            # _mu across schedule_pending — a route handler's multi-step
            # cache/queue transition must not interleave with the wave's
            # own mutations. Known tradeoff: the tenant's mux route keeps
            # buffering meanwhile, so a wave longer than buffer/event-rate
            # costs that route a bounded, route-local resync (never an
            # apiserver relist); size `buffer` for the worst solo wave.
            with t.ingest_mu:
                needs_solo = (snaps[t.name].gang is not None
                              or snaps[t.name].dims.has_node_name)
                if not needs_solo or not batches[t.name]:
                    continue
                s = t.sched
                for pod, attempts in batches[t.name]:
                    # attempts-1: the fleet pop and the solo wave's own pop are
                    # ONE real attempt — re-adding the post-pop count would let
                    # the solo pop double-increment and escalate a failing
                    # pod's backoff 4x per failure instead of 2x
                    s.queue.add_prompt_retry(pod, attempts=attempts - 1,
                                             now=now)
                solo = s.schedule_pending(now)
                st = tick.per_tenant[t.name]
                st.scheduled += solo.scheduled
                st.unschedulable += solo.unschedulable
                st.bind_errors += solo.bind_errors
                # aborted/requeued/failed_keys carry through too: a chaos-
                # injected abandonment inside the solo wave must show up in
                # THIS tenant's fleet counters (the chaos suite asserts
                # isolation from these, not from logs)
                st.aborted += solo.aborted
                st.requeued += solo.requeued
                st.failed_keys.extend(solo.failed_keys)
                st.assignments.update(solo.assignments)
                tick.dispatches += 1
                batches[t.name] = []
                solo_ran = True
        if solo_ran:
            # the solo waves consumed those batches, mutated their tenants'
            # caches, and may have grown the fleet bucket — re-snapshot
            # EVERY tenant so the whole stack agrees on the converged
            # bucket (unchanged tenants hit their cache's snapshot path; a
            # per-solo-tenant refresh would leave the others at the old
            # shapes and crash the restack with the batches already popped)
            snaps, keys = self._snapshot_round(tlist, batches)
            span.mark("solo")

        # ---- per-tenant engine grouping + shared static run bounds ---- #
        # no waves→scan downgrade here: nodeName-bearing batches were solo-
        # routed above, so every snapshot entering the shared programs has
        # has_node_name=False (re-snapshotted with an empty batch) — one
        # tenant's pin must never serialize the other K-1 tenants.
        # Tenants group by their configured engine; each group is one
        # sub-dispatch of this tick (one vmap'd program per group, so a
        # runs tenant's static bound never recompiles the waves group).
        groups: Dict[str, List] = {}
        for t in tlist:
            groups.setdefault(self._engine_for(t.name), []).append(t)
        order = {e: i for i, e in enumerate(self.ENGINES)}
        group_items = sorted(groups.items(),
                             key=lambda kv: order.get(kv[0], len(order)))
        tick.engine_groups = len(group_items)

        d = self._fleet_dims
        if self.supervisor.healthy:
            epoch = self._supervisor_epoch()
            if epoch != self._super_epoch:
                # the primary hung/failed or the backend was re-admitted
                # since the stacks' last refresh: a hung dispatch's
                # abandoned worker may STILL hold the resident buffers
                # (handle.result() returned the fallback's answer without
                # raising), and a sub-second probe can re-admit before the
                # next tick — donating those buffers would alias them out
                # from under the wedged execution. Full-restack fresh
                # instead (the fleet analog of the cache's
                # _dispatch_inflight copy gate).
                self._invalidate_stacks()
                self._super_epoch = epoch

        results: List[Tuple] = []
        for engine, gts in group_items:
            results.append(self._dispatch_group(
                engine, gts, batches, snaps, keys, d, tick, span))
        return results, snaps

    def _dispatch_group(self, engine, gts, batches, snaps, keys, d, tick,
                        span):
        """One engine group's sub-dispatch: refresh ITS resident stack,
        pad ITS quota vector, prewarm/sign under ITS fleet key, submit and
        read back. Returns (gts, out, exp) for _commit_tick."""
        from ..sched.cycle import _resolve_rc

        rc = 0
        if engine == "runs":
            for t in gts:
                sn = snaps[t.name]
                rc = max(rc, _resolve_rc(sn.pending, sn.runs))
                if sn.runs is not None:
                    tick.per_tenant[t.name].class_runs = sn.runs.n_runs

        # ---- resident stack refresh (donated per-tenant row patches) --- #
        stack = self._stack_for(engine)
        if self.supervisor.healthy:
            Kp = stack.refresh([snaps[t.name] for t in gts],
                               [keys[t.name] for t in gts], d)
        else:
            # degraded: the resident buffers live on the lost backend —
            # scattering onto them would dispatch onto dead hardware before
            # the supervisor's ladder even runs. Drop the stack (fresh
            # full restack on re-admission) and let the fallback re-encode
            # from host staging; submit() skips the primary while unhealthy.
            stack.invalidate()
            Kp = stack.padded_k(len(gts))
        span.mark("stack-refresh")
        quota = jnp.asarray(self._pad_quota(gts, Kp), jnp.float32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..parallel.mesh import TENANT_AXIS

            quota = jax.device_put(
                quota, NamedSharding(self.mesh, PartitionSpec(TENANT_AXIS)))

        # ---- compile-ahead + supervisor bookkeeping under the FLEET key - #
        fsig = fleet_signature(Kp)
        self.prewarmer.observe(
            d, n_nodes=max(t.sched.cache.node_count for t in gts),
            n_existing=max(t.sched.cache.pod_count for t in gts),
            engine=engine, mesh=self.mesh, rc=rc, fleet=fsig)
        self.prewarmer.ensure_warm(d, engine, mesh=self.mesh, rc=rc,
                                   fleet=fsig)
        self.supervisor.note_cycle_signature(d, engine, (), False, rc=rc,
                                             fleet=fsig)
        span.mark("prewarm")

        # ---- ONE vmap'd dispatch for this engine group ---- #
        # decision provenance (ISSUE 10): one flag for the whole stack —
        # tenants share the process env, and the vmap'd program is one
        # executable. Attribution fans back out per tenant in _commit_tick.
        explain_on = any(t.sched.explainer is not None for t in gts)

        def _primary():
            if stack.block is None:
                # the stack was invalidated AFTER the healthy check above
                # (tick started degraded, or the background prober
                # re-admitted the backend between that check and submit —
                # _readmit flips health asynchronously): full-restack from
                # THIS tick's snapshots instead of dereferencing the
                # dropped buffers
                stack.refresh([snaps[t.name] for t in gts],
                              [keys[t.name] for t in gts], d)
            out = dispatch_fleet(stack.tables, stack.pending, stack.keys,
                                 d.D, stack.existing, engine, quota,
                                 rc=rc, dims=d, prewarmer=self.prewarmer,
                                 mesh=self.mesh, explain=explain_on)
            res, exp = out if explain_on else (out, None)
            return jax.device_get(res), \
                (jax.device_get(exp) if exp is not None else None)

        def _fallback(dev, hung=False):
            # degraded fleet tick: re-encode this group's tenants onto the
            # CPU fallback from host staging (the single-cluster ladder,
            # per tenant) and dispatch the stack there — no resident
            # buffers of the lost backend are touched
            from ..sched.cycle import snapshot_with_keys
            from .tables import stack_blocks

            blocks = []
            for t in gts:
                sn, ky = snapshot_with_keys(
                    t.sched.cache, t.sched.encoder,
                    [p for p, _ in batches[t.name]], self._fleet_dims,
                    device=dev)
                snaps[t.name] = sn
                blocks.append((sn.tables, sn.pending, sn.existing, ky))
            if Kp > len(blocks):
                from .tables import empty_tenant_block

                blocks.extend([empty_tenant_block(d)] * (Kp - len(blocks)))
            tb, pe, ex, ky = jax.device_put(stack_blocks(blocks), dev)
            q = jax.device_put(jnp.asarray(self._pad_quota(gts, Kp),
                                           jnp.float32), dev)
            with jax.default_device(dev):
                out = dispatch_fleet(tb, pe, ky, d.D, ex, engine, q, rc=rc,
                                     explain=explain_on)
                res, exp = out if explain_on else (out, None)
                return jax.device_get(res), \
                    (jax.device_get(exp) if exp is not None else None)

        from ..parallel.mesh import mesh_key as _mesh_key

        handle = self.supervisor.submit(
            "cycle",
            (replace(d, has_node_name=False), engine, fsig,
             _mesh_key(self.mesh), rc),
            _primary, _fallback)
        span.mark("dispatch")
        out, exp = handle.result()
        span.mark("readback")
        tick.dispatches += 1
        return (gts, out, exp)

    def _commit_tick(self, results, batches, snaps, tick, now) -> None:
        """The per-tenant commit loops (PR 4 machinery per tenant): intent
        write → assume → fenced bind → retire, through each tenant's own
        Scheduler, plus the DRF violation check over each sub-dispatch's
        own outputs."""
        for gts, out, exp in results:
            self._commit_group(gts, out, exp, batches, snaps, tick, now)

    def _commit_group(self, tlist, out, exp, batches, snaps, tick,
                      now) -> None:
        node = np.asarray(out.node)
        admitted = np.asarray(out.admitted)
        share = np.asarray(out.share)
        dom = np.asarray(out.dom)
        # the DRF invariant the bench budget enforces, checked through the
        # SAME tensor helper the quota tests golden (pad tenants have zero
        # admitted demand and can never flag)
        viol = violation_headroom(
            share, dom, admitted,
            np.asarray(self._pad_quota(tlist, int(share.shape[0])),
                       np.float32), xp=np)
        tick.drf_violations += int(viol[:len(tlist)].sum())
        for k, t in enumerate(tlist):
            with t.ingest_mu:  # commit phase vs this tenant's route threads
                s = t.sched
                st = tick.per_tenant[t.name]
                order = snaps[t.name].node_order
                cycle = s.queue.current_cycle()
                # per-TENANT decision provenance (ISSUE 10): slice tenant k's
                # rows off the stacked attribution and feed ITS explainer —
                # quota-clamped pods (admitted=False) are excluded: they carry
                # no verdict this tick, and their zeroed attribution would
                # render as empty-reason noise
                if exp is not None and s.explainer is not None \
                        and batches[t.name]:
                    idx = [i for i in range(len(batches[t.name]))
                           if admitted[k, i]]
                    if idx:
                        from ..ops.assign import ExplainResult

                        sl = ExplainResult(*(np.asarray(a)[k][idx]
                                             for a in exp))
                        try:
                            rec = s.explainer.observe_wave(
                                [batches[t.name][i] for i in idx],
                                node[k][idx], sl, order, now=now)
                        except Exception:  # noqa: BLE001 - provenance must
                            rec = None     # never take down a tick
                        if rec:
                            self.telemetry.note_supervisor_event(
                                "explain", f"{t.name}: "
                                f"{rec.get('unschedulable', 0)} attributed")
                commits: List[Tuple] = []
                failures: List[Tuple] = []
                for i, (pod, attempts) in enumerate(batches[t.name]):
                    if not admitted[k, i]:
                        # quota-clamped, not unschedulable: the pod is fine,
                        # the tenant's headroom wasn't — defer promptly. The
                        # clamp count rides CycleStats so observe_fleet_tick
                        # emits the tenant-labelled DRF_CLAMPED series.
                        st.requeued += 1
                        st.drf_clamped += 1
                        tick.drf_clamped += 1
                        s.queue.add_prompt_retry(pod, attempts=attempts,
                                                 now=now)
                        continue
                    ni = int(node[k, i])
                    if ni < 0:
                        failures.append((pod, attempts))
                        continue
                    if s.cache.get_pod(pod.key) is not None:
                        continue  # skipPodSchedule (stale queue entry)
                    if ni >= len(order) or not order[ni]:
                        # a placement onto a node row outside this tenant's
                        # own cluster — the inert-row contract broke
                        tick.cross_tenant_placements += 1
                        failures.append((pod, attempts))
                        continue
                    commits.append((pod, order[ni], attempts))
                try:
                    intent = s._write_intent(cycle, commits)
                except Exception:  # noqa: BLE001 - ledger storage unavailable
                    for pod, _node, attempts in commits:
                        st.aborted += 1
                        st.requeued += 1
                        s.queue.add_prompt_retry(pod, attempts=attempts,
                                                 now=now)
                    commits = []
                    intent = None
                bound_keys: List[str] = []
                for ci, (pod, node_name, attempts) in enumerate(commits):
                    if s.governor is not None and not s.governor.commit_allowed():
                        # this tenant's breaker opened mid-commit: its
                        # remaining commits requeue promptly (the other
                        # tenants' loops are untouched — per-tenant breakers)
                        for pod2, _n2, attempts2 in commits[ci:]:
                            st.requeued += 1
                            s.queue.add_prompt_retry(pod2, attempts=attempts2,
                                                     now=now)
                        break
                    s._commit(pod, node_name, attempts, now, cycle, st,
                              latency_keys=bound_keys)
                # one batched span-close per tenant per tick (the scalar
                # per-pod path was most of the measured telemetry cost)
                if bound_keys:
                    s.telemetry.record_bound_many(bound_keys, s.clock())
                s._retire_intent(intent)
                for pod, attempts in failures:
                    st.unschedulable += 1
                    st.failed_keys.append(pod.key)
                    s.queue.add_unschedulable(pod, attempts, now, cycle=cycle)

    def _finish_tick(self, tick: FleetTickStats, span=None) -> None:
        from ..sched.metrics import observe_fleet_tick

        self.ticks += 1
        self.total_drf_violations += tick.drf_violations
        self.total_cross_tenant += tick.cross_tenant_placements
        self.total_drf_clamped += tick.drf_clamped
        self.max_dispatches_per_tick = max(self.max_dispatches_per_tick,
                                           tick.dispatches)
        self.max_engine_groups = max(self.max_engine_groups,
                                     tick.engine_groups)
        # per-tenant attribution happens INSIDE observe_fleet_tick now:
        # the chaos suite and bench assert tenant isolation (and the DRF
        # clamp) from the tenant-labelled metrics, routed through
        # CycleStats — never from FleetServer internals
        observe_fleet_tick(tick.per_tenant)
        if span is not None:
            self.telemetry.finish_wave(
                span, engine="fleet", dims=self._fleet_dims,
                fleet={name: {"attempted": st.attempted,
                              "scheduled": st.scheduled,
                              "requeued": st.requeued,
                              "degraded": st.degraded,
                              "drf_clamped": st.drf_clamped,
                              "shed": st.shed,
                              "aborted": st.aborted}
                       for name, st in tick.per_tenant.items()},
                extra={"dispatches": tick.dispatches,
                       "engine_groups": tick.engine_groups,
                       "drf_violations": tick.drf_violations,
                       "cross_tenant_placements":
                           tick.cross_tenant_placements})

    def run_until_idle(self, max_ticks: int = 64,
                       stall_ticks: int = 2) -> FleetTickStats:
        """Tick until every tenant's active queue drains, or nothing has
        scheduled for `stall_ticks` consecutive ticks (a quota-clamped
        tenant's deferred pods requeue promptly, so its active queue never
        empties — headroom, not the scheduler, is what it waits on)."""
        total = FleetTickStats()
        for t in self.tenants.values():
            total.per_tenant[t.name] = CycleStats()
        stalled = 0
        for _ in range(max_ticks):
            tk = self.tick()
            stalled = stalled + 1 if tk.scheduled == 0 else 0
            total.dispatches += tk.dispatches
            total.engine_groups = max(total.engine_groups, tk.engine_groups)
            total.drf_violations += tk.drf_violations
            total.drf_clamped += tk.drf_clamped
            total.cross_tenant_placements += tk.cross_tenant_placements
            total.tick_seconds += tk.tick_seconds
            for name, st in tk.per_tenant.items():
                agg = total.per_tenant[name]
                agg.attempted += st.attempted
                agg.scheduled += st.scheduled
                agg.unschedulable += st.unschedulable
                agg.bind_errors += st.bind_errors
                agg.aborted += st.aborted
                agg.requeued += st.requeued
                agg.degraded += st.degraded
                agg.drf_clamped += st.drf_clamped
                agg.assignments.update(st.assignments)
            if all(t.sched.queue.lengths()[0] == 0
                   for t in self.tenants.values()):
                break
            if stalled >= stall_ticks:
                break
        return total
