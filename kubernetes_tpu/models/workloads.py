"""Canonical benchmark workloads — the 'model zoo' of this framework.

Shapes mirror the reference's perf suites (BASELINE.md):
  * density      — scheduler_perf density test (config 1/2): N nodes, P pods,
    plain requests + optional nodeSelector/affinity variety
    (test/integration/scheduler_perf/scheduler_test.go:70, scheduler_bench_test.go:51-67)
  * flagship     — config 4: zones/racks topology, PodTopologySpread +
    InterPodAffinity/AntiAffinity across deployment groups — the 5k×50k
    north-star shape.

Workloads are deterministic (seeded) and built from a small number of pod
templates, like real clusters (Deployments/ReplicaSets stamp identical specs —
exactly the structure the class-interning design exploits).
"""

from __future__ import annotations

import random
from typing import List, Tuple

from ..api.types import (
    Affinity,
    LabelSelector,
    Node,
    Pod,
    PodAffinityTerm,
    Resources,
    TopologySpreadConstraint,
    UnsatisfiableAction,
)

ZONE = "topology.kubernetes.io/zone"
RACK = "topology.kubernetes.io/rack"
HOSTNAME = "kubernetes.io/hostname"


def make_nodes(
    n: int, zones: int = 16, racks_per_zone: int = 20,
    cpu: str = "32", memory: str = "128Gi", pods: int = 110,
) -> List[Node]:
    nodes = []
    for i in range(n):
        z = i % zones
        r = (i // zones) % racks_per_zone
        nodes.append(Node(
            name=f"node-{i}",
            labels={
                ZONE: f"zone-{z}",
                RACK: f"zone-{z}-rack-{r}",
                HOSTNAME: f"node-{i}",
            },
            allocatable=Resources.make(cpu=cpu, memory=memory, pods=pods),
        ))
    return nodes


_TIERS = [("100m", "128Mi"), ("250m", "512Mi"), ("500m", "1Gi"), ("1", "2Gi")]


def density_pods(n: int, groups: int = 50, seed: int = 0) -> List[Pod]:
    """Plain-requests density workload (scheduler_perf config 1)."""
    rng = random.Random(seed)
    tiers = [_TIERS[rng.randrange(len(_TIERS))] for _ in range(groups)]
    pods = []
    for i in range(n):
        g = i % groups
        cpu, mem = tiers[g]
        pods.append(Pod(
            name=f"pod-{g}-{i}",
            labels={"app": f"app-{g}"},
            requests=Resources.make(cpu=cpu, memory=mem),
            creation_index=i,
        ))
    return pods


def gang_workload_pods(n: int, seed: int = 0) -> List[Pod]:
    """Config-5 workload (BASELINE.md row 5): all-or-nothing ML jobs at
    5k nodes × 100k pods. Jobs cycle through gang sizes {8, 16, 32, 64} with
    minMember == size (classic data-parallel training: the job runs only at
    full world size); ~2% of jobs are 'monsters' whose per-member request
    exceeds any node (statically infeasible — they exercise the gang
    engine's bulk-rejection path, the analog of a Permit timeout storm).
    Deterministic by construction."""
    sizes = (8, 16, 32, 64)
    tiers = [("2", "4Gi"), ("4", "8Gi"), ("1", "2Gi"), ("8", "16Gi")]
    pods: List[Pod] = []
    job = 0
    i = 0
    while i < n:
        size = sizes[job % len(sizes)]
        size = min(size, n - i)
        monster = (job % 50) == 49
        cpu, mem = ("64", "512Gi") if monster else tiers[job % len(tiers)]
        for m in range(size):
            pods.append(Pod(
                name=f"job-{job}-w{m}",
                labels={"app": f"job-{job}"},
                requests=Resources.make(cpu=cpu, memory=mem),
                pod_group=f"job-{job}",
                min_member=size,
                priority=job % 3,
                creation_index=i + m,
            ))
        i += size
        job += 1
    return pods


def flagship_pods(n: int, groups: int = 50) -> List[Pod]:
    """Config-4 workload, fully deterministic (no randomness by construction):
    every group spreads across zones (hard, maxSkew≥1); a third of groups also
    anti-affine within hosts; a third require affinity to another group's pods
    in-zone (service co-location)."""
    pods = []
    per_group = max(n // groups, 1)
    for i in range(n):
        g = i % groups
        app = f"app-{g}"
        sel = LabelSelector.of(match_labels={"app": app})
        spread = (TopologySpreadConstraint(
            max_skew=max(2, per_group // 8),
            topology_key=ZONE,
            when_unsatisfiable=UnsatisfiableAction.DO_NOT_SCHEDULE,
            selector=sel,
        ),)
        anti = ()
        aff = ()
        if g % 3 == 1:
            # classic one-replica-per-node DB pattern; hostname domains keep
            # the group schedulable (rack-level would cap the group at #racks)
            anti = (PodAffinityTerm(selector=sel, topology_key=HOSTNAME),)
        elif g % 3 == 2:
            partner = LabelSelector.of(match_labels={"app": f"app-{g - 1}"})
            aff = (PodAffinityTerm(selector=partner, topology_key=ZONE),)
        cpu, mem = _TIERS[g % len(_TIERS)]
        pods.append(Pod(
            name=f"pod-{g}-{i}",
            labels={"app": app},
            requests=Resources.make(cpu=cpu, memory=mem),
            affinity=Affinity(pod_required=aff, anti_required=anti),
            topology_spread=spread,
            priority=g % 3,
            creation_index=i,
        ))
    return pods


def deployment_backlog_pods(n: int, deployments: int = 200,
                            seed: int = 0) -> List[Pod]:
    """Deployment-style backlog (ops/runs.py's motivating shape): each
    'Deployment' stamps its replicas in one contiguous creation burst —
    exactly what a controller scale-up produces — so the queue-ordered wave
    factors into ~`deployments` class runs. Specs are plain requests +
    labels (self-interaction-free classes: the run-collapsed engine's
    closed-form waterfill fires on every run). A few priority tiers ride
    along — each deployment carries ONE priority, so queue order (priority
    desc, creation asc) keeps its replica block contiguous."""
    rng = random.Random(seed)
    per = max(n // deployments, 1)
    pods: List[Pod] = []
    i = 0
    dep = 0
    while i < n:
        # per-deployment cpu makes each deployment a DISTINCT equivalence
        # class even under label projection (unreferenced `app` labels fold
        # out of class identity — state/encode.py), so the backlog really
        # carries `deployments` classes, not len(_TIERS)
        _, mem = _TIERS[rng.randrange(len(_TIERS))]
        cpu = f"{100 + dep}m"
        prio = dep % 3
        size = min(per, n - i)
        for _ in range(size):
            pods.append(Pod(
                name=f"dep-{dep}-{i}",
                labels={"app": f"dep-{dep}"},
                requests=Resources.make(cpu=cpu, memory=mem),
                priority=prio,
                creation_index=i,
            ))
            i += 1
        dep += 1
    return pods
