"""Converters between real Kubernetes v1 JSON objects and the framework's
scheduling object model.

The extender boundary receives full ``v1.Pod`` / ``v1.Node`` JSON from a stock
kube-scheduler (reference: pkg/scheduler/apis/extender/v1/types.go:71 — the
``ExtenderArgs.Pod`` field is a ``*v1.Pod``). These functions parse exactly the
scheduler-relevant slice of those objects into :mod:`kubernetes_tpu.api.types`.

Semantics mirrored from the reference:
  * Pod resource requests = sum over containers, element-wise max with each
    initContainer, plus spec.overhead
    (algorithm/predicates/predicates.go:763 GetResourceRequest).
  * Host ports collected from every container's ports[] with hostPort != 0
    (nodeinfo/node_info.go HostPortInfo population).
  * Affinity/tolerations/topologySpreadConstraints map field-for-field onto the
    dataclasses in api/types.py (staging/src/k8s.io/api/core/v1/types.go).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .types import (
    Affinity,
    HostPort,
    LabelSelector,
    Node,
    NodeSelector,
    NodeSelectorTerm,
    Op,
    Pod,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    Requirement,
    Resources,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOp,
    TopologySpreadConstraint,
    UnsatisfiableAction,
    WeightedPodAffinityTerm,
    parse_cpu_milli,
    parse_mem_kib,
    DEFAULT_SCHEDULER_NAME,
)

_OP = {
    "In": Op.IN,
    "NotIn": Op.NOT_IN,
    "Exists": Op.EXISTS,
    "DoesNotExist": Op.DOES_NOT_EXIST,
    "Gt": Op.GT,
    "Lt": Op.LT,
}
_OP_NAME = {v: k for k, v in _OP.items()}

_EFFECT = {
    "NoSchedule": TaintEffect.NO_SCHEDULE,
    "PreferNoSchedule": TaintEffect.PREFER_NO_SCHEDULE,
    "NoExecute": TaintEffect.NO_EXECUTE,
}
_EFFECT_NAME = {v: k for k, v in _EFFECT.items()}

_TOL_OP = {"Exists": TolerationOp.EXISTS, "Equal": TolerationOp.EQUAL, "": TolerationOp.EQUAL}

_UNSAT = {
    "DoNotSchedule": UnsatisfiableAction.DO_NOT_SCHEDULE,
    "ScheduleAnyway": UnsatisfiableAction.SCHEDULE_ANYWAY,
}


# --------------------------------------------------------------------------- #
# resource accounting (predicates.go:763 GetResourceRequest)
# --------------------------------------------------------------------------- #


def _req_of(requests: Dict[str, Any]) -> Tuple[int, int, int, Dict[str, int]]:
    cpu = parse_cpu_milli(requests.get("cpu", 0))
    mem = parse_mem_kib(requests.get("memory", 0))
    eph = parse_mem_kib(requests.get("ephemeral-storage", 0))
    scalars: Dict[str, int] = {}
    for k, v in requests.items():
        if k in ("cpu", "memory", "ephemeral-storage"):
            continue
        # extended/scalar resources are integer counts (hugepages-* are byte
        # quantities; parse through the suffix table)
        scalars[k] = parse_mem_kib(v) * 1024 if "hugepages" in k else int(parse_cpu_milli(v) / 1000)
    return cpu, mem, eph, scalars


def pod_request_from_spec(spec: Dict[str, Any]) -> Resources:
    """GetResourceRequest: Σ containers, max with each initContainer, + overhead."""
    cpu = mem = eph = 0
    scalars: Dict[str, int] = {}
    for c in spec.get("containers") or []:
        rc, rm, re, rs = _req_of((c.get("resources") or {}).get("requests") or {})
        cpu += rc
        mem += rm
        eph += re
        for k, v in rs.items():
            scalars[k] = scalars.get(k, 0) + v
    for c in spec.get("initContainers") or []:
        rc, rm, re, rs = _req_of((c.get("resources") or {}).get("requests") or {})
        cpu = max(cpu, rc)
        mem = max(mem, rm)
        eph = max(eph, re)
        for k, v in rs.items():
            scalars[k] = max(scalars.get(k, 0), v)
    oc, om, oe, osc = _req_of(spec.get("overhead") or {})
    cpu += oc
    mem += om
    eph += oe
    for k, v in osc.items():
        scalars[k] = scalars.get(k, 0) + v
    return Resources(
        milli_cpu=cpu, memory_kib=mem, ephemeral_kib=eph, pods=1,
        scalars=tuple(sorted(scalars.items())),
    )


# --------------------------------------------------------------------------- #
# selectors / affinity
# --------------------------------------------------------------------------- #


def _requirements(exprs: Optional[List[Dict[str, Any]]]) -> Tuple[Requirement, ...]:
    out = []
    for e in exprs or []:
        out.append(Requirement(e["key"], _OP[e["operator"]], tuple(e.get("values") or ())))
    return tuple(out)


def node_names_from_terms(terms) -> Optional[List[str]]:
    """metadata.name `In` values across raw v1 nodeSelectorTerms — the
    matchFields extraction shared by the PV topology walk
    (volume/pv_controller.py) and the daemon-pod target resolution
    (controllers/workloads.py). None when no such field exists (an
    unrestricted term list is not an empty restriction)."""
    names: List[str] = []
    restricted = False
    for t in terms or []:
        for f in t.get("matchFields") or []:
            if f.get("key") == "metadata.name" and f.get("operator") == "In":
                restricted = True
                names.extend(f.get("values") or [])
    return names if restricted else None


def _node_term(term: Dict[str, Any]) -> NodeSelectorTerm:
    fields = term.get("matchFields") or []
    names: Tuple[str, ...] = ()
    for f in fields:
        if f.get("key") == "metadata.name" and f.get("operator") == "In":
            names = names + tuple(f.get("values") or ())
    return NodeSelectorTerm(
        requirements=_requirements(term.get("matchExpressions")),
        field_name_in=names,
    )


def _label_selector(sel: Optional[Dict[str, Any]]) -> LabelSelector:
    if not sel:
        return LabelSelector()
    return LabelSelector.of(
        match_labels=sel.get("matchLabels") or {},
        expressions=list(_requirements(sel.get("matchExpressions"))),
    )


def _pod_aff_terms(terms: Optional[List[Dict[str, Any]]]) -> Tuple[PodAffinityTerm, ...]:
    return tuple(
        PodAffinityTerm(
            selector=_label_selector(t.get("labelSelector")),
            topology_key=t.get("topologyKey", ""),
            namespaces=tuple(t.get("namespaces") or ()),
        )
        for t in terms or []
    )


def _weighted_pod_aff_terms(
    terms: Optional[List[Dict[str, Any]]],
) -> Tuple[WeightedPodAffinityTerm, ...]:
    return tuple(
        WeightedPodAffinityTerm(
            weight=int(t.get("weight", 1)),
            term=_pod_aff_terms([t.get("podAffinityTerm") or {}])[0],
        )
        for t in terms or []
    )


def affinity_from_spec(spec: Dict[str, Any]) -> Affinity:
    aff = spec.get("affinity") or {}
    node_aff = aff.get("nodeAffinity") or {}
    pod_aff = aff.get("podAffinity") or {}
    anti_aff = aff.get("podAntiAffinity") or {}

    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    node_required = (
        NodeSelector(tuple(_node_term(t) for t in required.get("nodeSelectorTerms") or []))
        if required is not None
        else None
    )
    node_preferred = tuple(
        PreferredSchedulingTerm(weight=int(p.get("weight", 1)), term=_node_term(p.get("preference") or {}))
        for p in node_aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    )
    return Affinity(
        node_required=node_required,
        node_preferred=node_preferred,
        pod_required=_pod_aff_terms(pod_aff.get("requiredDuringSchedulingIgnoredDuringExecution")),
        pod_preferred=_weighted_pod_aff_terms(
            pod_aff.get("preferredDuringSchedulingIgnoredDuringExecution")),
        anti_required=_pod_aff_terms(anti_aff.get("requiredDuringSchedulingIgnoredDuringExecution")),
        anti_preferred=_weighted_pod_aff_terms(
            anti_aff.get("preferredDuringSchedulingIgnoredDuringExecution")),
    )


# --------------------------------------------------------------------------- #
# Pod / Node
# --------------------------------------------------------------------------- #


def pod_from_v1(obj: Dict[str, Any]) -> Pod:
    """Parse the scheduler-relevant slice of a v1.Pod JSON object."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}

    host_ports: List[HostPort] = []
    for c in (spec.get("containers") or []) + (spec.get("initContainers") or []):
        for p in c.get("ports") or []:
            hp = int(p.get("hostPort", 0) or 0)
            if hp > 0:
                host_ports.append(
                    HostPort(port=hp, protocol=p.get("protocol", "TCP") or "TCP",
                             host_ip=p.get("hostIP", "") or "")
                )

    tolerations = tuple(
        Toleration(
            key=t.get("key", "") or "",
            op=_TOL_OP.get(t.get("operator", ""), TolerationOp.EQUAL),
            value=t.get("value", "") or "",
            effect=_EFFECT.get(t.get("effect")) if t.get("effect") else None,
        )
        for t in spec.get("tolerations") or []
    )

    spread = tuple(
        TopologySpreadConstraint(
            max_skew=int(t.get("maxSkew", 1)),
            topology_key=t.get("topologyKey", ""),
            when_unsatisfiable=_UNSAT.get(t.get("whenUnsatisfiable", "DoNotSchedule"),
                                          UnsatisfiableAction.DO_NOT_SCHEDULE),
            selector=_label_selector(t.get("labelSelector")),
        )
        for t in spec.get("topologySpreadConstraints") or []
    )

    # gang scheduling: the coscheduling protocol's pod-carried group
    # reference (label or annotation pod-group.scheduling.sigs.k8s.io/name
    # + .../min-available); no in-tree reference equivalent (BASELINE #5).
    # Label wins over annotation for BOTH keys, so a single source supplies
    # a consistent (name, min) pair.
    labels = dict(meta.get("labels") or {})
    anns = dict(meta.get("annotations") or {})

    def _gang(key):
        full = f"pod-group.scheduling.sigs.k8s.io/{key}"
        return labels.get(full, "") or anns.get(full, "")

    group = _gang("name")
    try:
        min_member = int(_gang("min-available") or 0)
    except (TypeError, ValueError):
        min_member = 0

    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default") or "default",
        uid=meta.get("uid", "") or "",
        labels=labels,
        requests=pod_request_from_spec(spec),
        node_selector=dict(spec.get("nodeSelector") or {}),
        affinity=affinity_from_spec(spec),
        tolerations=tolerations,
        topology_spread=spread,
        host_ports=tuple(host_ports),
        priority=int(spec.get("priority", 0) or 0),
        node_name=spec.get("nodeName", "") or "",
        scheduler_name=spec.get("schedulerName", DEFAULT_SCHEDULER_NAME) or DEFAULT_SCHEDULER_NAME,
        pod_group=group,
        min_member=min_member,
    )


def node_from_v1(obj: Dict[str, Any]) -> Node:
    """Parse the scheduler-relevant slice of a v1.Node JSON object."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    alloc = status.get("allocatable") or {}

    scalars: Dict[str, int] = {}
    for k, v in alloc.items():
        if k in ("cpu", "memory", "ephemeral-storage", "pods"):
            continue
        scalars[k] = parse_mem_kib(v) * 1024 if "hugepages" in k else int(parse_cpu_milli(v) / 1000)

    taints = tuple(
        Taint(key=t.get("key", ""), value=t.get("value", "") or "",
              effect=_EFFECT.get(t.get("effect"), TaintEffect.NO_SCHEDULE))
        for t in spec.get("taints") or []
    )

    images: Dict[str, int] = {}
    for img in status.get("images") or []:
        size_kib = -(-int(img.get("sizeBytes", 0)) // 1024)
        for name in img.get("names") or []:
            images[name] = size_kib

    return Node(
        name=meta.get("name", ""),
        labels=dict(meta.get("labels") or {}),
        prefer_avoid_pods=(
            "scheduler.alpha.kubernetes.io/preferAvoidPods"
            in (meta.get("annotations") or {})),
        allocatable=Resources(
            milli_cpu=parse_cpu_milli(alloc.get("cpu", 0)),
            memory_kib=parse_mem_kib(alloc.get("memory", 0)),
            ephemeral_kib=parse_mem_kib(alloc.get("ephemeral-storage", 0)),
            pods=int(str(alloc.get("pods", 0))),
            scalars=tuple(sorted(scalars.items())),
        ),
        taints=taints,
        unschedulable=bool(spec.get("unschedulable", False)),
        images_kib=images,
    )


# --------------------------------------------------------------------------- #
# back to v1 JSON (for tests and for our own control-plane objects)
# --------------------------------------------------------------------------- #


def pod_to_v1(pod: Pod) -> Dict[str, Any]:
    """Minimal round-trippable v1.Pod JSON for a framework Pod."""
    spec: Dict[str, Any] = {
        "schedulerName": pod.scheduler_name,
        "priority": pod.priority,
        "containers": [{
            "name": "main",
            "resources": {"requests": {
                "cpu": f"{pod.requests.milli_cpu}m",
                "memory": f"{pod.requests.memory_kib}Ki",
                **({"ephemeral-storage": f"{pod.requests.ephemeral_kib}Ki"}
                   if pod.requests.ephemeral_kib else {}),
                **{k: str(v) for k, v in pod.requests.scalars},
            }},
            "ports": [
                {"hostPort": hp.port, "protocol": hp.protocol,
                 **({"hostIP": hp.host_ip} if hp.host_ip else {})}
                for hp in pod.host_ports
            ],
        }],
    }
    if pod.node_name:
        spec["nodeName"] = pod.node_name
    if pod.node_selector:
        spec["nodeSelector"] = dict(pod.node_selector)
    if pod.tolerations:
        spec["tolerations"] = [
            {"key": t.key, "operator": "Exists" if t.op == TolerationOp.EXISTS else "Equal",
             "value": t.value,
             **({"effect": _EFFECT_NAME[t.effect]} if t.effect is not None else {})}
            for t in pod.tolerations
        ]
    aff = _affinity_to_v1(pod.affinity)
    if aff:
        spec["affinity"] = aff
    if pod.topology_spread:
        spec["topologySpreadConstraints"] = [
            {"maxSkew": c.max_skew, "topologyKey": c.topology_key,
             "whenUnsatisfiable": ("DoNotSchedule"
                                   if c.when_unsatisfiable == UnsatisfiableAction.DO_NOT_SCHEDULE
                                   else "ScheduleAnyway"),
             "labelSelector": _selector_to_v1(c.selector)}
            for c in pod.topology_spread
        ]
    md: Dict[str, Any] = {"name": pod.name, "namespace": pod.namespace,
                          "uid": pod.uid, "labels": dict(pod.labels)}
    if pod.pod_group:
        anns: Dict[str, Any] = {
            "pod-group.scheduling.sigs.k8s.io/name": pod.pod_group}
        if pod.min_member:
            anns["pod-group.scheduling.sigs.k8s.io/min-available"] = \
                str(pod.min_member)
        md["annotations"] = anns
    return {"metadata": md, "spec": spec}


def _selector_to_v1(sel: LabelSelector) -> Dict[str, Any]:
    return {"matchExpressions": [
        {"key": r.key, "operator": _OP_NAME[r.op], "values": list(r.values)}
        for r in sel.requirements
    ]}


def _node_term_to_v1(t: NodeSelectorTerm) -> Dict[str, Any]:
    out: Dict[str, Any] = {"matchExpressions": [
        {"key": r.key, "operator": _OP_NAME[r.op], "values": list(r.values)}
        for r in t.requirements
    ]}
    if t.field_name_in:
        out["matchFields"] = [
            {"key": "metadata.name", "operator": "In", "values": list(t.field_name_in)}
        ]
    return out


def _pod_term_to_v1(t: PodAffinityTerm) -> Dict[str, Any]:
    return {"labelSelector": _selector_to_v1(t.selector), "topologyKey": t.topology_key,
            **({"namespaces": list(t.namespaces)} if t.namespaces else {})}


def _affinity_to_v1(aff: Affinity) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    node: Dict[str, Any] = {}
    if aff.node_required is not None:
        node["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [_node_term_to_v1(t) for t in aff.node_required.terms]
        }
    if aff.node_preferred:
        node["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": p.weight, "preference": _node_term_to_v1(p.term)}
            for p in aff.node_preferred
        ]
    if node:
        out["nodeAffinity"] = node
    if aff.pod_required or aff.pod_preferred:
        out["podAffinity"] = {
            **({"requiredDuringSchedulingIgnoredDuringExecution":
                [_pod_term_to_v1(t) for t in aff.pod_required]} if aff.pod_required else {}),
            **({"preferredDuringSchedulingIgnoredDuringExecution":
                [{"weight": w.weight, "podAffinityTerm": _pod_term_to_v1(w.term)}
                 for w in aff.pod_preferred]} if aff.pod_preferred else {}),
        }
    if aff.anti_required or aff.anti_preferred:
        out["podAntiAffinity"] = {
            **({"requiredDuringSchedulingIgnoredDuringExecution":
                [_pod_term_to_v1(t) for t in aff.anti_required]} if aff.anti_required else {}),
            **({"preferredDuringSchedulingIgnoredDuringExecution":
                [{"weight": w.weight, "podAffinityTerm": _pod_term_to_v1(w.term)}
                 for w in aff.anti_preferred]} if aff.anti_preferred else {}),
        }
    return out


def node_to_v1(node: Node) -> Dict[str, Any]:
    return {
        "metadata": {"name": node.name, "labels": dict(node.labels),
                     **({"annotations": {
                         "scheduler.alpha.kubernetes.io/preferAvoidPods":
                         "{}"}} if node.prefer_avoid_pods else {})},
        "spec": {
            **({"taints": [
                {"key": t.key, "value": t.value, "effect": _EFFECT_NAME[t.effect]}
                for t in node.taints
            ]} if node.taints else {}),
            **({"unschedulable": True} if node.unschedulable else {}),
        },
        "status": {
            "allocatable": {
                "cpu": f"{node.allocatable.milli_cpu}m",
                "memory": f"{node.allocatable.memory_kib}Ki",
                "ephemeral-storage": f"{node.allocatable.ephemeral_kib}Ki",
                "pods": str(node.allocatable.pods),
                **{k: str(v) for k, v in node.allocatable.scalars},
            },
            "images": [
                {"names": [name], "sizeBytes": kib * 1024}
                for name, kib in sorted(node.images_kib.items())
            ],
        },
    }
