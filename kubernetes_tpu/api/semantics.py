"""Executable specification of the reference scheduler's matching semantics.

Pure-Python, pod-at-a-time re-statement of the predicate/priority semantics in
`pkg/scheduler/algorithm/predicates/predicates.go` and
`staging/src/k8s.io/apimachinery/pkg/labels/selector.go`. This module is the
*oracle*: the tensorized device kernels in `kubernetes_tpu.ops` are golden-tested
bit-for-bit against it (mirroring how the reference table-tests predicates).

It is intentionally slow and obvious. Nothing here runs on the hot path.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .types import (
    Affinity,
    HostPort,
    LabelSelector,
    Node,
    NodeSelector,
    NodeSelectorTerm,
    Op,
    Pod,
    PodAffinityTerm,
    Requirement,
    Resources,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOp,
    TopologySpreadConstraint,
    UnsatisfiableAction,
)

# --------------------------------------------------------------------------- #
# labels.Requirement.Matches — apimachinery labels/selector.go:192-215
# --------------------------------------------------------------------------- #


def requirement_matches(req: Requirement, labels: Dict[str, str]) -> bool:
    has = req.key in labels
    if req.op == Op.IN:
        return has and labels[req.key] in req.values
    if req.op == Op.NOT_IN:
        # selector.go:199-203 — absent key satisfies NotIn
        return (not has) or labels[req.key] not in req.values
    if req.op == Op.EXISTS:
        return has
    if req.op == Op.DOES_NOT_EXIST:
        return not has
    if req.op in (Op.GT, Op.LT):
        # selector.go:208-233 — key must exist, both sides parse as int64
        if not has:
            return False
        try:
            lhs = int(labels[req.key])
            rhs = int(req.values[0])
        except (ValueError, IndexError):
            return False
        return lhs > rhs if req.op == Op.GT else lhs < rhs
    raise AssertionError(req.op)


def selector_matches(sel: LabelSelector, labels: Dict[str, str]) -> bool:
    """Empty selector matches everything (labels.Everything)."""
    return all(requirement_matches(r, labels) for r in sel.requirements)


def node_selector_term_matches(term: NodeSelectorTerm, node: Node) -> bool:
    """v1helper.MatchNodeSelectorTerms: empty term matches nothing; matchFields
    only supports metadata.name."""
    if not term.requirements and not term.field_name_in:
        return False
    for req in term.requirements:
        if not requirement_matches(req, node.labels):
            return False
    if term.field_name_in and node.name not in term.field_name_in:
        return False
    return True


def node_selector_matches(ns: NodeSelector, node: Node) -> bool:
    """OR of terms; empty term list matches nothing."""
    return any(node_selector_term_matches(t, node) for t in ns.terms)


# --------------------------------------------------------------------------- #
# Predicates
# --------------------------------------------------------------------------- #


def pod_matches_node_selector(pod: Pod, node: Node) -> bool:
    """PodMatchNodeSelector → podMatchesNodeSelectorAndAffinityTerms
    (predicates.go:867-914): spec.nodeSelector AND node-affinity required."""
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    if pod.affinity.node_required is not None:
        # nil RequiredDuringScheduling ⇒ match; non-nil delegates to
        # MatchNodeSelectorTerms (predicates.go:894-906)
        if not node_selector_matches(pod.affinity.node_required, node):
            return False
    return True


def pod_fits_host(pod: Pod, node: Node) -> bool:
    """PodFitsHost (predicates.go:926-935)."""
    return not pod.node_name or pod.node_name == node.name


def pod_fits_resources(
    pod: Pod, node: Node, used: Resources, used_pods: int
) -> Tuple[bool, List[str]]:
    """PodFitsResources (predicates.go:789-845): pods count, CPU, memory,
    ephemeral storage, then every scalar resource."""
    alloc = node.allocatable
    fails: List[str] = []
    if used_pods + 1 > alloc.pods:
        fails.append("pods")
    req = pod.requests
    if req.milli_cpu == 0 and req.memory_kib == 0 and req.ephemeral_kib == 0 and not req.scalars:
        return (not fails, fails)
    if req.milli_cpu > alloc.milli_cpu - used.milli_cpu:
        fails.append("cpu")
    if req.memory_kib > alloc.memory_kib - used.memory_kib:
        fails.append("memory")
    if req.ephemeral_kib > alloc.ephemeral_kib - used.ephemeral_kib:
        fails.append("ephemeral-storage")
    used_scalars = dict(used.scalars)
    alloc_scalars = dict(alloc.scalars)
    for name, amount in req.scalars:
        if amount > alloc_scalars.get(name, 0) - used_scalars.get(name, 0):
            fails.append(name)
    return (not fails, fails)


def tolerates_taint(tol: Toleration, taint: Taint) -> bool:
    """v1helper Toleration.ToleratesTaint."""
    if tol.effect is not None and tol.effect != taint.effect:
        return False
    if tol.key and tol.key != taint.key:
        return False
    # empty key with Exists matches all keys
    if tol.op == TolerationOp.EXISTS:
        return True
    return tol.value == taint.value


def pod_tolerates_node_taints(pod: Pod, node: Node) -> bool:
    """PodToleratesNodeTaints (predicates.go:1543-1549): only NoSchedule and
    NoExecute taints filter; PreferNoSchedule is score-only."""
    for taint in node.taints:
        if taint.effect == TaintEffect.PREFER_NO_SCHEDULE:
            continue
        if not any(tolerates_taint(t, taint) for t in pod.tolerations):
            return False
    return True


def _port_conflict(a: HostPort, b: HostPort) -> bool:
    """HostPortInfo conflict: same protocol+port, and IPs equal or either is
    wildcard (node_info.go hostPortInfo.CheckConflict)."""
    if a.protocol != b.protocol or a.port != b.port:
        return False
    wild = ("", "0.0.0.0")
    return a.host_ip in wild or b.host_ip in wild or a.host_ip == b.host_ip


def pod_fits_host_ports(pod: Pod, node_used_ports: Sequence[HostPort]) -> bool:
    """PodFitsHostPorts (predicates.go:1104-1120)."""
    for want in pod.host_ports:
        if want.port == 0:
            continue
        if any(_port_conflict(want, have) for have in node_used_ports):
            return False
    return True


def check_node_unschedulable(pod: Pod, node: Node) -> bool:
    """CheckNodeUnschedulablePredicate (predicates.go:1522-1541): node.spec
    .unschedulable blocks unless tolerated (key node.kubernetes.io/unschedulable,
    effect NoSchedule)."""
    if not node.unschedulable:
        return True
    fake = Taint(key="node.kubernetes.io/unschedulable", effect=TaintEffect.NO_SCHEDULE)
    return any(tolerates_taint(t, fake) for t in pod.tolerations)


# --------------------------------------------------------------------------- #
# Inter-pod affinity — predicates.go:1212-1520
# --------------------------------------------------------------------------- #


def term_namespaces(term: PodAffinityTerm, owner: Pod) -> Tuple[str, ...]:
    """GetNamespacesFromPodAffinityTerm: empty ⇒ the owner pod's namespace."""
    return term.namespaces if term.namespaces else (owner.namespace,)


def term_matches_pod(term: PodAffinityTerm, owner: Pod, other: Pod) -> bool:
    """PodMatchesTermsNamespaceAndSelector."""
    if other.namespace not in term_namespaces(term, owner):
        return False
    return selector_matches(term.selector, other.labels)


def interpod_affinity_fits(
    pod: Pod,
    node: Node,
    nodes_by_name: Dict[str, Node],
    existing: Sequence[Pod],
) -> bool:
    """InterPodAffinityMatches (predicates.go:1212-1260) for one candidate node:
      1. every required affinity term has ≥1 matching existing pod in the same
         topology domain — OR matches the incoming pod itself (the self-match
         rule, predicates.go:1438-1461);
      2. no required anti-affinity term of the incoming pod matches any existing
         pod in-domain (predicates.go:1463-1487);
      3. no existing pod has a required anti-affinity term matching the incoming
         pod in-domain (symmetry, satisfiesExistingPodsAntiAffinity :1319-1360).
    Pods on nodes lacking the topology key are never in-domain."""

    def in_domain(other_node_name: str, topology_key: str) -> bool:
        other = nodes_by_name.get(other_node_name)
        if other is None or topology_key not in node.labels or topology_key not in other.labels:
            return False
        return node.labels[topology_key] == other.labels[topology_key]

    # 1. required affinity: every term needs ≥1 matching existing pod in the
    # candidate's topology domain (nodeMatchesAllTopologyTerms). Escape hatch
    # (predicates.go:1436-1440): if NO existing pod on a keyed node matches ANY
    # term (the potential-affinity map is empty) and the pod matches all its
    # own terms, the pod passes on every node — no node-label condition.
    if pod.affinity.pod_required:
        def keyed(ex: Pod, topology_key: str) -> bool:
            exn = nodes_by_name.get(ex.node_name)
            return exn is not None and topology_key in exn.labels

        all_terms_hit = all(
            any(
                term_matches_pod(term, pod, ex) and in_domain(ex.node_name, term.topology_key)
                for ex in existing
            )
            for term in pod.affinity.pod_required
        )
        if not all_terms_hit:
            map_empty = not any(
                term_matches_pod(term, pod, ex) and keyed(ex, term.topology_key)
                for term in pod.affinity.pod_required
                for ex in existing
            )
            self_all = all(
                term_matches_pod(term, pod, pod) for term in pod.affinity.pod_required
            )
            if not (map_empty and self_all):
                return False
    # 2. incoming pod's anti-affinity vs existing pods (no escape hatch)
    for term in pod.affinity.anti_required:
        for ex in existing:
            if term_matches_pod(term, pod, ex) and in_domain(ex.node_name, term.topology_key):
                return False
    # 3. existing pods' anti-affinity vs incoming pod (symmetry)
    for ex in existing:
        for term in ex.affinity.anti_required:
            if term_matches_pod(term, ex, pod) and in_domain(ex.node_name, term.topology_key):
                return False
    return True


# --------------------------------------------------------------------------- #
# Pod topology spread (EvenPodsSpread) — predicates.go:1643-1703, metadata.go
# --------------------------------------------------------------------------- #


def topology_spread_fits(
    pod: Pod,
    node: Node,
    nodes: Sequence[Node],
    existing: Sequence[Pod],
) -> bool:
    """EvenPodsSpreadPredicate for hard (DoNotSchedule) constraints.

    For each constraint: candidate node must carry the topology key; the match
    count on the candidate's topology value, plus this pod (selfMatch,
    metadata.go podSpreadCache semantics), minus the global minimum match count
    over eligible topology values, must be ≤ maxSkew. Eligible values are those
    of nodes that pass the pod's nodeSelector/affinity *and* carry the key
    (metadata.go:114-176 — nodes are pre-filtered by PodMatchesNodeSelectorAndAffinityTerms)."""
    hard = [c for c in pod.topology_spread if c.when_unsatisfiable == UnsatisfiableAction.DO_NOT_SCHEDULE]
    if not hard:
        return True
    for c in hard:
        if c.topology_key not in node.labels:
            return False
        counts: Dict[str, int] = {}
        for n in nodes:
            if c.topology_key not in n.labels:
                continue
            if not pod_matches_node_selector(pod, n):
                continue
            counts.setdefault(n.labels[c.topology_key], 0)
        for ex in existing:
            ex_node = next((n for n in nodes if n.name == ex.node_name), None)
            if ex_node is None or c.topology_key not in ex_node.labels:
                continue
            val = ex_node.labels[c.topology_key]
            if val not in counts:
                continue  # node not eligible for this pod
            if ex.namespace == pod.namespace and selector_matches(c.selector, ex.labels):
                counts[val] += 1
        if not counts:
            # empty eligible-domain map ⇒ the constraint passes everywhere
            # (predicates.go:1661-1663: len(tpPairToMatchNum)==0 → true)
            continue
        self_match = 1 if selector_matches(c.selector, pod.labels) else 0
        val = node.labels[c.topology_key]
        # a pair absent from the map reads as matchNum 0 (Go map zero value)
        match_num = counts.get(val, 0)
        min_count = min(counts.values())
        if match_num + self_match - min_count > c.max_skew:
            return False
    return True


# --------------------------------------------------------------------------- #
# Priorities (scores) — pkg/scheduler/algorithm/priorities/
# --------------------------------------------------------------------------- #

MAX_NODE_SCORE = 100  # framework/v1alpha1/interface.go:87


def _fraction(req: int, cap: int) -> float:
    return 0.0 if cap == 0 else req / cap


def least_requested_score(req: Resources, used: Resources, alloc: Resources) -> int:
    """least_requested.go: ((cap-req)*MaxNodeScore/cap averaged over cpu+mem)."""

    def per(reqv: int, usedv: int, capv: int) -> int:
        total = usedv + reqv
        if capv == 0 or total > capv:
            return 0
        return ((capv - total) * MAX_NODE_SCORE) // capv

    return (
        per(req.milli_cpu, used.milli_cpu, alloc.milli_cpu)
        + per(req.memory_kib, used.memory_kib, alloc.memory_kib)
    ) // 2


def most_requested_score(req: Resources, used: Resources, alloc: Resources) -> int:
    """most_requested.go: (total*MaxNodeScore/cap averaged over cpu+mem)."""

    def per(reqv: int, usedv: int, capv: int) -> int:
        total = usedv + reqv
        if capv == 0 or total > capv:
            return 0
        return (total * MAX_NODE_SCORE) // capv

    return (
        per(req.milli_cpu, used.milli_cpu, alloc.milli_cpu)
        + per(req.memory_kib, used.memory_kib, alloc.memory_kib)
    ) // 2


def balanced_allocation_score(req: Resources, used: Resources, alloc: Resources) -> int:
    """balanced_resource_allocation.go: 100 - |cpuFraction-memFraction|*100
    (two-resource variant; volume fraction off by default)."""
    cpu = _fraction(used.milli_cpu + req.milli_cpu, alloc.milli_cpu)
    mem = _fraction(used.memory_kib + req.memory_kib, alloc.memory_kib)
    if cpu >= 1 or mem >= 1:
        return 0
    return int(100 - abs(cpu - mem) * 100)


def taint_toleration_score(pod: Pod, node: Node) -> int:
    """taint_toleration.go: count of intolerable PreferNoSchedule taints,
    reduced to 0..100 (fewer = better) by reduce (max-normalized elsewhere);
    here we return the raw intolerable count for the kernel golden test."""
    count = 0
    for taint in node.taints:
        if taint.effect != TaintEffect.PREFER_NO_SCHEDULE:
            continue
        if not any(tolerates_taint(t, taint) for t in pod.tolerations):
            count += 1
    return count


def node_affinity_score(pod: Pod, node: Node) -> int:
    """node_affinity.go CalculateNodeAffinityPriorityMap: sum of weights of
    matching preferred terms (raw, reduce normalizes)."""
    total = 0
    for pref in pod.affinity.node_preferred:
        if pref.weight == 0:
            continue
        if node_selector_term_matches(pref.term, node):
            total += pref.weight
    return total


def no_disk_conflict(pod: Pod, node_pods: Sequence[Pod]) -> bool:
    """NoDiskConflict (predicates.go:156-221): same (driver, volume) on one
    node conflicts unless both mounts are read-only."""
    for v in pod.volumes:
        for ex in node_pods:
            for ev in ex.volumes:
                if v.driver == ev.driver and v.vol_id == ev.vol_id \
                        and not (v.read_only and ev.read_only):
                    return False
    return True


def max_volume_count_fits(pod: Pod, node: Node,
                          node_pods: Sequence[Pod]) -> bool:
    """Max attachable volumes per driver (csi_volume_predicate.go:89-160):
    distinct volumes already attached plus the pod's new distinct volumes
    must stay within Node.volume_limits[driver] (absent = unlimited)."""
    if not pod.volumes or not node.volume_limits:
        return True
    attached: Dict[str, set] = {}
    for ex in node_pods:
        for ev in ex.volumes:
            attached.setdefault(ev.driver, set()).add(ev.vol_id)
    for v in pod.volumes:
        attached.setdefault(v.driver, set()).add(v.vol_id)
    for drv, lim in node.volume_limits.items():
        if lim >= 0 and len(attached.get(drv, ())) > lim:
            return False
    return True


# --------------------------------------------------------------------------- #
# Score parity set (priorities/) — pure-Python references for the tensor
# kernels in ops/scores.py; golden-tested in tests/test_scores.py
# --------------------------------------------------------------------------- #

DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1
IMG_MIN_KIB = 23 * 1024
IMG_MAX_KIB = 1000 * 1024
ZONE_WEIGHTING = 2.0 / 3.0
ZONE_LABELS = ("topology.kubernetes.io/zone",
               "failure-domain.beta.kubernetes.io/zone")


def _same_domain(a: Node, b: Node, key: str) -> bool:
    return key in a.labels and key in b.labels and a.labels[key] == b.labels[key]


def interpod_preferred_raw(
    pod: Pod,
    node: Node,
    nodes_by_name: Dict[str, Node],
    existing: Sequence[Pod],
    hard_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT,
) -> float:
    """Raw (un-normalized) preferred inter-pod affinity count for one candidate
    node — all four directions of interpod_affinity.go:119-215:
      + pod's preferred terms matching existing pods in-domain,
      − pod's preferred anti terms,
      + existing pods' REQUIRED affinity terms matching the pod × hard_weight,
      + existing pods' preferred terms matching the pod,
      − existing pods' preferred anti terms matching the pod."""
    raw = 0.0
    for ex in existing:
        exn = nodes_by_name.get(ex.node_name)
        if exn is None:
            continue
        for w in pod.affinity.pod_preferred:
            if term_matches_pod(w.term, pod, ex) and _same_domain(
                    node, exn, w.term.topology_key):
                raw += w.weight
        for w in pod.affinity.anti_preferred:
            if term_matches_pod(w.term, pod, ex) and _same_domain(
                    node, exn, w.term.topology_key):
                raw -= w.weight
        for term in ex.affinity.pod_required:
            if term_matches_pod(term, ex, pod) and _same_domain(
                    node, exn, term.topology_key):
                raw += hard_weight
        for w in ex.affinity.pod_preferred:
            if term_matches_pod(w.term, ex, pod) and _same_domain(
                    node, exn, w.term.topology_key):
                raw += w.weight
        for w in ex.affinity.anti_preferred:
            if term_matches_pod(w.term, ex, pod) and _same_domain(
                    node, exn, w.term.topology_key):
                raw -= w.weight
    return raw


def interpod_preferred_scores(
    pod: Pod, nodes: Sequence[Node], existing: Sequence[Pod],
    hard_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT,
) -> Dict[str, float]:
    """Min-max normalized 0..100 over all nodes (ops/interpod.py convention:
    the normalization domain is every valid node; constant raw ⇒ 0)."""
    by_name = {n.name: n for n in nodes}
    raw = {n.name: interpod_preferred_raw(pod, n, by_name, existing,
                                          hard_weight) for n in nodes}
    lo, hi = min(raw.values()), max(raw.values())
    if hi <= lo:
        return {n.name: 0.0 for n in nodes}
    return {k: 100.0 * (v - lo) / (hi - lo) for k, v in raw.items()}


def even_spread_soft_scores(
    pod: Pod, nodes: Sequence[Node], existing: Sequence[Pod]
) -> Dict[str, float]:
    """EvenPodsSpread SCORE over ScheduleAnyway constraints
    (even_pods_spread.go:106-227), normalization domain = all eligible nodes
    (docs/PARITY.md)."""
    soft = [c for c in pod.topology_spread
            if int(c.when_unsatisfiable) != 0]
    out = {n.name: 0.0 for n in nodes}
    if not soft:
        return out

    def node_matchable(n: Node) -> bool:
        return pod_matches_node_selector(pod, n)

    def elig(n: Node) -> bool:
        return node_matchable(n) and all(
            c.topology_key in n.labels for c in soft)

    # per (constraint, topo value) matching-pod counts over matchable nodes
    by_name = {n.name: n for n in nodes}
    counts: Dict[Tuple[int, str], int] = {}
    for ci, c in enumerate(soft):
        for ex in existing:
            exn = by_name.get(ex.node_name)
            if exn is None or not node_matchable(exn):
                continue
            if c.topology_key not in exn.labels:
                continue
            if ex.namespace != pod.namespace:
                continue
            if not selector_matches(c.selector, ex.labels):
                continue
            key = (ci, exn.labels[c.topology_key])
            counts[key] = counts.get(key, 0) + 1

    raw = {}
    for n in nodes:
        r = 0
        for ci, c in enumerate(soft):
            if c.topology_key in n.labels:
                r += counts.get((ci, n.labels[c.topology_key]), 0)
        raw[n.name] = r

    elig_nodes = [n for n in nodes if elig(n)]
    if not elig_nodes:
        return out
    total = sum(raw[n.name] for n in elig_nodes)
    mn = min(raw[n.name] for n in elig_nodes)
    denom = total - mn
    for n in elig_nodes:
        out[n.name] = (100.0 * (total - raw[n.name]) / denom
                       if denom > 0 else 100.0)
    return out


def selector_spread_scores(
    pod: Pod, nodes: Sequence[Node], existing: Sequence[Pod]
) -> Dict[str, float]:
    """SelectorSpread (selector_spreading.go:62-165): fewest same-owner pods
    per node, zone-blended 1/3:2/3 when zone labels exist."""
    out = {n.name: 0.0 for n in nodes}
    if not pod.spread_selectors:
        return out

    def matches(ex: Pod) -> bool:
        return ex.namespace == pod.namespace and all(
            selector_matches(s, ex.labels) for s in pod.spread_selectors)

    count = {n.name: 0 for n in nodes}
    for ex in existing:
        if ex.node_name in count and matches(ex):
            count[ex.node_name] += 1

    def zone_of(n: Node):
        for zl in ZONE_LABELS:
            if zl in n.labels:
                return (zl, n.labels[zl])
        return None

    max_n = max(count.values(), default=0)
    zcounts: Dict[tuple, int] = {}
    for n in nodes:
        z = zone_of(n)
        if z is not None:
            zcounts[z] = zcounts.get(z, 0) + count[n.name]
    max_z = max(zcounts.values(), default=0)
    have_zones = bool(zcounts)

    for n in nodes:
        f = 100.0
        if max_n > 0:
            f = 100.0 * (max_n - count[n.name]) / max_n
        z = zone_of(n)
        if have_zones and z is not None:
            zs = 100.0
            if max_z > 0:
                zs = 100.0 * (max_z - zcounts[z]) / max_z
            f = f * (1.0 - ZONE_WEIGHTING) + ZONE_WEIGHTING * zs
        out[n.name] = f
    return out


def image_locality_scores(
    pod: Pod, nodes: Sequence[Node]
) -> Dict[str, float]:
    """ImageLocality (image_locality.go:39-92): sum of spread-scaled sizes of
    the pod's images already present on the node, clamped and scaled."""
    total = max(len(nodes), 1)
    num_nodes = {
        img: sum(1 for n in nodes if img in n.images_kib)
        for n_ in nodes for img in n_.images_kib
    }
    sizes: Dict[str, int] = {}
    for n in nodes:
        for img, s in n.images_kib.items():
            sizes.setdefault(img, s)
    out = {}
    for n in nodes:
        s = 0.0
        for img in pod.images:
            if img in n.images_kib:
                spread = num_nodes.get(img, 0) / total
                s += sizes.get(img, 0) * spread
        s = min(max(s, IMG_MIN_KIB), IMG_MAX_KIB)
        out[n.name] = 100.0 * (s - IMG_MIN_KIB) / (IMG_MAX_KIB - IMG_MIN_KIB)
    return out
