"""Core-type validation corpus — the pkg/apis/core/validation seat.

The reference validates every inbound object against a large hand-written
rule set (`pkg/apis/core/validation/validation.go`, ~6k LoC;
`apimachinery/pkg/util/validation/validation.go` for the name/label
grammars). This module carries the shape-defining subset for the types this
framework serves — metadata name/label grammar, pod spec structure,
container resources/ports, node taints and quantities — returning the
reference's `field.ErrorList`-style strings ("path: kind: detail") that the
registry turns into 422 Invalid responses.

Grammar rules mirrored exactly (validation.go):
  * DNS-1123 label:      [a-z0-9]([-a-z0-9]*[a-z0-9])?          ≤ 63
  * DNS-1123 subdomain:  label(.label)*                          ≤ 253
  * qualified name:      [prefix/]name, prefix a subdomain, name
                         [A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])? ≤ 63
  * label value:         empty or qualified-name body             ≤ 63
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

from .types import parse_cpu_milli, parse_mem_kib

Obj = Dict[str, Any]

_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_DNS1123_SUB = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_QUAL_NAME = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")

_PROTOCOLS = ("TCP", "UDP", "SCTP")
_RESTART_POLICIES = ("Always", "OnFailure", "Never")
_TAINT_EFFECTS = ("NoSchedule", "PreferNoSchedule", "NoExecute")
_UNSATISFIABLE = ("DoNotSchedule", "ScheduleAnyway")


def is_dns1123_label(s: str) -> bool:
    return isinstance(s, str) and len(s) <= 63 and bool(
        _DNS1123_LABEL.match(s))


def is_dns1123_subdomain(s: str) -> bool:
    return isinstance(s, str) and len(s) <= 253 and bool(
        _DNS1123_SUB.match(s))


def is_qualified_name(s: str) -> bool:
    if not isinstance(s, str) or not s:
        return False
    parts = s.split("/")
    if len(parts) > 2:
        return False
    if len(parts) == 2:
        prefix, name = parts
        if not is_dns1123_subdomain(prefix):
            return False
    else:
        name = parts[0]
    return len(name) <= 63 and bool(_QUAL_NAME.match(name))


def is_label_value(s: str) -> bool:
    if not isinstance(s, str):
        return False
    if s == "":
        return True
    return len(s) <= 63 and bool(_QUAL_NAME.match(s))


def _as_int(v: Any):
    """Untrusted-input int coercion: None instead of ValueError — a
    malformed field must become a 422 field error, never a 500."""
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


def _valid_quantity(q: Any, mem: bool = False) -> bool:
    try:
        (parse_mem_kib if mem else parse_cpu_milli)(q)
        return True
    except (ValueError, TypeError, AttributeError):
        return False


def validate_object_meta(obj: Obj, namespaced: bool = True,
                         path: str = "metadata") -> List[str]:
    """ValidateObjectMeta (validation.go): name/namespace grammar, label and
    annotation key/value grammar."""
    errs: List[str] = []
    md = obj.get("metadata") or {}
    name = md.get("name", "")
    if not name:
        errs.append(f"{path}.name: Required value")
    elif not is_dns1123_subdomain(name):
        errs.append(f"{path}.name: Invalid value: {name!r}: a lowercase "
                    "RFC 1123 subdomain must consist of lower case "
                    "alphanumeric characters, '-' or '.'")
    ns = md.get("namespace", "")
    if namespaced and ns and not is_dns1123_label(ns):
        errs.append(f"{path}.namespace: Invalid value: {ns!r}")
    for k, v in (md.get("labels") or {}).items():
        if not is_qualified_name(k):
            errs.append(f"{path}.labels: Invalid value: {k!r}: "
                        "name part must consist of alphanumeric characters")
        if not is_label_value(v):
            errs.append(f"{path}.labels: Invalid value: {v!r}: must be 63 "
                        "characters or less")
    for k in (md.get("annotations") or {}):
        if not is_qualified_name(k):
            errs.append(f"{path}.annotations: Invalid value: {k!r}")
    return errs


def _validate_resources(res: Obj, path: str) -> List[str]:
    """ValidateResourceRequirements: quantities parse; requests ≤ limits for
    cpu/memory (validation.go ValidateResourceRequirements)."""
    errs: List[str] = []
    reqs = res.get("requests") or {}
    lims = res.get("limits") or {}
    for side, d in (("requests", reqs), ("limits", lims)):
        for rname, q in d.items():
            mem = rname in ("memory", "ephemeral-storage") \
                or "hugepages" in str(rname)
            if not _valid_quantity(q, mem=mem):
                errs.append(f"{path}.{side}[{rname}]: Invalid value: {q!r}: "
                            "quantities must match the regular expression")
    for rname in ("cpu", "memory"):
        if rname in reqs and rname in lims \
                and _valid_quantity(reqs[rname], mem=rname == "memory") \
                and _valid_quantity(lims[rname], mem=rname == "memory"):
            mem = rname == "memory"
            parse = parse_mem_kib if mem else parse_cpu_milli
            if parse(reqs[rname]) > parse(lims[rname]):
                errs.append(f"{path}.requests[{rname}]: Invalid value: "
                            "must be less than or equal to "
                            f"{rname} limit")
    return errs


def validate_pod_spec(spec: Obj, path: str = "spec") -> List[str]:
    """ValidatePodSpec: containers present, names unique DNS-1123 labels,
    images present, ports/protocols in range, restartPolicy enum,
    affinity weights, spread constraints (validation.go ValidatePodSpec /
    validateContainers / validateTopologySpreadConstraints)."""
    errs: List[str] = []
    containers = spec.get("containers")
    if not containers:
        errs.append(f"{path}.containers: Required value")
    seen = set()
    for i, c in enumerate(containers or []):
        cp = f"{path}.containers[{i}]"
        if not isinstance(c, dict):
            errs.append(f"{cp}: Invalid value: expected an object")
            continue
        nm = c.get("name", "")
        if not nm:
            errs.append(f"{cp}.name: Required value")
        elif not is_dns1123_label(nm):
            errs.append(f"{cp}.name: Invalid value: {nm!r}")
        elif nm in seen:
            errs.append(f"{cp}.name: Duplicate value: {nm!r}")
        seen.add(nm)
        if not c.get("image"):
            errs.append(f"{cp}.image: Required value")
        for j, p in enumerate(c.get("ports") or []):
            if not isinstance(p, dict):
                errs.append(f"{cp}.ports[{j}]: Invalid value: "
                            "expected an object")
                continue
            for fld in ("containerPort", "hostPort"):
                v = p.get(fld)
                if v is None:
                    continue
                iv = _as_int(v)
                if iv is None or not 0 < iv <= 65535:
                    errs.append(f"{cp}.ports[{j}].{fld}: Invalid value: "
                                f"{v!r}: must be between 1 and 65535")
            proto = p.get("protocol", "TCP")
            if proto not in _PROTOCOLS:
                errs.append(f"{cp}.ports[{j}].protocol: Unsupported value: "
                            f"{proto!r}")
        errs.extend(_validate_resources(c.get("resources") or {},
                                        f"{cp}.resources"))
    rp = spec.get("restartPolicy")
    if rp is not None and rp not in _RESTART_POLICIES:
        errs.append(f"{path}.restartPolicy: Unsupported value: {rp!r}")
    pri = spec.get("priority")
    if pri is not None and not isinstance(pri, int):
        errs.append(f"{path}.priority: Invalid value: {pri!r}: "
                    "must be an integer")
    for i, t in enumerate(spec.get("tolerations") or []):
        op = t.get("operator", "Equal")
        if op not in ("Equal", "Exists"):
            errs.append(f"{path}.tolerations[{i}].operator: "
                        f"Unsupported value: {op!r}")
        if op == "Exists" and t.get("value"):
            errs.append(f"{path}.tolerations[{i}].value: Invalid value: "
                        "value must be empty when `operator` is 'Exists'")
        eff = t.get("effect")
        if eff and eff not in _TAINT_EFFECTS:
            errs.append(f"{path}.tolerations[{i}].effect: "
                        f"Unsupported value: {eff!r}")
    aff = (spec.get("affinity") or {})
    for kind in ("podAffinity", "podAntiAffinity"):
        for i, w in enumerate((aff.get(kind) or {}).get(
                "preferredDuringSchedulingIgnoredDuringExecution") or []):
            wt = _as_int(w.get("weight", 0)) if isinstance(w, dict) else None
            if wt is None or not 1 <= wt <= 100:
                errs.append(f"{path}.affinity.{kind}.preferred[{i}].weight: "
                            "Invalid value: must be in the range 1-100")
    for i, c in enumerate(spec.get("topologySpreadConstraints") or []):
        tp = f"{path}.topologySpreadConstraints[{i}]"
        if not isinstance(c, dict):
            errs.append(f"{tp}: Invalid value: expected an object")
            continue
        skew = _as_int(c.get("maxSkew", 0) or 0)
        if skew is None or skew < 1:
            errs.append(f"{tp}.maxSkew: Invalid value: must be greater "
                        "than zero")
        if not c.get("topologyKey"):
            errs.append(f"{tp}.topologyKey: Required value")
        wu = c.get("whenUnsatisfiable", "DoNotSchedule")
        if wu not in _UNSATISFIABLE:
            errs.append(f"{tp}.whenUnsatisfiable: Unsupported value: {wu!r}")
    return errs


def validate_pod(obj: Obj) -> List[str]:
    return validate_object_meta(obj) + validate_pod_spec(
        obj.get("spec") or {})


def validate_node(obj: Obj) -> List[str]:
    """ValidateNode: metadata, taints (qualified key + effect enum),
    capacity/allocatable quantities."""
    errs = validate_object_meta(obj, namespaced=False)
    for i, t in enumerate((obj.get("spec") or {}).get("taints") or []):
        tp = f"spec.taints[{i}]"
        if not is_qualified_name(t.get("key", "")):
            errs.append(f"{tp}.key: Invalid value: {t.get('key')!r}")
        if t.get("value") and not is_label_value(t["value"]):
            errs.append(f"{tp}.value: Invalid value: {t['value']!r}")
        if t.get("effect") not in _TAINT_EFFECTS:
            errs.append(f"{tp}.effect: Unsupported value: "
                        f"{t.get('effect')!r}")
    status = obj.get("status") or {}
    for side in ("capacity", "allocatable"):
        for rname, q in (status.get(side) or {}).items():
            mem = rname in ("memory", "ephemeral-storage") \
                or "hugepages" in str(rname)
            if rname == "pods":
                ok = str(q).isdigit()
            else:
                ok = _valid_quantity(q, mem=mem)
            if not ok:
                errs.append(f"status.{side}[{rname}]: Invalid value: {q!r}")
    return errs
