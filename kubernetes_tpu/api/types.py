"""Core scheduling object model.

Host-side, schema-level mirror of the scheduling-relevant slice of the reference
API surface (reference: staging/src/k8s.io/api/core/v1/types.go — Pod, Node,
NodeSelector, Taint/Toleration, Affinity, TopologySpreadConstraint). These are
deliberately *not* the full Kubernetes objects: they carry exactly the fields the
scheduler reads, in a form that encodes losslessly into flat device arrays
(see kubernetes_tpu.state.encode).

Design notes (TPU-first, not a port):
  * All string worlds (label keys/values, taint keys, topology keys, resource
    names, ports) are interned into integer vocabularies before reaching the
    device; these dataclasses keep the strings for the host mirror only.
  * Resource quantities are canonicalized at parse time: CPU in milliCPU,
    memory/ephemeral-storage in KiB, extended/scalar resources in integer
    counts — so device arrays are exact int32 and comparisons are bit-faithful
    to the reference (predicates.go:789-845 PodFitsResources).
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


# --------------------------------------------------------------------------- #
# Operators and enums (reference: staging/src/k8s.io/api/core/v1/types.go)
# --------------------------------------------------------------------------- #


class Op(enum.IntEnum):
    """Selector requirement operator.

    NodeSelectorOperator (types.go:2560-2568) plus the label-selector operators
    (metav1.LabelSelectorOperator); Gt/Lt are node-selector only.
    """

    IN = 0
    NOT_IN = 1
    EXISTS = 2
    DOES_NOT_EXIST = 3
    GT = 4
    LT = 5


class TaintEffect(enum.IntEnum):
    """reference types.go:2771-2784."""

    NO_SCHEDULE = 0
    PREFER_NO_SCHEDULE = 1
    NO_EXECUTE = 2


class TolerationOp(enum.IntEnum):
    """reference types.go:2817-2821."""

    EXISTS = 0
    EQUAL = 1


class UnsatisfiableAction(enum.IntEnum):
    """TopologySpreadConstraint.WhenUnsatisfiable (types.go ~3269)."""

    DO_NOT_SCHEDULE = 0  # hard predicate (EvenPodsSpreadPredicate)
    SCHEDULE_ANYWAY = 1  # soft score (even_pods_spread priority)


# --------------------------------------------------------------------------- #
# Resources
# --------------------------------------------------------------------------- #

_QTY_RE = re.compile(r"^([0-9.]+)\s*(m|k|Ki|M|Mi|G|Gi|T|Ti|P|Pi|E|Ei)?$")

_SUFFIX = {
    None: 1,
    "": 1,
    "k": 1000,
    "M": 1000**2,
    "G": 1000**3,
    "T": 1000**4,
    "P": 1000**5,
    "E": 1000**6,
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}


def parse_cpu_milli(q: str | int | float) -> int:
    """Parse a CPU quantity into milliCPU (reference resource.Quantity.MilliValue)."""
    if isinstance(q, (int, float)):
        return int(round(float(q) * 1000))
    m = _QTY_RE.match(q.strip())
    if not m:
        raise ValueError(f"bad cpu quantity {q!r}")
    val, suf = m.groups()
    if suf == "m":
        return int(round(float(val)))
    return int(round(float(val) * _SUFFIX[suf] * 1000))


def parse_mem_kib(q: str | int | float) -> int:
    """Parse a memory quantity into KiB (rounded up); device arrays hold KiB so
    int32 covers 2 TiB/node while staying exact for all practical requests."""
    if isinstance(q, (int, float)):
        b = int(q)
    else:
        m = _QTY_RE.match(q.strip())
        if not m:
            raise ValueError(f"bad memory quantity {q!r}")
        val, suf = m.groups()
        if suf == "m":  # milli-bytes, legal but silly
            b = int(round(float(val) / 1000))
        else:
            b = int(round(float(val) * _SUFFIX[suf]))
    return -(-b // 1024)  # ceil division


# Fixed resource dimensions on device, in order. Scalar/extended resources get
# vocab slots after these (reference nodeinfo/node_info.go:143-151 Resource).
RES_CPU = 0  # milliCPU
RES_MEM = 1  # KiB
RES_EPHEMERAL = 2  # KiB
RES_PODS = 3  # pod count (AllowedPodNumber)
NUM_FIXED_RES = 4


@dataclass(frozen=True)
class Resources:
    """Canonical resource vector (reference Resource, node_info.go:143)."""

    milli_cpu: int = 0
    memory_kib: int = 0
    ephemeral_kib: int = 0
    pods: int = 0
    scalars: Tuple[Tuple[str, int], ...] = ()  # (resource name, integer amount)

    @staticmethod
    def make(
        cpu: str | int | float = 0,
        memory: str | int = 0,
        ephemeral: str | int = 0,
        pods: int = 0,
        scalars: Optional[Dict[str, int]] = None,
    ) -> "Resources":
        return Resources(
            milli_cpu=parse_cpu_milli(cpu),
            memory_kib=parse_mem_kib(memory),
            ephemeral_kib=parse_mem_kib(ephemeral),
            pods=pods,
            scalars=tuple(sorted((scalars or {}).items())),
        )


# --------------------------------------------------------------------------- #
# Selectors
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class Requirement:
    """One selector requirement (labels.Requirement, apimachinery
    labels/selector.go:192-215 for match semantics)."""

    key: str
    op: Op
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """Pod-label selector: AND of requirements; empty selector matches all
    (metav1.LabelSelector via LabelSelectorAsSelector)."""

    requirements: Tuple[Requirement, ...] = ()

    @staticmethod
    def of(match_labels: Optional[Dict[str, str]] = None,
           expressions: Optional[List[Requirement]] = None) -> "LabelSelector":
        reqs: List[Requirement] = [
            Requirement(k, Op.IN, (v,)) for k, v in sorted((match_labels or {}).items())
        ]
        reqs.extend(expressions or [])
        return LabelSelector(tuple(reqs))


@dataclass(frozen=True)
class NodeSelectorTerm:
    """AND of requirements; an empty term matches *nothing*
    (v1helper.MatchNodeSelectorTerms: empty matchExpressions+matchFields skipped)."""

    requirements: Tuple[Requirement, ...] = ()
    # matchFields on metadata.name, reference types.go:2540; kept separate
    # because it matches node *name*, not labels.
    field_name_in: Tuple[str, ...] = ()


@dataclass(frozen=True)
class NodeSelector:
    """OR of terms (reference types.go:2524-2529); empty term list matches nothing."""

    terms: Tuple[NodeSelectorTerm, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int  # 1-100, types.go:2534
    term: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


# --------------------------------------------------------------------------- #
# Affinity
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class PodAffinityTerm:
    """reference types.go ~2620: label selector over pods, namespaces,
    topologyKey. Empty namespaces ⇒ the incoming pod's own namespace
    (predicates.go GetNamespacesFromPodAffinityTerm)."""

    selector: LabelSelector = field(default_factory=LabelSelector)
    topology_key: str = ""
    namespaces: Tuple[str, ...] = ()


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int  # 1-100
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass(frozen=True)
class Affinity:
    """Node + pod (anti)affinity. Only the scheduler-relevant
    RequiredDuringSchedulingIgnoredDuringExecution /
    PreferredDuringSchedulingIgnoredDuringExecution variants exist in the
    reference at this version."""

    node_required: Optional[NodeSelector] = None
    node_preferred: Tuple[PreferredSchedulingTerm, ...] = ()
    pod_required: Tuple[PodAffinityTerm, ...] = ()
    pod_preferred: Tuple[WeightedPodAffinityTerm, ...] = ()
    anti_required: Tuple[PodAffinityTerm, ...] = ()
    anti_preferred: Tuple[WeightedPodAffinityTerm, ...] = ()


# --------------------------------------------------------------------------- #
# Taints / tolerations
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: TaintEffect = TaintEffect.NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    """reference types.go:2789-2813. Empty key + Exists tolerates everything;
    empty effect matches all effects (ToleratesTaint, v1/helper)."""

    key: str = ""
    op: TolerationOp = TolerationOp.EQUAL
    value: str = ""
    effect: Optional[TaintEffect] = None  # None = all effects


# --------------------------------------------------------------------------- #
# Topology spread
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class TopologySpreadConstraint:
    """reference types.go TopologySpreadConstraint (EvenPodsSpread feature)."""

    max_skew: int
    topology_key: str
    when_unsatisfiable: UnsatisfiableAction
    selector: LabelSelector = field(default_factory=LabelSelector)


# --------------------------------------------------------------------------- #
# Ports
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class VolumeRef:
    """An attachable volume a pod mounts (the GCE-PD/EBS/RBD/ISCSI/CSI
    subset NoDiskConflict and the max-volume-count predicates care about:
    predicates.go:156-221, csi_volume_predicate.go:89). `driver` scopes both
    the conflict check and the per-node attach limit; EBS-style volumes that
    conflict even read-only are modeled with read_only=False."""

    vol_id: str
    driver: str = "pd"
    read_only: bool = False


@dataclass(frozen=True)
class HostPort:
    """A (protocol, hostIP, hostPort) triple; conflict semantics per
    nodeinfo/node_info.go HostPortInfo (wildcard 0.0.0.0 conflicts with all IPs)."""

    port: int
    protocol: str = "TCP"
    host_ip: str = ""


# --------------------------------------------------------------------------- #
# Pod / Node
# --------------------------------------------------------------------------- #

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Fencing annotations on Binding writes (exactly-once HA binding): the
# scheduler stamps its lease generation (coordination.k8s.io Lease
# `leaseTransitions` at acquire time) into every Binding; the apiserver
# compares it against the live Lease and rejects a strictly older token —
# a deposed leader that wakes up mid-write cannot land a stale bind.
FENCING_TOKEN_ANNOTATION = "ktpu.io/fencing-token"
FENCING_LEASE_ANNOTATION = "ktpu.io/fencing-lease"  # "namespace/name"
DEFAULT_FENCING_LEASE = "kube-system/kube-scheduler"
# machine-readable marker the apiserver embeds in a fenced-off 409's
# message; clients detect fenced rejections by THIS token, not by prose
# (survives the HTTP transport, which carries only code/reason/message)
FENCED_BIND_MARKER = "FencedBind"


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    requests: Resources = field(default_factory=Resources)
    limits: Resources = field(default_factory=Resources)  # container limits sum
    node_selector: Dict[str, str] = field(default_factory=dict)  # spec.nodeSelector
    affinity: Affinity = field(default_factory=Affinity)
    tolerations: Tuple[Toleration, ...] = ()
    topology_spread: Tuple[TopologySpreadConstraint, ...] = ()
    host_ports: Tuple[HostPort, ...] = ()
    volumes: Tuple[VolumeRef, ...] = ()  # attachable volumes (NoDiskConflict)
    # container image names (ImageLocality; spec.containers[*].image)
    images: Tuple[str, ...] = ()
    # selectors of the Services/RCs/RSs/StatefulSets owning this pod —
    # the SelectorSpread inputs the reference resolves via listers
    # (selector_spreading.go getSelectors); resolved by the caller here
    spread_selectors: Tuple[LabelSelector, ...] = ()
    priority: int = 0
    node_name: str = ""  # spec.nodeName — set once bound
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    creation_index: int = 0  # monotonic stand-in for creationTimestamp ordering
    # Gang/co-scheduling (BASELINE config 5). The reference has no in-tree
    # equivalent; the semantics follow the sig-scheduling coscheduling
    # protocol: pods carry their group name (label/annotation
    # `pod-group.scheduling.sigs.k8s.io/name`) and the group's minimum
    # member count (`.../min-available`, or a PodGroup object's
    # spec.minMember). A group commits all-or-nothing per cycle: either
    # ≥ min_member members (counting already-bound members) place, or none.
    pod_group: str = ""   # namespaced group name; "" = not gang-scheduled
    min_member: int = 0   # group minMember hint carried on the pod

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def group_key(self) -> str:
        """Namespaced gang-group key ('' when ungrouped) — the ONE
        normalization site (encoder, cache accounting, and the Coscheduling
        plugin all key groups by this)."""
        if not self.pod_group:
            return ""
        return self.pod_group if "/" in self.pod_group \
            else f"{self.namespace}/{self.pod_group}"


@dataclass
class PodGroup:
    """A gang-scheduling pod group (coscheduling PodGroup CRD analog,
    scheduling.sigs.k8s.io/v1alpha1): all-or-nothing admission with
    spec.minMember. Members reference it via Pod.pod_group = "{ns}/{name}"."""

    name: str
    namespace: str = "default"
    min_member: int = 1

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Node:
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    allocatable: Resources = field(default_factory=Resources)
    taints: Tuple[Taint, ...] = ()
    unschedulable: bool = False  # spec.unschedulable (CheckNodeUnschedulable)
    images_kib: Dict[str, int] = field(default_factory=dict)  # image name -> size
    # per-driver attachable-volume limits (CSINode allocatable / cloud caps,
    # csi_volume_predicate.go getMaxVolumeFunc); absent driver = unlimited
    volume_limits: Dict[str, int] = field(default_factory=dict)
    # scheduler.alpha.kubernetes.io/preferAvoidPods annotation present
    # (NodePreferAvoidPods score, priorities/node_prefer_avoid_pods.go)
    prefer_avoid_pods: bool = False


WELL_KNOWN_ZONE_LABEL = "topology.kubernetes.io/zone"
WELL_KNOWN_HOSTNAME_LABEL = "kubernetes.io/hostname"
WELL_KNOWN_REGION_LABEL = "topology.kubernetes.io/region"
