"""Label selectors: parse + match.

Analog of apimachinery `pkg/labels/selector.go` (Parse, Requirement.Matches)
and `pkg/apis/meta/v1/helpers.go` (LabelSelectorAsSelector). Supports the full
string syntax the reference parser accepts:

    a=b, c==d, e!=f, g in (x,y), h notin (z), i, !j, k>5, l<9

An empty selector string selects everything; a metav1.LabelSelector dict of
None selects nothing (per LabelSelectorAsSelector).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

# Operators (labels/selector.go:42-52)
EQUALS = "="
DOUBLE_EQUALS = "=="
NOT_EQUALS = "!="
IN = "in"
NOT_IN = "notin"
EXISTS = "exists"
DOES_NOT_EXIST = "!"
GREATER_THAN = "gt"
LESS_THAN = "lt"

_LABEL_KEY_RE = re.compile(
    r"^([a-zA-Z0-9][-a-zA-Z0-9_.]*[a-zA-Z0-9]/)?"
    r"[a-zA-Z0-9]([-a-zA-Z0-9_.]*[a-zA-Z0-9])?$"
)
_LABEL_VAL_RE = re.compile(r"^([a-zA-Z0-9]([-a-zA-Z0-9_.]*[a-zA-Z0-9])?)?$")


class SelectorParseError(ValueError):
    pass


def validate_label_key(key: str) -> None:
    if not key or len(key) > 317 or not _LABEL_KEY_RE.match(key):
        raise SelectorParseError(f"invalid label key: {key!r}")


def validate_label_value(val: str) -> None:
    if len(val) > 63 or not _LABEL_VAL_RE.match(val):
        raise SelectorParseError(f"invalid label value: {val!r}")


@dataclass(frozen=True)
class Requirement:
    """labels.Requirement (selector.go:117): key op values."""

    key: str
    op: str
    values: Tuple[str, ...] = ()

    def matches(self, lbls: Dict[str, str]) -> bool:
        """Requirement.Matches (selector.go:192-215)."""
        if self.op in (IN, EQUALS, DOUBLE_EQUALS):
            return self.key in lbls and lbls[self.key] in self.values
        if self.op in (NOT_IN, NOT_EQUALS):
            # NotIn/NotEquals match when the key is absent too
            return self.key not in lbls or lbls[self.key] not in self.values
        if self.op == EXISTS:
            return self.key in lbls
        if self.op == DOES_NOT_EXIST:
            return self.key not in lbls
        if self.op in (GREATER_THAN, LESS_THAN):
            if self.key not in lbls:
                return False
            try:
                lhs = int(lbls[self.key])
                rhs = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if self.op == GREATER_THAN else lhs < rhs
        return False

    def __str__(self) -> str:
        if self.op == EXISTS:
            return self.key
        if self.op == DOES_NOT_EXIST:
            return f"!{self.key}"
        if self.op in (IN, NOT_IN):
            return f"{self.key} {self.op} ({','.join(self.values)})"
        if self.op == GREATER_THAN:
            return f"{self.key}>{self.values[0]}"
        if self.op == LESS_THAN:
            return f"{self.key}<{self.values[0]}"
        return f"{self.key}{self.op}{self.values[0]}"


@dataclass(frozen=True)
class Selector:
    """internalSelector: AND of requirements; empty = Everything()."""

    requirements: Tuple[Requirement, ...] = ()
    nothing: bool = False  # labels.Nothing(): matches no object

    def matches(self, lbls: Optional[Dict[str, str]]) -> bool:
        if self.nothing:
            return False
        lbls = lbls or {}
        return all(r.matches(lbls) for r in self.requirements)

    def empty(self) -> bool:
        return not self.nothing and not self.requirements

    def __str__(self) -> str:
        return ",".join(str(r) for r in self.requirements)


EVERYTHING = Selector()
NOTHING = Selector(nothing=True)


# --------------------------------------------------------------------------- #
# String-syntax parser (labels.Parse)
# --------------------------------------------------------------------------- #

_TOKEN_RE = re.compile(
    r"\s*(?:"
    r"(?P<comma>,)|(?P<open>\()|(?P<close>\))|"
    r"(?P<op>==|=|!=|>|<)|(?P<bang>!)|"
    r"(?P<word>[^\s,()=!<>]+)"
    r")"
)


def _tokenize(s: str) -> List[Tuple[str, str]]:
    toks, i = [], 0
    while i < len(s):
        m = _TOKEN_RE.match(s, i)
        if not m or m.end() == i:
            raise SelectorParseError(f"unparseable selector at {s[i:]!r}")
        i = m.end()
        for kind in ("comma", "open", "close", "op", "bang", "word"):
            if m.group(kind):
                toks.append((kind, m.group(kind)))
                break
    return toks


def parse(s: str) -> Selector:
    """labels.Parse: the general selector string syntax."""
    s = s.strip()
    if not s:
        return EVERYTHING
    toks = _tokenize(s)
    reqs: List[Requirement] = []
    i = 0

    def peek(k: int = 0) -> Optional[Tuple[str, str]]:
        return toks[i + k] if i + k < len(toks) else None

    while i < len(toks):
        kind, val = toks[i]
        if kind == "bang":
            nxt = peek(1)
            if not nxt or nxt[0] != "word":
                raise SelectorParseError("expected key after '!'")
            validate_label_key(nxt[1])
            reqs.append(Requirement(nxt[1], DOES_NOT_EXIST))
            i += 2
        elif kind == "word":
            key = val
            validate_label_key(key)
            nxt = peek(1)
            if nxt is None or nxt[0] == "comma":
                reqs.append(Requirement(key, EXISTS))
                i += 1
            elif nxt[0] == "op":
                op_tok = nxt[1]
                v = peek(2)
                if not v or v[0] != "word":
                    raise SelectorParseError(f"expected value after {key}{op_tok}")
                if op_tok in ("=", "=="):
                    validate_label_value(v[1])
                    reqs.append(Requirement(key, IN, (v[1],)))
                elif op_tok == "!=":
                    validate_label_value(v[1])
                    reqs.append(Requirement(key, NOT_IN, (v[1],)))
                elif op_tok == ">":
                    reqs.append(Requirement(key, GREATER_THAN, (v[1],)))
                else:
                    reqs.append(Requirement(key, LESS_THAN, (v[1],)))
                i += 3
            elif nxt[0] == "word" and nxt[1] in (IN, NOT_IN):
                op = nxt[1]
                if not peek(2) or peek(2)[0] != "open":
                    raise SelectorParseError(f"expected '(' after {op}")
                i += 3
                vals: List[str] = []
                while True:
                    t = peek()
                    if t is None:
                        raise SelectorParseError("unterminated value list")
                    if t[0] == "close":
                        i += 1
                        break
                    if t[0] == "comma":
                        i += 1
                        continue
                    if t[0] != "word":
                        raise SelectorParseError(f"bad token in value list: {t[1]!r}")
                    validate_label_value(t[1])
                    vals.append(t[1])
                    i += 1
                if not vals:
                    raise SelectorParseError(f"{op} requires at least one value")
                reqs.append(Requirement(key, op, tuple(sorted(vals))))
            else:
                raise SelectorParseError(f"unexpected token after key: {nxt[1]!r}")
        else:
            raise SelectorParseError(f"unexpected token {val!r}")
        # consume a separating comma
        t = peek()
        if t and t[0] == "comma":
            i += 1
            if i == len(toks):
                raise SelectorParseError("trailing comma")
    return Selector(tuple(reqs))


def selector_from_set(match_labels: Dict[str, str]) -> Selector:
    """labels.SelectorFromSet."""
    return Selector(tuple(
        Requirement(k, IN, (v,)) for k, v in sorted(match_labels.items())
    ))


def from_label_selector(ls: Optional[Dict]) -> Selector:
    """metav1.LabelSelectorAsSelector: dict {matchLabels, matchExpressions}.

    nil selector → Nothing; empty selector → Everything (helpers.go:34-40).
    """
    if ls is None:
        return NOTHING
    reqs: List[Requirement] = [
        Requirement(k, IN, (v,))
        for k, v in sorted((ls.get("matchLabels") or {}).items())
    ]
    for expr in ls.get("matchExpressions") or []:
        op = expr.get("operator", "")
        key = expr.get("key", "")
        vals = tuple(sorted(expr.get("values") or []))
        mapped = {"In": IN, "NotIn": NOT_IN, "Exists": EXISTS,
                  "DoesNotExist": DOES_NOT_EXIST}.get(op)
        if mapped is None:
            raise SelectorParseError(f"bad matchExpressions operator {op!r}")
        if mapped in (IN, NOT_IN) and not vals:
            raise SelectorParseError(f"{op} requires values")
        if mapped in (EXISTS, DOES_NOT_EXIST) and vals:
            raise SelectorParseError(f"{op} forbids values")
        reqs.append(Requirement(key, mapped, vals))
    return Selector(tuple(reqs))
