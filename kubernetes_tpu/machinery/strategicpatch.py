"""Strategic merge patch + JSON patch — the kubectl-apply merge machinery.

Analog of `staging/src/k8s.io/apimachinery/pkg/util/strategicpatch/patch.go`
(StrategicMergePatch) and `evanphx/json-patch` (RFC 6902, which the
reference serves for `application/json-patch+json`).

Strategic merge differs from RFC 7386 merge patch in ONE structural way:
list fields tagged `patchStrategy:"merge"` in the reference's types merge
ELEMENT-WISE by their `patchMergeKey` instead of being replaced wholesale.
That is what makes `kubectl apply` of a pod template with a modified
container list update the one container instead of dropping its siblings.

The reference carries the strategy in Go struct tags
(`staging/src/k8s.io/api/core/v1/types.go`, e.g. Containers:
patchStrategy:"merge" patchMergeKey:"name"); here the same facts live in
`MERGE_KEYS` — a longest-suffix path table, which handles the PodSpec
being embedded at different depths (pod spec.containers vs deployment
spec.template.spec.containers) without per-kind duplication.

Directives (patch.go directive constants):
  * `$patch: delete`  in a merge-list element: delete the element whose
    merge key matches (or, in a map value: delete semantics for maps).
  * `$patch: replace` as a list element or map entry: replace wholesale
    instead of merging.
  * `$deleteFromPrimitiveList/<field>: [v, ...]`: remove values from a
    primitive merge list (e.g. finalizers).
  * `$setElementOrder/<field>: [...]`: result list order (merge-key values
    for object lists, values for primitive lists).
  * `$retainKeys: [...]` in a map: drop keys not listed (the
    `patchStrategy:"retainKeys"` half of volumes' merge,retainKeys).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.machinery import errors

Obj = Dict[str, Any]

PATCH_DIRECTIVE = "$patch"
DELETE_FROM_PRIMITIVE = "$deleteFromPrimitiveList/"
SET_ELEMENT_ORDER = "$setElementOrder/"
RETAIN_KEYS = "$retainKeys"

# (path-suffix, merge key). Longest matching suffix wins; paths are tuples
# of field names with list indices elided. Mined from the reference's
# patchMergeKey/patchStrategy struct tags (api/core/v1 + apps/v1 +
# apimachinery/meta/v1 types.go).
MERGE_KEYS: List[Tuple[Tuple[str, ...], str]] = [
    (("containers", "ports"), "containerPort"),
    (("initContainers", "ports"), "containerPort"),
    (("ephemeralContainers", "ports"), "containerPort"),
    (("ports",), "port"),                 # Service spec.ports
    (("containers",), "name"),
    (("initContainers",), "name"),
    (("ephemeralContainers",), "name"),
    (("env",), "name"),
    (("volumeMounts",), "mountPath"),
    (("volumeDevices",), "devicePath"),
    (("volumes",), "name"),
    (("imagePullSecrets",), "name"),
    (("hostAliases",), "ip"),
    (("topologySpreadConstraints",), "topologyKey"),
    (("podIPs",), "ip"),
    (("secrets",), "name"),               # ServiceAccount.secrets
    (("ownerReferences",), "uid"),
    (("conditions",), "type"),
    (("addresses",), "type"),             # NodeStatus.addresses
]

# patchStrategy:"merge" on []string fields: values union / delete by value
PRIMITIVE_MERGE_FIELDS = {"finalizers", "podCIDRs"}


def merge_key_for(path: Tuple[str, ...]) -> Optional[str]:
    """Longest-suffix lookup into MERGE_KEYS; None → atomic list."""
    best: Optional[str] = None
    best_len = 0
    for suffix, key in MERGE_KEYS:
        if len(suffix) <= len(path) and path[-len(suffix):] == suffix \
                and len(suffix) > best_len:
            best, best_len = key, len(suffix)
    return best


def _is_primitive_merge(path: Tuple[str, ...]) -> bool:
    return bool(path) and path[-1] in PRIMITIVE_MERGE_FIELDS


def strategic_merge(cur: Any, patch: Any,
                    path: Tuple[str, ...] = ()) -> Any:
    """Apply a strategic merge patch. Returns the merged value (inputs are
    not mutated)."""
    if isinstance(patch, dict):
        if not isinstance(cur, dict):
            cur = {}
        return _merge_map(cur, patch, path)
    # non-map patch values replace (lists at this level were handled by the
    # parent map merge; a bare list patch replaces, as in patch.go)
    return copy.deepcopy(patch)


def _merge_map(cur: Obj, patch: Obj, path: Tuple[str, ...]) -> Obj:
    directive = patch.get(PATCH_DIRECTIVE)
    if directive == "replace":
        out = {k: copy.deepcopy(v) for k, v in patch.items()
               if k != PATCH_DIRECTIVE}
        return out
    if directive == "delete":
        return {}
    if directive is not None:
        raise errors.new_bad_request(
            f"invalid $patch directive {directive!r}")

    out = copy.deepcopy(cur)

    # $setElementOrder/<field> companions are consumed by the list merge
    orders: Dict[str, List[Any]] = {}
    deletions: Dict[str, List[Any]] = {}
    retain: Optional[List[str]] = None
    for k, v in patch.items():
        if k.startswith(SET_ELEMENT_ORDER):
            orders[k[len(SET_ELEMENT_ORDER):]] = v
        elif k.startswith(DELETE_FROM_PRIMITIVE):
            deletions[k[len(DELETE_FROM_PRIMITIVE):]] = v
        elif k == RETAIN_KEYS:
            retain = v

    for k, v in patch.items():
        if (k.startswith(SET_ELEMENT_ORDER)
                or k.startswith(DELETE_FROM_PRIMITIVE)
                or k == RETAIN_KEYS):
            continue
        child_path = path + (k,)
        if v is None:
            out.pop(k, None)
            continue
        if isinstance(v, dict):
            out[k] = _merge_map(out.get(k) if isinstance(out.get(k), dict)
                                else {}, v, child_path)
            continue
        if isinstance(v, list):
            out[k] = _merge_list(out.get(k), v, child_path,
                                 orders.get(k))
            continue
        out[k] = copy.deepcopy(v)

    # primitive-list deletions may arrive WITHOUT a companion field entry
    for field, values in deletions.items():
        have = out.get(field)
        if isinstance(have, list):
            out[field] = [x for x in have if x not in values]

    # order-only patches (kubectl apply reorders without changing content)
    for field, order in orders.items():
        if field not in patch and isinstance(out.get(field), list):
            out[field] = _reorder(out[field], order,
                                  merge_key_for(path + (field,)))

    if retain is not None:
        out = {k: v for k, v in out.items() if k in retain}
    return out


def _merge_list(cur: Any, patch: List[Any], path: Tuple[str, ...],
                order: Optional[List[Any]]) -> List[Any]:
    if not isinstance(cur, list):
        cur = []
    # `$patch: replace` as a list element: replace the whole list
    if any(isinstance(e, dict) and e.get(PATCH_DIRECTIVE) == "replace"
           for e in patch):
        return [copy.deepcopy(e) for e in patch
                if not (isinstance(e, dict)
                        and e.get(PATCH_DIRECTIVE) == "replace")]
    key = merge_key_for(path)
    if key is None:
        if _is_primitive_merge(path):
            merged = list(cur)
            for v in patch:
                if v not in merged:
                    merged.append(v)
            return merged
        return copy.deepcopy(patch)          # atomic list: replace

    merged: List[Any] = [copy.deepcopy(e) for e in cur]
    index = {e.get(key): i for i, e in enumerate(merged)
             if isinstance(e, dict)}
    for e in patch:
        if not isinstance(e, dict):
            raise errors.new_bad_request(
                f"strategic merge: element of {'.'.join(path)} "
                "is not an object")
        kv = e.get(key)
        if e.get(PATCH_DIRECTIVE) == "delete":
            merged = [m for m in merged
                      if not (isinstance(m, dict) and m.get(key) == kv)]
            index = {m.get(key): i for i, m in enumerate(merged)
                     if isinstance(m, dict)}
            continue
        if kv is None:
            raise errors.new_bad_request(
                f"strategic merge: element of {'.'.join(path)} "
                f"lacks merge key {key!r}")
        if kv in index:
            merged[index[kv]] = _merge_map(merged[index[kv]], e, path)
        else:
            index[kv] = len(merged)
            merged.append(_merge_map({}, e, path))
    if order is not None:
        merged = _reorder(merged, order, key)
    return merged


def _reorder(items: List[Any], order: List[Any],
             key: Optional[str]) -> List[Any]:
    """$setElementOrder: listed elements first in the given order, then the
    unlisted ones in their current relative order (patch.go order merge).
    Order entries come as objects bearing only the merge key (what kubectl
    emits) or as bare merge-key values; both normalize to the key value."""
    def sort_value(e):
        return e.get(key) if (key and isinstance(e, dict)) else e

    pos: Dict[Any, int] = {}
    for i, v in enumerate(order):
        v = sort_value(v)
        if isinstance(v, (dict, list)):
            raise errors.new_bad_request(
                "invalid $setElementOrder entry (expected merge-key value "
                "or an object bearing the merge key)")
        pos.setdefault(v, i)
    listed = [e for e in items if sort_value(e) in pos]
    unlisted = [e for e in items if sort_value(e) not in pos]
    listed.sort(key=lambda e: pos[sort_value(e)])
    return listed + unlisted


# --------------------------------------------------------------------- #
# kubectl-apply three-way patch body
# --------------------------------------------------------------------- #

LAST_APPLIED_ANNOTATION = "kubectl.kubernetes.io/last-applied-configuration"


def apply_patch_body(last: Obj, desired: Obj,
                     path: Tuple[str, ...] = (),
                     merge_lists: bool = True) -> Obj:
    """The patch `kubectl apply` sends: the full desired state plus the
    DELETIONS implied by last-applied-configuration — `null` for map keys
    and `$patch: delete` entries for merge-list elements that were in the
    last applied manifest but are gone from the new one (apply.go
    CreateThreeWayMergePatch's deletion half; the modification half is
    subsumed by sending the full desired state). With merge_lists=False
    the body is a plain 3-way JSON merge patch (lists replace wholesale) —
    the dialect kubectl uses for custom resources."""
    out: Obj = {}
    last = last if isinstance(last, dict) else {}
    for k in last:
        if k not in desired:
            out[k] = None  # deleted since last apply
    for k, dv in desired.items():
        child = path + (k,)
        lv = last.get(k)
        if isinstance(dv, dict):
            out[k] = apply_patch_body(lv if isinstance(lv, dict) else {},
                                      dv, child, merge_lists)
            continue
        if isinstance(dv, list) and merge_lists:
            mk = merge_key_for(child)
            if mk and all(isinstance(e, dict) for e in dv):
                last_by = {e.get(mk): e for e in (lv or [])
                           if isinstance(e, dict)}
                lst: List[Any] = []
                for e in dv:
                    le = last_by.get(e.get(mk))
                    lst.append(apply_patch_body(le, e, child, merge_lists)
                               if isinstance(le, dict)
                               else copy.deepcopy(e))
                gone = set(last_by) - {e.get(mk) for e in dv}
                lst.extend({mk: kv, PATCH_DIRECTIVE: "delete"}
                           for kv in sorted(gone, key=str))
                out[k] = lst
                continue
            if _is_primitive_merge(child):
                out[k] = copy.deepcopy(dv)
                removed = [x for x in (lv or []) if x not in dv]
                if removed:
                    out[DELETE_FROM_PRIMITIVE + k] = removed
                continue
        out[k] = copy.deepcopy(dv)
    return out


# --------------------------------------------------------------------- #
# RFC 6902 JSON patch (application/json-patch+json)
# --------------------------------------------------------------------- #


def _ptr_parts(pointer: str) -> List[str]:
    if pointer == "":
        return []
    if not pointer.startswith("/"):
        raise errors.new_bad_request(f"invalid JSON pointer {pointer!r}")
    return [p.replace("~1", "/").replace("~0", "~")
            for p in pointer[1:].split("/")]


def _list_index(tok: str, n: int, allow_end: bool = False) -> int:
    """A list token must be a valid in-range index (RFC 6902 → 400)."""
    if allow_end and tok == "-":
        return n
    try:
        idx = int(tok)
    except (TypeError, ValueError):
        raise errors.new_bad_request(
            f"JSON patch: invalid array index {tok!r}")
    if not 0 <= idx < n + (1 if allow_end else 0):
        raise errors.new_bad_request(
            f"JSON patch: array index {idx} out of range")
    return idx


def _ptr_walk(doc: Any, parts: Sequence[str]) -> Tuple[Any, Any]:
    """Walk to the parent of the target; returns (parent, last_token)."""
    cur = doc
    for p in parts[:-1]:
        if isinstance(cur, list):
            cur = cur[_list_index(p, len(cur))]
        elif isinstance(cur, dict):
            if p not in cur:
                raise errors.new_bad_request(
                    f"JSON pointer path /{'/'.join(parts)} not found")
            cur = cur[p]
        else:
            raise errors.new_bad_request(
                f"JSON pointer path /{'/'.join(parts)} not found")
    return cur, parts[-1] if parts else None


def json_patch(doc: Obj, ops: List[Obj]) -> Obj:
    """Apply an RFC 6902 op list. Returns the new document."""
    out = copy.deepcopy(doc)
    if not isinstance(ops, list):
        raise errors.new_bad_request("JSON patch body must be an array")
    for op in ops:
        kind = op.get("op")
        parts = _ptr_parts(op.get("path", ""))
        if kind in ("add", "replace", "test"):
            # RFC 6902 §4: add/replace/test REQUIRE the "value" member —
            # defaulting an absent value to null would silently null out
            # the target (evanphx/json-patch, the reference's library,
            # rejects it too)
            if "value" not in op:
                raise errors.new_bad_request(
                    f'JSON patch {kind}: missing "value" member')
            value = copy.deepcopy(op["value"])
        if kind == "move" or kind == "copy":
            f_parts = _ptr_parts(op.get("from", ""))
            parent, tok = _ptr_walk(out, f_parts)
            if isinstance(parent, list):
                value = parent[_list_index(tok, len(parent))]
            elif isinstance(parent, dict) and tok in parent:
                value = parent[tok]
            else:
                raise errors.new_bad_request(
                    f"JSON patch {kind}: {op.get('from')} not found")
            value = copy.deepcopy(value)
            if kind == "move":
                if isinstance(parent, list):
                    parent.pop(_list_index(tok, len(parent)))
                else:
                    parent.pop(tok)
        if not parts:
            if kind in ("add", "replace", "move", "copy"):
                if not isinstance(value, dict):
                    raise errors.new_bad_request(
                        "whole-document value must be an object")
                out = value
            elif kind == "test":
                if out != value:
                    raise errors.new_bad_request("JSON patch test failed")
            elif kind == "remove":
                raise errors.new_bad_request(
                    "JSON patch remove: cannot remove the root document")
            else:
                raise errors.new_bad_request(
                    f"invalid JSON patch op {kind!r}")
            continue
        parent, tok = _ptr_walk(out, parts)
        if kind in ("add", "move", "copy"):
            if isinstance(parent, list):
                parent.insert(_list_index(tok, len(parent),
                                          allow_end=True), value)
            elif isinstance(parent, dict):
                parent[tok] = value
            else:
                raise errors.new_bad_request(
                    f"JSON patch {kind}: {op.get('path')} not found")
        elif kind == "replace":
            if isinstance(parent, list):
                parent[_list_index(tok, len(parent))] = value
            elif isinstance(parent, dict) and tok in parent:
                parent[tok] = value
            else:
                raise errors.new_bad_request(
                    f"JSON patch replace: {op.get('path')} not found")
        elif kind == "remove":
            if isinstance(parent, list):
                parent.pop(_list_index(tok, len(parent)))
            elif isinstance(parent, dict) and tok in parent:
                parent.pop(tok)
            else:
                raise errors.new_bad_request(
                    f"JSON patch remove: {op.get('path')} not found")
        elif kind == "test":
            # RFC 6902: test against a NONEXISTENT target fails — a None
            # expected value must not pass via dict.get defaulting
            if isinstance(parent, list):
                got = parent[_list_index(tok, len(parent))]
            elif isinstance(parent, dict) and tok in parent:
                got = parent[tok]
            else:
                raise errors.new_bad_request("JSON patch test failed")
            if got != value:
                raise errors.new_bad_request("JSON patch test failed")
        else:
            raise errors.new_bad_request(f"invalid JSON patch op {kind!r}")
    return out
