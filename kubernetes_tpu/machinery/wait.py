"""Polling / backoff / run-until helpers.

Analog of apimachinery `pkg/util/wait` (PollImmediate, Until, Backoff) and
client-go's wait usage. Threads + Events instead of goroutines + channels.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional


class TimeoutError_(TimeoutError):
    pass


def poll_until(condition: Callable[[], bool], interval: float = 0.01,
               timeout: float = 10.0, immediate: bool = True) -> None:
    """wait.PollImmediate: run condition every interval until true/timeout."""
    deadline = time.monotonic() + timeout
    if immediate and condition():
        return
    while time.monotonic() < deadline:
        time.sleep(interval)
        if condition():
            return
    raise TimeoutError_(f"condition not met within {timeout}s")


def until(fn: Callable[[], None], period: float, stop: threading.Event) -> None:
    """wait.Until: run fn every period until stop is set. Runs inline; callers
    put it on a thread."""
    while not stop.is_set():
        fn()
        if stop.wait(period):
            return


def run_until(fn: Callable[[], None], period: float, stop: threading.Event,
              name: str = "wait.Until") -> threading.Thread:
    t = threading.Thread(target=until, args=(fn, period, stop), name=name, daemon=True)
    t.start()
    return t


@dataclass
class Backoff:
    """wait.Backoff / client-go workqueue exponential backoff parameters."""

    base: float = 0.005
    factor: float = 2.0
    max_delay: float = 10.0
    jitter: float = 0.1

    def delay(self, failures: int) -> float:
        d = min(self.base * (self.factor ** failures), self.max_delay)
        if self.jitter:
            d *= 1.0 + random.random() * self.jitter
        return min(d, self.max_delay)


def jittered(duration: float, max_factor: float = 1.0) -> float:
    """wait.Jitter."""
    return duration * (1.0 + random.random() * max_factor)
