"""API machinery: the object-model substrate shared by every component.

TPU-native analog of `staging/src/k8s.io/apimachinery/` (SURVEY.md layer 2).
The control plane here operates on *dict-shaped versioned objects* — the JSON
wire form is the in-memory form — rather than generated Go structs; a Scheme
registers kinds with defaulting/validation, and this package supplies the
meta/label/quantity/watch/error vocabulary everything else shares.

Modules:
  meta      — TypeMeta/ObjectMeta accessors (apimachinery pkg/apis/meta/v1)
  labels    — label Selector parse + match (apimachinery pkg/labels/selector.go)
  quantity  — resource.Quantity parse/format/arithmetic (pkg/api/resource)
  scheme    — kind registry + JSON codec (pkg/runtime Scheme/codec)
  watch     — watch.Event types (pkg/watch)
  errors    — api/errors Status error taxonomy → HTTP codes
  wait      — util/wait poll/backoff helpers
"""

from kubernetes_tpu.machinery import errors, labels, meta, quantity, scheme, wait, watch

__all__ = ["errors", "labels", "meta", "quantity", "scheme", "wait", "watch"]
