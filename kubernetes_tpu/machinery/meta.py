"""ObjectMeta/TypeMeta accessors over dict-shaped API objects.

Analog of apimachinery `pkg/apis/meta/v1/types.go` (ObjectMeta) and
`pkg/api/meta` accessor helpers. Objects are plain dicts in their JSON wire
shape: {"apiVersion", "kind", "metadata": {...}, "spec": {...}, "status": ...}.
"""

from __future__ import annotations

import copy
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

Obj = Dict[str, Any]


def ensure_meta(obj: Obj) -> Dict[str, Any]:
    return obj.setdefault("metadata", {})


def name(obj: Obj) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace(obj: Obj) -> str:
    return obj.get("metadata", {}).get("namespace", "")


def uid(obj: Obj) -> str:
    return obj.get("metadata", {}).get("uid", "")


def resource_version(obj: Obj) -> str:
    return obj.get("metadata", {}).get("resourceVersion", "")


def set_resource_version(obj: Obj, rv: str) -> None:
    ensure_meta(obj)["resourceVersion"] = rv


def generation(obj: Obj) -> int:
    return int(obj.get("metadata", {}).get("generation", 0))


def labels_of(obj: Obj) -> Dict[str, str]:
    return obj.get("metadata", {}).get("labels") or {}


def annotations_of(obj: Obj) -> Dict[str, str]:
    return obj.get("metadata", {}).get("annotations") or {}


def creation_timestamp(obj: Obj) -> str:
    return obj.get("metadata", {}).get("creationTimestamp", "")


def deletion_timestamp(obj: Obj) -> Optional[str]:
    return obj.get("metadata", {}).get("deletionTimestamp")


def finalizers(obj: Obj) -> List[str]:
    return obj.get("metadata", {}).get("finalizers") or []


def owner_references(obj: Obj) -> List[Dict[str, Any]]:
    return obj.get("metadata", {}).get("ownerReferences") or []


def controller_ref(obj: Obj) -> Optional[Dict[str, Any]]:
    """The ownerReference with controller=true, if any
    (metav1.GetControllerOf)."""
    for ref in owner_references(obj):
        if ref.get("controller"):
            return ref
    return None


def namespaced_key(obj: Obj) -> str:
    """cache.MetaNamespaceKeyFunc: "<ns>/<name>", or "<name>" cluster-scoped."""
    ns = namespace(obj)
    return f"{ns}/{name(obj)}" if ns else name(obj)


def split_key(key: str) -> Tuple[str, str]:
    """cache.SplitMetaNamespaceKey."""
    if "/" in key:
        ns, _, n = key.partition("/")
        return ns, n
    return "", key


def new_uid() -> str:
    return str(uuid.uuid4())


def now_rfc3339() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def parse_rfc3339(s: Optional[str]) -> Optional[float]:
    """Epoch seconds for an RFC3339 timestamp (now_rfc3339's Z form;
    fractional seconds dropped; ±HH:MM offsets applied), or None when
    absent/unparseable — the TTL sweep must treat a malformed stamp as
    'no stamp', never raise."""
    if not s or not isinstance(s, str):
        return None
    import calendar
    import re

    base = s[:19]  # YYYY-MM-DDTHH:MM:SS
    try:
        t = float(calendar.timegm(
            time.strptime(base, "%Y-%m-%dT%H:%M:%S")))
    except ValueError:
        return None
    m = re.match(r"^(?:\.\d+)?([+-])(\d{2}):?(\d{2})$", s[19:].rstrip("Z"))
    if m:
        sign, hh, mm = m.group(1), int(m.group(2)), int(m.group(3))
        off = hh * 3600 + mm * 60
        t += -off if sign == "+" else off
    return t


def gvk(obj: Obj) -> Tuple[str, str, str]:
    """(group, version, kind) from apiVersion/kind fields."""
    api_version = obj.get("apiVersion", "v1")
    kind = obj.get("kind", "")
    if "/" in api_version:
        group, _, version = api_version.partition("/")
    else:
        group, version = "", api_version
    return group, version, kind


def api_version_of(group: str, version: str) -> str:
    return f"{group}/{version}" if group else version


def owner_reference(owner: Obj, controller: bool = True,
                    block_owner_deletion: bool = True) -> Dict[str, Any]:
    """metav1.NewControllerRef."""
    return {
        "apiVersion": owner.get("apiVersion", "v1"),
        "kind": owner.get("kind", ""),
        "name": name(owner),
        "uid": uid(owner),
        "controller": controller,
        "blockOwnerDeletion": block_owner_deletion,
    }


def deep_copy(obj: Obj) -> Obj:
    """DeepCopyObject — generated per-type in the reference; one generic
    implementation suffices for dict-shaped objects."""
    return copy.deepcopy(obj)


def is_being_deleted(obj: Obj) -> bool:
    return deletion_timestamp(obj) is not None
