"""Scheme: kind registry, defaulting, validation, codec.

Analog of apimachinery `pkg/runtime` (Scheme/codecs). Objects live in their
versioned JSON-dict form; the Scheme maps (group, version, kind) and REST
resource names to registered type info with defaulting + validation hooks.
Since dicts are self-describing there is no hub-and-spoke conversion layer —
each kind registers at one storage version (the reference's internal types
collapse to the same thing for a single served version).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.machinery import errors, meta

Obj = Dict[str, Any]
DefaultFn = Callable[[Obj], None]
ValidateFn = Callable[[Obj], List[str]]


@dataclass
class ResourceInfo:
    """One served REST resource (≈ APIResource + RESTStorage registration)."""

    group: str
    version: str
    kind: str            # e.g. "Pod"
    resource: str        # plural REST name, e.g. "pods"
    namespaced: bool = True
    list_kind: str = ""  # e.g. "PodList"
    short_names: Tuple[str, ...] = ()
    subresources: Tuple[str, ...] = ()  # e.g. ("status", "binding")
    defaulter: Optional[DefaultFn] = None
    validator: Optional[ValidateFn] = None
    custom: bool = False  # CRD-served: no struct tags → strategic patch 415

    def __post_init__(self) -> None:
        if not self.list_kind:
            self.list_kind = self.kind + "List"

    @property
    def api_version(self) -> str:
        return meta.api_version_of(self.group, self.version)

    @property
    def gvr(self) -> Tuple[str, str, str]:
        return (self.group, self.version, self.resource)


class Scheme:
    """runtime.Scheme analog: register kinds, default, validate, encode/decode."""

    def __init__(self) -> None:
        self._by_gvk: Dict[Tuple[str, str, str], ResourceInfo] = {}
        self._by_resource: Dict[Tuple[str, str], ResourceInfo] = {}
        self._by_short: Dict[str, ResourceInfo] = {}

    def register(self, info: ResourceInfo) -> ResourceInfo:
        self._by_gvk[(info.group, info.version, info.kind)] = info
        self._by_resource[(info.group, info.resource)] = info
        for s in info.short_names:
            self._by_short[s] = info
        return info

    def unregister(self, group: str, resource: str) -> None:
        info = self._by_resource.pop((group, resource), None)
        if info is not None:
            self._by_gvk.pop((info.group, info.version, info.kind), None)
            for s in info.short_names:
                if self._by_short.get(s) is info:
                    del self._by_short[s]

    def resources(self) -> List[ResourceInfo]:
        return list(self._by_resource.values())

    def lookup_kind(self, group: str, version: str, kind: str) -> Optional[ResourceInfo]:
        return self._by_gvk.get((group, version, kind))

    def lookup_resource(self, group: str, resource: str) -> Optional[ResourceInfo]:
        """Resolve a REST resource name (plural, singular-ish, or short name)."""
        info = self._by_resource.get((group, resource))
        if info:
            return info
        info = self._by_short.get(resource)
        if info and info.group == group:
            return info
        # tolerate kind-cased or singular names (kubectl-style convenience)
        for (g, _), i in self._by_resource.items():
            if g == group and (i.kind.lower() == resource.lower()
                               or i.resource.rstrip("s") == resource):
                return i
        return None

    def default(self, obj: Obj) -> Obj:
        g, v, k = meta.gvk(obj)
        info = self.lookup_kind(g, v, k)
        if info and info.defaulter:
            info.defaulter(obj)
        return obj

    def validate(self, obj: Obj) -> None:
        g, v, k = meta.gvk(obj)
        info = self.lookup_kind(g, v, k)
        errs: List[str] = []
        if not meta.name(obj) and not (obj.get("metadata") or {}).get("generateName"):
            errs.append("metadata.name: Required value")
        if info and info.validator:
            errs.extend(info.validator(obj))
        if errs:
            raise errors.new_invalid(k or "Object", meta.name(obj), "; ".join(errs))

    # -- codec ------------------------------------------------------------- #
    @staticmethod
    def encode(obj: Obj) -> bytes:
        return json.dumps(obj, separators=(",", ":"), sort_keys=True).encode()

    @staticmethod
    def decode(data: bytes) -> Obj:
        obj = json.loads(data)
        if not isinstance(obj, dict):
            raise errors.new_bad_request("body must be a JSON object")
        return obj

    def new_list(self, info: ResourceInfo, items: List[Obj],
                 resource_version: str = "") -> Obj:
        return {
            "apiVersion": info.api_version,
            "kind": info.list_kind,
            "metadata": {"resourceVersion": resource_version},
            "items": items,
        }
