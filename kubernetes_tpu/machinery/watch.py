"""Watch event types and channels.

Analog of apimachinery `pkg/watch/watch.go`: an Interface delivering a stream
of {type, object} events. Here a watch is a closeable blocking queue; the
storage layer and clients share this shape.

The channel is the per-watcher BOUNDED delivery buffer of the cacher
contract (cacher.go forgetWatcher): a producer that finds it full terminates
THIS watcher instead of blocking the broadcast loop, and `terminate()` lets
it leave a terminal Status event (e.g. 410 "too old resource version") that
the consumer receives after draining whatever it had buffered — so even a
slow-but-alive client learns WHY its stream died instead of seeing a bare
socket EOF.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Dict, Iterator, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"
ERROR = "ERROR"


@dataclass(frozen=True)
class Event:
    type: str
    object: Dict[str, Any]


class Watch:
    """watch.Interface: ResultChan() + Stop(). Iteration ends on Stop or when
    the producer closes the stream; a terminal event set via `terminate()`
    is delivered exactly once, after the buffered events drain."""

    _SENTINEL = object()

    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=capacity)
        self._stopped = threading.Event()
        self._term_mu = threading.Lock()
        self._terminal: Optional[Event] = None
        # True iff a producer stopped this stream because the buffer was
        # FULL — the deaf-consumer case. Lets the dispatcher distinguish a
        # real backpressure eviction from a consumer that closed its own
        # stream a moment before the send (which must not be counted or
        # terminated as deaf).
        self.overflowed = False

    def send(self, event: Event, timeout: Optional[float] = 5.0) -> bool:
        """Producer side. Returns False if the watcher is gone/slow: the
        reference terminates slow watchers (cacher.go forgetWatcher) rather
        than blocking the event path."""
        if self._stopped.is_set():
            return False
        try:
            if timeout is not None and timeout <= 0:
                self._q.put_nowait(event)
            else:
                self._q.put(event, timeout=timeout)
            return True
        except queue.Full:
            self.overflowed = True
            self.stop()
            return False

    def terminate(self, event: Event) -> None:
        """Stop the stream with a terminal event the consumer still gets
        AFTER draining the buffer — works even when the buffer is full (the
        deaf-watcher case, where the failed send() already stopped the
        stream and a plain send() could never land the WHY)."""
        with self._term_mu:
            if self._terminal is None:
                self._terminal = event
        self.stop()

    def _take_terminal(self) -> Optional[Event]:
        with self._term_mu:
            t, self._terminal = self._terminal, None
            return t

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            try:
                self._q.put_nowait(self._SENTINEL)
            except queue.Full:
                pass

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def depth(self) -> int:
        """Buffered (undelivered) events — the backpressure signal the
        dispatcher exports as `watch_buffer_depth`."""
        return self._q.qsize()

    def __iter__(self) -> Iterator[Event]:
        while True:
            item = self._q.get()
            if item is self._SENTINEL:
                t = self._take_terminal()
                if t is not None:
                    yield t
                return
            yield item
            if self._stopped.is_set() and self._q.empty():
                t = self._take_terminal()
                if t is not None:
                    yield t
                return

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Blocking pop; the terminal event (if any) after drain; None on
        stop/timeout."""
        if self._stopped.is_set() and self._q.empty():
            return self._take_terminal()
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is self._SENTINEL:
            return self._take_terminal()
        return item
