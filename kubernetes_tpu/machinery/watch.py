"""Watch event types and channels.

Analog of apimachinery `pkg/watch/watch.go`: an Interface delivering a stream
of {type, object} events. Here a watch is a closeable blocking queue; the
storage layer and clients share this shape.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Dict, Iterator, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"
ERROR = "ERROR"


@dataclass(frozen=True)
class Event:
    type: str
    object: Dict[str, Any]


class Watch:
    """watch.Interface: ResultChan() + Stop(). Iteration ends on Stop or when
    the producer closes the stream."""

    _SENTINEL = object()

    def __init__(self, capacity: int = 1024):
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=capacity)
        self._stopped = threading.Event()

    def send(self, event: Event, timeout: Optional[float] = 5.0) -> bool:
        """Producer side. Returns False if the watcher is gone/slow: the
        reference terminates slow watchers (cacher.go forgetWatcher) rather
        than blocking the event path."""
        if self._stopped.is_set():
            return False
        try:
            if timeout is not None and timeout <= 0:
                self._q.put_nowait(event)
            else:
                self._q.put(event, timeout=timeout)
            return True
        except queue.Full:
            self.stop()
            return False

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            try:
                self._q.put_nowait(self._SENTINEL)
            except queue.Full:
                pass

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def __iter__(self) -> Iterator[Event]:
        while True:
            item = self._q.get()
            if item is self._SENTINEL:
                return
            yield item
            if self._stopped.is_set() and self._q.empty():
                return

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Blocking pop; None on stop/timeout."""
        if self._stopped.is_set() and self._q.empty():
            return None
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is self._SENTINEL:
            return None
        return item
