"""Binary object codec + content negotiation — the protobuf-serializer seat.

Every internal reference client negotiates
`application/vnd.kubernetes.protobuf` against the apiserver
(`staging/src/k8s.io/apimachinery/pkg/runtime/serializer/protobuf/
protobuf.go`: a 4-byte magic `k8s\\x00` + length-delimited proto `Unknown`
envelope); JSON is the fallback for humans and CRDs. This module fills that
seat for the TPU stack: a self-describing tagged binary encoding of the
JSON object model (protoc codegen for 251k LoC of schemas is exactly what
this rebuild does NOT carry), negotiated the same way — `Accept` /
`Content-Type: application/vnd.kubernetes.ktpu.binary` — with JSON remaining
the default. Watch streams frame events as varint-length-delimited records,
the shape of the reference's streaming protobuf serializer.

Wire format (original; magic `kTPB`):
    value   := tag payload
    tag     0x00 null | 0x01 true | 0x02 false
            0x03 int (zigzag LEB128)
            0x04 float64 (8B big-endian IEEE)
            0x05 str  (LEB128 byte length + UTF-8)
            0x06 list (LEB128 count + values)
            0x07 map  (LEB128 count + (str-payload key, value) pairs)
Dict key order is preserved (insertion order), so encode∘decode is the
identity on the JSON object model — the round-trip contract the fuzz test
enforces.
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple

MAGIC = b"kTPB"
BINARY_MEDIA_TYPE = "application/vnd.kubernetes.ktpu.binary"
JSON_MEDIA_TYPE = "application/json"

_T_NULL, _T_TRUE, _T_FALSE, _T_INT, _T_FLOAT, _T_STR, _T_LIST, _T_MAP = \
    range(8)


def _uvarint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _encode_value(out: bytearray, v: Any) -> None:
    if v is None:
        out.append(_T_NULL)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        out.append(_T_INT)
        # generic zigzag without 64-bit assumptions (python ints are wide)
        _uvarint(out, (v << 1) if v >= 0 else ((-v) << 1) - 1)
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out += struct.pack(">d", v)
    elif isinstance(v, str):
        out.append(_T_STR)
        b = v.encode()
        _uvarint(out, len(b))
        out += b
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        _uvarint(out, len(v))
        for item in v:
            _encode_value(out, item)
    elif isinstance(v, dict):
        out.append(_T_MAP)
        _uvarint(out, len(v))
        for k, item in v.items():
            kb = str(k).encode()
            _uvarint(out, len(kb))
            out += kb
            _encode_value(out, item)
    else:
        raise TypeError(f"not JSON-model encodable: {type(v).__name__}")


def encode(obj: Any) -> bytes:
    out = bytearray(MAGIC)
    _encode_value(out, obj)
    return bytes(out)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def uvarint(self) -> int:
        n = shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise ValueError("truncated binary payload")
        self.pos += n
        return b

    def value(self) -> Any:
        tag = self.buf[self.pos]
        self.pos += 1
        if tag == _T_NULL:
            return None
        if tag == _T_TRUE:
            return True
        if tag == _T_FALSE:
            return False
        if tag == _T_INT:
            z = self.uvarint()
            return (z >> 1) if not z & 1 else -((z + 1) >> 1)
        if tag == _T_FLOAT:
            return struct.unpack(">d", self.take(8))[0]
        if tag == _T_STR:
            return self.take(self.uvarint()).decode()
        if tag == _T_LIST:
            return [self.value() for _ in range(self.uvarint())]
        if tag == _T_MAP:
            out = {}
            for _ in range(self.uvarint()):
                k = self.take(self.uvarint()).decode()
                out[k] = self.value()
            return out
        raise ValueError(f"bad tag 0x{tag:02x} at {self.pos - 1}")


def decode(data: bytes) -> Any:
    if data[:4] != MAGIC:
        raise ValueError("not a kTPB payload (bad magic)")
    r = _Reader(data, 4)
    v = r.value()
    if r.pos != len(data):
        raise ValueError(f"{len(data) - r.pos} trailing bytes")
    return v


# ---------------------------------------------------------------------- #
# watch-stream framing (streaming serializer analog): varint length +
# MAGIC-less encoded value per event, so frames survive concatenation
# ---------------------------------------------------------------------- #

def encode_frame(obj: Any) -> bytes:
    body = bytearray()
    _encode_value(body, obj)
    head = bytearray()
    _uvarint(head, len(body))
    return bytes(head) + bytes(body)


def decode_frames(data: bytes) -> Tuple[List[Any], bytes]:
    """Decode as many complete frames as `data` holds; return (events,
    remainder) — the incremental read loop the watch client runs."""
    out: List[Any] = []
    pos = 0
    while pos < len(data):
        r = _Reader(data, pos)
        try:
            size = r.uvarint()
            body_start = r.pos
            if body_start + size > len(data):
                break
            rv = _Reader(data, body_start)
            out.append(rv.value())
            if rv.pos != body_start + size:
                raise ValueError("frame length mismatch")
            pos = body_start + size
        except IndexError:  # truncated varint header
            break
    return out, data[pos:]


def accepts_binary(accept_header: str) -> bool:
    return BINARY_MEDIA_TYPE in (accept_header or "")
