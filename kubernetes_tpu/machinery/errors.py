"""API error taxonomy ↔ HTTP status codes.

Analog of apimachinery `pkg/api/errors/errors.go`: every API failure is a
Status object with reason + code; helpers construct and classify them.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class StatusError(Exception):
    """api/errors.StatusError: carries a metav1.Status."""

    def __init__(self, code: int, reason: str, message: str,
                 details: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.code = code
        self.reason = reason
        self.message = message
        self.details = details or {}

    def status(self) -> Dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Status",
            "status": "Failure",
            "message": self.message,
            "reason": self.reason,
            "details": self.details,
            "code": self.code,
        }


def new_not_found(resource: str, name: str) -> StatusError:
    return StatusError(404, "NotFound", f'{resource} "{name}" not found',
                       {"name": name, "kind": resource})


def new_already_exists(resource: str, name: str) -> StatusError:
    return StatusError(409, "AlreadyExists", f'{resource} "{name}" already exists',
                       {"name": name, "kind": resource})


def new_conflict(resource: str, name: str, message: str) -> StatusError:
    return StatusError(409, "Conflict",
                       f'Operation cannot be fulfilled on {resource} "{name}": {message}',
                       {"name": name, "kind": resource})


def new_invalid(kind: str, name: str, message: str) -> StatusError:
    return StatusError(422, "Invalid", f'{kind} "{name}" is invalid: {message}',
                       {"name": name, "kind": kind})


def new_bad_request(message: str) -> StatusError:
    return StatusError(400, "BadRequest", message)


def new_forbidden(resource: str, name: str, message: str) -> StatusError:
    return StatusError(403, "Forbidden", f'{resource} "{name}" is forbidden: {message}')


def new_unauthorized(message: str = "Unauthorized") -> StatusError:
    return StatusError(401, "Unauthorized", message)


def new_method_not_supported(resource: str, action: str) -> StatusError:
    return StatusError(405, "MethodNotAllowed", f"{action} is not supported on {resource}")


def new_timeout(message: str, retry_seconds: int = 0) -> StatusError:
    return StatusError(504, "Timeout", message, {"retryAfterSeconds": retry_seconds})


def new_too_many_requests(message: str, retry_seconds: int = 1) -> StatusError:
    return StatusError(429, "TooManyRequests", message,
                       {"retryAfterSeconds": retry_seconds})


def new_service_unavailable(message: str) -> StatusError:
    """503 — aggregated APIService backend unreachable
    (kube-aggregator proxyHandler error path)."""
    return StatusError(503, "ServiceUnavailable", message)


def new_gone(message: str) -> StatusError:
    """410 Gone — watch/list from a compacted resourceVersion
    (storage.NewTooLargeResourceVersionError / etcd compaction)."""
    return StatusError(410, "Expired", message)


def is_not_found(e: Exception) -> bool:
    return isinstance(e, StatusError) and e.code == 404


def is_already_exists(e: Exception) -> bool:
    return isinstance(e, StatusError) and e.reason == "AlreadyExists"


def is_conflict(e: Exception) -> bool:
    return isinstance(e, StatusError) and e.reason == "Conflict"


def is_invalid(e: Exception) -> bool:
    return isinstance(e, StatusError) and e.code == 422


def is_forbidden(e: Exception) -> bool:
    return isinstance(e, StatusError) and e.code == 403


def is_gone(e: Exception) -> bool:
    return isinstance(e, StatusError) and e.code == 410


def from_status(status: Dict[str, Any]) -> StatusError:
    return StatusError(
        int(status.get("code", 500)),
        status.get("reason", "InternalError"),
        status.get("message", "unknown error"),
        status.get("details") or {},
    )
