"""resource.Quantity: parse, canonical format, arithmetic, comparison.

Analog of apimachinery `pkg/api/resource/quantity.go`. A Quantity is a
fixed-point decimal with binary-SI (Ki/Mi/...), decimal-SI (k/M/...), and
decimal-exponent (e3/E3) suffix forms. We store an exact integer count of
*milli-units* (the reference's internal int64+scale covers the same range for
every practical cluster quantity; milli is its smallest legal scale —
quantity.go "No fraction smaller than milli may be specified").
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from decimal import Decimal, ROUND_CEILING
from typing import Union

_QTY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>[0-9]+(?:\.[0-9]*)?|\.[0-9]+)"
    r"(?:(?P<suffix>[kKMGTPE]i?|m)|[eE](?P<exp>[+-]?[0-9]+))?$"
)

_DECIMAL_POW = {"k": 3, "M": 6, "G": 9, "T": 12, "P": 15, "E": 18}
_BINARY_POW = {"Ki": 10, "Mi": 20, "Gi": 30, "Ti": 40, "Pi": 50, "Ei": 60}

# Canonicalization ladders (quantity.go Suffixer): binary suffixes for
# BinarySI-formatted values, decimal for DecimalSI.
_BINARY_LADDER = [("Ei", 60), ("Pi", 50), ("Ti", 40), ("Gi", 30), ("Mi", 20), ("Ki", 10)]
_DECIMAL_LADDER = [("E", 18), ("P", 15), ("T", 12), ("G", 9), ("M", 6), ("k", 3)]

BINARY_SI = "BinarySI"
DECIMAL_SI = "DecimalSI"


class QuantityError(ValueError):
    pass


@dataclass(frozen=True, order=False)
class Quantity:
    """Exact quantity in milli-units with remembered format."""

    milli: int
    fmt: str = DECIMAL_SI

    # -- comparisons (Cmp) -------------------------------------------------- #
    def __lt__(self, o: "Quantity") -> bool:
        return self.milli < o.milli

    def __le__(self, o: "Quantity") -> bool:
        return self.milli <= o.milli

    def __gt__(self, o: "Quantity") -> bool:
        return self.milli > o.milli

    def __ge__(self, o: "Quantity") -> bool:
        return self.milli >= o.milli

    def __add__(self, o: "Quantity") -> "Quantity":
        return Quantity(self.milli + o.milli, self.fmt)

    def __sub__(self, o: "Quantity") -> "Quantity":
        return Quantity(self.milli - o.milli, self.fmt)

    def is_zero(self) -> bool:
        return self.milli == 0

    # -- accessors ---------------------------------------------------------- #
    def value(self) -> int:
        """Quantity.Value(): ceil to integer units."""
        return -(-self.milli // 1000) if self.milli >= 0 else -((-self.milli) // 1000)

    def milli_value(self) -> int:
        return self.milli

    # -- canonical string (String / CanonicalizeBytes) ---------------------- #
    def __str__(self) -> str:
        m = self.milli
        if m == 0:
            return "0"
        sign = "-" if m < 0 else ""
        m = abs(m)
        if m % 1000 != 0:
            # milli remainder: always formatted with the m suffix
            return f"{sign}{m}m"
        units = m // 1000
        ladder = _BINARY_LADDER if self.fmt == BINARY_SI else None
        if ladder:
            for suf, pow2 in ladder:
                if units % (1 << pow2) == 0:
                    return f"{sign}{units >> pow2}{suf}"
            return f"{sign}{units}"
        for suf, pow10 in _DECIMAL_LADDER:
            if units % (10 ** pow10) == 0:
                return f"{sign}{units // 10 ** pow10}{suf}"
        return f"{sign}{units}"


def parse(s: Union[str, int, float]) -> Quantity:
    """resource.ParseQuantity."""
    if isinstance(s, bool):
        raise QuantityError(f"bad quantity {s!r}")
    if isinstance(s, int):
        return Quantity(s * 1000)
    if isinstance(s, float):
        return _from_decimal(Decimal(str(s)), DECIMAL_SI)
    m = _QTY_RE.match(s.strip())
    if not m:
        raise QuantityError(f"bad quantity {s!r}")
    num = Decimal(m.group("num"))
    if m.group("sign") == "-":
        num = -num
    suffix = m.group("suffix")
    exp = m.group("exp")
    fmt = DECIMAL_SI
    if suffix == "m":
        return _from_decimal(num / 1000, DECIMAL_SI)
    if suffix in _BINARY_POW:
        num *= 1 << _BINARY_POW[suffix]
        fmt = BINARY_SI
    elif suffix in _DECIMAL_POW:
        num *= Decimal(10) ** _DECIMAL_POW[suffix]
    elif exp is not None:
        num *= Decimal(10) ** int(exp)
    return _from_decimal(num, fmt)


def _from_decimal(d: Decimal, fmt: str) -> Quantity:
    # Quantities may not be smaller than 1m; sub-milli rounds up
    # (quantity.go: "Fractional digits smaller than milli are rounded up").
    milli = int((d * 1000).to_integral_value(rounding=ROUND_CEILING))
    return Quantity(milli, fmt)


def parse_milli(s: Union[str, int, float]) -> int:
    return parse(s).milli


def add_resources(a: dict, b: dict) -> dict:
    """Sum two {resourceName: quantityString} maps (quota.Add)."""
    out = dict(a)
    for k, v in b.items():
        if k in out:
            out[k] = str(parse(out[k]) + parse(v))
        else:
            out[k] = v
    return out


def cmp(a: Union[str, int], b: Union[str, int]) -> int:
    qa, qb = parse(a), parse(b)
    return (qa.milli > qb.milli) - (qa.milli < qb.milli)
