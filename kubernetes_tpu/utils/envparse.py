"""Bounds-checked parsing for integer env knobs.

Every integer knob the scheduler or bench reads from the environment
(`KTPU_FLEET_TENANTS`, `KTPU_MESH`, `KTPU_FLEET_NODE_SHARDS`, bench shape
overrides, …) routes through one clamp helper — the
`storage/store._parse_watch_buffer` discipline generalized: garbage or an
unset value falls back to the default, out-of-range values clamp to a sane
range, and nothing ever crashes `int()` or builds a degenerate (0- or
negative-sized) mesh because an operator exported `KTPU_FLEET_TENANTS=lots`.
"""

from __future__ import annotations

import os
from typing import Optional


def clamped_int(value, default: int, lo: int, hi: int) -> int:
    """`value` as an int clamped to [lo, hi]; `default` (also clamped) when
    value is None, empty, or not an integer literal."""
    try:
        n = int(str(value).strip())
    except (TypeError, ValueError):
        n = default
    return max(lo, min(hi, n))


def env_int(name: str, default: int, lo: int, hi: int) -> int:
    """The env knob `name` parsed through `clamped_int`. Unset → default."""
    return clamped_int(os.environ.get(name), default, lo, hi)


def env_opt_int(name: str, lo: int, hi: int) -> Optional[int]:
    """Like `env_int` but unset/garbage → None (knob not configured) rather
    than a numeric default — for knobs whose absence selects a different
    code path entirely (e.g. `KTPU_MESH` unset = single-device serving)."""
    raw = os.environ.get(name)
    if raw is None or not str(raw).strip():
        return None
    try:
        n = int(str(raw).strip())
    except (TypeError, ValueError):
        return None
    return max(lo, min(hi, n))
