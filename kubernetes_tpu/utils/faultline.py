"""Seam-level chaos fault injection.

Production cluster schedulers are judged on what happens when the
infrastructure under them misbehaves: the device runtime hangs mid-dispatch,
the watch stream drops under a compaction storm, the apiserver restarts
between two requests. This module is the single switchboard those seams
consult, so the same build that serves traffic can be driven through every
failure mode deterministically — in tests, in the chaos bench stage, and in a
live canary via one environment variable.

Spec grammar (comma-separated entries in ``FAULT_SPEC``)::

    FAULT_SPEC="device.hang@cycle:3,watch.drop@0.1,store.cas_conflict@0.05,native.dlopen"

    entry     := fault [ "@" qualifier ]
    qualifier := site ":" N        fire exactly on the N-th should() call
                                   naming that site (one-shot)
               | site ":" N "+"    fire on every call at that site from the
                                   N-th on (persistent fault)
               | float in (0,1)    fire with that probability per call
                                   (seeded RNG — FAULT_SEED, default 0)
               | int N             fire exactly on the N-th call, any site
               | site              fire on every call at that site — the
                                   count is split off the RIGHT, so sites
                                   may themselves contain colons
                                   (proc.crash@wal:post_append:1 = the
                                   first hit at site "wal:post_append";
                                   wal.torn@tail = every hit at "tail")
    (no qualifier)                 fire on every call (e.g. native.dlopen)

    A qualifier segment that starts with a digit but parses as neither a
    count nor a probability (``wal:0.5`` missing its site, ``3x``) raises
    FaultSpecError instead of silently becoming a never-matching site.

Seams wired in this repo (fault name → injection point):

    device.hang / device.error / device.oom   sched/supervisor.py (per-kind
                                              sites: cycle, preempt, scores,
                                              prewarm, probe)
    device.fallback                           sched/supervisor.py CPU-fallback
                                              path (total-loss drills)
    store.cas_conflict                        storage/store.py
                                              guaranteed_update CAS loop
    store.compact                             storage/store.py watch() — a
                                              REAL kv compaction, so stale
                                              resumes earn genuine 410s
    watch.drop / watch.relist                 client/informers.py reflector
    native.dlopen                             storage/native.py new_kv()
    apiserver.restart                         apiserver/server.py handle_rest
    apiserver.slow                            apiserver/server.py (sites:
                                              handle_rest = every hit
                                              request stalls KTPU_SLOW_S
                                              before routing; bind = only
                                              the pods/binding commit path
                                              stalls) — the overload
                                              drills' commit-latency-SLO
                                              breach switch (ISSUE 9)
    store.latency                             storage/store.py
                                              guaranteed_update (site:
                                              guaranteed_update): a slow
                                              etcd — bind intents and
                                              Lease renews stall
                                              KTPU_SLOW_S per hit write
    watch.storm                               client/informers.py reflector
                                              (site: informer): forces a
                                              relist — the whole world
                                              redelivers as one burst of
                                              upserts, the ingest-side
                                              storm the overload governor's
                                              pressure signal reacts to
    proc.crash                                sched/scheduler.py bind
                                              lifecycle + sched/ledger.py
                                              reconciliation (sites:
                                              pre_intent, post_intent,
                                              post_bind, takeover) — raises
                                              InjectedCrash, a BaseException
                                              that punches through every
                                              `except Exception` guard the
                                              way SIGKILL punches through a
                                              process (restart drills)
    watch.stall@<route>                       client/watchmux.py (site =
                                              route/tenant name): ONE mux
                                              route's consumer goes deaf —
                                              that route is broken (queue
                                              cleared, sequence fence
                                              raised) and resyncs itself
                                              from the mux's indexer
                                              snapshot; the apiserver and
                                              sibling routes never notice
    watch.compact@floor                       storage/store.py dispatch
                                              pump: a REAL compaction at
                                              the current revision, with
                                              the compaction-boundary
                                              BOOKMARK broadcast — live
                                              opted-in streams stay
                                              resumable, stale resume
                                              tokens beneath the floor
                                              earn genuine 410s
    mux.die@<mux>|stream                      client/watchmux.py event fan:
                                              the mux's ONE upstream
                                              stream dies; tenants serve
                                              cached state (staleness
                                              grows) until
                                              FleetWatchPlane.maintain
                                              revives it — a RESUME from
                                              the last bookmarked RV, not
                                              K relists. Site = the mux
                                              name (pods/nodes) for a
                                              deterministic single-mux
                                              kill; "stream" is the
                                              shared any-mux site
    proc.crash@wal:{pre_fsync,                storage/wal.py append (pre/post
      post_fsync,post_append}                 fsync) + storage/native.py
                                              DurableKV commit (post_append):
                                              the APISERVER dies mid-commit —
                                              record appended / durable /
                                              applied-to-memory respectively.
                                              All three leave the record in
                                              the WAL, so the cold-restart
                                              drill's reboot replays it
                                              (committed-but-unacked writes
                                              may surface after reboot;
                                              acknowledged ones may never be
                                              lost)
    wal.torn@tail                             storage/wal.py load_state:
                                              bytes chopped off the FINAL
                                              segment before replay — the
                                              power cut landed mid-append;
                                              recovery truncates the torn
                                              frame and continues (the
                                              clean-truncate row of the
                                              decision table)
    disk.full@wal                             storage/wal.py append: the
                                              append is refused
                                              (WalWriteError) BEFORE any
                                              bytes land, so the in-memory
                                              store and the log never
                                              disagree; the caller sees a
                                              failed write, not a torn one
    tenant.storm                              fleet/server.py per-tenant
                                              tick (site = tenant name,
                                              e.g. "tenant.storm@t02:1+"):
                                              an injected watch storm for
                                              ONE tenant — its snapshot is
                                              invalidated (full re-encode)
                                              and its popped batch requeues
                                              promptly, degrading only that
                                              tenant's cycle stats; the
                                              chaos suite proves the other
                                              tenants' ticks are untouched

The hot-path contract: when no spec is installed, ``should()`` is one global
read and a ``None`` check — safe to call per storage CAS or per watch event.
"""

from __future__ import annotations

import os
import random
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class InjectedDeviceError(RuntimeError):
    """Stand-in for XlaRuntimeError raised by a chaos-injected device fault.
    The dispatch supervisor treats it exactly like the real thing."""


class InjectedCrash(BaseException):
    """Simulated abrupt process death (the SIGKILL analog) at a `proc.crash`
    crashpoint. Deliberately a BaseException: a real kill does not run
    `except Exception` recovery handlers, so neither does this — it unwinds
    straight out of the scheduling loop, leaving whatever durable state the
    crashed point had already committed (the bind-intent ledger, Binding
    writes) exactly as a power cut would. Restart drills catch it at the
    test/bench harness level and bring up a fresh scheduler incarnation."""


class FaultSpecError(ValueError):
    """Malformed FAULT_SPEC entry."""


_FLOAT_RE = re.compile(r"^0?\.\d+$|^0$|^1\.0$")


def _reject_numeric_site(entry: str, seg: str) -> None:
    """A would-be site segment that starts with a digit is a typo'd count or
    probability (proc.crash@wal:0.5, fault@3x) — no wired seam site begins
    with a digit. Installing it as an always-fire rule for a site that never
    matches would let a chaos drill pass without injecting anything, so
    refuse loudly instead."""
    if seg and seg[0].isdigit():
        raise FaultSpecError(
            f"{entry!r}: qualifier segment {seg!r} looks numeric but is not "
            "a valid count (N / N+) or probability in (0,1); site names "
            "never start with a digit")


@dataclass
class _Rule:
    fault: str
    site: str = ""          # "" = any site
    nth: int = 0            # 0 = not hit-count gated
    persistent: bool = False  # nth+: keep firing from the N-th hit on
    prob: float = 0.0       # 0 = not probability gated
    always: bool = False
    hits: int = 0           # should() calls matching this rule's site filter
    fired: int = 0


def parse_spec(spec: str) -> List[_Rule]:
    rules: List[_Rule] = []
    for raw in (spec or "").split(","):
        entry = raw.strip()
        if not entry:
            continue
        fault, _, qual = entry.partition("@")
        fault = fault.strip()
        if not fault:
            raise FaultSpecError(f"empty fault name in {entry!r}")
        if not qual:
            rules.append(_Rule(fault=fault, always=True))
        elif ":" in qual:
            # the count splits off the RIGHT so sites may contain colons
            # (proc.crash@wal:post_append:1); a qualifier whose final
            # segment is not a count is a bare colon-bearing SITE
            # (proc.crash@wal:post_append = always at that site)
            site, _, n = qual.rpartition(":")
            persistent = n.endswith("+")
            n = n[:-1] if persistent else n
            try:
                nth = int(n)
            except ValueError:
                _reject_numeric_site(entry, n)
                rules.append(_Rule(fault=fault, site=qual.strip(),
                                   always=True))
            else:
                rules.append(_Rule(fault=fault, site=site.strip(), nth=nth,
                                   persistent=persistent))
        elif _FLOAT_RE.match(qual):
            rules.append(_Rule(fault=fault, prob=float(qual)))
        else:
            try:
                nth = int(qual)
            except ValueError:
                # a bare site name (wal.torn@tail, disk.full@wal):
                # fire on every should() call naming that site
                _reject_numeric_site(entry, qual)
                rules.append(_Rule(fault=fault, site=qual.strip(),
                                   always=True))
            else:
                rules.append(_Rule(fault=fault, nth=nth))
    return rules


class FaultLine:
    """One parsed spec plus its firing state. Thread-safe: seams are consulted
    from the watch pump, reflector threads, the dispatch worker, and the
    scheduling loop concurrently."""

    def __init__(self, spec: str = "", seed: Optional[int] = None):
        self.spec = spec
        self._rules = parse_spec(spec)
        if seed is None:
            seed = int(os.environ.get("FAULT_SEED", "0") or 0)
        self._rng = random.Random(seed)
        self._mu = threading.Lock()

    def should(self, fault: str, site: str = "") -> bool:
        """Consult the spec for one potential fault at one seam. Increments
        hit counters for matching rules; returns True when any rule fires."""
        fire = False
        with self._mu:
            for r in self._rules:
                if r.fault != fault:
                    continue
                if r.site and r.site != site:
                    continue
                r.hits += 1
                hit = False
                if r.always:
                    hit = True
                elif r.nth:
                    hit = (r.hits >= r.nth if r.persistent
                           else r.hits == r.nth)
                elif r.prob:
                    hit = self._rng.random() < r.prob
                if hit:
                    r.fired += 1
                    fire = True
        return fire

    def fired(self, fault: str, site: str = "") -> int:
        """Total firings for a fault (optionally one site) — test assertions
        read this to prove the seam was actually exercised."""
        with self._mu:
            return sum(r.fired for r in self._rules
                       if r.fault == fault and (not site or r.site == site))

    def counts(self) -> Dict[str, int]:
        with self._mu:
            out: Dict[str, int] = {}
            for r in self._rules:
                key = f"{r.fault}@{r.site}" if r.site else r.fault
                out[key] = out.get(key, 0) + r.fired
            return out


# ---- process-global switchboard ---------------------------------------- #

_active: Optional[FaultLine] = None
_install_mu = threading.Lock()


def install(spec: Optional[str] = None, seed: Optional[int] = None) -> FaultLine:
    """Install a FaultLine as the process-global injector. spec=None reads
    FAULT_SPEC from the environment (empty env → inactive no-op line)."""
    global _active
    if spec is None:
        spec = os.environ.get("FAULT_SPEC", "")
    with _install_mu:
        _active = FaultLine(spec, seed=seed)
        return _active


def uninstall() -> None:
    global _active
    with _install_mu:
        _active = None


def active() -> Optional[FaultLine]:
    return _active


def should(fault: str, site: str = "") -> bool:
    """The seam entry point. Near-zero cost when no injector is installed."""
    fl = _active
    return fl is not None and fl.should(fault, site)


def crashpoint(site: str) -> None:
    """A `proc.crash@site` seam in the bind lifecycle: when the spec names
    this site, the process "dies" here (InjectedCrash). Sites wired:
    pre_intent / post_intent / post_bind (sched/scheduler.py wave commit)
    and takeover (sched/ledger.py reconciliation replay)."""
    if should("proc.crash", site):
        raise InjectedCrash(f"proc.crash@{site}")


# env-driven startup: a process launched with FAULT_SPEC set is under chaos
# from its first request, no code change required
if os.environ.get("FAULT_SPEC"):
    install()
