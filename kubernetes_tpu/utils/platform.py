"""Backend/platform plumbing.

This container's interpreter is armed with an axon TPU-relay site hook
(sitecustomize via PYTHONPATH) that claims the TPU at interpreter start when
PALLAS_AXON_POOL_IPS is set. If a process then asks for the CPU backend
(JAX_PLATFORMS=cpu), jax backend init deadlocks against the half-initialized
claim — the only reliable fix is a fresh interpreter with the hook disarmed.
"""

from __future__ import annotations

import os
import sys


def ensure_cpu_backend_safe(argv: list[str] | None = None) -> None:
    """Call BEFORE importing jax in any process that targets JAX_PLATFORMS=cpu.
    Re-execs the interpreter once with the axon hook disarmed if needed."""
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return  # hook already disarmed
    if os.environ.get("KTPU_CPU_REEXEC") == "1":
        return  # already re-exec'd; don't loop
    # NB: "jax already imported" is the NORMAL armed case — the site hook
    # imports jax at interpreter start, before any user code could run. The
    # re-exec'd child is a fresh process with the hook disarmed, so re-exec
    # is exactly as safe here as before the import.
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["KTPU_CPU_REEXEC"] = "1"
    os.execve(sys.executable, [sys.executable] + (argv or sys.argv), env)
