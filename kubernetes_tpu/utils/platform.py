"""Backend/platform plumbing.

This container's interpreter is armed with an axon TPU-relay site hook
(sitecustomize via PYTHONPATH) that claims the TPU at interpreter start when
PALLAS_AXON_POOL_IPS is set. If a process then asks for the CPU backend
(JAX_PLATFORMS=cpu), jax backend init deadlocks against the half-initialized
claim — the only reliable fix is a fresh interpreter with the hook disarmed.
"""

from __future__ import annotations

import os
import sys


def ensure_cpu_backend_safe(argv: list[str] | None = None) -> None:
    """Call BEFORE importing jax in any process that targets JAX_PLATFORMS=cpu.
    Re-execs the interpreter once with the axon hook disarmed if needed."""
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return  # hook already disarmed
    if os.environ.get("KTPU_CPU_REEXEC") == "1":
        return  # already re-exec'd; don't loop
    # NB: "jax already imported" is the NORMAL armed case — the site hook
    # imports jax at interpreter start, before any user code could run. The
    # re-exec'd child is a fresh process with the hook disarmed, so re-exec
    # is exactly as safe here as before the import.
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["KTPU_CPU_REEXEC"] = "1"
    os.execve(sys.executable, [sys.executable] + (argv or _original_args()), env)


def cpu_disarmed_env(env: dict | None = None) -> dict:
    """A copy of `env` (default os.environ) set up so a fresh child process
    comes up on the XLA CPU backend with the axon site hook disarmed — the
    subprocess counterpart of ensure_cpu_backend_safe()."""
    out = dict(os.environ if env is None else env)
    out["JAX_PLATFORMS"] = "cpu"
    out["PALLAS_AXON_POOL_IPS"] = ""  # disarm the axon site hook
    out["KTPU_CPU_REEXEC"] = "1"  # child needs no re-exec
    return out


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point jax at a persistent on-disk compilation cache so a process restart
    does not re-pay XLA compile time for shapes it has already seen (the 5k×50k
    lattice costs ~2 min to compile cold). The reference has no analog — Go
    compiles ahead of time — so this is pure TPU-runtime plumbing.

    KTPU_COMPILE_CACHE=0 disables; KTPU_COMPILE_CACHE=<dir> overrides the
    location (default: <repo>/.cache/xla). Returns the directory or None.
    Safe to call any number of times, before or after jax import."""
    env = os.environ.get("KTPU_COMPILE_CACHE", "")
    if env == "0":
        return None
    d = path or env or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".cache", "xla")
    try:
        os.makedirs(d, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", d)
        # cache every compile that takes noticeable time, not just >1s ones
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        return d
    except Exception:
        return None  # cache is an optimization; never fail the caller


def _original_args() -> list[str]:
    """Interpreter args of THIS process, faithfully enough to re-exec.

    sys.argv is lossy: under ``python -c "code"`` it is ``['-c', ...]`` — the
    code string is gone, so re-exec'ing sys.argv hands the child a bare ``-c``.
    /proc/self/cmdline has the real thing (NUL-separated, includes interpreter
    flags like -X/-O that sys.argv also drops), so prefer it on Linux.
    """
    try:
        raw = open("/proc/self/cmdline", "rb").read().split(b"\0")
        args = [a.decode() for a in raw if a]
        if len(args) >= 2:
            return args[1:]  # drop the interpreter path itself
    except OSError:
        pass
    if sys.argv and sys.argv[0] in ("-c", "-m"):
        raise RuntimeError(
            "ensure_cpu_backend_safe: cannot reconstruct a `python %s` command "
            "line without /proc; set PALLAS_AXON_POOL_IPS='' KTPU_CPU_REEXEC=1 "
            "in the environment instead" % sys.argv[0]
        )
    return sys.argv
