"""Multi-chip sharding of the scheduling lattice.

The reference scales Filter/Score with 16 goroutines on one box
(workqueue.ParallelizeUntil, generic_scheduler.go:537,770) and has no multi-
machine compute path at all — the control plane shards by *resource type*, not
by data. The TPU-native design shards the **node axis** across chips with a
`jax.sharding.Mesh`:

  * NodeArrays rows, the static [SC, N] lattice, per-node count carries
    (CNT/HOLD [S, N]) and the scan's [N]-wide dynamic rows are all partitioned
    on N — each chip owns N/n_devices nodes, exactly like the reference's
    goroutine chunking but over ICI instead of shared memory;
  * class/term tables are small and replicated;
  * the per-step argmax over N and `mask.any()` become cross-chip reductions —
    XLA GSPMD inserts the collectives (psum/all-gather over ICI) from the
    sharding annotations alone; no hand-written communication.

Pod-axis (batch) sharding — the long-context analog — composes on top for the
class-level matrices when SC×N outgrows one chip's HBM; the scan itself stays
sequential in pods by design (assume semantics).

Serving integration (the live path, not just the dryrun): `MeshState` owns
the mesh the scheduler dispatches on — `state/cache.py` keeps the encoded
`ClusterTables` RESIDENT on it (node axis split, patched with donated
scatters), `sched/prewarm.py` keys executables on the mesh signature, and
`sched/supervisor.py` drops/reforms the mesh across backend loss.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..state.arrays import ClusterTables, NodeArrays

NODE_AXIS = "nodes"

# the FLEET axis (fleet/ subsystem): K virtual tenant clusters stacked on a
# leading axis and split across chips — each chip owns K/n_devices whole
# tenants, so the vmap'd fleet cycle needs NO cross-chip collectives at all
# (tenants are independent by construction; contrast the node-axis split,
# whose per-step argmax/psum spans every chip)
TENANT_AXIS = "tenants"

XLA_MESH_HINT = (
    "set XLA_FLAGS=--xla_force_host_platform_device_count=<n> and "
    "JAX_PLATFORMS=cpu for a virtual mesh"
)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        err = RuntimeError(
            f"make_mesh({n}): only {len(devs)} devices visible — a multichip "
            "proof run on fewer devices than requested would validate nothing"
        )
        # PEP 678 notes: the actionable hint rides on the exception even
        # through re-raise/wrapping layers (3.10 tracebacks don't print
        # __notes__, so the hint is also queryable: err.__notes__)
        err.__notes__ = [XLA_MESH_HINT]
        raise err
    return Mesh(np.array(devs[:n]), (NODE_AXIS,))


def mesh_key(mesh: Optional[Mesh]) -> Optional[Tuple]:
    """Hashable signature of a mesh for executable/budget keying: shape and
    the concrete device ids. Two meshes with the same shape over DIFFERENT
    devices (pre- vs post-reform) must not share compiled programs — the old
    executable is pinned to the lost devices."""
    if mesh is None:
        return None
    return (mesh.devices.shape,
            tuple(d.id for d in mesh.devices.flat))


def padded_node_count(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices ≥ n."""
    return ((n + n_devices - 1) // n_devices) * n_devices


def pad_node_tables(tables: ClusterTables, n_devices: int) -> ClusterTables:
    """Pad the node axis with inert rows (valid=False, zero capacity, every
    id -1 — the same fill as Encoder.empty_node_arrays' unoccupied slots) so
    N divides the mesh evenly. Inert rows are masked by `nodes.valid`
    everywhere the engines look, so they can never admit a pod; the padding
    test (tests/test_mesh.py) holds that to zero phantom admissions."""
    N = int(tables.nodes.valid.shape[0])
    Np = padded_node_count(N, n_devices)
    if Np == N:
        return tables
    pad = Np - N

    def _pad(a):
        a = np.asarray(a)
        fill = np.zeros((pad,) + a.shape[1:], a.dtype)
        if a.dtype == np.int32:
            # id columns pad with -1 (absent); count/usage columns with 0.
            # -1 is the safe universal fill for an INVALID row: every
            # consumer is already gated on nodes.valid, and -1 matches the
            # empty_node_arrays convention for id planes
            fill[:] = -1
        return np.concatenate([a, fill], axis=0)

    nodes = NodeArrays(
        valid=_pad(tables.nodes.valid),
        name_id=_pad(tables.nodes.name_id),
        alloc=np.concatenate([np.asarray(tables.nodes.alloc),
                              np.zeros((pad,) + np.asarray(
                                  tables.nodes.alloc).shape[1:],
                                  np.asarray(tables.nodes.alloc).dtype)]),
        used=np.concatenate([np.asarray(tables.nodes.used),
                             np.zeros((pad,) + np.asarray(
                                 tables.nodes.used).shape[1:],
                                 np.asarray(tables.nodes.used).dtype)]),
        label_keys=_pad(tables.nodes.label_keys),
        label_vals=_pad(tables.nodes.label_vals),
        label_ints=np.concatenate([np.asarray(tables.nodes.label_ints),
                                   np.zeros((pad,) + np.asarray(
                                       tables.nodes.label_ints).shape[1:],
                                       np.int32)]),
        unschedulable=np.concatenate([np.asarray(tables.nodes.unschedulable),
                                      np.ones((pad,), bool)]),
        taint_keys=_pad(tables.nodes.taint_keys),
        taint_vals=_pad(tables.nodes.taint_vals),
        taint_effects=_pad(tables.nodes.taint_effects),
        topo=_pad(tables.nodes.topo),
        domain=_pad(tables.nodes.domain),
        port_pair_any=_pad(tables.nodes.port_pair_any),
        port_pair_wild=_pad(tables.nodes.port_pair_wild),
        port_triple=_pad(tables.nodes.port_triple),
        img_words=_pad(tables.nodes.img_words),
        vol_any=_pad(tables.nodes.vol_any),
        vol_rw=_pad(tables.nodes.vol_rw),
        vol_limit=_pad(tables.nodes.vol_limit),
        avoid=np.concatenate([np.asarray(tables.nodes.avoid),
                              np.zeros((pad,), bool)]),
    )
    return tables._replace(nodes=nodes)


def _node_sharded_tables_spec(tables: ClusterTables) -> ClusterTables:
    """PartitionSpecs: NodeArrays sharded on axis 0 (the N axis); everything
    else replicated."""
    node_specs = type(tables.nodes)(
        *[P(NODE_AXIS) for _ in tables.nodes]
    )
    rep = lambda t: type(t)(*[P() for _ in t])
    return ClusterTables(
        nodes=node_specs,
        reqs=rep(tables.reqs),
        labelsets=rep(tables.labelsets),
        nterms=rep(tables.nterms),
        tolsets=rep(tables.tolsets),
        portsets=rep(tables.portsets),
        terms=rep(tables.terms),
        classes=rep(tables.classes),
        images=rep(tables.images),
        zone_keys=P(),
        volsets=rep(tables.volsets),
        drv_masks=P(),
    )


def table_shardings(tables: ClusterTables, mesh: Mesh) -> ClusterTables:
    """NamedSharding pytree matching `shard_tables`' placement — shared by
    the live placement path (state/cache.py) and the AOT prewarm path
    (sched/prewarm.py builds ShapeDtypeStructs carrying these)."""
    specs = _node_sharded_tables_spec(tables)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_tables(tables: ClusterTables, mesh: Mesh) -> ClusterTables:
    """Place tables on the mesh: node axis split across chips, rest
    replicated. When dims.N does not divide the mesh evenly, the node axis is
    padded with inert rows first (zero capacity, invalid, unschedulable) —
    bucketed capacities make the divisible case the common one, but a raw
    Dims(N=...) from a caller must not crash the mesh path."""
    nd = len(mesh.devices.flat)
    tables = pad_node_tables(tables, nd)
    specs = _node_sharded_tables_spec(tables)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tables, specs
    )


def replicate(tree, mesh: Mesh):
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )


# ---------------------------------------------------------------------- #
# fleet (tenant-axis) sharding — fleet/tables.py stacks K tenant clusters
# on a leading axis; these helpers split that axis across the mesh
# ---------------------------------------------------------------------- #


def make_fleet_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over the TENANT axis. Same device discipline as
    `make_mesh` (raises with the virtual-mesh hint when short), different
    axis name so a fleet program and a node-sharded program can never
    accidentally share sharding annotations."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        err = RuntimeError(
            f"make_fleet_mesh({n}): only {len(devs)} devices visible")
        err.__notes__ = [XLA_MESH_HINT]
        raise err
    return Mesh(np.array(devs[:n]), (TENANT_AXIS,))


def padded_tenant_count(k: int, n_devices: int) -> int:
    """Smallest multiple of n_devices ≥ k — inert (empty-cluster) tenant
    slots pad the difference, exactly the `pad_node_tables` inert-row
    contract lifted one axis up."""
    return padded_node_count(k, n_devices)


def fleet_sharding(mesh: Mesh) -> NamedSharding:
    """The one NamedSharding of the fleet layout: every stacked leaf splits
    its leading (tenant) axis; later axes stay unsharded."""
    return NamedSharding(mesh, P(TENANT_AXIS))


def shard_fleet(tree, mesh: Mesh):
    """Place a stacked fleet pytree (every leaf [K, …]) on the mesh, tenant
    axis split. K must already be a multiple of the mesh size — the fleet
    stack pads with inert tenants first (fleet/tables.py)."""
    sh = fleet_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


class MeshState:
    """The serving scheduler's mesh lifecycle (sched/supervisor.py owns the
    health transitions):

      * `mesh` — the live mesh the next snapshot/dispatch should use, or
        None (single-device serving, exactly the pre-mesh behavior).
      * `on_backend_loss()` — a device of the mesh died (XlaRuntimeError,
        watchdog timeout): the WHOLE mesh is untrusted (GSPMD collectives
        span every chip), so serving drops to the supervisor's single-device
        CPU fallback immediately. The lost width is remembered.
      * `reform()` — re-admission: rebuild a mesh from the devices that are
        live NOW. After a loss the reformed mesh is SMALLER (largest power of
        two strictly below the lost width — the failed chip cannot be
        re-trusted blindly) unless the prober proved full width, in which
        case `reform(full=True)` restores it. A fresh Mesh object is built
        either way: state/cache.py keys residency on mesh identity, so
        reform forces the re-shard-from-host-staging path by construction.

    Device counts stay powers of two so the bucketed node axis (state/dims.py
    grown_for keeps N pow2-friendly) divides evenly without padding in the
    steady state; `shard_tables` pads when a raw shape doesn't."""

    def __init__(self, n_devices: Optional[int] = None):
        self._mu = threading.Lock()
        self._requested = n_devices
        self._lost_width: Optional[int] = None
        self.reforms = 0
        self.demotions = 0
        m = None
        avail = len(jax.devices())
        want = n_devices or avail
        if want > 1 and avail >= 2:
            m = make_mesh(self._pow2_floor(min(want, avail)))
        self.mesh: Optional[Mesh] = m

    @staticmethod
    def _pow2_floor(n: int) -> int:
        return 1 << (max(n, 1).bit_length() - 1)

    @property
    def n_devices(self) -> int:
        with self._mu:
            return len(self.mesh.devices.flat) if self.mesh is not None else 1

    def on_backend_loss(self) -> None:
        """A mesh device is gone: drop the mesh entirely (collectives span
        all chips — there is no partial trust) and remember the width so
        reform comes back narrower."""
        with self._mu:
            if self.mesh is None:
                return
            self._lost_width = len(self.mesh.devices.flat)
            self.mesh = None
            self.demotions += 1

    def reform(self, full: bool = False) -> Optional[Mesh]:
        """Rebuild the mesh on re-admission. `full=True` (the prober proved
        every device answers) restores the requested width; otherwise the
        reformed mesh halves the lost width — losing one device of an 8-way
        mesh serves on 4 until a full-width probe passes."""
        with self._mu:
            avail = len(jax.devices())
            want = self._requested or avail
            if not full and self._lost_width is not None:
                want = min(want, max(self._lost_width // 2, 1))
            want = self._pow2_floor(min(want, avail))
            if want <= 1:
                self.mesh = None
                return None
            self.mesh = make_mesh(want)
            if full:
                self._lost_width = None
            self.reforms += 1
            return self.mesh
