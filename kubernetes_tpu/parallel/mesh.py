"""Multi-chip sharding of the scheduling lattice.

The reference scales Filter/Score with 16 goroutines on one box
(workqueue.ParallelizeUntil, generic_scheduler.go:537,770) and has no multi-
machine compute path at all — the control plane shards by *resource type*, not
by data. The TPU-native design shards the **node axis** across chips with a
`jax.sharding.Mesh`:

  * NodeArrays rows, the static [SC, N] lattice, per-node count carries
    (CNT/HOLD [S, N]) and the scan's [N]-wide dynamic rows are all partitioned
    on N — each chip owns N/n_devices nodes, exactly like the reference's
    goroutine chunking but over ICI instead of shared memory;
  * class/term tables are small and replicated;
  * the per-step argmax over N and `mask.any()` become cross-chip reductions —
    XLA GSPMD inserts the collectives (psum/all-gather over ICI) from the
    sharding annotations alone; no hand-written communication.

Pod-axis (batch) sharding — the long-context analog — composes on top for the
class-level matrices when SC×N outgrows one chip's HBM; the scan itself stays
sequential in pods by design (assume semantics).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..state.arrays import ClusterTables, PodArrays

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise RuntimeError(
            f"make_mesh({n}): only {len(devs)} devices visible — a multichip "
            "proof run on fewer devices than requested would validate nothing "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count and "
            "JAX_PLATFORMS=cpu for a virtual mesh)"
        )
    return Mesh(np.array(devs[:n]), (NODE_AXIS,))


def _node_sharded_tables_spec(tables: ClusterTables) -> ClusterTables:
    """PartitionSpecs: NodeArrays sharded on axis 0 (the N axis); everything
    else replicated."""
    node_specs = type(tables.nodes)(
        *[P(NODE_AXIS) for _ in tables.nodes]
    )
    rep = lambda t: type(t)(*[P() for _ in t])
    return ClusterTables(
        nodes=node_specs,
        reqs=rep(tables.reqs),
        labelsets=rep(tables.labelsets),
        nterms=rep(tables.nterms),
        tolsets=rep(tables.tolsets),
        portsets=rep(tables.portsets),
        terms=rep(tables.terms),
        classes=rep(tables.classes),
        images=rep(tables.images),
        zone_keys=P(),
        volsets=rep(tables.volsets),
        drv_masks=P(),
    )


def shard_tables(tables: ClusterTables, mesh: Mesh) -> ClusterTables:
    """Place tables on the mesh: node axis split across chips, rest replicated.
    Requires dims.N % n_devices == 0 (bucketed capacities make this easy)."""
    specs = _node_sharded_tables_spec(tables)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tables, specs
    )


def replicate(tree, mesh: Mesh):
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )
