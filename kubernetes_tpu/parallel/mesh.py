"""Multi-chip sharding of the scheduling lattice.

The reference scales Filter/Score with 16 goroutines on one box
(workqueue.ParallelizeUntil, generic_scheduler.go:537,770) and has no multi-
machine compute path at all — the control plane shards by *resource type*, not
by data. The TPU-native design shards the **node axis** across chips with a
`jax.sharding.Mesh`:

  * NodeArrays rows, the static [SC, N] lattice, per-node count carries
    (CNT/HOLD [S, N]) and the scan's [N]-wide dynamic rows are all partitioned
    on N — each chip owns N/n_devices nodes, exactly like the reference's
    goroutine chunking but over ICI instead of shared memory;
  * class/term tables are small and replicated;
  * the per-step argmax over N and `mask.any()` become cross-chip reductions —
    XLA GSPMD inserts the collectives (psum/all-gather over ICI) from the
    sharding annotations alone; no hand-written communication.

Pod-axis (batch) sharding — the long-context analog — composes on top for the
class-level matrices when SC×N outgrows one chip's HBM; the scan itself stays
sequential in pods by design (assume semantics).

Serving integration (the live path, not just the dryrun): `MeshState` owns
the mesh the scheduler dispatches on — `state/cache.py` keeps the encoded
`ClusterTables` RESIDENT on it (node axis split, patched with donated
scatters), `sched/prewarm.py` keys executables on the mesh signature, and
`sched/supervisor.py` drops/reforms the mesh across backend loss.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..state.arrays import ClusterTables, NodeArrays

NODE_AXIS = "nodes"

# the FLEET axis (fleet/ subsystem): K virtual tenant clusters stacked on a
# leading axis and split across chips. On a 1-D fleet mesh each chip owns
# K/n_devices whole tenants, so the vmap'd fleet cycle needs NO cross-chip
# collectives at all (tenants are independent by construction). The 2-D
# fleet mesh (TENANT_AXIS, NODE_AXIS) additionally splits each tenant's
# node tables across a device row — one huge tenant spreads over NODE_AXIS
# instead of capping the fleet — and the per-step argmax/psum become
# row-local collectives, exactly the reductions the single-cluster
# node-axis path already proves.
TENANT_AXIS = "tenants"

XLA_MESH_HINT = (
    "set XLA_FLAGS=--xla_force_host_platform_device_count=<n> and "
    "JAX_PLATFORMS=cpu for a virtual mesh"
)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        err = RuntimeError(
            f"make_mesh({n}): only {len(devs)} devices visible — a multichip "
            "proof run on fewer devices than requested would validate nothing"
        )
        # PEP 678 notes: the actionable hint rides on the exception even
        # through re-raise/wrapping layers (3.10 tracebacks don't print
        # __notes__, so the hint is also queryable: err.__notes__)
        err.__notes__ = [XLA_MESH_HINT]
        raise err
    return Mesh(np.array(devs[:n]), (NODE_AXIS,))


def mesh_key(mesh: Optional[Mesh]) -> Optional[Tuple]:
    """Hashable signature of a mesh for executable/budget keying: shape and
    the concrete device ids. Two meshes with the same shape over DIFFERENT
    devices (pre- vs post-reform) must not share compiled programs — the old
    executable is pinned to the lost devices."""
    if mesh is None:
        return None
    return (mesh.devices.shape,
            tuple(d.id for d in mesh.devices.flat))


def padded_node_count(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices ≥ n."""
    return ((n + n_devices - 1) // n_devices) * n_devices


def _pad_node_arrays(nodes: NodeArrays, pad: int, axis: int = 0) -> NodeArrays:
    """Concatenate `pad` inert node rows along `axis` — the one fill rule
    both the single-cluster path (axis 0, the N axis) and the stacked fleet
    path (axis 1, the per-tenant N axis inside [K, N, …]) share. Id planes
    (int32) pad with -1 (absent — the empty_node_arrays convention);
    count/usage planes with 0; `unschedulable` with True; everything else
    with its dtype's zero. Every consumer is already gated on
    `nodes.valid`, so an inert row can never admit a pod."""

    def _concat(a, fill_value):
        a = np.asarray(a)
        shape = list(a.shape)
        shape[axis] = pad
        return np.concatenate(
            [a, np.full(shape, fill_value, a.dtype)], axis=axis)

    def _auto(a):
        arr = np.asarray(a)
        return _concat(arr, -1 if arr.dtype == np.int32 else 0)

    return NodeArrays(
        valid=_auto(nodes.valid),
        name_id=_auto(nodes.name_id),
        alloc=_concat(nodes.alloc, 0),
        used=_concat(nodes.used, 0),
        label_keys=_auto(nodes.label_keys),
        label_vals=_auto(nodes.label_vals),
        label_ints=_concat(nodes.label_ints, 0),
        unschedulable=_concat(nodes.unschedulable, True),
        taint_keys=_auto(nodes.taint_keys),
        taint_vals=_auto(nodes.taint_vals),
        taint_effects=_auto(nodes.taint_effects),
        topo=_auto(nodes.topo),
        domain=_auto(nodes.domain),
        port_pair_any=_auto(nodes.port_pair_any),
        port_pair_wild=_auto(nodes.port_pair_wild),
        port_triple=_auto(nodes.port_triple),
        img_words=_auto(nodes.img_words),
        vol_any=_auto(nodes.vol_any),
        vol_rw=_auto(nodes.vol_rw),
        vol_limit=_auto(nodes.vol_limit),
        avoid=_concat(nodes.avoid, False),
    )


def pad_node_tables(tables: ClusterTables, n_devices: int) -> ClusterTables:
    """Pad the node axis with inert rows (valid=False, zero capacity, every
    id -1 — the same fill as Encoder.empty_node_arrays' unoccupied slots) so
    N divides the mesh evenly. Inert rows are masked by `nodes.valid`
    everywhere the engines look, so they can never admit a pod; the padding
    test (tests/test_mesh.py) holds that to zero phantom admissions."""
    N = int(tables.nodes.valid.shape[0])
    Np = padded_node_count(N, n_devices)
    if Np == N:
        return tables
    return tables._replace(
        nodes=_pad_node_arrays(tables.nodes, Np - N, axis=0))


def _node_sharded_tables_spec(tables: ClusterTables) -> ClusterTables:
    """PartitionSpecs: NodeArrays sharded on axis 0 (the N axis); everything
    else replicated."""
    node_specs = type(tables.nodes)(
        *[P(NODE_AXIS) for _ in tables.nodes]
    )
    rep = lambda t: type(t)(*[P() for _ in t])
    return ClusterTables(
        nodes=node_specs,
        reqs=rep(tables.reqs),
        labelsets=rep(tables.labelsets),
        nterms=rep(tables.nterms),
        tolsets=rep(tables.tolsets),
        portsets=rep(tables.portsets),
        terms=rep(tables.terms),
        classes=rep(tables.classes),
        images=rep(tables.images),
        zone_keys=P(),
        volsets=rep(tables.volsets),
        drv_masks=P(),
    )


def table_shardings(tables: ClusterTables, mesh: Mesh) -> ClusterTables:
    """NamedSharding pytree matching `shard_tables`' placement — shared by
    the live placement path (state/cache.py) and the AOT prewarm path
    (sched/prewarm.py builds ShapeDtypeStructs carrying these)."""
    specs = _node_sharded_tables_spec(tables)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_tables(tables: ClusterTables, mesh: Mesh) -> ClusterTables:
    """Place tables on the mesh: node axis split across chips, rest
    replicated. When dims.N does not divide the mesh evenly, the node axis is
    padded with inert rows first (zero capacity, invalid, unschedulable) —
    bucketed capacities make the divisible case the common one, but a raw
    Dims(N=...) from a caller must not crash the mesh path."""
    nd = len(mesh.devices.flat)
    tables = pad_node_tables(tables, nd)
    specs = _node_sharded_tables_spec(tables)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tables, specs
    )


def replicate(tree, mesh: Mesh):
    return jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree
    )


# ---------------------------------------------------------------------- #
# fleet (tenant × node-shard) sharding — fleet/tables.py stacks K tenant
# clusters on a leading axis; these helpers split that axis across the
# mesh, and (2-D mesh) additionally split each tenant's node tables
# across a device row
# ---------------------------------------------------------------------- #


def make_fleet_mesh(n_devices: Optional[int] = None,
                    node_shards: int = 1) -> Mesh:
    """The fleet mesh. `node_shards=1` (default) is the legacy 1-D mesh
    over the TENANT axis — each chip owns whole tenants, no collectives.
    `node_shards=kn > 1` reshapes the same devices into a 2-D
    `(TENANT_AXIS, NODE_AXIS)` mesh of shape (n/kn, kn): each tenant's node
    tables split across a kn-wide device row, so one huge tenant spreads
    over the row instead of capping the fleet. Same device discipline as
    `make_mesh` (raises with the virtual-mesh hint when short); distinct
    axis names keep fleet and single-cluster programs from ever sharing
    sharding annotations by accident."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        err = RuntimeError(
            f"make_fleet_mesh({n}): only {len(devs)} devices visible")
        err.__notes__ = [XLA_MESH_HINT]
        raise err
    kn = int(node_shards or 1)
    if kn <= 1:
        return Mesh(np.array(devs[:n]), (TENANT_AXIS,))
    if kn > n or n % kn:
        raise ValueError(
            f"make_fleet_mesh({n}, node_shards={kn}): node_shards must "
            "divide the device count — the 2-D mesh is a (tenants, "
            "node-shards) reshape of the same devices")
    return Mesh(np.array(devs[:n]).reshape(n // kn, kn),
                (TENANT_AXIS, NODE_AXIS))


def fleet_mesh_shape(mesh: Mesh) -> Tuple[int, int]:
    """(tenant-axis width, node-shard width) of a fleet mesh. A legacy 1-D
    tenant mesh reads as (n, 1); the tenant width — NOT the flat device
    count — is what K pads up to (FleetStack.padded_k)."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    kt = shape.get(TENANT_AXIS, len(mesh.devices.flat))
    return int(kt), int(shape.get(NODE_AXIS, 1))


def padded_tenant_count(k: int, n_devices: int) -> int:
    """Smallest multiple of n_devices ≥ k — inert (empty-cluster) tenant
    slots pad the difference, exactly the `pad_node_tables` inert-row
    contract lifted one axis up."""
    return padded_node_count(k, n_devices)


def pad_fleet_node_tables(tables: ClusterTables,
                          node_shards: int) -> ClusterTables:
    """Pad a STACKED `[K, N, …]` ClusterTables tree so each tenant's node
    axis (axis 1) divides `node_shards` evenly — the `pad_node_tables`
    inert-row contract applied per tenant inside the stacked tree. The
    serving path never needs this (FleetServer grows the fleet bucket's N
    to a node-shard multiple before encoding), but a directly-constructed
    stack must not crash the 2-D mesh path."""
    N = int(tables.nodes.valid.shape[1])
    Np = padded_node_count(N, node_shards)
    if Np == N:
        return tables
    return tables._replace(
        nodes=_pad_node_arrays(tables.nodes, Np - N, axis=1))


def fleet_sharding(mesh: Mesh) -> NamedSharding:
    """The base NamedSharding of the fleet layout: a stacked leaf splits
    its leading (tenant) axis; later axes stay unsharded (on a 2-D mesh
    that means replicated across the node-shard row). Node planes of the
    stacked ClusterTables get the 2-D spec instead — see `fleet_specs`."""
    return NamedSharding(mesh, P(TENANT_AXIS))


def fleet_specs(tree, mesh: Mesh):
    """PartitionSpec pytree for a stacked fleet tree (every leaf [K, …]).
    Mirrors `_node_sharded_tables_spec` one axis up: on a 2-D mesh the
    stacked NodeArrays planes ([K, N, …]) shard (TENANT_AXIS, NODE_AXIS) —
    each tenant's nodes split across its device row — while every other
    leaf (class/term/req tables, pending/existing pods, keys, quotas)
    shards the tenant axis only, i.e. replicates across the row, because
    the per-step argmax over N reads every pod row on every row chip.
    On a 1-D mesh this degenerates to P(TENANT_AXIS) everywhere."""
    _, kn = fleet_mesh_shape(mesh)
    node_p = P(TENANT_AXIS, NODE_AXIS) if kn > 1 else P(TENANT_AXIS)
    tenant_p = P(TENANT_AXIS)

    def _specs(sub):
        if isinstance(sub, ClusterTables):
            return ClusterTables(
                nodes=type(sub.nodes)(*[node_p for _ in sub.nodes]),
                reqs=type(sub.reqs)(*[tenant_p for _ in sub.reqs]),
                labelsets=type(sub.labelsets)(
                    *[tenant_p for _ in sub.labelsets]),
                nterms=type(sub.nterms)(*[tenant_p for _ in sub.nterms]),
                tolsets=type(sub.tolsets)(*[tenant_p for _ in sub.tolsets]),
                portsets=type(sub.portsets)(
                    *[tenant_p for _ in sub.portsets]),
                terms=type(sub.terms)(*[tenant_p for _ in sub.terms]),
                classes=type(sub.classes)(*[tenant_p for _ in sub.classes]),
                images=type(sub.images)(*[tenant_p for _ in sub.images]),
                zone_keys=tenant_p,
                volsets=type(sub.volsets)(*[tenant_p for _ in sub.volsets]),
                drv_masks=tenant_p,
            )
        return jax.tree.map(lambda _: tenant_p, sub)

    return jax.tree.map(_specs, tree,
                        is_leaf=lambda x: isinstance(x, ClusterTables))


def fleet_shardings(tree, mesh: Mesh):
    """NamedSharding pytree matching `shard_fleet`'s placement — shared by
    the live placement path (fleet/tables.py FleetStack) and the AOT
    prewarm path (abstract_fleet_args), so compiled input shardings can
    never drift from what the server actually places."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        fleet_specs(tree, mesh),
                        is_leaf=lambda x: isinstance(x, P))


def shard_fleet(tree, mesh: Mesh):
    """Place a stacked fleet pytree (every leaf [K, …]) on the mesh: tenant
    axis split, and on a 2-D mesh each tenant's node planes additionally
    split across the node-shard row. K must already be a multiple of the
    tenant-axis width — the fleet stack pads with inert tenants first
    (fleet/tables.py) — and stacked node axes must divide the node-shard
    width (`pad_fleet_node_tables` when constructed directly)."""
    return jax.tree.map(jax.device_put, tree, fleet_shardings(tree, mesh))


class MeshState:
    """The serving scheduler's mesh lifecycle (sched/supervisor.py owns the
    health transitions):

      * `mesh` — the live mesh the next snapshot/dispatch should use, or
        None (single-device serving, exactly the pre-mesh behavior).
      * `on_backend_loss()` — a device of the mesh died (XlaRuntimeError,
        watchdog timeout): the WHOLE mesh is untrusted (GSPMD collectives
        span every chip), so serving drops to the supervisor's single-device
        CPU fallback immediately. The lost width is remembered.
      * `reform()` — re-admission: rebuild a mesh from the devices that are
        live NOW. After a loss the reformed mesh is SMALLER (largest power of
        two strictly below the lost width — the failed chip cannot be
        re-trusted blindly) unless the prober proved full width, in which
        case `reform(full=True)` restores it. A fresh Mesh object is built
        either way: state/cache.py keys residency on mesh identity, so
        reform forces the re-shard-from-host-staging path by construction.

    Device counts stay powers of two so the bucketed node axis (state/dims.py
    grown_for keeps N pow2-friendly) divides evenly without padding in the
    steady state; `shard_tables` pads when a raw shape doesn't.

    Fleet mode (`fleet_node_shards` not None): meshes are built with
    `make_fleet_mesh` instead — 1-D tenant mesh when node_shards is 1, the
    2-D (TENANT_AXIS, NODE_AXIS) mesh otherwise — so degrade/reform under
    the 2-D signature rides the exact same ladder: a loss drops the whole
    mesh, reform rebuilds (narrower after an unproven loss) with the
    node-shard width clamped to the reformed device count. Both widths are
    powers of two, so the clamp always divides."""

    def __init__(self, n_devices: Optional[int] = None,
                 fleet_node_shards: Optional[int] = None):
        self._mu = threading.Lock()
        self._requested = n_devices
        self._lost_width: Optional[int] = None
        self._fleet_ns = fleet_node_shards
        self.reforms = 0
        self.demotions = 0
        m = None
        avail = len(jax.devices())
        want = n_devices or avail
        if want > 1 and avail >= 2:
            m = self._build(self._pow2_floor(min(want, avail)))
        self.mesh: Optional[Mesh] = m

    def _build(self, width: int) -> Mesh:
        if self._fleet_ns is None:
            return make_mesh(width)
        ns = self._pow2_floor(max(int(self._fleet_ns), 1))
        return make_fleet_mesh(width, node_shards=min(ns, width))

    @staticmethod
    def _pow2_floor(n: int) -> int:
        return 1 << (max(n, 1).bit_length() - 1)

    @property
    def n_devices(self) -> int:
        with self._mu:
            return len(self.mesh.devices.flat) if self.mesh is not None else 1

    def on_backend_loss(self) -> None:
        """A mesh device is gone: drop the mesh entirely (collectives span
        all chips — there is no partial trust) and remember the width so
        reform comes back narrower."""
        with self._mu:
            if self.mesh is None:
                return
            self._lost_width = len(self.mesh.devices.flat)
            self.mesh = None
            self.demotions += 1

    def reform(self, full: bool = False) -> Optional[Mesh]:
        """Rebuild the mesh on re-admission. `full=True` (the prober proved
        every device answers) restores the requested width; otherwise the
        reformed mesh halves the lost width — losing one device of an 8-way
        mesh serves on 4 until a full-width probe passes."""
        with self._mu:
            avail = len(jax.devices())
            want = self._requested or avail
            if not full and self._lost_width is not None:
                want = min(want, max(self._lost_width // 2, 1))
            want = self._pow2_floor(min(want, avail))
            if want <= 1:
                self.mesh = None
                return None
            self.mesh = self._build(want)
            if full:
                self._lost_width = None
            self.reforms += 1
            return self.mesh
