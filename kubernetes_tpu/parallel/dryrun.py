"""Multichip dryrun: shard the node axis of the full cycle across an n-device
mesh and assert sharded == single-device bit-for-bit, at three rungs:

  1. spec rung (small shape): BOTH engines — waves and the sequential
     scan — so neither loses its multi-chip story;
  2. production rung (4096 nodes × 8192+ mixed flagship+gang pods): the
     waves engine behind the GANG loop, where every device holds >1
     bucket of real node data and the argsort/segment collectives run
     over non-trivial shards;
  3. BENCH rung (5120 nodes × 50k flagship pods): the multi-chip claim at
     the shapes the bench reports, not toy ones (VERDICT r4 weakness 5).

XLA GSPMD inserts the ICI collectives (argmax/any/sort movements over the
sharded node axis) from the sharding annotations alone.

This module is the ONE home for the dryrun (ISSUE 3 satellite: the driver
logic used to live duplicated in __graft_entry__.py): `bench.py --stage`
runs it as the budgeted `multichip` stage emitting the MULTICHIP_OUT
artifact, and __graft_entry__.py delegates here for the historical
entry-point behavior.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.workloads import flagship_pods, make_nodes
from ..ops.assign import assign_batch, initial_state
from ..ops.lattice import build_cycle
from ..ops.waves import assign_waves
from ..sched.cycle import UNSCHEDULABLE_TAINT_KEY
from ..state.dims import Dims
from ..state.encode import Encoder
from .mesh import make_mesh, pad_node_tables, replicate, shard_tables


def encode_flagship(n_nodes: int, n_pods: int):
    """Flagship workload (zones/racks, InterPodAffinity + PodTopologySpread)
    encoded for one dryrun dispatch."""
    nodes = make_nodes(n_nodes, zones=min(8, n_nodes), racks_per_zone=4)
    pods = flagship_pods(n_pods, groups=min(12, n_pods))
    enc = Encoder()
    enc.vocabs.label_keys.intern(UNSCHEDULABLE_TAINT_KEY)
    enc.vocabs.label_vals.intern("")
    tables, ex, pe, d = enc.encode_cluster(
        nodes, [], pods, Dims(N=n_nodes, P=n_pods)
    )
    uk = jnp.int32(enc.vocabs.label_keys.get(UNSCHEDULABLE_TAINT_KEY))
    ev = jnp.int32(enc.vocabs.label_vals.get(""))
    return tables, pe, ex, (uk, ev), d


def encode_mixed(n_nodes: int, n_pods: int):
    """Flagship (affinity/spread) + gang (pod groups) pods in one batch —
    the widest single-dispatch surface the engines serve."""
    import dataclasses

    from ..api.types import Pod, Resources
    from ..models.workloads import gang_workload_pods

    nodes = make_nodes(n_nodes, zones=min(8, n_nodes), racks_per_zone=4)
    half = n_pods // 2
    gang_half = [p for p in gang_workload_pods(half - 8)]
    pods = flagship_pods(n_pods - half, groups=min(12, n_pods)) + [
        # re-index so gang pods queue after the flagship half
        dataclasses.replace(p, creation_index=p.creation_index + n_pods)
        for p in gang_half]
    # one statically-infeasible gang so the dryrun exercises the rejection
    # loop's collectives too (per-member request exceeds any node)
    pods += [Pod(name=f"monster-w{m}", pod_group="monster", min_member=8,
                 requests=Resources.make(cpu="512", memory="1Ti"),
                 creation_index=2 * n_pods + m) for m in range(8)]
    enc = Encoder()
    enc.vocabs.label_keys.intern(UNSCHEDULABLE_TAINT_KEY)
    enc.vocabs.label_vals.intern("")
    tables, ex, pe, d = enc.encode_cluster(
        nodes, [], pods, Dims(N=n_nodes, P=n_pods))
    gang = enc.build_gang_arrays(pods, d)
    uk = jnp.int32(enc.vocabs.label_keys.get(UNSCHEDULABLE_TAINT_KEY))
    ev = jnp.int32(enc.vocabs.label_vals.get(""))
    return tables, pe, ex, gang, (uk, ev), d


def memory_report(tables_sharded, tables_single, n_nodes: int,
                  n_devices: int) -> Dict:
    """Per-device HBM accounting for the sharded state (SURVEY §2.3: shard
    the node axis when the lattice outgrows one chip's HBM). Reports measured
    bytes plus a linear projection of the node-axis share to 5k/100k/1M nodes
    against a 16 GiB v5e chip."""
    def nbytes(a):
        return int(np.prod(a.shape)) * a.dtype.itemsize

    total = sum(nbytes(a) for a in jax.tree.leaves(tables_single))
    node_axis = sum(nbytes(a) for a in jax.tree.leaves(tables_single.nodes))
    replicated = total - node_axis
    per_dev = 0
    for a in jax.tree.leaves(tables_sharded):
        per_dev += int(np.prod(a.sharding.shard_shape(a.shape))) \
            * a.dtype.itemsize
    return {
        "n_nodes": n_nodes, "n_devices": n_devices,
        "table_bytes_single_device": total,
        "table_bytes_per_device_sharded": per_dev,
        "node_axis_bytes": node_axis, "replicated_bytes": replicated,
        "projection_hbm16gib": {
            # node-axis bytes scale linearly in N; one chip overflows
            # when node_axis*(N'/N) + replicated > 16 GiB, and an
            # 8-way node shard divides exactly the node-axis term
            str(n): {
                "single_chip_gib": round(
                    (node_axis * n / n_nodes + replicated) / 2**30, 3),
                "per_chip_sharded_gib": round(
                    (node_axis * n / n_nodes / n_devices + replicated)
                    / 2**30, 3),
            } for n in (5000, 100_000, 1_000_000)
        },
    }


def run_dryrun(n_devices: int,
               log: Optional[Callable[[str], None]] = None,
               bench_pods: int = 50_000) -> Dict:
    """All three rungs; returns the structured report bench.py writes to
    the MULTICHIP_OUT artifact. `log` receives one short human line per
    rung (each well under the 1500-char stdout contract). Raises on any
    bit-inequality — a silent shard/unshard divergence must fail the run."""
    emit = log or (lambda s: None)
    rungs: List[Dict] = []
    report: Dict = {"n_devices": n_devices, "rungs": rungs}
    mesh = make_mesh(n_devices)

    # ---- rung 1: engine-spec equality at small shape, both engines ----
    n_nodes = max(n_devices * 8, 16)
    tables, pending, existing, keys, d = encode_flagship(n_nodes, 64)
    D = d.D

    # the single-device reference runs at the SAME padded capacity the
    # sharded tables carry: shard_tables pads non-divisible node counts
    # with inert rows, and the wave engine's tie-break rotation is keyed
    # mod N — comparing across capacities would be comparing two
    # legitimate placements (tests/test_mesh.py TestNodeAxisPadding)
    tables = pad_node_tables(tables, n_devices)
    st = shard_tables(tables, mesh)
    sp = replicate(pending, mesh)
    se = replicate(existing, mesh)
    uk = jax.device_put(keys[0])
    ev = jax.device_put(keys[1])

    for engine_name, engine in (("waves", assign_waves),
                                ("scan", assign_batch)):
        t0 = time.perf_counter()

        @jax.jit
        def cycle_step(tables, pending, existing, uk, ev, engine=engine):
            cyc = build_cycle(tables, existing, uk, ev, D)
            init = initial_state(tables, cyc)
            res = engine(tables, cyc, pending, init)
            return res.node, res.feasible

        ref_node, ref_feas = jax.tree.map(
            np.asarray, cycle_step(tables, pending, existing,
                                   keys[0], keys[1]))
        node, feasible = cycle_step(st, sp, se, uk, ev)
        node.block_until_ready()
        n_ok = int(feasible.sum())
        assert n_ok > 0, f"multichip dryrun ({engine_name}) scheduled nothing"
        assert int((node >= 0).sum()) == n_ok
        np.testing.assert_array_equal(np.asarray(node), ref_node)
        np.testing.assert_array_equal(np.asarray(feasible), ref_feas)
        rungs.append({"rung": "spec", "engine": engine_name,
                      "nodes": n_nodes, "pods": 64, "scheduled": n_ok,
                      "bit_equal": True,
                      "wall_seconds": round(time.perf_counter() - t0, 2)})
        emit(f"dryrun_multichip({n_devices}) [{engine_name}]: scheduled "
             f"{n_ok} pods across {n_nodes} nodes on "
             f"{len(mesh.devices.flat)} devices, bit-equal to single-device")

    # ---- rung 2: production scale — 4k nodes, mixed flagship+gang batch ----
    from ..ops.gang import assign_gang

    n_nodes = 4096
    n_pods = 8192
    t0 = time.perf_counter()
    tables, pending, existing, gang, keys, d = encode_mixed(n_nodes, n_pods)
    D2 = d.D

    tables = pad_node_tables(tables, n_devices)  # reference at padded N
    st = shard_tables(tables, mesh)
    sp = replicate(pending, mesh)
    se = replicate(existing, mesh)
    sg = replicate(gang, mesh)
    uk = jax.device_put(keys[0])
    ev = jax.device_put(keys[1])

    @jax.jit
    def gang_step(tables, pending, existing, gang, uk, ev):
        cyc = build_cycle(tables, existing, uk, ev, D2)
        init = initial_state(tables, cyc)
        res, dead = assign_gang(tables, cyc, pending, init, gang)
        return res.node, res.feasible, dead

    ref = jax.tree.map(np.asarray, gang_step(
        tables, pending, existing, gang, keys[0], keys[1]))
    out = gang_step(st, sp, se, sg, uk, ev)
    jax.block_until_ready(out)
    node, feasible, dead = (np.asarray(x) for x in out)
    n_ok = int(feasible.sum())
    assert n_ok > 0, "production-rung dryrun scheduled nothing"
    np.testing.assert_array_equal(node, ref[0])
    np.testing.assert_array_equal(feasible, ref[1])
    np.testing.assert_array_equal(dead, ref[2])
    rungs.append({"rung": "production", "engine": "waves+gang",
                  "nodes": n_nodes, "pods": n_pods, "scheduled": n_ok,
                  "rejected_gangs": int(dead.sum()), "bit_equal": True,
                  "wall_seconds": round(time.perf_counter() - t0, 2),
                  "memory": memory_report(st, tables, n_nodes, n_devices)})
    emit(f"dryrun_multichip({n_devices}) [waves+gang @ {n_nodes} nodes × "
         f"{n_pods} pods]: scheduled {n_ok}, rejected gang groups: "
         f"{int(dead.sum())}, bit-equal to single-device "
         f"({n_nodes // n_devices} nodes per device)")

    # ---- rung 3: BENCH scale — 5120 nodes × 50k flagship pods sharded ----
    # (VERDICT r4 weakness 5: the multi-chip claim must be load-bearing at
    # the shapes the bench reports, not toy ones.)
    n_nodes = 5120
    n_pods = bench_pods
    t0 = time.perf_counter()
    tables, pending, existing, keys, d = encode_flagship(n_nodes, n_pods)
    D3 = d.D

    tables = pad_node_tables(tables, n_devices)  # reference at padded N
    st = shard_tables(tables, mesh)
    sp = replicate(pending, mesh)
    se = replicate(existing, mesh)
    uk = jax.device_put(keys[0])
    ev = jax.device_put(keys[1])

    @jax.jit
    def bench_step(tables, pending, existing, uk, ev):
        cyc = build_cycle(tables, existing, uk, ev, D3)
        init = initial_state(tables, cyc)
        res = assign_waves(tables, cyc, pending, init)
        return res.node, res.feasible

    ref_node, ref_feas = jax.tree.map(np.asarray, bench_step(
        tables, pending, existing, keys[0], keys[1]))
    t_sharded = time.perf_counter()
    node, feasible = bench_step(st, sp, se, uk, ev)
    jax.block_until_ready(node)
    t_sharded = time.perf_counter() - t_sharded
    n_ok = int(np.asarray(feasible).sum())
    assert n_ok > 0, "bench-scale sharded dryrun scheduled nothing"
    np.testing.assert_array_equal(np.asarray(node), ref_node)
    np.testing.assert_array_equal(np.asarray(feasible), ref_feas)
    rungs.append({"rung": "bench", "engine": "waves",
                  "nodes": n_nodes, "pods": n_pods, "scheduled": n_ok,
                  "bit_equal": True,
                  "sharded_dispatch_seconds": round(t_sharded, 3),
                  "wall_seconds": round(time.perf_counter() - t0, 2),
                  "memory": memory_report(st, tables, n_nodes, n_devices)})
    emit(f"dryrun_multichip({n_devices}) [waves @ {n_nodes} nodes × "
         f"{n_pods} pods, BENCH scale]: scheduled {n_ok}, bit-equal to "
         f"single-device ({n_nodes // n_devices} nodes per device)")
    report["ok"] = True
    return report
