"""Scheduler metrics (pkg/scheduler/metrics/metrics.go:29-99).

Same metric names as the reference so dashboards port over:
scheduling_duration_seconds / e2e_scheduling_duration_seconds histograms,
attempt counters by result, queue depth and cache size gauges
(cache.go:692-696, scheduling_queue.go:237-243), preemption counters.
"""

from __future__ import annotations

from kubernetes_tpu.component.metrics import DEFAULT_REGISTRY as REG

SCHEDULING_DURATION = REG.histogram(
    "scheduler_scheduling_duration_seconds",
    "Scheduling cycle latency (one batched wave)", labels=("operation",))
E2E_SCHEDULING_DURATION = REG.histogram(
    "scheduler_e2e_scheduling_duration_seconds",
    "End-to-end scheduling latency per wave")
BINDING_DURATION = REG.histogram(
    "scheduler_binding_duration_seconds", "Binding latency")
POD_SCHEDULE_ATTEMPTS = REG.counter(
    "scheduler_pod_scheduling_attempts_total",
    "Pods attempted, by result", labels=("result",))
PENDING_PODS = REG.gauge(
    "scheduler_pending_pods", "Pending pods by queue",
    labels=("queue",))
CACHE_SIZE = REG.gauge(
    "scheduler_cache_size", "Scheduler cache objects", labels=("type",))
PREEMPTION_VICTIMS = REG.counter(
    "scheduler_pod_preemption_victims_total", "Preemption victims")
PREEMPTION_ATTEMPTS = REG.counter(
    "scheduler_total_preemption_attempts_total", "Preemption attempts")
WAVE_SIZE = REG.histogram(
    "scheduler_wave_batch_size", "Pods per batched device wave",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192))
# cache-consistency sweep (sched/debugger.py ConsistencySweeper — the kube
# cacheComparer made periodic): divergences found between the resident
# encoded state and informer truth, and self-heal re-encodes taken
CACHE_CONSISTENCY_SWEEPS = REG.counter(
    "scheduler_cache_consistency_sweeps_total",
    "Cache-vs-informer consistency sweeps run")
CACHE_CONSISTENCY_DIVERGENCES = REG.counter(
    "scheduler_cache_consistency_divergences_total",
    "Divergences found by the consistency sweep", labels=("kind",))
CACHE_CONSISTENCY_HEALS = REG.counter(
    "scheduler_cache_consistency_heals_total",
    "Self-heal full re-encodes triggered by the sweep")
# restart/HA (sched/ledger.py): intent replay outcomes per recovery pass
RECOVERED_INTENTS = REG.counter(
    "scheduler_recovered_bind_intents_total",
    "Unretired bind intents replayed at startup/takeover",
    labels=("outcome",))
# fleet serving (fleet/server.py): per-TENANT per-tick counters, so the
# chaos suite and the fleet bench stage prove tenant isolation from
# metrics (one tenant's storm degrades only its own series)
TENANT_ADMITTED = REG.counter(
    "scheduler_fleet_tenant_admitted_total",
    "Pods admitted (bound) per tenant per fleet tick", labels=("tenant",))
TENANT_REQUEUED = REG.counter(
    "scheduler_fleet_tenant_requeued_total",
    "Pods requeued without a failure verdict (quota clamp, storm, abort) "
    "per tenant", labels=("tenant",))
TENANT_DEGRADED = REG.counter(
    "scheduler_fleet_tenant_degraded_ticks_total",
    "Fleet ticks in which the tenant was storm-degraded",
    labels=("tenant",))
DRF_CLAMPED = REG.counter(
    "scheduler_fleet_drf_clamped_total",
    "Pending pods clamped inert by the DRF quota pre-mask",
    labels=("tenant",))
# ISSUE 7 flight-recorder + e2e latency (sched/telemetry.py): the per-pod
# watch→bind histogram ROADMAP item 2's p99 target is defined in. With
# streaming micro-waves (ISSUE 18) the operating regime is sub-100 ms, so
# the ladder is densest from 5–100 ms (where the micro p50/p99 live —
# roughly one bucket per 1.3–1.5× step, enough to read a p99 shift of
# tens of ms straight off /metrics) and still extends to 60 s so a
# brownout's cycle-granular latencies land inside a bounded bucket.
POD_E2E_LATENCY = REG.histogram(
    "scheduler_pod_e2e_latency_seconds",
    "Per-pod end-to-end latency: informer ingest / queue add (first seen, "
    "surviving requeues) to Binding commit",
    buckets=(0.001, 0.0025, 0.005, 0.0075, 0.01, 0.015, 0.02, 0.03, 0.04,
             0.05, 0.065, 0.08, 0.1, 0.15, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0))
# ISSUE 18 streaming micro-waves (sched/scheduler.py): how many waves were
# micro admissions — small fresh-delta batches grafted onto the resident
# snapshot between bulk cycles. Ratio against wave counts elsewhere tells
# whether the streaming path is actually carrying the watch traffic.
MICRO_WAVES = REG.counter(
    "scheduler_micro_waves_total",
    "Micro-waves dispatched (streaming sub-cycle admission of fresh watch "
    "deltas; bulk backlog waves are not counted)",
    labels=("scheduler",))
FLIGHT_DUMPS = REG.counter(
    "scheduler_flight_recorder_dumps_total",
    "Flight-recorder ring dumps, by trigger (abandoned, watchdog_timeout, "
    "degraded, storm, takeover, debug-endpoint, ...)", labels=("trigger",))
# ISSUE 9 overload governor (sched/overload.py): the brownout mode ladder,
# the commit-path circuit breaker, and priority-aware shedding — the
# governor's OWN control signals (per-lane depths ride PENDING_PODS above,
# now including the deferred lane) must be scrapeable from /metrics. All
# series carry the GOVERNOR label (the scheduler's name; fleet = the
# tenant) — per-tenant governors share one registry, and an unlabeled
# gauge would let tenant B's NORMAL overwrite tenant A's live brownout.
OVERLOAD_MODE = REG.gauge(
    "scheduler_overload_mode",
    "Brownout mode ladder position (0=NORMAL, 1=SHED_LOW, 2=TRICKLE)",
    labels=("governor",))
MODE_TRANSITIONS = REG.counter(
    "scheduler_overload_mode_transitions_total",
    "Brownout mode transitions, by destination mode",
    labels=("governor", "to"))
BREAKER_STATE = REG.gauge(
    "scheduler_commit_breaker_state",
    "Commit-path circuit breaker (0=closed, 1=half_open, 2=open)",
    labels=("governor",))
BREAKER_TRANSITIONS = REG.counter(
    "scheduler_commit_breaker_transitions_total",
    "Commit-path breaker transitions, by destination state",
    labels=("governor", "to"))
SHED_PODS = REG.counter(
    "scheduler_overload_shed_pods_total",
    "Low-priority pods parked in the deferred lane by the governor "
    "(deferred, never dropped — they re-admit when shedding ends)",
    labels=("governor",))
# ISSUE 10 decision provenance (sched/explain.py): per-predicate rejection
# attribution for unschedulable pods and the winning node's score-component
# decomposition for scheduled ones — the on-device reduction's metric sinks.
UNSCHEDULABLE_REASONS = REG.counter(
    "scheduler_unschedulable_reasons_total",
    "Rejected-node attributions for unschedulable pods, by predicate "
    "(one increment per rejected node per unschedulable pod-wave — the "
    "tensor analog of FailedScheduling reason counts)",
    labels=("predicate",))
SCORE_SHARE = REG.counter(
    "scheduler_scheduled_score_share",
    "Accumulated score-component contribution at the winning node of every "
    "scheduled pod (a component's share = its value / the sum across "
    "components) — the explainability signal the learned-scoring roadmap "
    "items train against",
    labels=("component",))
FAILED_EVENTS = REG.counter(
    "scheduler_failed_scheduling_events_total",
    "FailedScheduling event dispositions from the decision-provenance "
    "pipeline: emitted (written through the apiserver), deduped (suppressed "
    "by the per-(pod, fingerprint) exponential backoff), capped (deferred "
    "by the per-wave write budget; re-qualifies next occurrence), error "
    "(write failed past the retry budget), unsinked (no sink attached)",
    labels=("outcome",))
# ISSUE 13 fleet watch plane (fleet/server.py FleetWatchPlane): how far
# behind live watch truth each tenant's serving state is. ~0 on a healthy
# stream (bookmarks refresh it even when the resource is quiet); grows while
# the mux stream is dead (tenants keep serving from cached state instead of
# dropping ticks); decays back to ~0 after the revive's resume.
TENANT_STALENESS = REG.gauge(
    "tenant_staleness_seconds",
    "Seconds since the tenant's watch route last heard from upstream "
    "(event, bookmark, or list)", labels=("tenant",))


def observe_tenant_staleness(staleness_by_tenant) -> None:
    """Export per-tenant watch staleness ({tenant → seconds}) — called from
    FleetWatchPlane.maintain() every fleet tick."""
    for name, s in staleness_by_tenant.items():
        TENANT_STALENESS.set(round(float(s), 3), tenant=name)


def observe_fleet_tick(per_tenant) -> None:
    """Record one fleet tick's per-tenant outcomes (fleet/server.py calls
    this with {tenant name → CycleStats}). DRF clamp counts route through
    CycleStats.drf_clamped so the fleet bench asserts `drf_clamped >= 1`
    from the metric, not from FleetServer internals."""
    for name, st in per_tenant.items():
        if st.scheduled:
            TENANT_ADMITTED.inc(st.scheduled, tenant=name)
        if st.requeued:
            TENANT_REQUEUED.inc(st.requeued, tenant=name)
        if st.degraded:
            TENANT_DEGRADED.inc(st.degraded, tenant=name)
        if getattr(st, "drf_clamped", 0):
            DRF_CLAMPED.inc(st.drf_clamped, tenant=name)


def observe_queue_depths(depths) -> None:
    """Export every queue lane (activeQ/backoffQ/unschedulableQ/deferred)
    as a `scheduler_pending_pods{queue=...}` gauge — `depths` is
    `PriorityQueue.depths()`. The overload governor consumes these same
    numbers; exporting them makes its control signals scrapeable."""
    for lane, n in depths.items():
        PENDING_PODS.set(n, queue=lane)


def observe_wave(stats, queue_lengths, cache_counts) -> None:
    """Record one wave's outcome (called from the scheduler server loop).
    `queue_lengths` is the legacy (active, backoff, unschedulable) tuple
    or a `PriorityQueue.depths()` dict (which adds the deferred lane)."""
    if stats.attempted:
        SCHEDULING_DURATION.observe(stats.cycle_seconds, operation="wave")
        E2E_SCHEDULING_DURATION.observe(stats.cycle_seconds)
        WAVE_SIZE.observe(stats.attempted)
    if stats.scheduled:
        POD_SCHEDULE_ATTEMPTS.inc(stats.scheduled, result="scheduled")
    if stats.unschedulable:
        POD_SCHEDULE_ATTEMPTS.inc(stats.unschedulable, result="unschedulable")
    if stats.bind_errors:
        POD_SCHEDULE_ATTEMPTS.inc(stats.bind_errors, result="error")
    if isinstance(queue_lengths, dict):
        observe_queue_depths(queue_lengths)
    else:
        active, backoff, unsched = queue_lengths
        PENDING_PODS.set(active, queue="active")
        PENDING_PODS.set(backoff, queue="backoff")
        PENDING_PODS.set(unsched, queue="unschedulable")
    nodes, pods = cache_counts
    CACHE_SIZE.set(nodes, type="nodes")
    CACHE_SIZE.set(pods, type="pods")
