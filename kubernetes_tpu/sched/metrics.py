"""Scheduler metrics (pkg/scheduler/metrics/metrics.go:29-99).

Same metric names as the reference so dashboards port over:
scheduling_duration_seconds / e2e_scheduling_duration_seconds histograms,
attempt counters by result, queue depth and cache size gauges
(cache.go:692-696, scheduling_queue.go:237-243), preemption counters.
"""

from __future__ import annotations

from kubernetes_tpu.component.metrics import DEFAULT_REGISTRY as REG

SCHEDULING_DURATION = REG.histogram(
    "scheduler_scheduling_duration_seconds",
    "Scheduling cycle latency (one batched wave)", labels=("operation",))
E2E_SCHEDULING_DURATION = REG.histogram(
    "scheduler_e2e_scheduling_duration_seconds",
    "End-to-end scheduling latency per wave")
BINDING_DURATION = REG.histogram(
    "scheduler_binding_duration_seconds", "Binding latency")
POD_SCHEDULE_ATTEMPTS = REG.counter(
    "scheduler_pod_scheduling_attempts_total",
    "Pods attempted, by result", labels=("result",))
PENDING_PODS = REG.gauge(
    "scheduler_pending_pods", "Pending pods by queue",
    labels=("queue",))
CACHE_SIZE = REG.gauge(
    "scheduler_cache_size", "Scheduler cache objects", labels=("type",))
PREEMPTION_VICTIMS = REG.counter(
    "scheduler_pod_preemption_victims_total", "Preemption victims")
PREEMPTION_ATTEMPTS = REG.counter(
    "scheduler_total_preemption_attempts_total", "Preemption attempts")
WAVE_SIZE = REG.histogram(
    "scheduler_wave_batch_size", "Pods per batched device wave",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192))
# cache-consistency sweep (sched/debugger.py ConsistencySweeper — the kube
# cacheComparer made periodic): divergences found between the resident
# encoded state and informer truth, and self-heal re-encodes taken
CACHE_CONSISTENCY_SWEEPS = REG.counter(
    "scheduler_cache_consistency_sweeps_total",
    "Cache-vs-informer consistency sweeps run")
CACHE_CONSISTENCY_DIVERGENCES = REG.counter(
    "scheduler_cache_consistency_divergences_total",
    "Divergences found by the consistency sweep", labels=("kind",))
CACHE_CONSISTENCY_HEALS = REG.counter(
    "scheduler_cache_consistency_heals_total",
    "Self-heal full re-encodes triggered by the sweep")
# restart/HA (sched/ledger.py): intent replay outcomes per recovery pass
RECOVERED_INTENTS = REG.counter(
    "scheduler_recovered_bind_intents_total",
    "Unretired bind intents replayed at startup/takeover",
    labels=("outcome",))


def observe_wave(stats, queue_lengths, cache_counts) -> None:
    """Record one wave's outcome (called from the scheduler server loop)."""
    if stats.attempted:
        SCHEDULING_DURATION.observe(stats.cycle_seconds, operation="wave")
        E2E_SCHEDULING_DURATION.observe(stats.cycle_seconds)
        WAVE_SIZE.observe(stats.attempted)
    if stats.scheduled:
        POD_SCHEDULE_ATTEMPTS.inc(stats.scheduled, result="scheduled")
    if stats.unschedulable:
        POD_SCHEDULE_ATTEMPTS.inc(stats.unschedulable, result="unschedulable")
    if stats.bind_errors:
        POD_SCHEDULE_ATTEMPTS.inc(stats.bind_errors, result="error")
    active, backoff, unsched = queue_lengths
    PENDING_PODS.set(active, queue="active")
    PENDING_PODS.set(backoff, queue="backoff")
    PENDING_PODS.set(unsched, queue="unschedulable")
    nodes, pods = cache_counts
    CACHE_SIZE.set(nodes, type="nodes")
    CACHE_SIZE.set(pods, type="pods")
