"""Compile-ahead for capacity-bucket growth: kill the cold-compile cliff.

Capacities bucket to coarse shapes (state/dims.py) so steady-state cycles hit
one compiled program — but CROSSING a bucket (cluster grows past 2,048 nodes,
existing pods double past E) swaps the shape signature and pays a fresh XLA
compile, which at 2k+ nodes is minutes (BENCH_r03: 106 s at the 2k×20k
bucket). In a live cluster that is a scheduling stall at exactly the moment
the cluster is growing.

The fix is the same trick ahead-of-time-compiled systems use: when occupancy
of a growing axis crosses `threshold` (default 80%), a background thread
AOT-compiles the NEXT bucket's program from abstract shapes only —
`jit(...).lower(ShapeDtypeStructs).compile()` needs no real arrays and no
device dispatch. The persistent compilation cache (utils/platform.py
enable_compile_cache) is keyed by the HLO, so when the live path first calls
with the new shapes it deserializes the already-built executable (~seconds)
instead of compiling (~minutes). The scheduler keeps cycling on the current
bucket the whole time; nothing blocks.

The reference needs no analog (Go is AOT-compiled; its scheduler has no
shape-specialized programs) — this is pure XLA-runtime plumbing, documented
in docs/PERF.md.
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import Callable, Optional

from ..state.dims import Dims

# axes that grow monotonically in a live cluster and cross buckets: nodes,
# bound pods. (P — the pending batch — is bounded by batch_size and churns
# rather than grows.)
_GROWTH_AXES = ("N", "E")


def _abstract_tables(tables, mesh):
    """(abstract ClusterTables, replicated-sharding-or-None) — the shared
    half of abstract_cycle_args / abstract_preempt_args. With a mesh, the
    node tables carry the node-axis NamedShardings and everything else the
    replicated one, so both AOT paths compile the SAME GSPMD placement the
    live mesh path dispatches; layout changes live in parallel/mesh.py
    table_shardings, in exactly one place."""
    import jax

    if mesh is None:
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tables), None
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import table_shardings

    rep = NamedSharding(mesh, PartitionSpec())
    tsh = table_shardings(tables, mesh)
    abstract = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        tables, tsh)
    return abstract, rep


def abstract_cycle_args(d: Dims, gang: bool = False, mesh=None):
    """ShapeDtypeStruct pytrees for one _schedule_batch_impl call at dims
    `d` — built from a throwaway Encoder's empty tables, so shapes/dtypes
    and pytree structure are BY CONSTRUCTION the ones the live path passes.
    `gang=True` adds abstract GangArrays (gang-bearing batches trace a
    structurally different program — the restart loop). `mesh` attaches the
    serving shardings (node axis split on the tables, everything else
    replicated — parallel/mesh.py), so the AOT compile produces the SAME
    GSPMD-partitioned executable the live mesh path dispatches."""
    import jax
    import jax.numpy as jnp

    from ..ops.gang import GangArrays
    from ..ops.lattice import default_engine_config
    from ..state.arrays import ClusterTables
    from ..state.encode import Encoder

    enc = Encoder()
    tables = ClusterTables(
        nodes=enc.empty_node_arrays(d),
        reqs=enc.build_req_table(d),
        labelsets=enc.build_labelset_table(d),
        nterms=enc.build_nterm_table(d),
        tolsets=enc.build_tolset_table(d),
        portsets=enc.build_portset_table(d),
        terms=enc.build_term_table(d),
        classes=enc.build_class_table(d),
        images=enc.build_image_table(d),
        zone_keys=enc.build_zone_keys(),
        volsets=enc.build_volset_table(d),
        drv_masks=enc.build_drv_masks(d),
    )
    pending = enc.build_pod_arrays([], d, capacity=d.P)
    existing = enc.build_pod_arrays([], d, capacity=d.E)
    abstract_tables, rep = _abstract_tables(tables, mesh)
    abstract = lambda t: jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep), t)
    scalar_i32 = jax.ShapeDtypeStruct((), jnp.int32, sharding=rep)
    scalar_f32 = jax.ShapeDtypeStruct((), jnp.float32, sharding=rep)
    gang_args = None
    if gang:
        gang_args = GangArrays(
            group=jax.ShapeDtypeStruct((d.P,), jnp.int32, sharding=rep),
            needed=jax.ShapeDtypeStruct((d.GR,), jnp.int32, sharding=rep),
            valid=jax.ShapeDtypeStruct((d.GR,), jnp.bool_, sharding=rep),
            rank=jax.ShapeDtypeStruct((d.GR,), jnp.int32, sharding=rep),
        )
    return (abstract_tables, abstract(pending), (scalar_i32, scalar_i32),
            abstract(existing), scalar_f32,
            jax.tree.map(lambda _: scalar_f32, default_engine_config()),
            gang_args)


def abstract_preempt_args(d: Dims, burst: int, mesh=None):
    """ShapeDtypeStruct pytrees for one sched.preemption._preempt call at
    dims `d` with a preemptor burst of `burst` lanes — the preemption analog
    of abstract_cycle_args, so the burst program can compile in the
    background BEFORE the first preemption storm hits the live path. `mesh`
    attaches the serving shardings (the burst's what-if runs over the SAME
    mesh-resident tables as the wave cycle)."""
    import jax
    import jax.numpy as jnp

    from ..ops.lattice import default_engine_config
    from ..state.arrays import ClusterTables
    from ..state.encode import Encoder

    enc = Encoder()
    tables = ClusterTables(
        nodes=enc.empty_node_arrays(d),
        reqs=enc.build_req_table(d),
        labelsets=enc.build_labelset_table(d),
        nterms=enc.build_nterm_table(d),
        tolsets=enc.build_tolset_table(d),
        portsets=enc.build_portset_table(d),
        terms=enc.build_term_table(d),
        classes=enc.build_class_table(d),
        images=enc.build_image_table(d),
        zone_keys=enc.build_zone_keys(),
        volsets=enc.build_volset_table(d),
        drv_masks=enc.build_drv_masks(d),
    )
    existing = enc.build_pod_arrays([], d, capacity=d.E)
    abstract_tables, rep = _abstract_tables(tables, mesh)
    abstract = lambda t: jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep), t)
    scalar_i32 = jax.ShapeDtypeStruct((), jnp.int32, sharding=rep)
    scalar_f32 = jax.ShapeDtypeStruct((), jnp.float32, sharding=rep)
    vec_i32 = jax.ShapeDtypeStruct((burst,), jnp.int32, sharding=rep)
    pdb = jax.ShapeDtypeStruct((d.E,), jnp.bool_, sharding=rep)
    return (abstract_tables, abstract(existing), vec_i32, vec_i32, vec_i32,
            (scalar_i32, scalar_i32), pdb, scalar_f32,
            jax.tree.map(lambda _: scalar_f32, default_engine_config()))


class BucketPrewarmer:
    """Watches per-cycle occupancy and compiles the next bucket ahead of
    need. One in-flight compile at a time; each (dims, engine) signature is
    warmed at most once per process.

    Compiled executables are KEPT (self.compiled) and the dispatch layer
    calls them directly (`sched/cycle.py _schedule_batch`): re-tracing the
    wave engine at a big shape costs seconds even with the persistent XLA
    cache, which would blow the boundary-cycle budget right when the
    cluster crosses a bucket. Calling the stored jax Compiled skips
    trace+lower+compile entirely — the first post-boundary cycle pays only
    the snapshot patch and the dispatch itself."""

    def __init__(self, threshold: float = 0.8, min_axis: int = 256,
                 compile_fn: Optional[Callable] = None):
        # min_axis: below this capacity a fresh compile is cheap enough that
        # warming would just burn test/laptop CPU — skip.
        # KTPU_PREWARM_MIN_AXIS overrides (small-shape bench validation).
        import os

        self.threshold = threshold
        self.min_axis = int(os.environ.get("KTPU_PREWARM_MIN_AXIS", min_axis))
        self.enabled = True   # bench/test gate: observe() is a no-op when off
        self._warmed: set = set()
        self._mu = threading.Lock()
        self._inflight: Optional[threading.Thread] = None
        # the preempt program warms on its OWN slot: a next-bucket cycle
        # compile can run for the better part of a minute, and serializing
        # behind it would leave the first preemption storm paying the
        # burst compile synchronously (XLA compiles release the GIL, so
        # two background compiles genuinely overlap)
        self._inflight_preempt: Optional[threading.Thread] = None
        self._compile_fn = compile_fn or self._compile
        self.warm_log: list = []   # (dims, engine) actually compiled — tests
        # (dims, engine, extras, gang, rc, fleet, mesh sig) → jax Compiled
        # for the cycle program (rc = the run-collapsed engine's static run
        # capacity, 0 for the other engines; fleet = the tenant-stack count
        # K of a fleet/cycle.py program, None for single-cluster — the slot
        # that makes it impossible for a K-tenant Compiled to be handed a
        # single cluster's arrays or vice versa);
        # ("preempt", dims, burst) → Compiled for the preemption burst
        self.compiled: dict = {}
        # bumped by invalidate(): a background compile that STARTED before a
        # backend loss must not register its executable afterward — it may
        # be bound to the dead runtime, and calling it would re-poison the
        # freshly recovered backend (recovery flap)
        self._epoch = 0
        # dispatch supervisor (sched/supervisor.py): background compile
        # failures that look like backend loss are reported so the health
        # machinery reacts to them exactly as to a failed live dispatch
        self.supervisor = None

    @staticmethod
    def _mesh_sig(mesh):
        from ..parallel.mesh import mesh_key

        return mesh_key(mesh)

    def observe(self, d: Dims, n_nodes: int, n_existing: int,
                engine: str = "waves", extras: tuple = (),
                gang: bool = False, mesh=None, rc: int = 0,
                fleet=None) -> None:
        """Call once per cycle with live occupancy (and whether batches are
        gang-bearing — gangs trace a different program; and which mesh the
        cycle dispatches on — a sharded program is a different executable).
        Cheap when nothing is near a boundary. Warms one target per call;
        multiple crossing axes warm on successive cycles (single-axis
        targets first — the common case is one axis crossing at a time —
        then the joint one)."""
        if not self.enabled:
            return
        live = {"N": n_nodes, "E": n_existing}
        crossing = [ax for ax in _GROWTH_AXES
                    if getattr(d, ax) >= self.min_axis
                    and live[ax] >= self.threshold * getattr(d, ax)]
        if not crossing:
            return
        targets = [d.grown_for(**{ax: getattr(d, ax) + 1}) for ax in crossing]
        if len(crossing) > 1:
            targets.append(d.grown_for(
                **{ax: getattr(d, ax) + 1 for ax in crossing}))
        msig = self._mesh_sig(mesh)
        for target in targets:
            if target == d:
                continue
            key = (replace(target, has_node_name=False), engine, extras,
                   gang, rc, fleet, msig)
            with self._mu:
                if key in self._warmed:
                    continue
                if self._inflight is not None and self._inflight.is_alive():
                    return  # one compile at a time; retry next cycle
                self._warmed.add(key)
                t = threading.Thread(
                    target=self._compile_fn,
                    args=(target, engine, extras, gang, mesh, rc, fleet),
                    name=f"ktpu-prewarm-{target.N}x{target.E}", daemon=True)
                # start BEFORE publishing: wait() joins _inflight without
                # the lock, and joining a not-yet-started thread raises
                t.start()
                self._inflight = t
            return

    def _compile(self, d: Dims, engine: str, extras: tuple,
                 gang: bool, mesh=None, rc: int = 0, fleet=None) -> None:
        key = (replace(d, has_node_name=False), engine, extras, gang,
               rc, fleet, self._mesh_sig(mesh))
        epoch = self._epoch
        try:
            from ..utils import faultline
            from ..utils.faultline import InjectedDeviceError
            from .cycle import _schedule_batch_impl

            if faultline.should("device.error", "prewarm"):
                raise InjectedDeviceError(
                    "injected XlaRuntimeError at prewarm")
            if fleet is not None:
                # a tenant-stack program (fleet/cycle.py): K virtual
                # clusters per dispatch — a structurally different
                # executable from the single-cluster one at the same dims
                from ..fleet.cycle import _fleet_cycle_impl
                from ..fleet.tables import abstract_fleet_args

                (tables, pending, keys, existing, quota,
                 hw, ecfg) = abstract_fleet_args(d, int(fleet), mesh=mesh)
                compiled = _fleet_cycle_impl.lower(
                    tables, pending, keys, d.D, existing, engine, quota,
                    hw, ecfg, rc,
                ).compile()
            else:
                (tables, pending, keys, existing, hw, ecfg,
                 gang_args) = abstract_cycle_args(d, gang=gang, mesh=mesh)
                compiled = _schedule_batch_impl.lower(
                    tables, pending, keys, d.D, existing, engine, hw, ecfg,
                    extras, tuple(1.0 for _ in extras), gang_args,
                    False, rc,
                ).compile()
            with self._mu:
                if epoch != self._epoch:
                    # invalidate() ran mid-compile (backend loss): this
                    # executable may be bound to the dead runtime — drop it
                    # and let a post-recovery warm redo the work
                    self._warmed.discard(key)
                    return
                self.compiled[key] = compiled
            self.warm_log.append((d, engine))
        except Exception as e:
            # prewarming is an optimization: a failed background compile
            # must never take down the scheduling loop; the live path will
            # compile on demand exactly as without a prewarmer. A failure
            # that smells like backend loss IS reported to the supervisor.
            with self._mu:
                self._warmed.discard(key)
            if self.supervisor is not None:
                self.supervisor.note_compile_failure(e)

    def lookup(self, d: Dims, engine: str, extras: tuple, gang: bool,
               mesh=None, rc: int = 0, fleet=None):
        """The stored Compiled for this cycle signature, or None. Called on
        the dispatch hot path — one dict probe. The mesh signature is part
        of the key, so a single-device caller can NEVER receive a
        mesh-sharded executable (or vice versa) — the isolation that keeps
        a degraded wave from resharding its arrays onto lost devices. The
        fleet slot isolates the same way one layer up: a K-tenant stacked
        program and a single-cluster program at identical dims are
        different executables (fleet/cycle.py)."""
        return self.compiled.get(
            (replace(d, has_node_name=False), engine, extras, gang,
             rc, fleet, self._mesh_sig(mesh)))

    def invalidate(self) -> None:
        """Drop every stored executable and warm record, and fence out
        in-flight compiles (epoch bump: one that started before the loss
        must not register afterward). Called on backend loss
        (sched/supervisor.py): a Compiled bound to a dead runtime would
        raise mid-wave exactly when the system is trying to degrade."""
        with self._mu:
            self._epoch += 1
            self.compiled.clear()
            self._warmed.clear()

    def rewarm(self, d: Dims, engine: str = "waves", extras: tuple = (),
               gang: bool = False, mesh=None, rc: int = 0,
               fleet=None) -> bool:
        """Force a background compile of the CURRENT dims regardless of
        occupancy thresholds — the backend re-admission path: the recovered
        device's first wave should deserialize a warm executable, not pay a
        cold compile on the hot path. `mesh` is the mesh the NEXT wave will
        dispatch on (the supervisor passes the post-reform mesh, which may
        be narrower than the lost one — never the dead signature). If a
        compile is already in flight the rewarm CHAINS behind it (one
        compile at a time still holds) rather than being dropped. Returns
        True when the compile ran or was scheduled."""
        if not self.enabled:
            return False
        if max(d.N, d.E) < self.min_axis:
            return False  # small shapes recompile in seconds on demand
        key = (replace(d, has_node_name=False), engine, extras, gang,
               rc, fleet, self._mesh_sig(mesh))
        with self._mu:
            self._warmed.add(key)
            prev = self._inflight
            if prev is not None and prev.is_alive():
                def chained():
                    prev.join()
                    self._compile_fn(d, engine, extras, gang, mesh, rc,
                                     fleet)

                t = threading.Thread(
                    target=chained,
                    name=f"ktpu-rewarm-{d.N}x{d.E}", daemon=True)
            else:
                t = threading.Thread(
                    target=self._compile_fn,
                    args=(d, engine, extras, gang, mesh, rc, fleet),
                    name=f"ktpu-rewarm-{d.N}x{d.E}", daemon=True)
            # start BEFORE publishing (wait() joins without the lock; a
            # not-yet-started thread would raise there). rewarm runs on the
            # PROBER thread, so this race is cross-thread and real.
            t.start()
            self._inflight = t
        return True

    def ensure_warm(self, d: Dims, engine: str = "waves", extras: tuple = (),
                    gang: bool = False, mesh=None, rc: int = 0,
                    fleet=None) -> bool:
        """The warm-standby beat (Scheduler.warm_standby): compile this
        exact signature in the background IF it is neither compiled nor
        already compiling — idempotent, unlike rewarm (which always
        respawns; it is the re-admission path where the old executable is
        known-poisoned). Returns True when a compile was scheduled."""
        if not self.enabled or max(d.N, d.E) < self.min_axis:
            return False
        key = (replace(d, has_node_name=False), engine, extras, gang,
               rc, fleet, self._mesh_sig(mesh))
        with self._mu:
            # _warmed covers both finished compiles (the key stays) and
            # in-flight ones (added before the thread starts)
            if key in self._warmed:
                return False
        return self.rewarm(d, engine, extras, gang, mesh, rc, fleet)

    def ensure_patch_ladder(self, cache, snap, mesh=None) -> bool:
        """Background compile-ahead for the resident patch-scatter ladder
        (state/cache.py warm_patch_ladder): the per-bucket `_patch_rows`
        specializations the incremental snapshot path dispatches. Bulk
        waves amortize a first-seen rung's compile across thousands of
        pods; a streaming micro-wave (ISSUE 18) cannot — a 3-pod
        admission stalling ~0.5 s on a fresh rung IS the p99. Keyed by
        plane shapes, so a capacity growth re-warms the new ladder.
        Returns True when a compile pass was scheduled."""
        if not self.enabled or snap is None \
                or max(snap.dims.N, snap.dims.E) < self.min_axis:
            return False
        key = ("patch-ladder", snap.dims.N, snap.dims.E, snap.dims.P,
               self._mesh_sig(mesh))
        with self._mu:
            if key in self._warmed:
                return False
            if self._inflight is not None and self._inflight.is_alive():
                return False  # one compile at a time; retry next cycle
            self._warmed.add(key)

            def _run():
                try:
                    cache.warm_patch_ladder(snap, mesh=mesh)
                except Exception as e:  # noqa: BLE001 - warm is an
                    # optimization (see _compile); backend-loss-shaped
                    # failures still reach the supervisor
                    with self._mu:
                        self._warmed.discard(key)
                    if self.supervisor is not None:
                        self.supervisor.note_compile_failure(e)

            t = threading.Thread(target=_run, daemon=True,
                                 name=f"ktpu-prewarm-ladder-{snap.dims.N}"
                                      f"x{snap.dims.E}")
            t.start()
            self._inflight = t
        return True

    # ---- preemption-burst program (sched/preemption.py _preempt) ---- #

    @classmethod
    def _preempt_key(cls, d: Dims, burst: int, mesh=None):
        # the burst program never sees the pending arrays, so P (and the
        # per-batch has_node_name flag) must not split the key: the warm
        # happens against the WAVE snapshot's dims while the lookup uses
        # the preemption pass's fresh snapshot — any P drift between the
        # two would orphan the prewarmed executable exactly when a storm
        # needs it
        return ("preempt", replace(d, has_node_name=False, P=1), burst,
                cls._mesh_sig(mesh))

    def observe_preempt(self, d: Dims, burst: int, mesh=None) -> None:
        """Warm the preemption-burst program for the CURRENT dims in the
        background. Unlike the cycle program (compiled by the first wave),
        nothing compiles the preempt what-if until the first preemption
        storm — which is exactly when a multi-second compile stall hurts
        most. The scheduler calls this once per steady cycle; each
        (dims, burst, mesh) signature compiles at most once."""
        if not self.enabled:
            return
        if max(d.N, d.E) < self.min_axis:
            return
        key = self._preempt_key(d, burst, mesh)
        with self._mu:
            if key in self._warmed:
                return
            if self._inflight_preempt is not None \
                    and self._inflight_preempt.is_alive():
                return  # one preempt compile at a time; retry next cycle
            self._warmed.add(key)
            t = threading.Thread(
                target=self._compile_preempt, args=(d, burst, mesh),
                name=f"ktpu-prewarm-preempt-{d.N}x{d.E}", daemon=True)
            t.start()  # before publishing: see observe()
            self._inflight_preempt = t

    def _compile_preempt(self, d: Dims, burst: int, mesh=None) -> None:
        key = self._preempt_key(d, burst, mesh)
        epoch = self._epoch
        try:
            from ..utils import faultline
            from ..utils.faultline import InjectedDeviceError
            from .preemption import _preempt

            if faultline.should("device.error", "prewarm"):
                raise InjectedDeviceError(
                    "injected XlaRuntimeError at prewarm")
            (tables, existing, cls, nnr, prio, keys, pdb,
             hw, ecfg) = abstract_preempt_args(d, burst, mesh=mesh)
            compiled = _preempt.lower(
                tables, existing, cls, nnr, prio, d.D, keys, pdb, hw, ecfg,
            ).compile()
            with self._mu:
                if epoch != self._epoch:
                    self._warmed.discard(key)  # invalidated mid-compile
                    return
                self.compiled[key] = compiled
            self.warm_log.append((d, "preempt"))
        except Exception as e:
            # same contract as _compile: never takes down the loop, but a
            # device-class failure is a backend-loss signal the supervisor
            # must hear
            with self._mu:
                self._warmed.discard(key)
            if self.supervisor is not None:
                self.supervisor.note_compile_failure(e)

    def lookup_preempt(self, d: Dims, burst: int, mesh=None):
        return self.compiled.get(self._preempt_key(d, burst, mesh))

    def wait(self, timeout: Optional[float] = None) -> None:
        """Test/shutdown helper: join the in-flight compiles."""
        with self._mu:
            threads = (self._inflight, self._inflight_preempt)
        for t in threads:
            if t is not None:
                t.join(timeout)
