"""Compile-ahead for capacity-bucket growth: kill the cold-compile cliff.

Capacities bucket to coarse shapes (state/dims.py) so steady-state cycles hit
one compiled program — but CROSSING a bucket (cluster grows past 2,048 nodes,
existing pods double past E) swaps the shape signature and pays a fresh XLA
compile, which at 2k+ nodes is minutes (BENCH_r03: 106 s at the 2k×20k
bucket). In a live cluster that is a scheduling stall at exactly the moment
the cluster is growing.

The fix is the same trick ahead-of-time-compiled systems use: when occupancy
of a growing axis crosses `threshold` (default 80%), a background thread
AOT-compiles the NEXT bucket's program from abstract shapes only —
`jit(...).lower(ShapeDtypeStructs).compile()` needs no real arrays and no
device dispatch. The persistent compilation cache (utils/platform.py
enable_compile_cache) is keyed by the HLO, so when the live path first calls
with the new shapes it deserializes the already-built executable (~seconds)
instead of compiling (~minutes). The scheduler keeps cycling on the current
bucket the whole time; nothing blocks.

The reference needs no analog (Go is AOT-compiled; its scheduler has no
shape-specialized programs) — this is pure XLA-runtime plumbing, documented
in docs/PERF.md.
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import Callable, Optional

from ..state.dims import Dims

# axes that grow monotonically in a live cluster and cross buckets: nodes,
# bound pods. (P — the pending batch — is bounded by batch_size and churns
# rather than grows.)
_GROWTH_AXES = ("N", "E")


def abstract_cycle_args(d: Dims, gang: bool = False):
    """ShapeDtypeStruct pytrees for one _schedule_batch_impl call at dims
    `d` — built from a throwaway Encoder's empty tables, so shapes/dtypes
    and pytree structure are BY CONSTRUCTION the ones the live path passes.
    `gang=True` adds abstract GangArrays (gang-bearing batches trace a
    structurally different program — the restart loop)."""
    import jax
    import jax.numpy as jnp

    from ..ops.gang import GangArrays
    from ..ops.lattice import default_engine_config
    from ..state.arrays import ClusterTables
    from ..state.encode import Encoder

    enc = Encoder()
    tables = ClusterTables(
        nodes=enc.empty_node_arrays(d),
        reqs=enc.build_req_table(d),
        labelsets=enc.build_labelset_table(d),
        nterms=enc.build_nterm_table(d),
        tolsets=enc.build_tolset_table(d),
        portsets=enc.build_portset_table(d),
        terms=enc.build_term_table(d),
        classes=enc.build_class_table(d),
        images=enc.build_image_table(d),
        zone_keys=enc.build_zone_keys(),
        volsets=enc.build_volset_table(d),
        drv_masks=enc.build_drv_masks(d),
    )
    pending = enc.build_pod_arrays([], d, capacity=d.P)
    existing = enc.build_pod_arrays([], d, capacity=d.E)
    abstract = lambda t: jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), t)
    scalar_i32 = jax.ShapeDtypeStruct((), jnp.int32)
    scalar_f32 = jax.ShapeDtypeStruct((), jnp.float32)
    gang_args = None
    if gang:
        gang_args = GangArrays(
            group=jax.ShapeDtypeStruct((d.P,), jnp.int32),
            needed=jax.ShapeDtypeStruct((d.GR,), jnp.int32),
            valid=jax.ShapeDtypeStruct((d.GR,), jnp.bool_),
            rank=jax.ShapeDtypeStruct((d.GR,), jnp.int32),
        )
    return (abstract(tables), abstract(pending), (scalar_i32, scalar_i32),
            abstract(existing), scalar_f32,
            jax.tree.map(lambda _: scalar_f32, default_engine_config()),
            gang_args)


class BucketPrewarmer:
    """Watches per-cycle occupancy and compiles the next bucket ahead of
    need. One in-flight compile at a time; each (dims, engine) signature is
    warmed at most once per process."""

    def __init__(self, threshold: float = 0.8, min_axis: int = 256,
                 compile_fn: Optional[Callable] = None):
        # min_axis: below this capacity a fresh compile is cheap enough that
        # warming would just burn test/laptop CPU — skip.
        # KTPU_PREWARM_MIN_AXIS overrides (small-shape bench validation).
        import os

        self.threshold = threshold
        self.min_axis = int(os.environ.get("KTPU_PREWARM_MIN_AXIS", min_axis))
        self._warmed: set = set()
        self._mu = threading.Lock()
        self._inflight: Optional[threading.Thread] = None
        self._compile_fn = compile_fn or self._compile
        self.warm_log: list = []   # (dims, engine) actually compiled — tests

    def observe(self, d: Dims, n_nodes: int, n_existing: int,
                engine: str = "waves", extras: tuple = (),
                gang: bool = False) -> None:
        """Call once per cycle with live occupancy (and whether batches are
        gang-bearing — gangs trace a different program). Cheap when nothing
        is near a boundary. Warms one target per call; multiple crossing
        axes warm on successive cycles (single-axis targets first — the
        common case is one axis crossing at a time — then the joint one)."""
        live = {"N": n_nodes, "E": n_existing}
        crossing = [ax for ax in _GROWTH_AXES
                    if getattr(d, ax) >= self.min_axis
                    and live[ax] >= self.threshold * getattr(d, ax)]
        if not crossing:
            return
        targets = [d.grown_for(**{ax: getattr(d, ax) + 1}) for ax in crossing]
        if len(crossing) > 1:
            targets.append(d.grown_for(
                **{ax: getattr(d, ax) + 1 for ax in crossing}))
        for target in targets:
            if target == d:
                continue
            key = (replace(target, has_node_name=False), engine, extras, gang)
            with self._mu:
                if key in self._warmed:
                    continue
                if self._inflight is not None and self._inflight.is_alive():
                    return  # one compile at a time; retry next cycle
                self._warmed.add(key)
                t = threading.Thread(
                    target=self._compile_fn,
                    args=(target, engine, extras, gang),
                    name=f"ktpu-prewarm-{target.N}x{target.E}", daemon=True)
                self._inflight = t
                t.start()
            return

    def _compile(self, d: Dims, engine: str, extras: tuple,
                 gang: bool) -> None:
        try:
            from .cycle import _schedule_batch_impl

            (tables, pending, keys, existing, hw, ecfg,
             gang_args) = abstract_cycle_args(d, gang=gang)
            _schedule_batch_impl.lower(
                tables, pending, keys, d.D, existing, engine, hw, ecfg,
                extras, tuple(1.0 for _ in extras), gang_args,
            ).compile()
            self.warm_log.append((d, engine))
        except Exception:
            # prewarming is an optimization: a failed background compile
            # must never take down the scheduling loop; the live path will
            # compile on demand exactly as without a prewarmer
            with self._mu:
                self._warmed.discard(
                    (replace(d, has_node_name=False), engine, extras, gang))

    def wait(self, timeout: Optional[float] = None) -> None:
        """Test/shutdown helper: join the in-flight compile."""
        t = self._inflight
        if t is not None:
            t.join(timeout)
