"""Decision provenance, host side (ISSUE 10): render the on-device
attribution reduction (ops/assign.py `explain_assignments` — per-predicate
rejected-node counts for unschedulable pods, winning-node score decomposition
for scheduled ones) into operator-facing surfaces:

  * kube-style FailedScheduling messages — "0/5000 nodes are available:
    3200 Insufficient resources, 1800 node(s) had taints …" — deduped and
    rate-limited per (pod, reason-fingerprint) EventCorrelator-style (first
    occurrence emits, then exponential backoff by occurrence count), and
    written as v1 Events through the apiserver with the PR 8 retry budget
    (client/rest.py RetryPolicy: 429/503 absorbed, everything else fails
    fast — `APIEventSink`);
  * the `scheduler_unschedulable_reasons_total{predicate}` /
    `scheduler_scheduled_score_share{component}` metric series;
  * the flight-recorder wave record (`observe_wave`'s return value rides
    `SchedulerTelemetry.finish_wave(extra=...)`), so `last_dump` alone
    reconstructs WHY a wave placed what it placed;
  * the why-pending debug surface: `why(key)` serves the pod's latest
    attribution to the TelemetryGateway's `GET /debug/why/<ns>/<pod>`.

Kill switch: ``KTPU_EXPLAIN`` (default off — `build_explainer` returns None
and the wave pipeline dispatches the byte-for-byte pre-provenance program;
the same discipline as ``KTPU_OVERLOAD``/``KTPU_MESH``). The
KubeSchedulerConfiguration `decisionProvenance: true` flag enables it per
process without the env.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..ops.assign import EXPLAIN_PREDICATES, EXPLAIN_SCORE_COMPONENTS
from .metrics import FAILED_EVENTS, SCORE_SHARE, UNSCHEDULABLE_REASONS

#: kube-flavored reason text per predicate plane (error.go ErrReason*) —
#: what the FailedScheduling message renders per nonzero count
REASON_TEXT = {
    "node_match": "node(s) didn't match node selector",
    "taints": "node(s) had taints that the pod didn't tolerate",
    "fit": "Insufficient resources",
    "ports": "node(s) didn't have free ports for the requested pod ports",
    "affinity": "node(s) didn't match pod affinity rules",
    "anti": "node(s) didn't satisfy inter-pod anti-affinity rules",
    "spread": "node(s) didn't match pod topology spread constraints",
    "host": "node(s) didn't match the requested hostname",
    "volumes": "node(s) had volume conflicts or exceeded volume limits",
}


def render_unschedulable(valid_nodes: int, reasons: Dict[str, int],
                         feasible_nodes: int = 0) -> str:
    """The FailedScheduling message body. Predicate-unschedulable
    (feasible_nodes == 0): kube-style '0/N nodes are available: <count>
    <reason>, …' with reasons ordered count-desc (the dominant predicate
    leads) then name for determinism. Pods that are individually FEASIBLE
    but still came back node == -1 (group-atomic gang rejection, same-wave
    contention) must NOT claim 'zero nodes available' — the message says
    what actually happened."""
    if feasible_nodes > 0:
        return (f"{feasible_nodes}/{valid_nodes} nodes are available but "
                f"the pod was not admitted this wave (group-atomic gang "
                f"admission or same-wave contention); it will retry.")
    parts = [f"{c} {REASON_TEXT.get(p, p)}"
             for p, c in sorted(reasons.items(), key=lambda kv: (-kv[1], kv[0]))
             if c > 0]
    if not parts:
        return f"0/{valid_nodes} nodes are available."
    return f"0/{valid_nodes} nodes are available: " + ", ".join(parts) + "."


def reason_fingerprint(reasons: Dict[str, int],
                       feasible_nodes: int = 0) -> str:
    """Dedupe key for a pod's failure shape: the SET of rejecting predicates
    plus the dominant one — count jitter between waves (a node drained, two
    more filled) must not defeat the correlator, while a genuinely new
    failure mode (taints appeared where fit dominated) must re-emit. A
    feasible-but-not-admitted verdict (gang rejection, contention) is its
    own mode."""
    if feasible_nodes > 0:
        return "not-admitted"
    nz = sorted(p for p, c in reasons.items() if c > 0)
    dom = max(reasons.items(), key=lambda kv: (kv[1], kv[0]))[0] \
        if nz else "none"
    return dom + "|" + ",".join(nz)


class ReasonCorrelator:
    """EventCorrelator-style emission gate, per (pod, fingerprint): the
    first occurrence emits; afterwards occurrence counts 2, 4, 8, … (doubling,
    capped at every `cap`th) emit — exponential backoff keyed on occurrence
    COUNT, not wall time, so injected-clock tests and storm replays are
    deterministic. Bounded LRU over keys."""

    def __init__(self, cap: int = 64, max_keys: int = 4096):
        self.cap = cap
        self.max_keys = max_keys
        self._mu = threading.Lock()
        # (pod_key, fp) -> [occurrences, next_emit_at]
        self._seen: "OrderedDict[Tuple[str, str], List[int]]" = OrderedDict()

    def should_emit(self, pod_key: str, fp: str) -> bool:
        with self._mu:
            ent = self._seen.get((pod_key, fp))
            if ent is None:
                self._seen[(pod_key, fp)] = [1, 2]
                while len(self._seen) > self.max_keys:
                    self._seen.popitem(last=False)
                return True
            self._seen.move_to_end((pod_key, fp))
            ent[0] += 1
            if ent[0] >= ent[1]:
                ent[1] = min(ent[0] * 2, ent[0] + self.cap)
                return True
            return False

    def defer(self, pod_key: str, fp: str) -> None:
        """An emission that qualified but was CAPPED by the per-wave write
        budget re-arms for the very next occurrence instead of waiting out
        the doubled threshold — without this, pods that always lose the
        budget race to earlier-indexed pods at the same power-of-two
        occurrence counts would starve forever."""
        with self._mu:
            ent = self._seen.get((pod_key, fp))
            if ent is not None:
                ent[1] = ent[0] + 1

    def occurrences(self, pod_key: str, fp: str) -> int:
        with self._mu:
            ent = self._seen.get((pod_key, fp))
            return ent[0] if ent else 0

    def forget(self, pod_key: str) -> None:
        with self._mu:
            for k in [k for k in self._seen if k[0] == pod_key]:
                del self._seen[k]


class APIEventSink:
    """FailedScheduling events through the apiserver, on the APIBinder's
    transport discipline (ISSUE 10): creates v1 Events via the REST client
    under the PR 8 RetryPolicy — 429 (max-inflight shed) and 503 (restart
    window) absorbed by a capped-exponential budget, every other failure
    fails fast and is counted, never raised into the wave. Repeat emissions
    for the same (pod, fingerprint) bump the existing Event's `count`
    (EventSeries aggregation) instead of creating a new object."""

    def __init__(self, client, component: str = "default-scheduler",
                 retry=None, pod_lookup: Optional[Callable] = None):
        from ..client.rest import RetryPolicy

        self.client = client
        self.component = component
        self.pod_lookup = pod_lookup  # (ns, name) -> live pod dict or None
        self.retry = retry or RetryPolicy(attempts=3, base_s=0.05,
                                          cap_s=1.0, deadline_s=3.0)
        self.writes = 0     # Events created or count-bumped server-side
        self.errors = 0
        self._mu = threading.Lock()
        # dedup -> Event name, LRU-bounded: pod churn (failed batch jobs
        # deleted and replaced forever) must not grow this without bound —
        # an evicted entry just means the next emission creates a fresh
        # Event instead of bumping the old one's count
        self._names: "OrderedDict[Tuple[str, str, str], str]" = OrderedDict()
        self._names_cap = 4096

    def emit(self, namespace: str, pod_name: str, reason: str,
             message: str, fingerprint: str = "") -> bool:
        from ..machinery import errors, meta

        ns = namespace or "default"
        dedup = (ns, pod_name, fingerprint or reason)
        with self._mu:
            existing = self._names.get(dedup)
        try:
            if existing:
                bumped = self._bump(existing, ns, message)
                if bumped is False:
                    # transient failure bumping the EXISTING event: give
                    # up this emission (keep the name mapping) — creating
                    # a fresh object beside the live one would duplicate
                    # the series
                    return False
                if bumped is not None:
                    self.writes += 1
                    return True
                # None: the event is GONE server-side (TTL sweep, GC) —
                # forget the stale name and create afresh
                with self._mu:
                    self._names.pop(dedup, None)
            involved = {"kind": "Pod", "namespace": ns, "name": pod_name}
            if self.pod_lookup is not None:
                obj = self.pod_lookup(ns, pod_name)
                if obj is not None:
                    involved["uid"] = meta.uid(obj)
            name = f"{pod_name}.{meta.new_uid()[:13]}"
            self.retry.run(lambda: self.client.events.create({
                "apiVersion": "v1", "kind": "Event",
                "metadata": {"name": name, "namespace": ns},
                "involvedObject": involved,
                "reason": reason, "message": message, "type": "Warning",
                "source": {"component": self.component},
                "firstTimestamp": meta.now_rfc3339(),
                "lastTimestamp": meta.now_rfc3339(),
                "count": 1,
            }, ns))
            with self._mu:
                self._names[dedup] = name
                self._names.move_to_end(dedup)
                while len(self._names) > self._names_cap:
                    self._names.popitem(last=False)
            self.writes += 1
            return True
        except errors.StatusError:
            self.errors += 1
            return False

    def _bump(self, name: str, ns: str, message: str):
        """The updated Event on success; None when the event no longer
        exists (caller recreates); False on any other failure (caller
        gives up this emission — recreating beside a live object would
        duplicate the series)."""
        from ..machinery import errors, meta

        try:
            cur = self.retry.run(lambda: self.client.events.get(name, ns))
            cur["count"] = int(cur.get("count", 1)) + 1
            cur["message"] = message  # latest counts win
            cur["lastTimestamp"] = meta.now_rfc3339()
            return self.retry.run(lambda: self.client.events.update(cur, ns))
        except errors.StatusError as e:
            if errors.is_not_found(e):
                return None  # TTL-swept or GC'd: recreate
            self.errors += 1
            return False


class DecisionExplainer:
    """One per Scheduler (fleet: one per tenant, via each tenant's own
    Scheduler). Consumes the wave's device attribution, feeds the three
    sinks, and keeps a bounded latest-attribution map for /debug/why.
    Thread-aware only as far as needed: observe_wave runs on the serving
    loop; `why()` is read from the TelemetryGateway thread under `_mu`."""

    #: failed pods whose per-pod reasons ride the flight-recorder record
    #: (the record must stay bounded; totals always ride)
    RECORD_PODS = 16
    #: max synchronous event writes per wave (see _maybe_emit)
    WAVE_EVENT_BUDGET = 64

    def __init__(self, name: str = "scheduler",
                 clock: Callable[[], float] = time.monotonic,
                 sink: Optional[APIEventSink] = None,
                 keep: int = 4096):
        self.name = name
        self.clock = clock
        self.sink = sink
        self.keep = keep
        self.correlator = ReasonCorrelator()
        self._mu = threading.Lock()
        self._latest: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.events_emitted = 0
        self.events_deduped = 0
        self.waves_observed = 0
        self.unschedulable_observed = 0  # pod-wave failure verdicts seen

    # ------------------------------------------------------------------ #
    # the wave feed
    # ------------------------------------------------------------------ #

    def observe_wave(self, batch, node_idx, exp, node_order,
                     now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Consume one wave's attribution. `batch` is the popped
        [(pod, attempts)] list, `node_idx` the engine's per-pod verdicts,
        `exp` the device ExplainResult (host numpy after device_get),
        `node_order` the dispatched snapshot's node-name order. Returns the
        wave-record dict for the flight recorder (None when nothing to
        say). Aggregates are vectorized; per-pod python work happens for
        FAILED pods only (the why-pending surface)."""
        if not batch or exp is None:
            return None
        now = self.clock() if now is None else now
        self.waves_observed += 1
        n = len(batch)
        node = np.asarray(node_idx)[:n]
        reasons = np.asarray(exp.reasons)[:n]
        validn = np.asarray(exp.valid_nodes)[:n]
        feas = np.asarray(exp.feasible_nodes)[:n]
        topn = np.asarray(exp.top_nodes)[:n]
        tops = np.asarray(exp.top_scores)[:n]
        parts = np.asarray(exp.score_parts)[:n]
        pnode = np.asarray(exp.part_node)[:n]
        failed = node < 0
        sched = ~failed

        rec: Dict[str, Any] = {}
        wave_budget = [self.WAVE_EVENT_BUDGET]
        # ---- metric sinks, one labeled inc per wave (not per pod) ---- #
        if failed.any():
            totals = reasons[failed].sum(axis=0)
            for p, c in zip(EXPLAIN_PREDICATES, totals):
                if c:
                    UNSCHEDULABLE_REASONS.inc(int(c), predicate=p)
            rec["reasons_total"] = {
                p: int(c) for p, c in zip(EXPLAIN_PREDICATES, totals) if c}
            rec["unschedulable"] = int(failed.sum())
        if sched.any():
            ptot = parts[sched].sum(axis=0)
            for comp, v in zip(EXPLAIN_SCORE_COMPONENTS, ptot):
                if v:
                    SCORE_SHARE.inc(float(v), component=comp)
            rec["score_parts_total"] = {
                comp: round(float(v), 3)
                for comp, v in zip(EXPLAIN_SCORE_COMPONENTS, ptot) if v}

        # ---- per-failed-pod: latest attribution + events ---- #
        pods_rec: Dict[str, Any] = {}
        for i in np.nonzero(failed)[0]:
            pod, attempts = batch[i]
            rmap = {p: int(c) for p, c in zip(EXPLAIN_PREDICATES, reasons[i])
                    if c}
            cands = [{"node": node_order[j] if 0 <= j < len(node_order)
                      else int(j),
                      "score": round(float(s), 3)}
                     for j, s in zip(topn[i], tops[i]) if j >= 0]
            doc = {
                "outcome": "unschedulable",
                "reasons": rmap,
                "valid_nodes": int(validn[i]),
                "feasible_nodes": int(feas[i]),
                "candidates": cands,
                "score_parts": {
                    comp: round(float(v), 3)
                    for comp, v in zip(EXPLAIN_SCORE_COMPONENTS, parts[i])},
                "message": render_unschedulable(int(validn[i]), rmap,
                                                feasible_nodes=int(feas[i])),
                "attempts": attempts,
                "t_observed": round(now, 3),
            }
            self.unschedulable_observed += 1
            self._remember(pod.key, doc)
            if len(pods_rec) < self.RECORD_PODS:
                pods_rec[pod.key] = {"reasons": rmap,
                                     "feasible": int(feas[i]),
                                     "valid": int(validn[i])}
            self._maybe_emit(pod, doc, wave_budget)
        # scheduled pods that PREVIOUSLY attributed as unschedulable get
        # their resolution written over the stale failure doc (the
        # why-pending mystery closes with the winning breakdown). Pods
        # that bound first try stay out of the map — per-pod python work
        # on the happy path would be the attribution overhead budget's
        # biggest line item, for a surface nobody queries about them.
        if sched.any():
            idxs = np.nonzero(sched)[0]
            with self._mu:
                # membership checks under ONE lock acquisition — a full
                # set(self._latest) copy per happy-path wave was measurable
                # against the attribution overhead budget
                tracked = [int(i) for i in idxs
                           if batch[int(i)][0].key in self._latest]
            for i in tracked:
                pod, attempts = batch[i]
                j = int(pnode[i])
                self._remember(pod.key, {
                    "outcome": "scheduled",
                    "node": node_order[j] if 0 <= j < len(node_order)
                    else int(j),
                    "score_parts": {
                        comp: round(float(v), 3)
                        for comp, v in zip(EXPLAIN_SCORE_COMPONENTS,
                                           parts[i])},
                    "attempts": attempts,
                    "t_observed": round(now, 3),
                })
        if pods_rec:
            rec["pods"] = pods_rec
        return rec or None

    def _remember(self, key: str, doc: Dict[str, Any]) -> None:
        with self._mu:
            self._latest[key] = doc
            self._latest.move_to_end(key)
            while len(self._latest) > self.keep:
                self._latest.popitem(last=False)

    def _maybe_emit(self, pod, doc: Dict[str, Any],
                    wave_budget: List[int]) -> None:
        fp = reason_fingerprint(doc["reasons"],
                                feasible_nodes=doc["feasible_nodes"])
        if not self.correlator.should_emit(pod.key, fp):
            self.events_deduped += 1
            FAILED_EVENTS.inc(outcome="deduped")
            return
        if self.sink is None:
            FAILED_EVENTS.inc(outcome="unsinked")
            return
        if wave_budget[0] <= 0:
            # sink writes are synchronous apiserver round-trips on the
            # serving loop: a storm's first wave of thousands of DISTINCT
            # newly-failing pods (every correlator check a first
            # occurrence) must not stall the wave for minutes. Capped
            # pods re-arm for their NEXT occurrence (defer — not the
            # doubled threshold, which would let budget-race losers
            # starve), so emission spreads over subsequent waves instead
            # of being lost.
            self.correlator.defer(pod.key, fp)
            FAILED_EVENTS.inc(outcome="capped")
            return
        wave_budget[0] -= 1
        ok = self.sink.emit(pod.namespace, pod.name, "FailedScheduling",
                            doc["message"], fingerprint=fp)
        if ok:
            self.events_emitted += 1
            FAILED_EVENTS.inc(outcome="emitted")
        else:
            FAILED_EVENTS.inc(outcome="error")

    # ------------------------------------------------------------------ #
    # the why-pending surface
    # ------------------------------------------------------------------ #

    def why(self, key: str) -> Optional[Dict[str, Any]]:
        """The pod's latest attribution document, or None."""
        with self._mu:
            doc = self._latest.get(key)
            return dict(doc) if doc is not None else None

    def forget(self, key: str) -> None:
        with self._mu:
            self._latest.pop(key, None)
        self.correlator.forget(key)


def build_explainer(name: str = "scheduler",
                    clock: Callable[[], float] = time.monotonic,
                    enabled: Optional[bool] = None,
                    sink: Optional[APIEventSink] = None
                    ) -> Optional[DecisionExplainer]:
    """The KTPU_EXPLAIN kill-switch gate: None (the default — env unset, 0
    or off) keeps the wave pipeline byte-for-byte the pre-provenance
    program; anything else builds the explainer and flips the dispatch's
    static explain flag on."""
    if enabled is None:
        enabled = os.environ.get("KTPU_EXPLAIN", "0") not in ("", "0", "off")
    if not enabled:
        return None
    return DecisionExplainer(name=name, clock=clock, sink=sink)
